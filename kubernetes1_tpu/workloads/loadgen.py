"""Open-loop synthetic load for the serving data plane.

The millions-of-users stand-in: arrivals fire on a clock schedule
(Poisson or constant interarrival at a configured QPS) and NEVER wait
on completions — a slow server meets the same offered load as a fast
one, which is the only load model under which tail latency and
saturation behavior mean anything (a closed loop self-throttles into
flattering numbers).  Per-request accounting is token-granular: time to
first token and every inter-token gap land in the recorder, so the
bench's token p50/p99 comes from the CLIENT side of the stream, proxy
hops included.

Mechanics:
- one arrival thread computes the schedule; each due request is handed
  to a bounded worker pool (in-flight cap => a wedged server degrades
  to counted SHEDS, not a thread explosion — the arrivals stay open-loop
  either way);
- each request passes the ``loadgen.request`` faultline gate, then
  rides `client/retry.call_with_retries` for transient failures (the
  KTPU013 policy: no bespoke sleep loops);
- an ACKED request is one whose complete response was delivered; the
  zero-lost-acked chaos verdict counts these against server-side
  ledgers.
"""

from __future__ import annotations

import http.client
import json
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..client import retry as _retry
from ..utils import faultline, locksan


def _pctl(xs: List[float], q: float) -> Optional[float]:
    if not xs:
        return None
    s = sorted(xs)
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


class LoadGen:
    """Open-loop generator against one base URL (a DecodeServer or a
    balancer fronting many).  ``arrival`` is ``poisson`` (exponential
    interarrivals) or ``constant``; ``stream=True`` consumes the
    per-token ndjson stream (token-gap recording), ``False`` posts for
    the buffered JSON body."""

    def __init__(self, url: str, qps: float, arrival: str = "poisson",
                 seed: int = 0, tokens: Tuple[int, ...] = (1, 2, 3),
                 max_new: int = 8, stream: bool = True,
                 max_inflight: int = 64, retries: int = 2,
                 timeout: float = 30.0):
        if arrival not in ("poisson", "constant"):
            raise ValueError(f"unknown arrival process {arrival!r}")
        host, _, port = url.split("//", 1)[-1].partition(":")
        self.host, self.port = host, int(port or 80)
        self.qps = qps
        self.arrival = arrival
        self.tokens = list(tokens)
        self.max_new = max_new
        self.stream = stream
        self.max_inflight = max_inflight
        self.retries = retries
        self.timeout = timeout
        self._rng = random.Random(seed)
        self.offered = 0
        self.issued = 0
        self.acked = 0
        self.failed = 0
        self.shed = 0
        self.ttft_s: List[float] = []
        self.token_gap_s: List[float] = []
        self.request_s: List[float] = []
        self._inflight = 0
        self._lock = locksan.make_lock("LoadGen._lock")
        self._stopev = threading.Event()
        self._threads: List[threading.Thread] = []
        self._t0 = 0.0
        self._t1 = 0.0

    # ----------------------------------------------------------- control

    def start(self) -> "LoadGen":
        self._t0 = time.monotonic()
        th = threading.Thread(target=self._arrivals, name="loadgen-arrivals",
                              daemon=True)
        th.start()
        self._threads.append(th)
        return self

    def stop(self, drain_s: float = 5.0):
        """Stop arrivals, then give in-flight requests ``drain_s`` to
        finish (their outcomes still count)."""
        self._stopev.set()
        deadline = time.monotonic() + drain_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    break
            time.sleep(0.02)
        self._t1 = time.monotonic()

    def run(self, duration: float) -> "LoadGen":
        self.start()
        time.sleep(duration)
        self.stop()
        return self

    # ---------------------------------------------------------- arrivals

    def _interarrival(self) -> float:
        rate = max(self.qps, 1e-3)
        if self.arrival == "poisson":
            return self._rng.expovariate(rate)
        return 1.0 / rate

    def _arrivals(self):
        next_t = time.monotonic() + self._interarrival()
        while not self._stopev.is_set():
            now = time.monotonic()
            if now < next_t:
                self._stopev.wait(min(next_t - now, 0.05))
                continue
            next_t += self._interarrival()
            self.offered += 1
            with self._lock:
                if self._inflight >= self.max_inflight:
                    self.shed += 1
                    continue
                self._inflight += 1
                self.issued += 1
            th = threading.Thread(target=self._one, name="loadgen-req",
                                  daemon=True)
            th.start()

    # ----------------------------------------------------------- request

    def _one(self):
        t_start = time.monotonic()
        try:
            gaps: List[float] = []
            ttft: List[float] = []

            def attempt():
                # a retry is a fresh request: wipe any partial recording
                gaps.clear()
                ttft.clear()
                faultline.check("loadgen.request")
                self._request(t_start, ttft, gaps)

            _retry.call_with_retries(
                attempt, steps=self.retries + 1,
                backoff=_retry.Backoff(base=0.01, cap=0.2),
                reason="loadgen.request",
                classify=lambda e: isinstance(
                    e, (OSError, http.client.HTTPException,
                        faultline.FaultInjected)))
            with self._lock:
                self.acked += 1
                self.ttft_s.extend(ttft)
                self.token_gap_s.extend(gaps)
                self.request_s.append(time.monotonic() - t_start)
        except Exception:  # noqa: BLE001 — counted: open-loop errors are data
            with self._lock:
                self.failed += 1
        finally:
            with self._lock:
                self._inflight -= 1

    def _request(self, t_start: float, ttft: List[float],
                 gaps: List[float]):
        body = json.dumps({"tokens": self.tokens, "max_new": self.max_new,
                           "stream": self.stream}).encode()
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        try:
            conn.request("POST", "/generate", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            if resp.status != 200:
                resp.read()
                raise http.client.HTTPException(f"status {resp.status}")
            if not self.stream:
                out = json.loads(resp.read() or b"{}")
                if "tokens" not in out:
                    raise http.client.HTTPException("no tokens in response")
                ttft.append(time.monotonic() - t_start)
                return
            # ndjson token stream: one line per decode step
            t_prev = t_start
            first = True
            done = False
            while True:
                line = resp.readline()
                if not line:
                    break
                line = line.strip()
                if not line:
                    continue
                msg = json.loads(line)
                now = time.monotonic()
                if msg.get("done"):
                    done = True
                    break
                if "token" in msg:
                    if first:
                        ttft.append(now - t_start)
                        first = False
                    else:
                        gaps.append(now - t_prev)
                    t_prev = now
            if not done:
                raise http.client.HTTPException("stream truncated")
        finally:
            conn.close()

    # ----------------------------------------------------------- results

    def summary(self) -> Dict[str, object]:
        wall = max((self._t1 or time.monotonic()) - self._t0, 1e-6)
        return {
            "arrival": self.arrival,
            "offered_qps": round(self.offered / wall, 3),
            "achieved_qps": round(self.acked / wall, 3),
            "offered": self.offered,
            "issued": self.issued,
            "acked": self.acked,
            "failed": self.failed,
            "shed": self.shed,
            "ttft_p50_s": _pctl(self.ttft_s, 0.50),
            "ttft_p99_s": _pctl(self.ttft_s, 0.99),
            "token_p50_s": _pctl(self.token_gap_s, 0.50),
            "token_p99_s": _pctl(self.token_gap_s, 0.99),
            "request_p50_s": _pctl(self.request_s, 0.50),
            "request_p99_s": _pctl(self.request_s, 0.99),
        }
