"""TPU peak-FLOPs tables, normalized to JAX device granularity.

`jax.devices()` granularity differs by generation: on v2/v3 each entry is
one *core* (two cores per chip, each with its own MXU + HBM view); on v4+
(megacore) each entry is one *chip*.  MFU and per-chip throughput numbers
must divide by the right peak for what one `jax.Device` actually is, or
they are off by 2x on v2/v3.

Peak bf16 numbers are per *chip* from the public cloud.google.com/tpu docs;
`peak_flops_per_device` converts to per-jax-device using the core-vs-chip
granularity of the generation.
"""

from __future__ import annotations

# bf16 peak TFLOP/s per CHIP by device kind (public cloud.google.com/tpu docs).
PEAK_FLOPS_PER_CHIP = {
    "TPU v2": 45e12,       # 22.5 per core x 2 cores
    "TPU v3": 123e12,      # 61.5 per core x 2 cores
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,      # v5p: 229.5 per core x 2 (one megacore device)
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # trillium
    "TPU v6e": 918e12,
    "TPU7x": 2307e12,
}

# Generations whose jax.Device is a single core (2 cores per chip).
_CORE_GRANULARITY_KINDS = {"TPU v2", "TPU v3"}
_CORES_PER_CHIP = 2


def peak_flops_per_device(device) -> tuple:
    """(peak bf16 FLOP/s for ONE jax.Device, granularity label).

    granularity is "chip" when a jax device is a whole chip (v4+ megacore)
    and "core" on v2/v3 where each of the chip's two cores is its own
    device.  Unknown kinds (CPU/GPU hosts in tests) return (0.0, "device").
    """
    kind = getattr(device, "device_kind", "")
    matched = kind if kind in PEAK_FLOPS_PER_CHIP else None
    if matched is None:
        # tolerate minor kind-string drift ("TPU v3 pod", "TPU v5 lite" …);
        # longest prefix wins so "TPU v5p..." doesn't match "TPU v5"
        for known in sorted(PEAK_FLOPS_PER_CHIP, key=len, reverse=True):
            if kind.startswith(known):
                matched = known
                break
    if matched is None:
        return 0.0, "device"
    per_chip = PEAK_FLOPS_PER_CHIP[matched]
    if matched in _CORE_GRANULARITY_KINDS:
        return per_chip / _CORES_PER_CHIP, "core"
    return per_chip, "chip"
