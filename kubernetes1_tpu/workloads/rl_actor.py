"""Podracer-style RL actor/learner pairing + the actor-swarm churn driver.

The Podracer architectures (PAPERS.md) run RL at scale as thousands of
SHORT-LIVED actor pods — sub-minute lifetimes, continuous create/delete
churn — streaming experience to a long-lived, gang-scheduled learner
slice.  This module is that workload shape for the framework:

- ``rollout`` / ``Learner`` / ``run_actor``: a real (tiny) RL loop —
  numpy-only REINFORCE on a multi-armed bandit.  Actors run rollouts with
  their current policy weights and POST experience batches over HTTP (the
  Service-fronted learner address in ``KTPU_LEARNER_ADDR``); the learner
  folds batches into a policy update and serves /stats.  Deliberately
  CPU-cheap: the point is the CONTROL-PLANE shape (pod churn, endpoints
  fan-out, gang placement), not the math — actors pack on non-TPU
  capacity while learners gang on slices.

- spec builders (``actor_pod``, ``learner_job``, ``fleet_service``): the
  typed objects a driver/bench/chaos schedule creates.

- ``ChurnDriver``: recycles an actor fleet at a target churn rate
  (creates+deletes per second) against a live cluster — delete via ONE
  pods/delete:batch per wave (or singleton DELETEs for the A/B control)
  and immediate replacement creates under fresh generation-suffixed
  names.  Measures achieved ops/s and per-slot actor-restart latency
  (delete issued -> replacement Ready), the churn bench's two core
  numbers.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional

from ..api import types as t
from ..utils import locksan

ACTOR_APP_LABEL = "rl-actor"
LEARNER_APP_LABEL = "rl-learner"
LEARNER_PORT = 8476


# ------------------------------------------------------------ the RL math
#
# A K-armed bandit with a softmax policy: rollouts sample arms, rewards
# are arm-dependent Bernoulli draws, and the learner applies REINFORCE
# (reward-weighted log-prob gradients).  Numpy only — runs anywhere the
# test tier runs.

def _softmax(w):
    import numpy as np

    z = np.exp(w - w.max())
    return z / z.sum()


def rollout(weights, steps: int = 64, seed: int = 0) -> Dict[str, list]:
    """One experience batch: sampled arms + observed rewards under the
    current policy.  JSON-shaped (lists), ready to POST."""
    import numpy as np

    rng = np.random.default_rng(seed)
    w = np.asarray(weights, dtype=np.float64)
    # fixed latent arm qualities: arm i pays with prob (i+1)/(K+1)
    k = len(w)
    probs = _softmax(w)
    arms = rng.choice(k, size=steps, p=probs)
    pay = (arms + 1) / (k + 1)
    rewards = (rng.random(steps) < pay).astype(np.float64)
    return {"arms": arms.tolist(), "rewards": rewards.tolist()}


def reinforce_update(weights, batch: Dict[str, list], lr: float = 0.05):
    """One REINFORCE step over an experience batch; returns new weights
    and the batch's mean reward."""
    import numpy as np

    w = np.asarray(weights, dtype=np.float64).copy()
    arms = np.asarray(batch.get("arms") or [], dtype=np.int64)
    rewards = np.asarray(batch.get("rewards") or [], dtype=np.float64)
    if arms.size == 0:
        return w, 0.0
    baseline = rewards.mean()
    probs = _softmax(w)
    for a, r in zip(arms, rewards):
        grad = -probs
        grad[a] += 1.0
        w += lr * (r - baseline) * grad
    return w, float(baseline)


class Learner:
    """The long-lived half: accumulates experience over HTTP, applies
    policy updates, serves weights + stats.  One instance per learner
    pod; the ThreadingHTTPServer shape matches the repo's other tiny
    control servers."""

    def __init__(self, arms: int = 8, port: int = 0, lr: float = 0.05):
        import numpy as np

        from ..obs.appmetrics import AppMetrics

        self.weights = np.zeros(arms, dtype=np.float64)
        self.lr = lr
        self.batches = 0
        self.frames = 0
        self.updates = 0
        self.mean_reward = 0.0
        self._lock = locksan.make_lock("rl_actor.Learner._lock")
        self._srv = None
        self._port = port
        # workload SLIs on the same HTTP surface (/metrics), the
        # obs.ktpu.io scrape contract: the learner's ingest QPS is the
        # signal an HPA scales an actor fleet's learner tier on
        self.metrics = AppMetrics()
        self.ingest_total = self.metrics.counter(
            "ktpu_rl_ingest_total", "experience batches ingested")
        self.ingest_inflight = self.metrics.gauge(
            "ktpu_rl_ingest_inflight", "ingest requests in flight")
        self.ingest_latency = self.metrics.histogram(
            "ktpu_rl_ingest_latency_seconds", "ingest handling latency")
        self.ingest_errors_total = self.metrics.counter(
            "ktpu_rl_ingest_errors_total", "rejected experience batches")

    def ingest(self, batch: Dict[str, list]):
        t0 = time.monotonic()
        self.ingest_inflight.inc()
        try:
            with self._lock:
                self.weights, mean_r = reinforce_update(
                    self.weights, batch, lr=self.lr)
                self.batches += 1
                self.frames += len(batch.get("arms") or [])
                self.updates += 1
                self.mean_reward = mean_r
        except Exception:
            # a rejected batch must NOT count toward the ingest SLIs —
            # an HPA scaling on ktpu_rl_ingest_qps would read a stream
            # of garbage requests as phantom load
            self.ingest_errors_total.inc()
            raise
        finally:
            self.ingest_inflight.inc(-1)
        self.ingest_total.inc()
        self.metrics.mark("ktpu_rl_ingest_qps")
        self.ingest_latency.observe(time.monotonic() - t0)

    def stats(self) -> dict:
        with self._lock:
            return {"batches": self.batches, "frames": self.frames,
                    "updates": self.updates,
                    "mean_reward": round(self.mean_reward, 4),
                    "weights": [round(float(x), 4) for x in self.weights]}

    # ------------------------------------------------------------- server

    def start(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        learner = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/stats"):
                    self._json(200, learner.stats())
                elif self.path.startswith("/weights"):
                    self._json(200, {"weights": list(learner.weights)})
                elif self.path.startswith("/metrics"):
                    body = learner.metrics.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._json(404, {"error": "unknown path"})

            def do_POST(self):
                if not self.path.startswith("/experience"):
                    self._json(404, {"error": "unknown path"})
                    return
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    batch = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._json(400, {"error": "bad json"})
                    return
                try:
                    learner.ingest(batch)
                except (ValueError, TypeError, AttributeError):
                    self._json(400, {"error": "bad batch"})
                    return
                self._json(200, {"ok": True})

        self._srv = ThreadingHTTPServer(("127.0.0.1", self._port), Handler)
        self._srv.daemon_threads = True
        th = threading.Thread(target=self._srv.serve_forever, daemon=True,
                              name="rl-learner")
        th.start()
        return self

    @property
    def url(self) -> str:
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self):
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()


def run_actor(learner_url: str, lifetime_s: float = 30.0,
              steps_per_batch: int = 64, seed: int = 0,
              interval_s: float = 0.05) -> dict:
    """The short-lived half: pull weights, rollout, POST experience,
    repeat until the lifetime expires, then EXIT — recycling (the churn)
    is the fleet controller's job, not the actor's.  Transport errors are
    absorbed: an actor outliving its learner for a beat must not crash
    the fleet."""
    import urllib.request

    import numpy as np

    w = None
    sent = frames = errors = 0
    deadline = time.monotonic() + lifetime_s
    i = 0
    while time.monotonic() < deadline:
        if w is None:
            try:
                with urllib.request.urlopen(
                        learner_url + "/weights", timeout=2.0) as r:
                    w = np.asarray(
                        json.loads(r.read()).get("weights") or [0.0] * 8)
            except OSError:
                w = np.zeros(8)
        batch = rollout(w, steps=steps_per_batch, seed=seed * 100003 + i)
        i += 1
        data = json.dumps(batch).encode()
        req = urllib.request.Request(
            learner_url + "/experience", data=data,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=2.0):
                pass
            sent += 1
            frames += len(batch["arms"])
            w = None  # refresh policy next round
        except OSError:
            errors += 1
        if interval_s:
            time.sleep(interval_s)  # ktpulint: ignore[KTPU013] rollout production cadence — the workload's configured send interval, not a retry delay
    return {"batches_sent": sent, "frames": frames, "errors": errors}


# -------------------------------------------------------- spec builders

def actor_pod(slot: int, gen: int = 0, ns: str = "default",
              app: str = ACTOR_APP_LABEL, name_prefix: str = "actor",
              tpus: int = 0, lifetime_s: float = 30.0,
              learner_addr: str = "", cpu: str = "10m") -> t.Pod:
    """One actor: generation-suffixed name (slot recycling never reuses a
    live name), fleet label for the Service selector, CPU-packable by
    default (tpus=0) — Podracer actors share hosts; learners own slices."""
    pod = t.Pod()
    pod.metadata.name = f"{name_prefix}-{slot}-g{gen}"
    pod.metadata.namespace = ns
    pod.metadata.labels = {"app": app, "rl.ktpu.io/slot": str(slot)}
    c = t.Container(
        name="actor", image="ktpu/rl-actor",
        command=["python", "-m", "kubernetes1_tpu.workloads.rl_actor",
                 "--actor", "--lifetime", str(lifetime_s)])
    c.resources.requests = {"cpu": cpu}
    if learner_addr:
        c.env = [t.EnvVar(name="KTPU_LEARNER_ADDR", value=learner_addr)]
    pod.spec.containers = [c]
    pod.spec.restart_policy = "Never"
    if tpus:
        per = t.PodExtendedResource(
            name=f"{pod.metadata.name}-tpu", resource="google.com/tpu",
            quantity=tpus)
        pod.spec.extended_resources = [per]
        c.extended_resource_requests = [per.name]
    return pod


def learner_job(name: str = "rl-learner", ns: str = "default",
                workers: int = 2, tpus_per_worker: int = 1,
                gang: bool = True, scrape_port: int = 0,
                scrape_host: str = "") -> t.Job:
    """The learner slice: an Indexed Job, gang-scheduled when the gate is
    on, each worker holding TPU chips — the long-lived half actors stream
    into.  `scrape_port` opts the workers into kubelet /metrics scraping
    (the learner serves ingest SLIs at /metrics; in-process clusters
    also pass the loopback `scrape_host` of the live Learner, since pod
    IPs are synthetic there)."""
    from ..obs.appmetrics import scrape_annotations

    job = t.Job()
    job.metadata.name = name
    job.metadata.namespace = ns
    job.spec.completions = workers
    job.spec.parallelism = workers
    job.spec.completion_mode = "Indexed"
    job.spec.gang_scheduling = gang
    job.spec.backoff_limit = 20
    c = t.Container(
        name="learner", image="ktpu/rl-learner",
        command=["python", "-m", "kubernetes1_tpu.workloads.rl_actor",
                 "--learner"])
    if tpus_per_worker:
        c.resources.limits = {"google.com/tpu": tpus_per_worker}
    job.spec.template.metadata.labels = {"app": LEARNER_APP_LABEL}
    if scrape_port:
        job.spec.template.metadata.annotations = scrape_annotations(
            scrape_port, host=scrape_host)
    job.spec.template.spec.containers = [c]
    return job


def fleet_service(name: str, ns: str = "default",
                  app: str = ACTOR_APP_LABEL,
                  port: int = LEARNER_PORT) -> t.Service:
    """Service fronting a fleet by its app label — the discovery surface
    whose Endpoints object churns with the fleet."""
    svc = t.Service()
    svc.metadata.name = name
    svc.metadata.namespace = ns
    svc.spec.selector = {"app": app}
    svc.spec.ports = [t.ServicePort(name="rl", port=port, target_port=port)]
    return svc


def ready_fleet_ips(cs, namespace: str = "default",
                    app: str = ACTOR_APP_LABEL):
    """IPs of Running+Ready, non-terminating fleet pods — THE definition
    the bench convergence check and the chaos verdict both compare a
    fleet Service's Endpoints against (one copy, or the two drift).
    None when the control plane couldn't answer."""
    from ..machinery import ApiError

    try:
        pods, _ = cs.pods.list(namespace=namespace,
                               label_selector=f"app={app}")
    except (ApiError, ConnectionError, TimeoutError, OSError):
        return None
    return {p.status.pod_ip or p.status.host_ip for p in pods
            if p.status.phase == t.POD_RUNNING
            and not p.metadata.deletion_timestamp
            and any(c.type == "Ready" and c.status == "True"
                    for c in p.status.conditions)}


def service_endpoint_ips(cs, name: str, namespace: str = "default"):
    """Address set of a Service's Endpoints object; None when it hasn't
    been written (or the control plane couldn't answer)."""
    from ..machinery import ApiError

    try:
        ep = cs.endpoints.get(name, namespace)
    except (ApiError, ConnectionError, TimeoutError, OSError):
        return None
    return {a.ip for s in ep.subsets for a in s.addresses}


# -------------------------------------------------------- churn driver

class ChurnDriver:
    """Recycle an actor fleet at a target churn rate against a live
    cluster.

    One recycle = delete the slot's current pod + create its replacement
    under the next generation name = 2 ops toward the rate.  Deletes ship
    as ONE ``pods/delete:batch`` per wave (``use_batch=False`` = singleton
    DELETEs, the A/B control).  Replacement readiness is watched through
    a label-selected informer; per-slot restart latency is delete-issued
    -> replacement Ready (``ready_mode="running"``: phase Running;
    ``"bound"``: spec.nodeName set — the no-kubelet sched_perf topology).

    With ``wait_ready=True`` (default) only READY slots recycle: the
    driver measures the churn the WHOLE pipeline (schedule + kubelet
    restart) sustains, and never open-loop piles work onto a wedged
    control plane (starved ticks are counted instead).
    ``wait_ready=False`` is the capacity probe: a slot recycles as soon
    as its replacement is CREATED — the cycle rate is then bounded by
    the control plane's create+delete path itself (pods die Pending
    too, which is exactly the scheduler-queue-purge stress)."""

    def __init__(self, cs, namespace: str = "default", actors: int = 16,
                 rate: float = 50.0, use_batch: bool = True,
                 grace_seconds: int = 0, tpus_per_actor: int = 0,
                 ready_mode: str = "running", recycle_chunk: int = 16,
                 name_prefix: str = "actor", app: str = ACTOR_APP_LABEL,
                 lifetime_s: float = 30.0, learner_addr: str = "",
                 wait_ready: bool = True):
        from ..client.informer import SharedInformer

        if ready_mode not in ("running", "bound"):
            raise ValueError(f"ready_mode must be running|bound, "
                             f"got {ready_mode!r}")
        self.cs = cs
        self.namespace = namespace
        self.actors = int(actors)
        self.rate = float(rate)
        self.use_batch = bool(use_batch)
        self.grace_seconds = grace_seconds
        self.tpus_per_actor = int(tpus_per_actor)
        self.ready_mode = ready_mode
        self.recycle_chunk = max(1, int(recycle_chunk))
        self.name_prefix = name_prefix
        self.app = app
        self.lifetime_s = lifetime_s
        self.learner_addr = learner_addr
        self.wait_ready = bool(wait_ready)
        self._slots: List[dict] = [
            {"slot": i, "gen": 0, "name": "", "state": "new",
             "t_issue": 0.0, "created": False}
            for i in range(self.actors)]
        self._ready_names: set = set()
        self._ready_lock = locksan.make_lock("rl_actor.ChurnDriver._ready_lock")
        # measurement counters are bumped from N recycle workers
        self._stat_lock = locksan.make_lock("rl_actor.ChurnDriver._stat_lock")
        self._informer = SharedInformer(
            cs.pods, namespace=namespace, label_selector=f"app={app}")
        self._informer.add_handler(on_add=self._observe,
                                   on_update=lambda _o, n: self._observe(n))
        # old-generation names whose delete FAILED (or may not have
        # landed): retried on every settle pass so a fault window never
        # leaks a pod past the run (the chaos schedule's leak verdict).
        # Guarded by _stat_lock: N recycle workers add while a sweep
        # snapshots (an unguarded sorted() over a mutating set raises
        # and would silently kill the worker thread).
        self._garbage: set = set()
        self._garbage_retry_at = 0.0
        # measurements
        self.creates = 0
        self.deletes = 0
        self.create_errors = 0
        self.delete_errors = 0
        self.starved_ticks = 0
        self.restart_latencies: List[float] = []
        self._wall = 0.0

    # ------------------------------------------------------------ plumbing

    def _is_ready(self, pod: t.Pod) -> bool:
        if pod.metadata.deletion_timestamp:
            return False
        if self.ready_mode == "bound":
            return bool(pod.spec.node_name)
        return pod.status.phase == t.POD_RUNNING

    def _observe(self, pod: t.Pod):
        if self._is_ready(pod):
            with self._ready_lock:
                self._ready_names.add(pod.metadata.name)

    def _pod_for(self, slot: dict) -> t.Pod:
        return actor_pod(slot["slot"], gen=slot["gen"], ns=self.namespace,
                         app=self.app, name_prefix=self.name_prefix,
                         tpus=self.tpus_per_actor,
                         lifetime_s=self.lifetime_s,
                         learner_addr=self.learner_addr)

    def _create(self, slot: dict) -> bool:
        from ..machinery import AlreadyExists, ApiError

        try:
            self.cs.pods.create(self._pod_for(slot))
        except AlreadyExists:
            pass  # a prior attempt's create DID land
        except (ApiError, ConnectionError, TimeoutError, OSError):
            with self._stat_lock:
                self.create_errors += 1
            return False
        slot["name"] = f"{self.name_prefix}-{slot['slot']}-g{slot['gen']}"
        slot["created"] = True
        with self._stat_lock:
            self.creates += 1
        return True

    # ------------------------------------------------------------- control

    def start(self, ready_timeout: float = 60.0):
        """Create the initial fleet and wait until every slot is Ready."""
        self._informer.start()
        self._informer.wait_for_sync(15.0)
        for slot in self._slots:
            slot["state"] = "recycling"
            # t_issue 0.0 = fleet bring-up, not a recycle: cold-start
            # readiness must not pollute the actor-RESTART latency
            # distribution (_settle skips the sample)
            slot["t_issue"] = 0.0
            self._create(slot)
        deadline = time.monotonic() + ready_timeout
        while time.monotonic() < deadline:
            self._settle()
            if all(s["state"] == "ready" for s in self._slots):
                return self
            time.sleep(0.1)
        ready = sum(1 for s in self._slots if s["state"] == "ready")
        raise RuntimeError(
            f"churn fleet never became ready: {ready}/{self.actors}")

    def _settle(self, slots=None):
        """Fold informer observations into slot state (a worker settles
        only ITS partition — slots never cross workers); restart latency
        closes when a recycling slot's replacement turns Ready.  Also
        retries garbage (old generations whose delete failed) so faults
        can't leak pods past the run."""
        with self._ready_lock:
            ready = set(self._ready_names)
        for slot in (self._slots if slots is None else slots):
            if slot["state"] == "recycling":
                if not slot["created"]:
                    self._create(slot)  # earlier create failed: retry
                elif slot["name"] in ready:
                    slot["state"] = "ready"
                    if slot["t_issue"]:
                        with self._stat_lock:
                            self.restart_latencies.append(
                                time.monotonic() - slot["t_issue"])
        with self._stat_lock:
            sweep_due = (self._garbage
                         and time.monotonic() >= self._garbage_retry_at)
            if sweep_due:
                self._garbage_retry_at = time.monotonic() + 0.5
        if sweep_due:
            self._sweep_garbage()

    def _sweep_garbage(self):
        from ..machinery import ApiError, NotFound

        with self._stat_lock:
            names = sorted(self._garbage)
        if not names:
            return
        try:
            outs = self.cs.delete_batch(
                self.namespace, [{"name": n} for n in names],
                grace_seconds=0)
        except (ApiError, ConnectionError, TimeoutError, OSError):
            return  # still faulted: next settle retries
        with self._stat_lock:
            for n, err in zip(names, outs):
                if err is None or isinstance(err, NotFound):
                    self._garbage.discard(n)

    def _recycle(self, slots: List[dict]):
        from ..machinery import ApiError, NotFound

        if not slots:
            return
        now = time.monotonic()
        doomed = []
        for slot in slots:
            doomed.append({"name": slot["name"]})
            slot["state"] = "recycling"
            slot["t_issue"] = now
            slot["gen"] += 1
            slot["created"] = False
        with self._ready_lock:
            # prune dead generations: the set must track ~live names,
            # not every name a long run ever minted
            for d in doomed:
                self._ready_names.discard(d["name"])
        if self.use_batch:
            try:
                outs = self.cs.delete_batch(
                    self.namespace, doomed, grace_seconds=self.grace_seconds)
                # count LANDED deletes only (success or already-gone),
                # exactly like the singleton leg — an A/B must not let
                # the batched side book failed items as ops
                with self._stat_lock:
                    for d, e in zip(doomed, outs):
                        if e is None or isinstance(e, NotFound):
                            self.deletes += 1
                        else:
                            self.delete_errors += 1
                            self._garbage.add(d["name"])
            except (ApiError, ConnectionError, TimeoutError, OSError):
                # the envelope MAY have landed server-side: sweep the
                # names until the API proves them gone (idempotent)
                with self._stat_lock:
                    self.delete_errors += len(doomed)
                    self._garbage.update(d["name"] for d in doomed)
        else:
            for d in doomed:
                try:
                    self.cs.pods.delete(d["name"], self.namespace,
                                        grace_seconds=self.grace_seconds)
                    with self._stat_lock:
                        self.deletes += 1
                except NotFound:
                    with self._stat_lock:
                        self.deletes += 1
                except (ApiError, ConnectionError, TimeoutError, OSError):
                    with self._stat_lock:
                        self.delete_errors += 1
                        self._garbage.add(d["name"])
        for slot in slots:
            self._create(slot)

    def run(self, duration: float = 20.0, tick: float = 0.05,
            workers: int = 1) -> dict:
        """Drive churn for `duration` seconds at the target rate; returns
        the result block.  `workers` recycle threads partition the slot
        space (slot % workers) and split the rate — a capacity probe
        needs concurrent requests in flight to saturate a multi-process
        control plane (ApiClient keeps one connection per thread)."""
        workers = max(1, int(workers))
        t0 = time.monotonic()
        if workers == 1:
            self._run_worker(self._slots, self.rate, duration, tick, t0)
        else:
            parts = [[s for s in self._slots if s["slot"] % workers == w]
                     for w in range(workers)]
            threads = [threading.Thread(
                target=self._run_worker,
                args=(parts[w], self.rate / workers, duration, tick, t0),
                daemon=True, name=f"churn-worker-{w}")
                for w in range(workers)]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=duration + 60.0)
        self._wall = time.monotonic() - t0
        self._settle()
        return self.result()

    def _run_worker(self, slots: List[dict], rate: float, duration: float,
                    tick: float, t0: float):
        issued = 0
        next_slot = 0
        while True:
            elapsed = time.monotonic() - t0
            if elapsed >= duration:
                break
            self._settle(slots)
            want = int((rate * elapsed) // 2) - issued
            # issue whole waves until the tick's deficit is paid or no
            # slot is eligible — each wave's synchronous deletes+creates
            # self-pace the loop, so a capacity probe (huge rate) runs
            # the control plane flat out instead of one wave per tick
            while want > 0:
                if self.wait_ready:
                    eligible = [s for s in slots if s["state"] == "ready"]
                else:
                    # capacity probe: a CREATED replacement is enough —
                    # the current name exists to delete
                    eligible = [s for s in slots if s["created"]]
                if not eligible:
                    with self._stat_lock:
                        self.starved_ticks += 1
                    break
                # round-robin over slots so every actor churns
                eligible.sort(
                    key=lambda s: (s["slot"] - next_slot) % self.actors)
                chunk = eligible[:min(want, self.recycle_chunk)]
                next_slot = (chunk[-1]["slot"] + 1) % self.actors
                self._recycle(chunk)
                issued += len(chunk)
                want -= len(chunk)
                if time.monotonic() - t0 >= duration:
                    break
            time.sleep(tick)

    def drain(self, timeout: float = 30.0):
        """Delete the whole fleet — slots, garbage, and anything else
        wearing the fleet label (list-driven, so fault-window strays
        can't survive) — and wait for the API to show zero actors (the
        leak check's clean baseline)."""
        deadline = time.monotonic() + timeout
        names = {s["name"] for s in self._slots if s["created"]}
        names |= self._garbage
        while time.monotonic() < deadline:
            try:
                pods, _ = self.cs.pods.list(
                    namespace=self.namespace,
                    label_selector=f"app={self.app}")
            except Exception:  # noqa: BLE001 — settling control plane
                time.sleep(0.2)  # ktpulint: ignore[KTPU013] bench teardown drain poll against a deliberately-settling control plane — fixed cadence, deadline-bounded, not a production path
                continue
            names |= {p.metadata.name for p in pods}
            if not pods and not names:
                return True
            if names:
                from ..machinery import ApiError

                try:
                    self.cs.delete_batch(
                        self.namespace, [{"name": n} for n in sorted(names)],
                        grace_seconds=0)
                    names.clear()
                except (ApiError, ConnectionError, TimeoutError, OSError):
                    pass  # settling/faulted control plane: retried next loop
            elif not pods:
                return True
            time.sleep(0.2)  # ktpulint: ignore[KTPU013] bench teardown drain poll — fixed cadence, deadline-bounded, not a production path
        return False

    def stop(self):
        self._informer.stop()

    def live_names(self) -> set:
        """The names the driver believes exist (the API-vs-driver leak
        check's expected set)."""
        return {s["name"] for s in self._slots if s["created"]}

    def result(self) -> dict:
        lats = sorted(self.restart_latencies)

        def pct(q):
            return round(lats[min(len(lats) - 1, int(q * len(lats)))], 4) \
                if lats else None

        ops = self.creates + self.deletes
        return {
            "actors": self.actors,
            "target_rate_ops_s": self.rate,
            "ops": ops,
            "creates": self.creates,
            "deletes": self.deletes,
            "wall_s": round(self._wall, 2),
            "ops_per_s": round(ops / self._wall, 1) if self._wall else None,
            "recycles_completed": len(lats),
            "actor_restart_p50_s": pct(0.50),
            "actor_restart_p99_s": pct(0.99),
            "create_errors": self.create_errors,
            "delete_errors": self.delete_errors,
            "starved_ticks": self.starved_ticks,
            "mode": "batched" if self.use_batch else "singleton",
            "grace_seconds": self.grace_seconds,
        }


# ------------------------------------------------------------------ main

def main():
    import argparse
    import os

    ap = argparse.ArgumentParser(description="Podracer-style RL actor/learner")
    ap.add_argument("--actor", action="store_true")
    ap.add_argument("--learner", action="store_true")
    ap.add_argument("--lifetime", type=float, default=30.0)
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--port", type=int, default=LEARNER_PORT)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    if args.learner:
        learner = Learner(port=args.port).start()
        print(f"learner serving on {learner.url}", flush=True)
        try:
            while True:
                time.sleep(5)
                print(json.dumps(learner.stats()), flush=True)
        except KeyboardInterrupt:
            learner.stop()
        return
    addr = os.environ.get("KTPU_LEARNER_ADDR", f"http://127.0.0.1:{args.port}")
    if not addr.startswith("http"):
        addr = f"http://{addr}"
    out = run_actor(addr, lifetime_s=args.lifetime,
                    steps_per_batch=args.steps, seed=args.seed)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
