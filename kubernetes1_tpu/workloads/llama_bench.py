"""Llama training benchmark payload — runs INSIDE a scheduled pod.

The flagship workload half of BASELINE.md ("Llama-3-8B JAX training Job");
this module produces the measured single-chip tokens/sec + MFU number the
bench board carries (the 8B config itself is multi-host — a single v5e chip
cannot hold 8B params + optimizer state, so the single-chip bench runs a
smaller preset of the SAME architecture and records every knob in the
output so the number is reproducible and honest).

Like resnet_bench, it is launched by bench.py as a Job container command so
the number reflects the full stack: admission rewrote the google.com/tpu
limit, the scheduler allocated the chip, the kubelet's ProcessRuntime
started this process with the device-plugin-injected TPU env.

Two utilization numbers are reported:
- mfu: model-FLOPs utilization, analytic 6N + attention convention
  (PaLM appendix-B style: 6*N_matmul_params + 12*L*S*d per token,
  fwd+bwd) — does NOT credit remat recompute.
- hfu: hardware-FLOPs utilization from XLA's cost analysis of the compiled
  step (includes rematerialized FLOPs), when available.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from .tpu_peaks import peak_flops_per_device

# Presets are Llama-3-family architectures scaled to the memory on hand.
# "1b" ~= TinyLlama-1.1B geometry; fits one 16GB v5e chip with adafactor +
# remat. "8b" is the real multi-host flagship (dryrun/multichip only).
PRESETS = {
    "tiny": dict(vocab=256, d_model=64, n_layers=2, n_heads=4,
                 n_kv_heads=2, d_ff=128),
    "1b": dict(vocab=32000, d_model=2048, n_layers=22, n_heads=32,
               n_kv_heads=4, d_ff=5632),
    # TPU-first 1B geometry: head_dim=128 matches the MXU's 128 lanes
    # (measured +25% MFU on v5e vs "1b"'s hd=64).  NOT flop-identical to
    # "1b": kv-proj width doubles (4 kv heads x 128), ~+23M params; the
    # reported MFU is computed from THIS config's analytic flops
    "1b-tpu": dict(vocab=32000, d_model=2048, n_layers=22, n_heads=16,
                   n_kv_heads=4, d_ff=5632),
    "8b": dict(vocab=128256, d_model=4096, n_layers=32, n_heads=32,
               n_kv_heads=8, d_ff=14336),
}


def n_matmul_params(cfg) -> int:
    """Parameter count in the matmuls (excl. norms; incl. embed+unembed,
    which are real matmuls in this implementation)."""
    d, hd = cfg.d_model, cfg.head_dim
    per_layer = (d * cfg.n_heads * hd            # wq
                 + 2 * d * cfg.n_kv_heads * hd   # wk, wv
                 + cfg.n_heads * hd * d          # wo
                 + 3 * d * cfg.d_ff)             # gate, up, down
    return cfg.n_layers * per_layer + 2 * cfg.vocab * d


def model_flops_per_token(cfg, seq: int) -> float:
    """Analytic fwd+bwd FLOPs per trained token (no remat credit):
    6 * matmul params + attention 12 * L * S * d."""
    return 6.0 * n_matmul_params(cfg) + 12.0 * cfg.n_layers * seq * cfg.d_model


def make_optimizer(name: str, lr: float):
    import optax

    if name == "adamw":
        return optax.adamw(lr, weight_decay=0.1)
    if name == "adafactor":
        return optax.adafactor(lr)
    if name == "sgdm":
        return optax.sgd(lr, momentum=0.9)
    raise ValueError(f"unknown optimizer {name!r}")


def run(preset: str, batch: int, seq: int, steps: int, optimizer: str,
        warmup: int = 2, lr: float = 3e-4, remat: bool = True,
        watchdog=None, profile: bool = True) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from . import sharding as sh
    from .llama import LlamaConfig, init_params, loss_fn

    devices = jax.devices()
    if watchdog is not None:
        watchdog.cancel()  # chip claim succeeded: stand down
    n_dev = len(devices)
    cfg = LlamaConfig(max_seq=seq, remat=remat, **PRESETS[preset])
    tx = make_optimizer(optimizer, lr)
    mesh = sh.auto_mesh()

    from functools import partial

    import optax

    with sh.use_mesh(mesh):
        params = jax.jit(partial(init_params, cfg))(jax.random.key(0))
        opt_state = jax.jit(tx.init)(params)

        @partial(jax.jit, donate_argnums=(0, 1))
        def step(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(partial(loss_fn, cfg))(params, tokens)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        rng = np.random.default_rng(0)
        # +1: loss_fn trains next-token over tokens[:, :-1] -> [:, 1:]
        tokens = jnp.asarray(rng.integers(0, cfg.vocab, (batch, seq + 1)),
                             jnp.int32)

        exec_flops = None
        try:
            cost = step.lower(params, opt_state, tokens).compile().cost_analysis()
            if cost and cost.get("flops"):
                exec_flops = float(cost["flops"])
        except Exception as e:  # noqa: BLE001 — cost_analysis is best-effort on some backends
            print(f"llama_bench: cost_analysis unavailable: {e}")

        # barrier = float(loss): a device-to-host transfer of the step's
        # result.  block_until_ready alone is NOT a reliable fence on the
        # tunneled single-chip platform after a manual lower().compile()
        # (observed: it returns immediately and all work piles up on the
        # next transfer), and a wrong fence here silently inflates MFU 1000x.
        t_c0 = time.perf_counter()
        for _ in range(warmup):
            params, opt_state, loss = step(params, opt_state, tokens)
        float(loss)
        compile_s = time.perf_counter() - t_c0

        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, tokens)
        float(loss)
        wall = time.perf_counter() - t0

        prof = None
        if profile:
            import tempfile

            from .benchguard import collect_profile

            def one_step():
                nonlocal params, opt_state, loss
                params, opt_state, loss = step(params, opt_state, tokens)
                float(loss)

            prof = collect_profile(
                one_step, tempfile.mkdtemp(prefix="llama-prof-"))

    peak, granularity = peak_flops_per_device(devices[0])
    steps_per_sec = steps / wall
    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps_per_sec
    model_fps = model_flops_per_token(cfg, seq) * tokens_per_step
    mfu = (model_fps * steps_per_sec / (peak * n_dev)) if peak else None
    # XLA's cost analysis counts a lax.scan body ONCE, not n_layers times,
    # so exec_flops badly undercounts scanned models; only report hfu when
    # the count is at least plausible relative to the analytic model flops
    if exec_flops is not None and exec_flops < 0.5 * model_fps:
        exec_flops = None
    hfu = (exec_flops * steps_per_sec / (peak * n_dev)) \
        if (peak and exec_flops) else None
    return {
        "workload": f"llama-{preset}",
        "device_kind": devices[0].device_kind,
        "platform": devices[0].platform,
        "n_devices": n_dev,
        "device_granularity": granularity,
        "params_matmul": n_matmul_params(cfg),
        "batch": batch,
        "seq": seq,
        "steps": steps,
        "optimizer": optimizer,
        "remat": remat,
        "compile_s": round(compile_s, 2),
        "step_time_ms": round(1000 * wall / steps, 2),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "tokens_per_sec_per_device": round(tokens_per_sec / n_dev, 1),
        "model_flops_per_step": model_fps,
        "exec_flops_per_step": exec_flops,
        "peak_flops_per_device": peak,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "hfu": round(hfu, 4) if hfu is not None else None,
        "final_loss": float(loss),
        "profile": prof,
    }


def run_sweep(candidates, preset, seq, steps, optimizer, remat=True,
              watchdog=None, profile=True, probe_steps=3) -> dict:
    """Batch sweep (the r3 ask toward 0.42 MFU): probe each candidate
    batch with a few steps, run the winner at full length.  An OOM
    candidate (RESOURCE_EXHAUSTED) is recorded and skipped — HBM limits
    are discovered, not guessed."""
    probes = {}
    best, best_tps = None, -1.0
    for i, b in enumerate(candidates):
        try:
            r = run(preset, b, seq, probe_steps, optimizer, warmup=1,
                    remat=remat, watchdog=watchdog if i == 0 else None,
                    profile=False)
            probes[b] = {"tokens_per_sec": r["tokens_per_sec"],
                         "mfu": r["mfu"]}
            if r["tokens_per_sec"] > best_tps:
                best, best_tps = b, r["tokens_per_sec"]
        except Exception as e:  # noqa: BLE001 — OOM candidate: record, skip
            if i == 0 and watchdog is not None:
                # run() may have raised before reaching its cancel(): a
                # still-armed timer would hard-kill a later healthy run
                watchdog.cancel()
            probes[b] = {"error": f"{type(e).__name__}: {str(e)[:160]}"}
    if best is None:
        return {"error": "every sweep candidate failed", "sweep": probes}
    result = run(preset, best, seq, steps, optimizer, remat=remat,
                 profile=profile)
    result["sweep"] = probes
    result["sweep_winner_batch"] = best
    return result


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="", help="write result JSON here")
    ap.add_argument("--preset", default="1b-tpu", choices=sorted(PRESETS))
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--sweep", default="",
                    help="comma-separated batch candidates; probe each, "
                         "run the best at full --steps")
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--optimizer", default="adafactor",
                    choices=["adamw", "adafactor", "sgdm"])
    ap.add_argument("--no-remat", action="store_true")
    ap.add_argument("--no-profile", action="store_true")
    ap.add_argument("--acquire-timeout", type=float, default=180.0,
                    help="hard exit if the chip claim hangs this long")
    ap.add_argument("--platform", default="",
                    help="force a jax platform (the env var alone loses "
                         "to this image's sitecustomize axon hook)")
    args = ap.parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    from .benchguard import device_acquisition_watchdog

    watchdog = device_acquisition_watchdog(args.out, args.acquire_timeout)
    try:
        if args.sweep:
            result = run_sweep(
                [int(b) for b in args.sweep.split(",") if b.strip()],
                args.preset, args.seq, args.steps, args.optimizer,
                remat=not args.no_remat, watchdog=watchdog,
                profile=not args.no_profile)
        else:
            result = run(args.preset, args.batch, args.seq, args.steps,
                         args.optimizer, remat=not args.no_remat,
                         watchdog=watchdog, profile=not args.no_profile)
    except Exception as e:  # noqa: BLE001
        result = {"error": f"{type(e).__name__}: {e}"}
        print(json.dumps(result), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump(result, f)
        sys.exit(1)
    print(json.dumps(result), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f)


if __name__ == "__main__":
    main()
