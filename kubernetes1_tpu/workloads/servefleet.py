"""Serving backend fleet: the pod <-> live-backend bridge for the L7
data plane.

An in-process cluster's pod IPs are synthetic — no kernel, no netns, no
process per pod — so "a Deployment of decode servers" needs a harness
that makes each serving pod REAL on loopback: ``ServeFleet`` watches
the app's pods and keeps exactly one live HTTP backend per Running pod,
publishing the pod-NAME -> (host, port) registry the balancer's
endpoints sync resolves through (the Endpoints addresses carry
``targetRef`` = pod name precisely because every in-process pod shares
the loopback pod IP; see `proxy.balancer.EndpointsBalancerSync`).

Lifecycle mirrors the drain contract end to end:
- pod reaches Running  -> backend starts, pod is annotated with the
  obs.ktpu.io scrape contract at ITS OWN port (per-pod slot/QPS metrics
  for the HPA, not one shared surface);
- pod starts terminating -> nothing here: the endpoints controller
  moves it to notReadyAddresses, the balancer stops picking it, and its
  open responses keep streaming from the still-live backend;
- pod object deleted -> the backend lingers ``linger_s`` (the tail of
  any in-flight response), then stops.

``SyntheticBackend`` is the tests/chaos stand-in: the DecodeServer's
HTTP + streaming + metrics contract with a configurable per-token delay
instead of a forward pass.  ``rolling_update`` drives a mid-traffic
RollingUpdate of the serving Deployment and measures what the rollout
did to the fleet (duration, peak unavailability) — the loadgen's
failed-request count judged against it is the zero-downtime verdict.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..api import types as t
from ..client import retry as _retry
from ..utils import locksan

Addr = Tuple[str, int]


class SyntheticBackend:
    """DecodeServer's serving contract without the model: POST
    /generate (buffered or ndjson streaming), GET /metrics with the
    same ktpu_llama_* names (slot gauges included), GET /healthz.
    ``token_delay_s`` shapes per-token pacing — a skewed replica in a
    bench is just a backend with a bigger delay.  ``slots`` is real
    capacity, same semantics as the BatchEngine pool: at most ``slots``
    requests decode concurrently and the rest QUEUE, so an overloaded
    replica shows up as growing latency (what least-inflight routes
    around) instead of unbounded concurrency hiding the saturation."""

    def __init__(self, token_delay_s: float = 0.002, slots: int = 8,
                 seed: int = 0):
        from ..obs.appmetrics import AppMetrics

        self.token_delay_s = token_delay_s
        self.slots = slots
        self._stopping = False
        self.metrics = AppMetrics()
        self.requests_total = self.metrics.counter(
            "ktpu_llama_requests_total", "requests served")
        self.errors_total = self.metrics.counter(
            "ktpu_llama_request_errors_total", "malformed requests")
        self.inflight = self.metrics.gauge(
            "ktpu_llama_inflight", "requests in flight")
        self.latency = self.metrics.histogram(
            "ktpu_llama_request_latency_seconds", "request latency")
        self.slots_total = self.metrics.gauge(
            "ktpu_llama_slots_total", "slot pool size")
        self.slots_used = self.metrics.gauge(
            "ktpu_llama_slots_used", "slots leased")
        self.slots_total.set(float(slots))
        self._active = 0
        self._cond = locksan.make_condition(name="SyntheticBackend._cond")
        self._srv = None

    def start(self) -> "SyntheticBackend":
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        backend = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code, body: bytes):
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/metrics"):
                    body = backend.metrics.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.startswith("/healthz"):
                    self._send(200, b'{"status":"ok"}')
                else:
                    self._send(404, b'{"error":"unknown path"}')

            def do_POST(self):
                if not self.path.startswith("/generate"):
                    self._send(404, b'{"error":"unknown path"}')
                    return
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                    toks = [int(x) for x in (req.get("tokens") or [1])]
                    max_new = min(64, int(req.get("max_new") or 8))
                    stream = bool(req.get("stream"))
                except (ValueError, TypeError):
                    backend.errors_total.inc()
                    self._send(400, b'{"error":"bad request"}')
                    return
                t0 = time.monotonic()
                backend.inflight.inc()
                # slot admission: block (queue) until a slot frees — the
                # per-handler thread is the queue entry, like a request
                # parked at the engine's _pending list
                with backend._cond:
                    while (backend._active >= backend.slots
                           and not backend._stopping):
                        backend._cond.wait(timeout=0.5)
                    if backend._stopping:
                        backend.inflight.inc(-1)
                        backend.errors_total.inc()
                        self._send(503, b'{"error":"shutting down"}')
                        return
                    backend._active += 1
                    backend.slots_used.set(float(backend._active))
                try:
                    out = [(sum(toks) + i) % 256 for i in range(max_new)]
                    if stream:
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/x-ndjson")
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()

                        def chunk(payload: bytes):
                            self.wfile.write(b"%x\r\n%s\r\n"
                                             % (len(payload), payload))

                        for tok in out:
                            time.sleep(backend.token_delay_s)
                            chunk(b'{"token":%d}\n' % tok)
                        chunk(b'{"done":true}\n')
                        self.wfile.write(b"0\r\n\r\n")
                    else:
                        time.sleep(backend.token_delay_s * max_new)
                        self._send(200, json.dumps({"tokens": out}).encode())
                finally:
                    backend.inflight.inc(-1)
                    with backend._cond:
                        backend._active -= 1
                        backend.slots_used.set(float(backend._active))
                        backend._cond.notify()
                    backend.requests_total.inc()
                    backend.metrics.mark("ktpu_llama_qps")
                    backend.metrics.mark("ktpu_llama_tokens_per_s", max_new)
                    backend.latency.observe(time.monotonic() - t0)

        self._srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._srv.daemon_threads = True
        threading.Thread(target=self._srv.serve_forever, daemon=True,
                         name="synthetic-backend").start()
        return self

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self):
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
        self.metrics.stop()


def synthetic_factory(token_delay_s: float = 0.002, slots: int = 8):
    """A ServeFleet backend factory of SyntheticBackends."""

    def make(pod: t.Pod):
        return SyntheticBackend(token_delay_s=token_delay_s,
                                slots=slots).start()

    return make


class ServeFleet:
    """One live backend per Running pod of ``app`` (see module
    docstring).  ``backend_factory(pod)`` returns a started object with
    ``.port`` and ``.stop()``; the default is a SyntheticBackend."""

    def __init__(self, clientset, factory, app: str,
                 backend_factory: Optional[Callable] = None,
                 namespace: str = "default", linger_s: float = 0.5,
                 annotate: bool = True):
        self.cs = clientset
        self.app = app
        self.namespace = namespace
        self.backend_factory = backend_factory or synthetic_factory()
        self.linger_s = linger_s
        self.annotate = annotate
        self._lock = locksan.make_lock("ServeFleet._lock")
        self._by_uid: Dict[str, object] = {}      # pod uid -> backend
        # pod NAME -> (host, port): pod identity, not pod_ip — every
        # in-process pod shares the loopback ip (see EndpointAddress
        # .target_ref, which is what the balancer sync resolves with)
        self._by_name: Dict[str, Addr] = {}
        self._uid_name: Dict[str, str] = {}
        self.started = 0
        self.stopped = 0
        # best-effort paths count their failures instead of hiding them
        self.annotate_errors = 0
        self.teardown_errors = 0
        self._informer = factory.informer("pods")
        self._informer.add_handler(
            on_add=self._pod_event,
            on_update=lambda _o, n: self._pod_event(n),
            on_delete=self._pod_deleted,
        )

    # ----------------------------------------------------------- events

    def _mine(self, pod: t.Pod) -> bool:
        return (pod.metadata.namespace == self.namespace
                and pod.metadata.labels.get("app") == self.app)

    def _pod_event(self, pod: t.Pod):
        if not self._mine(pod) or pod.status.phase != t.POD_RUNNING:
            return
        uid = pod.metadata.uid
        with self._lock:
            if uid in self._by_uid:
                return
            # reserve the slot under the lock; build outside it
            self._by_uid[uid] = None
        backend = self.backend_factory(pod)
        name = pod.metadata.name
        with self._lock:
            self._by_uid[uid] = backend
            self._by_name[name] = ("127.0.0.1", backend.port)
            self._uid_name[uid] = name
            self.started += 1
        if self.annotate:
            self._annotate_pod(pod, backend.port)

    def _annotate_pod(self, pod: t.Pod, port: int):
        """Point the kubelet's pod-scrape at THIS pod's own backend
        metrics (per-pod slot saturation for the HPA)."""
        from ..obs.appmetrics import scrape_annotations

        def patch():
            cur = self.cs.pods.get(pod.metadata.name, self.namespace)
            cur.metadata.annotations = dict(cur.metadata.annotations or {})
            cur.metadata.annotations.update(
                scrape_annotations(port, host="127.0.0.1"))
            self.cs.pods.update(cur)

        try:
            _retry.retry_on_conflict(patch)
        except Exception:  # noqa: BLE001 — scrape annotation is best-effort; serving works without it
            with self._lock:
                self.annotate_errors += 1

    def _pod_deleted(self, pod: t.Pod):
        if not self._mine(pod):
            return
        uid = pod.metadata.uid
        with self._lock:
            backend = self._by_uid.pop(uid, None)
            name = self._uid_name.pop(uid, None)
            if name is not None:
                self._by_name.pop(name, None)
        if backend is None:
            return

        def stop_later():
            # the drain tail: the balancer stopped picking this backend
            # when it left Endpoints; give the last in-flight response
            # its tail before tearing the socket down
            time.sleep(self.linger_s)
            try:
                backend.stop()
            except Exception:  # noqa: BLE001 — teardown best-effort
                with self._lock:
                    self.teardown_errors += 1
            with self._lock:
                self.stopped += 1

        threading.Thread(target=stop_later, name="servefleet-drain",
                         daemon=True).start()

    # ------------------------------------------------------------ lookup

    def resolver(self, key: str, port: int) -> Optional[Addr]:
        """EndpointsBalancerSync resolver: endpoint identity (the
        address's targetRef, i.e. the pod NAME — falling back to the ip
        when targetRef is empty) -> live loopback backend address
        (None while the backend is still starting)."""
        with self._lock:
            return self._by_name.get(key)

    def backends(self) -> List[Addr]:
        with self._lock:
            return list(self._by_name.values())

    def wait_backends(self, want: int, timeout: float = 30.0) -> int:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                n = len(self._by_name)
            if n >= want:
                return n
            time.sleep(0.05)
        with self._lock:
            return len(self._by_name)

    def stop(self):
        with self._lock:
            backends = [b for b in self._by_uid.values() if b is not None]
            self._by_uid.clear()
            self._by_name.clear()
            self._uid_name.clear()
        for b in backends:
            try:
                b.stop()
            except Exception:  # noqa: BLE001 — teardown best-effort
                with self._lock:
                    self.teardown_errors += 1


# ------------------------------------------------------------- rollout


def rolling_update(cs, name: str, namespace: str = "default",
                   mutate: Optional[Callable[[t.Deployment], None]] = None,
                   timeout: float = 60.0, poll_s: float = 0.05) -> dict:
    """Trigger a RollingUpdate of ``name`` (template bump; ``mutate``
    customizes it) and watch it through: returns duration, the minimum
    simultaneously-Ready count observed (the maxUnavailable floor the
    PDB + rolling logic must hold), and the final replica state."""

    def bump():
        dep = cs.deployments.get(name, namespace)
        ann = dict(dep.spec.template.metadata.annotations or {})
        ann["ktpu.io/restartedAt"] = str(time.time())  # ktpulint: ignore[KTPU005] the annotation VALUE just needs to differ per rollout; wall time is the kubectl idiom
        dep.spec.template.metadata.annotations = ann
        if mutate is not None:
            mutate(dep)
        cs.deployments.update(dep)
        return dep

    old_pods, _ = cs.pods.list(namespace=namespace,
                               label_selector=f"app={name}")
    old_names = {p.metadata.name for p in old_pods}
    # conflicts AND transient wire faults both retry: a rollout driven
    # mid-chaos (cluster_life's conducted windows hit client.*) must not
    # abort on one injected drop
    dep = _retry.call_with_retries(
        lambda: _retry.retry_on_conflict(bump), steps=5,
        backoff=_retry.Backoff(base=0.05, cap=0.5),
        reason="servefleet.rollout", classify=_retry.is_transient)
    want = dep.spec.replicas
    t0 = time.monotonic()
    min_ready = want
    done = False
    poll_errors = 0
    while time.monotonic() - t0 < timeout:
        try:
            pods, _ = cs.pods.list(namespace=namespace,
                                   label_selector=f"app={name}")
        except Exception:  # noqa: BLE001 — transient client fault: counted, next poll retries
            poll_errors += 1
            time.sleep(poll_s)  # ktpulint: ignore[KTPU013] fixed rollout poll cadence — the error is counted, the next deadline-bounded poll is the retry; backoff would skew min_ready sampling
            continue
        ready = [
            p for p in pods
            if p.status.phase == t.POD_RUNNING
            and not p.metadata.deletion_timestamp
            and any(c.type == "Ready" and c.status == "True"
                    for c in p.status.conditions)
        ]
        min_ready = min(min_ready, len(ready))
        # done = every Ready pod is a NEW pod and we have a full set —
        # pod identity, not status counters: right after the bump the
        # stale DeploymentStatus still reports updated==ready==want, so
        # counter polling declares victory before the roll even starts
        new_ready = [p for p in ready if p.metadata.name not in old_names]
        if len(new_ready) >= want and len(ready) == len(new_ready):
            try:
                cur = cs.deployments.get(name, namespace)
            except Exception:  # noqa: BLE001 — transient client fault: counted, next poll retries
                poll_errors += 1
                time.sleep(poll_s)  # ktpulint: ignore[KTPU013] fixed rollout poll cadence — counted error, next poll retries
                continue
            st = cur.status
            if (st.updated_replicas >= want and st.ready_replicas >= want
                    and st.replicas == want):
                done = True
                break
        time.sleep(poll_s)  # ktpulint: ignore[KTPU013] fixed sampling cadence — min_ready_observed (the PDB-floor verdict) is sampled at this rate; jitter would thin the samples

    return {
        "completed": done,
        "duration_s": round(time.monotonic() - t0, 3),
        "min_ready_observed": min_ready,
        "replicas": want,
        "poll_errors": poll_errors,
    }
