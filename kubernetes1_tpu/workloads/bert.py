"""BERT-large-class encoder, TPU-first (BASELINE config 4: multi-host
v5p-32 BERT-large IndexedJob with ICI-topology-aware gang scheduling).

Same TPU playbook as llama.py, adapted to the bidirectional encoder shape:

- stacked layers iterated with lax.scan (one compiled layer body, static
  shapes), jax.checkpoint on the body for HBM headroom;
- megatron tensor parallelism on heads/FFN + fsdp on the remaining weight
  dim via per-leaf PartitionSpecs; XLA inserts the ICI collectives;
- bf16 compute / f32 params+adam; non-causal fused attention via
  jax.nn.dot_product_attention;
- learned position embeddings + masked-LM head (tied decode against the
  token embedding), the pretraining objective BERT benchmarks report.

BERT-large = BertConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096,
vocab=30522, max_seq=512).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MASK_TOKEN = 0  # reserved id used by the synthetic MLM batch maker


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab: int = 30522
    d_model: int = 1024
    n_layers: int = 24
    n_heads: int = 16
    d_ff: int = 4096
    max_seq: int = 512
    dtype: Any = jnp.bfloat16
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def bert_large() -> BertConfig:
    return BertConfig()


def tiny(vocab: int = 256, d_model: int = 64, n_layers: int = 2, n_heads: int = 4,
         d_ff: int = 128, max_seq: int = 64) -> BertConfig:
    return BertConfig(vocab=vocab, d_model=d_model, n_layers=n_layers,
                      n_heads=n_heads, d_ff=d_ff, max_seq=max_seq, remat=False)


def param_specs(cfg: BertConfig) -> Dict[str, Any]:
    """Per-leaf PartitionSpecs; the leading stacked-layer axis of layer
    params (for scan) is never sharded."""
    return {
        "embed": P("tp", "fsdp"),              # (vocab, d)
        "pos_embed": P(None, "fsdp"),          # (max_seq, d)
        "layers": {
            "ln1_scale": P(None, None), "ln1_bias": P(None, None),
            "wq": P(None, "fsdp", "tp"), "wk": P(None, "fsdp", "tp"),
            "wv": P(None, "fsdp", "tp"),
            "wo": P(None, "tp", "fsdp"),
            "ln2_scale": P(None, None), "ln2_bias": P(None, None),
            "w_in": P(None, "fsdp", "tp"),     # (L, d, f)
            "w_out": P(None, "tp", "fsdp"),    # (L, f, d)
        },
        "final_ln_scale": P(None), "final_ln_bias": P(None),
        "mlm_dense": P("fsdp", "tp"),          # (d, d) transform head
        "mlm_bias": P(None),                   # (vocab,) decode bias
    }


def init_params(cfg: BertConfig, key: jax.Array) -> Dict[str, Any]:
    k = jax.random.split(key, 9)
    d, L = cfg.d_model, cfg.n_layers

    def w(key, shape, fan_in):
        return jax.random.normal(key, shape, jnp.float32) / np.sqrt(fan_in)

    return {
        "embed": w(k[0], (cfg.vocab, d), d),
        "pos_embed": w(k[1], (cfg.max_seq, d), d),
        "layers": {
            "ln1_scale": jnp.ones((L, d), jnp.float32),
            "ln1_bias": jnp.zeros((L, d), jnp.float32),
            "wq": w(k[2], (L, d, d), d),
            "wk": w(k[3], (L, d, d), d),
            "wv": w(k[4], (L, d, d), d),
            "wo": w(k[5], (L, d, d), d),
            "ln2_scale": jnp.ones((L, d), jnp.float32),
            "ln2_bias": jnp.zeros((L, d), jnp.float32),
            "w_in": w(k[6], (L, d, cfg.d_ff), d),
            "w_out": w(k[7], (L, cfg.d_ff, d), cfg.d_ff),
        },
        "final_ln_scale": jnp.ones((d,), jnp.float32),
        "final_ln_bias": jnp.zeros((d,), jnp.float32),
        "mlm_dense": w(k[8], (d, d), d),
        "mlm_bias": jnp.zeros((cfg.vocab,), jnp.float32),
    }


# ------------------------------------------------------------------ modules


def layernorm(x: jax.Array, scale: jax.Array, bias: jax.Array,
              eps: float = 1e-6) -> jax.Array:
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * scale + bias).astype(x.dtype)


def layer_fn(cfg: BertConfig, x: jax.Array, lp: Dict[str, jax.Array]) -> jax.Array:
    """Post-LN transformer encoder block (BERT ordering)."""
    B, S, d = x.shape
    h, hd = cfg.n_heads, cfg.head_dim
    q = (x @ lp["wq"].astype(cfg.dtype)).reshape(B, S, h, hd)
    kk = (x @ lp["wk"].astype(cfg.dtype)).reshape(B, S, h, hd)
    v = (x @ lp["wv"].astype(cfg.dtype)).reshape(B, S, h, hd)
    # bidirectional: no causal mask — lowers to the fused TPU attention
    attn = jax.nn.dot_product_attention(q, kk, v)
    attn = attn.reshape(B, S, h * hd) @ lp["wo"].astype(cfg.dtype)
    x = layernorm(x + attn, lp["ln1_scale"], lp["ln1_bias"])
    ff = jax.nn.gelu(x @ lp["w_in"].astype(cfg.dtype)) @ lp["w_out"].astype(cfg.dtype)
    return layernorm(x + ff, lp["ln2_scale"], lp["ln2_bias"])


def forward(cfg: BertConfig, params: Dict[str, Any], tokens: jax.Array) -> jax.Array:
    """tokens (B, S) int32 -> MLM logits (B, S, vocab)."""
    B, S = tokens.shape
    x = params["embed"].astype(cfg.dtype)[tokens]
    x = x + params["pos_embed"].astype(cfg.dtype)[:S][None, :, :]

    body = partial(layer_fn, cfg)
    if cfg.remat:
        body = jax.checkpoint(body)

    def scan_step(x, lp):
        return body(x, lp), None

    x, _ = jax.lax.scan(scan_step, x, params["layers"])
    x = layernorm(x, params["final_ln_scale"], params["final_ln_bias"])
    x = jax.nn.gelu(x @ params["mlm_dense"].astype(cfg.dtype))
    # tied decode: reuse the token embedding as the output projection
    logits = x @ params["embed"].astype(cfg.dtype).T + params["mlm_bias"]
    return logits.astype(jnp.float32)


def mlm_loss_fn(cfg: BertConfig, params, tokens: jax.Array,
                mask: jax.Array) -> jax.Array:
    """Masked-LM: predict original tokens at masked positions only.
    `mask` (B, S) is 1 where the input was replaced by MASK_TOKEN."""
    masked_in = jnp.where(mask == 1, MASK_TOKEN, tokens)
    logits = forward(cfg, params, masked_in)
    logp = jax.nn.log_softmax(logits, axis=-1)
    tok_logp = jnp.take_along_axis(logp, tokens[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1)
    return -(tok_logp * mask).sum() / denom


def make_train_state(cfg: BertConfig, mesh: Mesh, lr: float = 1e-4,
                     seed: int = 0) -> Tuple[Dict[str, Any], Any, optax.GradientTransformation]:
    tx = optax.adamw(lr, weight_decay=0.01)
    specs = param_specs(cfg)
    shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                             is_leaf=lambda s: isinstance(s, P))
    init = jax.jit(partial(init_params, cfg), out_shardings=shardings)
    params = init(jax.random.key(seed))
    opt_state = jax.jit(tx.init)(params)
    return params, opt_state, tx


def make_train_step(cfg: BertConfig, mesh: Mesh, tx: optax.GradientTransformation):
    from . import sharding as sh

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens, mask):
        tokens = sh.constrain(tokens, P(("dp", "fsdp"), None))
        mask = sh.constrain(mask, P(("dp", "fsdp"), None))
        loss, grads = jax.value_and_grad(partial(mlm_loss_fn, cfg))(
            params, tokens, mask
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step


def synthetic_batch(cfg: BertConfig, batch: int, seq: int, seed: int = 0,
                    mask_rate: float = 0.15):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(1, cfg.vocab, (batch, seq))  # 0 reserved for MASK
    mask = (rng.random((batch, seq)) < mask_rate).astype(np.int32)
    mask[:, 0] = 1  # at least one masked position per row
    return jnp.asarray(tokens, jnp.int32), jnp.asarray(mask, jnp.int32)


def train_demo(cfg: Optional[BertConfig] = None, mesh: Optional[Mesh] = None,
               steps: int = 3, batch: int = 8, seq: int = 32,
               lr: float = 1e-3) -> float:
    """A few MLM steps on one synthetic batch; returns final loss (used by
    the node e2e as a Job container command and by dryrun_multichip)."""
    from . import sharding as sh

    cfg = cfg or tiny()
    mesh = mesh or sh.auto_mesh()
    with sh.use_mesh(mesh):
        params, opt_state, tx = make_train_state(cfg, mesh, lr=lr)
        step = make_train_step(cfg, mesh, tx)
        tokens, mask = synthetic_batch(cfg, batch, seq)
        loss = None
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, tokens, mask)
        return float(loss)
