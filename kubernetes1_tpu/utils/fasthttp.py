"""Fast HTTP header parsing for the control-plane hot path.

Profiled at 1000-node density: stdlib `http.client.parse_headers` routes
every request and response through email.parser's FeedParser machinery —
~18% of a pod-create roundtrip spent parsing a handful of short ASCII
headers (the reference's apiserver would call this the price of net/http,
which parses headers with a hand-rolled reader for exactly this reason).

Design: the replacement reads the header block EXACTLY like stdlib
(same line/limit checks, same socket consumption), then takes a fast
path ONLY when every line is a strictly-valid single-line CRLF header
(`token ":" value` with a non-empty RFC 7230 token name) — the only
shape this framework's clients and servers ever produce.  Anything else
— folds, defects, empty names, bare-LF endings — is handed VERBATIM to
stdlib's own email.parser call, so malformed input gets stdlib's exact
(quirky) semantics by construction rather than by emulation.  There is
deliberately no hand-written defect handling to drift from stdlib: the
only observable difference between installed and not is speed.

tests/test_fasthttp.py asserts parity empirically against stdlib —
including adversarial defect shapes and identical socket consumption.
"""

from __future__ import annotations

import email.parser
import http.client
import re

_orig_parse_headers = http.client.parse_headers

# RFC 7230 token, non-empty (note: stdlib's own headerRE admits an EMPTY
# name — such lines take the fallback so stdlib decides their meaning)
_NAME_RE = re.compile(r"[\041-\071\073-\176]+")


def _fast_parse_headers(fp, _class=http.client.HTTPMessage):
    # Block read is a faithful copy of stdlib's loop: same limits, same
    # counting (the blank terminator counts toward _MAXHEADERS), same
    # socket consumption — framing can never differ.
    headers = []
    while True:
        line = fp.readline(http.client._MAXLINE + 1)
        if len(line) > http.client._MAXLINE:
            raise http.client.LineTooLong("header line")
        headers.append(line)
        if len(headers) > http.client._MAXHEADERS:
            raise http.client.HTTPException(
                f"got more than {http.client._MAXHEADERS} headers")
        if line in (b"\r\n", b"\n", b""):
            break
    msg = _class()
    for raw in headers[:-1]:
        if raw[-2:] != b"\r\n":
            break  # bare-LF or EOF-truncated line: stdlib decides
        text = raw[:-2].decode("iso-8859-1")
        name, sep, value = text.partition(":")
        if not sep or not _NAME_RE.fullmatch(name):
            break  # fold, defect, or exotic name: stdlib decides
        msg[name] = value.lstrip(" \t")
    else:
        return msg
    # slow path: the exact call stdlib's parse_headers makes, on the
    # exact bytes it would make it on
    hstring = b"".join(headers).decode("iso-8859-1")
    return email.parser.Parser(_class=_class).parsestr(hstring)


def install():
    """Idempotent; installed by Master/ApiClient at construction (not at
    module import).  Process-global by necessity — both
    BaseHTTPRequestHandler and HTTPResponse resolve
    http.client.parse_headers at call time — but behavior-neutral: valid
    headers parse identically by inspection, everything else falls back
    to stdlib's own parser."""
    http.client.parse_headers = _fast_parse_headers


def uninstall():
    http.client.parse_headers = _orig_parse_headers
