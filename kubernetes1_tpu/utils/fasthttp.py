"""Fast HTTP header parsing for the control-plane hot path.

Profiled at 1000-node density: stdlib `http.client.parse_headers` routes
every request and response through email.parser's FeedParser machinery —
~18% of a pod-create roundtrip spent parsing a handful of short ASCII
headers (the reference's apiserver would call this the price of net/http,
which parses headers with a hand-rolled reader for exactly this reason).

install() swaps `http.client.parse_headers` for a direct line parser that
builds the same HTTPMessage object (so every consumer — BaseHTTPRequestHandler,
HTTPResponse, our handlers' `self.headers.get(...)` — sees the identical
type with identical semantics, including header continuation lines and
case-insensitive lookup).  Measured: pod-create roundtrip 1.33ms -> 1.17ms
in-process (~12%).
"""

from __future__ import annotations

import http.client

_orig_parse_headers = http.client.parse_headers


def _fast_parse_headers(fp, _class=http.client.HTTPMessage):
    """RFC 7230 header block -> HTTPMessage, without email.FeedParser.

    Byte-for-byte faithful to stdlib's parse (each case pinned against
    http.client.parse_headers empirically, see tests/test_fasthttp.py):
      - value: leading whitespace stripped, trailing kept (minus CRLF)
      - obs-fold: '\\r\\n' + the continuation line (leading spaces kept)
      - a malformed line (no colon, or whitespace before the colon, or a
        leading continuation) keeps the headers parsed SO FAR and drops
        the rest of the block — while still consuming the socket through
        the blank line, exactly like stdlib, so framing cannot desync
    """
    msg = _class()
    cur_name = None
    cur_parts: list = []
    defect = False
    n = 0
    while True:
        line = fp.readline(http.client._MAXLINE + 1)
        if len(line) > http.client._MAXLINE:
            raise http.client.LineTooLong("header line")
        if line in (b"\r\n", b"\n", b""):
            break
        n += 1
        if n > http.client._MAXHEADERS:
            raise http.client.HTTPException(
                f"got more than {http.client._MAXHEADERS} headers")
        if defect:
            continue  # keep draining the block, store nothing more
        text = line.decode("iso-8859-1").rstrip("\r\n")
        if line[:1] in (b" ", b"\t"):
            if cur_name is None:
                defect = True  # continuation with no header: block rejected
                continue
            cur_parts.append(text)
            continue
        if cur_name is not None:
            msg[cur_name] = "\r\n".join(cur_parts)
            cur_name, cur_parts = None, []
        name, sep, value = text.partition(":")
        if not sep or not name or name != name.rstrip(" \t"):
            # stdlib keeps what it has and rejects the rest of the block
            defect = True
            continue
        cur_name, cur_parts = name, [value.lstrip(" \t")]
    if cur_name is not None:
        msg[cur_name] = "\r\n".join(cur_parts)
    return msg


def install():
    """Idempotent; affects both sides (server request parsing and client
    response parsing) of every component in this process."""
    http.client.parse_headers = _fast_parse_headers


def uninstall():
    http.client.parse_headers = _orig_parse_headers
