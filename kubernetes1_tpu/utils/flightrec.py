"""Flight recorder: bounded per-component rings of structured events.

When a chaos seed fails, the verdict JSON says WHAT broke (an invariant)
but nothing in the system can say what each component WAS DOING — the
counters the components keep (lease steals, sheds, gang attempts, WAL
repairs) are totals, not timelines.  The flight recorder is the timeline:
every existing counter site additionally drops one structured event into
a bounded in-process ring, and the ring is

- dumped at ``/debug/flightrecorder`` on every component HTTP surface
  (utils/metrics.MetricsServer, the apiserver, the kubelet server) and
  unioned fleet-wide by the ObsCollector;
- written into the per-seed chaos artifact whenever a verdict fails, so
  a red seed ships its own black box.

Event kinds are a CLOSED ENUM (the module constants below): call sites
pass ``flightrec.note(component, flightrec.LEASE_STEAL, shard=3)`` —
never an ad-hoc string.  ktpulint KTPU011 enforces this statically (a
string literal in the kind position is a finding), and ``note`` enforces
it at runtime, so grepping one constant finds every producer AND every
consumer of that event kind.

Rings are process-global, keyed by component name: in a LocalCluster one
process hosts every component and one dump shows the whole cluster's
interleaved story; in a multi-process deployment each process dumps its
own components and the collector merges by component name.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

# ----------------------------------------------------------- event kinds
#
# The declared enum (KTPU011): one constant per recorded event kind.
# Adding a kind = adding a constant here; call sites must reference it.

LEASE_STEAL = "lease_steal"            # LeaseSet took a peer's expired shard
LEASE_SHED = "lease_shed"              # LeaseSet handed a shard to its winner
STANDBY_PROMOTION = "standby_promotion"  # standby store promoted to primary
SHED_429 = "shed_429"                  # apiserver refused a mutating request
GANG_ATTEMPT = "gang_attempt"          # whole-gang recreate attempt bumped
GANG_TEARDOWN = "gang_teardown"        # gang member force-finalized
DEVICE_CLAIM_CONFLICT = "device_claim_conflict"  # optimistic bind lost a chip
WAL_REPAIR = "wal_repair"              # torn-tail truncation / write rollback
INFORMER_RELIST = "informer_relist"    # informer fell back to a full LIST
WATCH_RECONNECT = "watch_reconnect"    # informer re-dialed mid-stream
DELETE_BATCH = "delete_batch"          # pods/delete:batch group deletion
HPA_RESCALE = "hpa_rescale"            # autoscaler changed a target's replicas
INVARIANT_VIOLATION = "invariant_violation"  # utils/invariants probe tripped
SLO_BREACH = "slo_breach"              # scorecard burn-rate alert fired
SCORECARD_PHASE = "scorecard_phase"    # cluster-life mixer phase transition
DISPATCHER_STALL = "dispatcher_stall"  # loopsan: dispatcher lag over threshold

KINDS = frozenset({
    LEASE_STEAL, LEASE_SHED, STANDBY_PROMOTION, SHED_429, GANG_ATTEMPT,
    GANG_TEARDOWN, DEVICE_CLAIM_CONFLICT, WAL_REPAIR, INFORMER_RELIST,
    WATCH_RECONNECT, DELETE_BATCH, HPA_RESCALE, INVARIANT_VIOLATION,
    SLO_BREACH, SCORECARD_PHASE, DISPATCHER_STALL,
})

# Per-component ring bound: forensics wants the recent tail.  512 events
# x ~10 components x ~200 bytes is ~1MB worst case — flat, never grows.
RING_CAPACITY = 512

_rings: Dict[str, deque] = {}
_lock = threading.Lock()  # ktpulint: ignore[KTPU007] hot leaf lock around one deque append per noted event


def note(component: str, kind: str, **fields) -> None:
    """Record one event on ``component``'s ring.  ``kind`` must be one of
    the declared constants (programmer error otherwise — the enum is the
    contract the dump consumers grep against)."""
    if kind not in KINDS:
        raise ValueError(f"flightrec kind {kind!r} is not in the declared "
                         f"enum (utils/flightrec.py KINDS)")
    ev = {
        "t_mono": round(time.monotonic(), 6),
        # wall time is for the human reading a dump next to logs; every
        # ordering/lag computation uses the monotonic stamp
        "wall": round(time.time(), 3),  # ktpulint: ignore[KTPU005] user-visible timestamp in the dump, not a deadline
        "kind": kind,
    }
    for k, v in fields.items():
        ev[k] = v if isinstance(v, (int, float, bool, type(None))) else str(v)
    with _lock:
        ring = _rings.get(component)
        if ring is None:
            ring = _rings[component] = deque(maxlen=RING_CAPACITY)
        ring.append(ev)


def dump(component: str = "") -> dict:
    """{"components": {name: [events oldest->newest]}} — one component's
    ring, or every ring."""
    with _lock:
        if component:
            ring = _rings.get(component)
            comps = {component: list(ring)} if ring is not None else {}
        else:
            comps = {name: list(ring) for name, ring in _rings.items()}
    return {"components": comps}


def to_json(component: str = "") -> bytes:
    return json.dumps(dump(component), separators=(",", ":")).encode()


def components() -> List[str]:
    with _lock:
        return sorted(_rings)


def event_count(component: str = "") -> int:
    with _lock:
        if component:
            ring = _rings.get(component)
            return len(ring) if ring is not None else 0
        return sum(len(r) for r in _rings.values())


def last_event(component: str) -> Optional[dict]:
    with _lock:
        ring = _rings.get(component)
        return ring[-1] if ring else None


def reset() -> None:
    """Clear every ring (chaos seeds and tests: each run's dump must be
    ITS timeline, not the process's history)."""
    with _lock:
        _rings.clear()
