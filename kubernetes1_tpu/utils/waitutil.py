"""Polling/retry helpers (ref: apimachinery util/wait/wait.go)."""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


def poll_until(
    condition: Callable[[], bool],
    interval: float = 0.05,
    timeout: float = 10.0,
    desc: str = "condition",
) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if condition():
            return True
        time.sleep(interval)
    return False


def must_poll_until(condition, interval=0.05, timeout=10.0, desc="condition"):
    if not poll_until(condition, interval, timeout, desc):
        raise TimeoutError(f"timed out waiting for {desc}")


def until(fn: Callable[[], None], period: float, stop: threading.Event):
    """Run fn every `period` seconds until stop is set (wait.Until)."""
    while not stop.is_set():
        try:
            fn()
        except Exception:  # noqa: BLE001 — control loops must not die
            import traceback

            traceback.print_exc()
        stop.wait(period)


def run_until(fn: Callable[[], None], period: float, stop: threading.Event, name: str = "") -> threading.Thread:
    t = threading.Thread(target=until, args=(fn, period, stop), daemon=True, name=name)
    t.start()
    return t
