"""Feature gates: one `--feature-gates` map shared by every binary
(ref: pkg/features/kube_features.go — a single alpha/beta switchboard;
e.g. DevicePlugins :76, Accelerators :70, TaintBasedEvictions).

Gates that are actually consulted in this codebase:

- DevicePlugins (default on): kubelet runs the device manager / plugin
  watcher; off = CPU-only kubelet.
- ExtendedResourceToleration (default on): admission auto-tolerates taints
  keyed by requested extended resources.
- DefaultTolerationSeconds (default on): admission injects the 300s
  not-ready/unreachable tolerations.
- TaintBasedEvictions (default off, alpha in the reference): the node
  lifecycle controller taints NotReady nodes with
  node.kubernetes.io/not-ready:NoSchedule instead of relying purely on the
  readiness predicate.
- DynamicKubeletConfig (default on): kubelet live-reloads its
  KubeletConfiguration from a ConfigMap with last-known-good rollback.
- GangScheduling (default on): scheduler honors scheduling_gang
  all-or-nothing placement.
"""

from __future__ import annotations

from typing import Dict, Optional
from . import locksan

DEFAULT_GATES: Dict[str, bool] = {
    "DevicePlugins": True,
    "ExtendedResourceToleration": True,
    "DefaultTolerationSeconds": True,
    "TaintBasedEvictions": False,
    "DynamicKubeletConfig": True,
    "GangScheduling": True,
}


class FeatureGates:
    def __init__(self, spec: str = "", defaults: Optional[Dict[str, bool]] = None):
        self._lock = locksan.make_lock("FeatureGates._lock")
        self._gates = dict(defaults if defaults is not None else DEFAULT_GATES)
        if spec:
            self.apply(spec)

    def apply(self, spec: str):
        """Parse 'Gate1=true,Gate2=false' (the --feature-gates flag form).
        Unknown gates are an error — a typo silently doing nothing is how
        clusters run for months with the wrong config."""
        for pair in spec.split(","):
            pair = pair.strip()
            if not pair:
                continue
            name, sep, val = pair.partition("=")
            if not sep or val.lower() not in ("true", "false"):
                raise ValueError(f"feature gate {pair!r}: want Name=true|false")
            with self._lock:
                if name not in self._gates:
                    raise ValueError(
                        f"unknown feature gate {name!r} "
                        f"(known: {', '.join(sorted(self._gates))})"
                    )
                self._gates[name] = val.lower() == "true"

    def enabled(self, name: str) -> bool:
        with self._lock:
            if name not in self._gates:
                raise KeyError(f"unknown feature gate {name!r}")
            return self._gates[name]

    def snapshot(self) -> Dict[str, bool]:
        with self._lock:
            return dict(self._gates)


# the process-wide instance every component consults; binaries call
# gates.apply(args.feature_gates) at startup
gates = FeatureGates()
