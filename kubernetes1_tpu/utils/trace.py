"""Operation tracing: named steps, logged only when the whole op is slow.

Ref: staging/src/k8s.io/apiserver/pkg/util/trace/trace.go:39 — the
reference creates a Trace at the top of a hot operation (scheduler's
Schedule at generic_scheduler.go:110-112, apiserver handlers), calls
trace.Step(...) at milestones, and defers LogIfLong(threshold): nothing is
emitted in the fast path, while a slow op logs every step with per-step
latency, making tail-latency forensics free.

Python shape: context manager; steps are (elapsed, msg) pairs; on exit the
trace logs through the provided sink iff total >= threshold.  A module-wide
`trace_sink` hook lets tests capture output and components route to their
own loggers.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, List, Optional, Tuple

# process-wide default sink (tests may swap it)
trace_sink: Callable[[str], None] = lambda line: print(line, file=sys.stderr)


class Trace:
    """utiltrace.Trace analog.

    with Trace("scheduling", threshold=0.1, pod="ns/name") as tr:
        ...
        tr.step("computed predicates")
        ...
        tr.step("prioritized")
    # on exit: logs all steps iff the op took >= threshold seconds
    """

    def __init__(self, name: str, threshold: Optional[float] = None,
                 sink: Optional[Callable[[str], None]] = None, **fields):
        self.name = name
        self.threshold = threshold
        self.fields = fields
        self._sink = sink
        self._t0 = time.perf_counter()
        self._steps: List[Tuple[float, str]] = []
        # attach to the thread's active span (utils/spans): the slow-op log
        # line carries the trace id, and steps land on the span too, so
        # /debug/traces and the step log cross-reference each other
        from . import spans as _spans

        self._span = _spans.current_span()
        if self._span is not None:
            self.fields.setdefault("trace", self._span.trace_id)

    # -- utiltrace API ------------------------------------------------------

    def step(self, msg: str):
        self._steps.append((time.perf_counter() - self._t0, msg))
        if self._span is not None:
            self._span.log(f"{self.name}: {msg}")

    @property
    def total_seconds(self) -> float:
        return time.perf_counter() - self._t0

    def log_if_long(self, threshold: Optional[float] = None):
        th = threshold if threshold is not None else self.threshold
        total = self.total_seconds
        if th is None or total < th:
            return
        self._emit(total, th)

    def _emit(self, total: float, th: Optional[float]):
        sink = self._sink or trace_sink
        tag = " ".join(f"{k}={v}" for k, v in self.fields.items())
        th_part = (f"threshold {th * 1000:.0f}ms" if th is not None
                   else "exception exit")
        lines = [f'Trace "{self.name}"{(" " + tag) if tag else ""} '
                 f"(total {total * 1000:.1f}ms, {th_part}):"]
        prev = 0.0
        for at, msg in self._steps:
            lines.append(f"  [{at * 1000:8.1f}ms] (+{(at - prev) * 1000:.1f}ms) {msg}")
            prev = at
        lines.append(f"  [{total * 1000:8.1f}ms] end")
        sink("\n".join(lines))

    # -- context manager ----------------------------------------------------

    def __enter__(self) -> "Trace":
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            # an op that died mid-flight is ALWAYS worth its breakdown —
            # record what blew up and log regardless of threshold (the
            # exception's traceback says where; the trace says how long
            # each step before it took)
            self.step(f"error={exc_type.__name__}")
            # th=None labels the line "exception exit" — a threshold label
            # here would read as a threshold the op never actually crossed
            self._emit(self.total_seconds, None)
        else:
            self.log_if_long()
        return False
