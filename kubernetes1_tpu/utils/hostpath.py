"""Shared hostPath normalization for the security gates.

Both the PodSecurityPolicy admission check (allowedHostPaths) and the
kubelet's unprivileged-/dev gate must judge a path by what it RESOLVES to,
not how it is spelled — '/tmp/../dev/accel0' and '//dev/accel0' are /dev
paths.  One implementation, because two drifting copies of a security
normalizer is how one side quietly stops catching what the other does.
"""

from __future__ import annotations

import posixpath


def normalize_abs(path: str) -> str:
    """Absolute, '..'-free, single-leading-slash form of `path`.  The
    lstrip matters: POSIX normpath PRESERVES a double leading slash."""
    return posixpath.normpath("/" + (path or "").lstrip("/"))


def is_under(path: str, prefix: str) -> bool:
    """True when normalized `path` equals or lives under normalized
    `prefix` (path-segment aware: /var/database is NOT under /var/data)."""
    p = normalize_abs(path)
    pre = normalize_abs(prefix)
    return p == pre or p.startswith(pre.rstrip("/") + "/")
