"""Box-contention stamp for benchmark outputs.

Round-3 lesson: the same benchmark swung 22x (29ms vs 0.65s steady-state
p99) purely from concurrent load on this one-CPU box, and the JSON recorded
nothing about it — making round-over-round comparisons noise-prone.  Every
bench JSON now carries this stamp; judges and scripts compare only
like-with-like and treat contaminated=true runs as unusable.
"""

from __future__ import annotations

import os
import time


def _calibration_spin_ms(iters: int = 2_000_000) -> float:
    """Wall time of a fixed arithmetic loop — the most direct measure of
    how much CPU this process is actually getting.  Best-of-3 so a single
    descheduling blip doesn't poison the stamp itself."""
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        x = 0
        for i in range(iters):
            x += i & 7
        best = min(best, time.perf_counter() - t0)
    return best * 1000.0


def contention_stamp() -> dict:
    cpus = os.cpu_count() or 1
    try:
        with open("/proc/loadavg") as f:
            load1 = float(f.read().split()[0])
    except (OSError, ValueError):
        load1 = -1.0
    spin_ms = round(_calibration_spin_ms(), 1)
    return {
        "load1": load1,
        "cpus": cpus,
        "spin_ms": spin_ms,
        # More than ~1.25 busy cores per core before we start = someone
        # else is eating the box.  (Ambient load1 on the bench VM idles
        # around 0.3-1.0 with full CPU access per the spin — genuinely
        # dirty runs showed load1 3.4+ with a 2x spin.)  spin_ms is the
        # direct signal: compare it across runs on the same host.
        "contaminated": bool(load1 >= 0 and load1 > 1.25 * cpus),
    }
