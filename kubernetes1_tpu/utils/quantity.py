"""Resource quantity parsing (ref: apimachinery/pkg/api/resource/quantity.go).

Supports the forms the scheduler and kubelet actually compare: plain ints,
milli-units ("500m"), and binary/decimal suffixes ("1Gi", "2G").  Internally
everything is converted to milli-units for cpu-like resources and bytes for
memory-like ones; comparison happens on canonical ints.
"""

from __future__ import annotations

_SUFFIX = {
    "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50,
}


def parse_quantity(q) -> float:
    """Parse to a float in base units (cpu cores, bytes, device count)."""
    if q is None:
        return 0.0
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q).strip()
    if not s:
        return 0.0
    if s.endswith("m") and s[:-1].replace(".", "", 1).lstrip("-").isdigit():
        return float(s[:-1]) / 1000.0
    for suf in ("Ki", "Mi", "Gi", "Ti", "Pi", "k", "M", "G", "T", "P"):
        if s.endswith(suf):
            return float(s[: -len(suf)]) * _SUFFIX[suf]
    return float(s)


def parse_milli(q) -> int:
    """Parse to integer milli-units (the scheduler's cpu accounting unit)."""
    return int(round(parse_quantity(q) * 1000))
