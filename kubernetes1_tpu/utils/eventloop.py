"""Shared selectors-based event loop: ONE dispatcher thread for every
long-lived connection and every scrape timer in the process.

The thread-per-connection model was the control plane's scale wall: a
parked ``ThreadingHTTPServer`` thread per watch stream and a daemon
thread per scrape target cost thousands of stacks at hollow-watcher
density (RSS +123MB at just 1000 watchers) plus GIL context-switch tax
on every fan-out.  This module is the replacement substrate:

- ``EventLoop`` — a single daemon thread multiplexing I/O readiness
  (``selectors.DefaultSelector``), cross-thread callbacks
  (``call_soon`` via a self-pipe), and a timer heap (``call_later`` —
  watch heartbeats, scrape intervals, watch deadlines).  Timer fire lag
  lands in the ``ktpu_eventloop_lag_seconds`` histogram: a dispatcher
  that falls behind its timers is saturated, and the histogram is the
  proof, on /metrics, before the symptom (late heartbeats, stale
  scrapes).
- ``shared_loop()`` — the process-wide dispatcher every serving plane
  registers with (apiserver watch connections, obs-collector targets,
  kubelet pod-scrape targets).  One loop per process is the point: the
  10k-connection budget is N file descriptors + N small state machines
  on one stack.
- ``shared_pool()`` — a small BOUNDED worker pool for blocking work the
  dispatcher must never run inline (scrape HTTP fetches through
  urllib).  The pool is the sanctioned remainder of the thread model:
  its size bounds concurrent blocking I/O, and a wedged target wedges
  one slot, never the dispatcher.
- ``wait_readable()`` — the one-shot readiness helper bespoke
  ``select.select`` poll loops migrate onto (kubelet log-follow).

Standing invariant (ROADMAP): new long-lived connections register with
the dispatcher — never a dedicated thread.  ktpulint KTPU015 enforces it
mechanically in the serving/scrape modules.

Threading contract: ``register``/``modify``/``unregister`` and timer
callbacks run ON the loop thread.  Cross-thread producers use
``call_soon`` (lock-free deque append + non-blocking self-pipe write —
safe to call under an owner's commit lock, which is exactly where the
Watcher notify hook fires from).
"""

from __future__ import annotations

import heapq
import itertools
import os
import queue
import selectors
import threading
import time
from collections import deque
from typing import Callable, List, Optional

from . import loopsan
from .logutil import RateLimitedReporter
from .metrics import Histogram

# Timer-lag buckets: a healthy dispatcher fires timers within single-digit
# milliseconds; 100ms+ of lag means some callback blocked the loop.
_LAG_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                2.5, 5.0)

# One histogram for the process (there is one shared dispatcher): rendered
# on the apiserver's /metrics next to the connection-count gauge.
loop_lag_seconds = Histogram(
    "ktpu_eventloop_lag_seconds",
    "dispatcher timer fire lag (scheduled -> ran)",
    buckets=_LAG_BUCKETS)

# Blocking-I/O slots for the scrape planes.  Sized for concurrency of
# SLOW scrapes (each bounded by the caller's fetch timeout + retries);
# healthy scrapes are millisecond-scale and never queue.
DEFAULT_POOL_SIZE = 8


class Timer:
    """A scheduled callback handle.  ``cancel()`` is safe from any
    thread: the loop skips cancelled entries at pop time, so cancel
    never needs to find the entry inside the heap."""

    __slots__ = ("when", "seq", "fn", "cancelled")

    def __init__(self, when: float, seq: int, fn: Callable[[], None]):
        self.when = when
        self.seq = seq
        self.fn = fn
        self.cancelled = False

    def cancel(self):
        self.cancelled = True

    def __lt__(self, other: "Timer") -> bool:
        return (self.when, self.seq) < (other.when, other.seq)


class EventLoop:
    """See module docstring.  start() spawns the dispatcher thread."""

    def __init__(self, name: str = "ktpu-dispatcher"):
        self.name = name
        self._sel = selectors.DefaultSelector()
        # lock-free cross-thread queue: deque.append is atomic, and the
        # self-pipe write is non-blocking — call_soon never blocks a
        # producer, even one holding its owner's commit lock
        self._soon: "deque[Callable[[], None]]" = deque()
        self._timers: List[Timer] = []  # heap; loop thread only
        self._seq = itertools.count()   # count().__next__ is atomic
        self._stopping = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._err = RateLimitedReporter(f"eventloop/{name}", window=30.0)
        # registered long-lived connections (the ktpu_eventloop_connections
        # gauge source); adjusted on the loop thread, read anywhere (int
        # reads are atomic)
        self.connections = 0
        r, w = os.pipe()
        os.set_blocking(r, False)
        os.set_blocking(w, False)
        self._wake_r, self._wake_w = r, w
        self._sel.register(r, selectors.EVENT_READ, self._drain_wakeup)

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "EventLoop":
        if self._thread is None:
            # the dispatcher thread IS the rule: every long-lived
            # connection multiplexes onto this one stack
            self._thread = threading.Thread(  # ktpulint: ignore[KTPU015] the singleton dispatcher thread connections register WITH — not a per-connection thread
                target=self._run, daemon=True, name=self.name)
            self._thread.start()
        return self

    def is_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stop(self, join_timeout: float = 3.0):
        self._stopping.set()
        self._wakeup()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)

    def in_loop(self) -> bool:
        return threading.current_thread() is self._thread

    # --------------------------------------------------------- scheduling

    def call_soon(self, fn: Callable[[], None]):
        """Run ``fn`` on the loop thread ASAP.  Thread-safe and
        non-blocking (the Watcher notify hook calls this under the
        cacher's commit lock)."""
        if loopsan.active():
            fn = loopsan.wrap_callback(fn, "call_soon")
        self._soon.append(fn)
        self._wakeup()

    def call_later(self, delay: float, fn: Callable[[], None]) -> Timer:
        """Run ``fn`` on the loop thread after ``delay`` seconds.
        Thread-safe: off-loop callers route the heap push through
        call_soon; the returned handle's cancel() works either way."""
        if loopsan.active():
            fn = loopsan.wrap_callback(fn, "call_later")
        tm = Timer(time.monotonic() + max(0.0, delay), next(self._seq), fn)  # ktpulint: ignore[KTPU004,KTPU015] this module's own heap-entry Timer handle (class above), not threading.Timer
        if self.in_loop():
            heapq.heappush(self._timers, tm)
        else:
            self.call_soon(lambda: heapq.heappush(self._timers, tm))
        return tm

    # ------------------------------------------------- I/O registration
    # Loop-thread only (route through call_soon from elsewhere): the
    # selector's internal state is not shared-access safe.

    def register(self, fileobj, events: int, callback):
        if loopsan.active():
            callback = loopsan.wrap_io_callback(callback, "register")
        self._sel.register(fileobj, events, callback)

    def modify(self, fileobj, events: int, callback):
        if loopsan.active():
            callback = loopsan.wrap_io_callback(callback, "modify")
        self._sel.modify(fileobj, events, callback)

    def unregister(self, fileobj):
        try:
            self._sel.unregister(fileobj)
        except KeyError:
            pass  # already unregistered (teardown paths can race close)

    def add_connection(self):
        self.connections += 1

    def remove_connection(self):
        self.connections -= 1

    # -------------------------------------------------------------- loop

    def _wakeup(self):
        try:
            os.write(self._wake_w, b"x")
        except BlockingIOError:
            pass  # pipe already holds a pending wakeup — that's enough
        except OSError:
            pass  # loop shut down under us — nothing left to wake

    def _drain_wakeup(self, mask: int):
        try:
            while os.read(self._wake_r, 4096):
                pass
        except BlockingIOError:
            pass  # drained

    def _guard(self, fn: Callable[[], None]):
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — one bad callback must not kill every connection on the dispatcher
            self._err.report(f"callback {getattr(fn, '__name__', fn)!r}: {e}")

    def _run(self):
        # unconditional (one set-add per loop lifetime): loopsan armed
        # mid-run must still know which thread is the dispatcher
        loopsan.mark_dispatcher()
        while not self._stopping.is_set():
            timeout = None
            if self._timers:
                timeout = max(0.0, self._timers[0].when - time.monotonic())
            if self._soon:
                timeout = 0.0
            try:
                events = self._sel.select(timeout)
            except OSError:
                continue  # fd closed mid-select (a conn torn down racily)
            for key, mask in events:
                self._guard(lambda cb=key.data, m=mask: cb(m))
            while self._soon:
                try:
                    fn = self._soon.popleft()
                except IndexError:
                    break
                self._guard(fn)
            now = time.monotonic()
            while self._timers and self._timers[0].when <= now:
                tm = heapq.heappop(self._timers)
                if tm.cancelled:
                    continue
                lag = now - tm.when
                loop_lag_seconds.observe(lag)
                if loopsan.active():
                    loopsan.note_lag(lag)
                self._guard(tm.fn)
        loopsan.unmark_dispatcher()
        try:
            self._sel.close()
            os.close(self._wake_r)
            os.close(self._wake_w)
        except OSError:
            pass  # already closed


class WorkerPool:
    """Bounded daemon workers for blocking I/O submitted off the
    dispatcher (scrape fetches).  Deliberately simple: an unbounded
    submit queue whose depth is naturally bounded by the callers (each
    scrape target re-arms only after its previous fetch completes, so at
    most one job per target is ever queued)."""

    def __init__(self, size: int = DEFAULT_POOL_SIZE,
                 name: str = "ktpu-pool"):
        self._q: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue()
        self._err = RateLimitedReporter(f"workerpool/{name}", window=30.0)
        self._threads = [
            threading.Thread(  # ktpulint: ignore[KTPU015] the bounded worker pool the refactor sanctions — size-limited blocking-I/O slots, not per-connection threads
                target=self._work, daemon=True, name=f"{name}-{i}")
            for i in range(size)
        ]
        for th in self._threads:
            th.start()

    def submit(self, fn: Callable[[], None]):
        self._q.put(fn)

    def _work(self):
        while True:
            fn = self._q.get()
            if fn is None:
                return
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — a failing scrape job must not kill a shared pool slot
                self._err.report(f"job {getattr(fn, '__name__', fn)!r}: {e}")


_shared_lock = threading.Lock()  # ktpulint: ignore[KTPU007] module-init leaf lock guarding two singletons; locksan's factory would itself need this module
_shared_loop: Optional[EventLoop] = None
_shared_pool: Optional[WorkerPool] = None


def shared_loop() -> EventLoop:
    """The process-wide dispatcher (started on first use).  Daemon
    thread: it lives for the process — components register/unregister
    their connections and timers, they do not own the loop."""
    global _shared_loop
    with _shared_lock:
        if _shared_loop is None or not _shared_loop.is_alive():
            _shared_loop = EventLoop().start()
        return _shared_loop


def shared_pool() -> WorkerPool:
    """The process-wide blocking-I/O pool (started on first use)."""
    global _shared_pool
    with _shared_lock:
        if _shared_pool is None:
            _shared_pool = WorkerPool()
        return _shared_pool


def connection_count() -> int:
    """Registered long-lived connections on the shared dispatcher (the
    ktpu_eventloop_connections gauge; 0 when the loop never started)."""
    loop = _shared_loop
    return loop.connections if loop is not None else 0


def wait_readable(sock, timeout: float) -> bool:
    """One-shot readability poll — the shared selectors helper bespoke
    ``select.select([sock], [], [], t)`` loops migrate onto.  A fresh
    selector per call keeps the helper stateless; callers poll at
    sub-Hz rates (log-follow hangup detection), not per-byte."""
    with selectors.DefaultSelector() as sel:
        sel.register(sock, selectors.EVENT_READ)
        return bool(sel.select(timeout))
