"""Minimal 5-field cron schedule parser + next-fire computation.

The reference's CronJob controller delegates to robfig/cron
(pkg/controller/cronjob/utils.go getRecentUnmetScheduleTimes); this is a
self-contained equivalent supporting the standard syntax subset the
controller needs: "*", numbers, ranges (a-b), steps (*/n, a-b/n) and
comma lists, over minute hour day-of-month month day-of-week.
"""

from __future__ import annotations

import datetime
from typing import List, Set, Tuple

_FIELDS = [
    ("minute", 0, 59),
    ("hour", 0, 23),
    ("dom", 1, 31),
    ("month", 1, 12),
    ("dow", 0, 6),  # 0 = Sunday; 7 accepted as Sunday too
]


def _parse_field(expr: str, lo: int, hi: int, name: str) -> Set[int]:
    # dow accepts 7 as Sunday, including as a range endpoint ("5-7" = Fri-Sun)
    if name == "dow":
        hi = 7
    out: Set[int] = set()
    for part in expr.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            if not step_s.isdigit() or int(step_s) < 1:
                raise ValueError(f"bad step in {name} field")
            step = int(step_s)
        if part == "*":
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            if not (a.isdigit() and b.isdigit()):
                raise ValueError(f"bad range in {name} field")
            start, end = int(a), int(b)
        elif part.isdigit():
            start = end = int(part)
        else:
            raise ValueError(f"bad value {part!r} in {name} field")
        if start < lo or end > hi or start > end:
            raise ValueError(f"{name} value out of range {lo}-{hi}")
        values = range(start, end + 1, step)
        out.update(v % 7 for v in values) if name == "dow" else out.update(values)
    return out


_MONTH_MAX_DAY = {2: 29, 4: 30, 6: 30, 9: 30, 11: 30}


def parse_cron(schedule: str) -> List[Set[int]]:
    parts = schedule.split()
    if len(parts) != 5:
        raise ValueError("schedule must have 5 fields (min hour dom month dow)")
    fields = [
        _parse_field(p, lo, hi, name)
        for p, (name, lo, hi) in zip(parts, _FIELDS)
    ]
    minute, hour, dom, month, dow = fields
    # reject schedules that can never fire (e.g. "0 0 31 2 *"): next_fire
    # would otherwise scan 4 years of minutes before erroring on every sync
    dom_star = dom == set(range(1, 32))
    dow_star = dow == set(range(0, 7))
    if not dom_star and dow_star:
        if all(min(dom) > _MONTH_MAX_DAY.get(m, 31) for m in month):
            raise ValueError("schedule never fires (day-of-month vs month)")
    return fields


def _matches(fields: List[Set[int]], dt: datetime.datetime) -> bool:
    minute, hour, dom, month, dow = fields
    # cron semantics: if both dom and dow are restricted, either may match
    dom_star = dom == set(range(1, 32))
    dow_star = dow == set(range(0, 7))
    day_ok = (
        (dt.day in dom) or (dt.isoweekday() % 7 in dow)
        if not dom_star and not dow_star
        else dt.day in dom and dt.isoweekday() % 7 in dow
    )
    return (
        dt.minute in minute and dt.hour in hour and dt.month in month and day_ok
    )


def next_fire(schedule: str, after: datetime.datetime) -> datetime.datetime:
    """First matching minute strictly after `after` (minute granularity)."""
    fields = parse_cron(schedule)
    dt = after.replace(second=0, microsecond=0) + datetime.timedelta(minutes=1)
    # bounded scan: 4 years covers any 5-field schedule incl. Feb 29
    for _ in range(4 * 366 * 24 * 60):
        if _matches(fields, dt):
            return dt
        dt += datetime.timedelta(minutes=1)
    raise ValueError(f"schedule {schedule!r} never fires")


def unmet_times(
    schedule: str,
    earliest: datetime.datetime,
    now: datetime.datetime,
    limit: int = 100,
) -> Tuple[List[datetime.datetime], bool]:
    """Scheduled times in (earliest, now]; (times, truncated). Mirrors
    getRecentUnmetScheduleTimes' too-many-missed-starts guard."""
    times: List[datetime.datetime] = []
    cur = earliest
    while True:
        cur = next_fire(schedule, cur)
        if cur > now:
            return times, False
        times.append(cur)
        if len(times) > limit:
            return times, True
