"""faultline: seeded, deterministic wire-level fault injection.

The reference survives real clusters because every layer assumes the layer
below it fails *partially* — etcd clients retry with backoff, apiservers
shed load, watches resume after any disconnect.  This module is the lever
that makes those partial failures REPRODUCIBLE: every socket/file boundary
in the framework calls a named *site* hook, and an activated injector
decides — from a seeded RNG, so the same seed replays the same schedule of
decisions per site — whether that I/O proceeds, stalls, dies cleanly, or
dies MID-FRAME.

Activation (either):
  - environment: ``KTPU_FAULTS="<seed>:<spec>"`` (parsed at import, so
    spawned server subprocesses inherit faults with zero plumbing);
  - programmatic: ``faultline.activate(seed, spec)`` / ``deactivate()``
    (what the chaos suite uses in-process).

Spec grammar (documented in README "Fault injection & recovery")::

    spec  = rule[;rule...]
    rule  = <site>=<fault>[|<fault>...]
    fault = <action>[:<param>][@<prob>]

    actions:
      drop              abort the op before any bytes move (FaultInjected,
                        a ConnectionError — transport-error handlers fire)
      delay:<dur>       sleep <dur> (``20ms``, ``0.5s``, or bare seconds),
                        then proceed — a stalled link / slow disk
      error             fail the op as if the kernel said EIO (same
                        exception class as drop; counted separately)
      sever[:frac]      byte-stream ops deliver only a PREFIX of the
                        payload (frac of it, default seeded-random), then
                        fail — the mid-frame cut that leaves torn JSON on
                        the peer; non-stream ops treat it as drop
      truncate[:frac]   same cut, intended for at-rest writes (the WAL
                        site): the prefix IS persisted, the writer errors,
                        and recovery must repair the torn tail

    prob: @0.1 fires on ~10% of decisions at that site (seeded RNG);
    default 1.0.  Multiple faults on one site evaluate in spec order; the
    first that fires wins.

Wired sites:
  client.dial / client.request / client.watch   (client/rest.py — every
                                                 apiserver client, incl. the
                                                 kubelet's informer, status
                                                 PUTs, heartbeats, and the
                                                 scheduler's shard-lease
                                                 renew/steal traffic)
  client.bindstream                             (client/bindstream.py — the
                                                 persistent zero-copy bind
                                                 leg: dial, round start, and
                                                 outbound frame bytes via the
                                                 BinFramer filter; sever/
                                                 truncate tear the stream and
                                                 the batch falls back cleanly
                                                 to the per-request HTTP path)
  store.rpc / store.watch                       (storage/remote.py op checks
                                                 AND storage/wire.py framer
                                                 sends: on a negotiated
                                                 binary connection sever/
                                                 truncate cut the length-
                                                 prefixed frame mid-byte —
                                                 the receiver must surface
                                                 FrameTruncated, never hang)
  store.shard.rpc / store.shard.watch           (the SHARD links: each
                                                 ShardedStore shard's
                                                 RemoteStore dials with
                                                 site_prefix="store.shard"
                                                 — storage/shardmap.py —
                                                 so chaos can fault shard
                                                 traffic independently of
                                                 an unsharded store's)
  repl.link                                     (storage/server.py sender,
                                                 storage/standby.py consumer)
  wal.write                                     (storage/store.py)
  plugin.dial / plugin.rpc / plugin.watch       (deviceplugin/api.py: the
                                                 kubelet<->device-plugin
                                                 socket — dial, AdmitPod/
                                                 InitContainer RPCs, and
                                                 the ListAndWatch stream)
  device.health                                 (deviceplugin/tpu_plugin.py:
                                                 an injected fault on a
                                                 health pass flips a chip
                                                 unhealthy — seeded chip
                                                 death through ListAndWatch)
  obs.scrape                                    (obs/collector.py: every
                                                 ObsCollector fetch —
                                                 /metrics scrapes and the
                                                 /debug fan-outs.  Standing
                                                 invariant: a dead or slow
                                                 scrape target may only
                                                 stall its own per-target
                                                 thread, never the
                                                 collector's serving path —
                                                 scripts/chaos.py
                                                 --schedule obs proves it)
  obs.pod_scrape                                (kubelet/podscrape.py: the
                                                 kubelet's pod /metrics
                                                 fetches — same invariant,
                                                 node-local: a wedged pod
                                                 endpoint stalls only its
                                                 own per-pod thread, never
                                                 the kubelet sync loop;
                                                 --schedule obs covers it)
  cri.dial                                      (kubelet/cri.py: the CRI
                                                 socket dial — checked
                                                 BEFORE the fd exists so an
                                                 injected drop cannot leak
                                                 a socket)
  kubelet.probe                                 (kubelet/prober.py: one
                                                 exec/http/tcp probe
                                                 attempt — a drop is a
                                                 probe failure, feeding the
                                                 restart/readiness logic)
  kubelet.statefile                             (kubelet.py resolv.conf,
                                                 containermanager.py,
                                                 cpumanager.py,
                                                 volumemanager.py: node-
                                                 local state writes — a
                                                 drop exercises each
                                                 manager's torn/absent-
                                                 state recovery)
  proxy.upstream                                (proxy/proxier.py + ipvs.py
                                                 + balancer.py: the backend
                                                 dial behind a Service VIP —
                                                 a drop is a dead endpoint
                                                 the proxier/balancer must
                                                 route around)
  proxy.upstream_send                           (proxy/balancer.py: the L7
                                                 request-forward leg to a
                                                 picked backend — checked
                                                 via check_deferred on the
                                                 shared dispatcher; a drop
                                                 before any response byte
                                                 is acked retries on a
                                                 surviving backend, never
                                                 a lost request)
  loadgen.request                               (workloads/loadgen.py: one
                                                 open-loop client request —
                                                 a drop is a client-side
                                                 failure the retry policy
                                                 (client/retry) absorbs;
                                                 arrivals never stall)
  dns.upstream                                  (dns/server.py _forward: the
                                                 recursive upstream hop —
                                                 FaultInjected ⊂ OSError ⇒
                                                 SERVFAIL, never a hang)
  stream.upgrade                                (utils/streams.py
                                                 upgrade_request: the exec/
                                                 attach/port-forward dial
                                                 leg, client->apiserver and
                                                 apiserver->kubelet both)

With no injector active every hook is identity — one module-global ``is
None`` test on the hot path; no locks, no RNG, no allocation.

Every site hook doubles as a `utils/schedsan.py` preemption point: the
same site names that inject faults also widen interleaving windows when
``KTPU_SCHEDSAN=<seed>`` is set, so the I/O boundary map is ONE list
serving both sanitizers (ktpulint KTPU012 keeps it complete).
"""

from __future__ import annotations

import os
import random
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from . import schedsan

ENV_VAR = "KTPU_FAULTS"


class FaultInjected(ConnectionError):
    """An injected transport/storage fault.  Subclasses ConnectionError on
    purpose: every recovery path under test already classifies connection
    errors as transient, and the injector must exercise THOSE paths, not
    grow special cases for itself."""


class FaultSpecError(ValueError):
    """Malformed KTPU_FAULTS spec — raised at activation, never mid-run."""


def _parse_duration(s: str) -> float:
    s = s.strip()
    if s.endswith("ms"):
        return float(s[:-2]) / 1000.0
    if s.endswith("s"):
        return float(s[:-1])
    return float(s)


class _Fault:
    __slots__ = ("action", "param", "prob")

    ACTIONS = ("drop", "delay", "error", "sever", "truncate")

    def __init__(self, action: str, param: Optional[float], prob: float):
        self.action = action
        self.param = param
        self.prob = prob


class _Site:
    """One named injection point: its fault list, its own seeded RNG (so
    decision sequences are per-site deterministic regardless of which
    other sites fire), and decision counters."""

    def __init__(self, name: str, seed: int):
        self.name = name
        # independent, stable stream per (seed, site)
        self.rng = random.Random((seed << 32) ^ zlib.crc32(name.encode()))
        self.faults: List[_Fault] = []
        self.injected: Dict[str, int] = {}
        self.decisions = 0


class _LockedJitter:
    """Thread-safe facade over the injector's jitter stream.  Exposes the
    one method Backoff draws with; the lock keeps concurrent client
    threads from corrupting the shared Random state (Random is not
    thread-safe for seeded use).  Draw ORDER across threads still follows
    the scheduler, so exact sleep replay holds per thread interleave —
    single-threaded consumers (the unit tests) replay exactly."""

    __slots__ = ("_rng", "_lock")

    def __init__(self, seed: int):
        self._rng = random.Random((seed << 32) ^ 0x6A177E12)
        self._lock = threading.Lock()  # ktpulint: ignore[KTPU007] leaf lock around one RNG draw; only taken when faults are ACTIVE

    def uniform(self, a: float, b: float) -> float:
        with self._lock:
            return self._rng.uniform(a, b)


class Injector:
    def __init__(self, seed: int, spec: str):
        self.seed = seed
        self.spec = spec
        self._sites: Dict[str, _Site] = {}
        # one leaf lock serializes RNG draws + counters; sites are touched
        # from many threads and Random is not thread-safe
        self._lock = threading.Lock()  # ktpulint: ignore[KTPU007] hot leaf lock inside the injector; taken only when faults are ACTIVE
        # a dedicated jitter stream for consumers (client/retry backoff)
        # that want deterministic randomness under an active schedule
        self.jitter_rng = _LockedJitter(seed)
        for rule in spec.split(";"):
            rule = rule.strip()
            if not rule:
                continue
            site_name, sep, faults = rule.partition("=")
            site_name = site_name.strip()
            if not sep or not site_name:
                raise FaultSpecError(f"rule {rule!r} is not <site>=<fault>")
            site = self._sites.get(site_name)
            if site is None:
                site = self._sites[site_name] = _Site(site_name, seed)
            for f in faults.split("|"):
                f = f.strip()
                if not f:
                    continue
                body, _, prob_s = f.partition("@")
                action, _, param_s = body.partition(":")
                action = action.strip()
                if action not in _Fault.ACTIONS:
                    raise FaultSpecError(
                        f"unknown action {action!r} in rule {rule!r} "
                        f"(want one of {_Fault.ACTIONS})")
                try:
                    prob = float(prob_s) if prob_s else 1.0
                    param: Optional[float] = None
                    if param_s:
                        param = (_parse_duration(param_s)
                                 if action == "delay" else float(param_s))
                except ValueError as e:
                    raise FaultSpecError(
                        f"bad parameter in fault {f!r}: {e}") from e
                if not 0.0 <= prob <= 1.0:
                    raise FaultSpecError(f"probability {prob} not in [0,1]")
                site.faults.append(_Fault(action, param, prob))

    # ------------------------------------------------------------ decisions

    def decide(self, site_name: str) -> Optional[Tuple[str, Optional[float]]]:
        """(action, param) when a fault fires at this site, else None.
        One seeded draw per configured fault per decision — the schedule
        is a pure function of (seed, site, decision index)."""
        site = self._sites.get(site_name)
        if site is None:
            return None
        with self._lock:
            site.decisions += 1
            for f in site.faults:
                if f.prob >= 1.0 or site.rng.random() < f.prob:
                    site.injected[f.action] = \
                        site.injected.get(f.action, 0) + 1
                    if f.action in ("sever", "truncate") and f.param is None:
                        # the cut point is part of the schedule: draw it
                        # under the same per-site stream
                        return (f.action, site.rng.uniform(0.1, 0.9))
                    return (f.action, f.param)
        return None

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {name: dict(s.injected)
                    for name, s in self._sites.items() if s.injected}


_injector: Optional[Injector] = None


def active() -> bool:
    return _injector is not None


def activate(seed: int, spec: str) -> Injector:
    """Install an injector process-wide (replacing any active one)."""
    global _injector
    inj = Injector(int(seed), spec)
    _injector = inj
    return inj


def activate_from_value(value: str) -> Injector:
    """Parse the ``<seed>:<spec>`` env form and activate it."""
    seed_s, sep, spec = value.partition(":")
    if not sep:
        raise FaultSpecError(
            f"{ENV_VAR} must be <seed>:<spec>, got {value!r}")
    try:
        seed = int(seed_s)
    except ValueError as e:
        raise FaultSpecError(f"bad seed {seed_s!r}: {e}") from e
    return activate(seed, spec)


def deactivate() -> None:
    global _injector
    _injector = None


def rng() -> Optional["_LockedJitter"]:
    """The active injector's dedicated jitter stream (None when inactive).
    Backoff jitter rides this so a seeded chaos run's sleeps come from the
    schedule's seed; draws are lock-serialized across threads."""
    inj = _injector
    return inj.jitter_rng if inj is not None else None


def stats() -> Dict[str, Dict[str, int]]:
    """Per-site injected-fault counts (empty when inactive) — the chaos
    runner's proof that a schedule actually exercised its sites."""
    inj = _injector
    return inj.stats() if inj is not None else {}


def check(site: str) -> None:
    """Gate a non-stream operation (a dial, an RPC, a frame read): no-op
    when inactive; may sleep (delay) or raise FaultInjected (drop/error —
    sever/truncate degrade to drop here, there are no bytes to cut)."""
    schedsan.preempt(site)  # every I/O boundary is an interleaving point
    inj = _injector
    if inj is None:
        return
    d = inj.decide(site)
    if d is None:
        return
    action, param = d
    if action == "delay":
        time.sleep(param or 0.0)
        return
    raise FaultInjected(f"faultline[{site}]: injected {action}")


def check_deferred(site: str) -> Optional[float]:
    """``check()`` for event-loop callers: NEVER sleeps.  A delay
    decision is RETURNED (seconds) for the caller to schedule
    (``loop.call_later`` and resume); drop/error/sever/truncate raise
    FaultInjected exactly like ``check()`` (there are no bytes to cut
    at a gate, so the cutting actions degrade to drop).  Returns None
    when no fault fires.  This is the variant dispatcher-run code uses
    — a sleeping check on the shared loop would stall every connection
    in the process (the KTPU016 invariant)."""
    schedsan.preempt(site)
    inj = _injector
    if inj is None:
        return None
    d = inj.decide(site)
    if d is None:
        return None
    action, param = d
    if action == "delay":
        return param or 0.0
    raise FaultInjected(f"faultline[{site}]: injected {action}")


def filter_bytes(site: str, data: bytes) -> Tuple[bytes, Optional[Exception]]:
    """Gate a byte-stream write.  Returns (bytes_to_write, exc): the
    caller MUST write the returned bytes, then raise exc if set — that
    ordering is what puts a torn frame on the wire / a torn record on
    disk before the failure surfaces (the partial-failure shape whole-
    process kills can never produce)."""
    schedsan.preempt(site)  # every I/O boundary is an interleaving point
    inj = _injector
    if inj is None:
        return data, None
    d = inj.decide(site)
    if d is None:
        return data, None
    action, param = d
    if action == "delay":
        time.sleep(param or 0.0)
        return data, None
    if action in ("sever", "truncate") and len(data) > 1:
        frac = param if param is not None else 0.5
        cut = max(1, min(len(data) - 1, int(len(data) * frac)))
        return data[:cut], FaultInjected(
            f"faultline[{site}]: injected {action} at byte {cut}/{len(data)}")
    if action == "error":
        return b"", FaultInjected(f"faultline[{site}]: injected error")
    return b"", FaultInjected(f"faultline[{site}]: injected {action}")


_env = os.environ.get(ENV_VAR, "")
if _env:
    activate_from_value(_env)
