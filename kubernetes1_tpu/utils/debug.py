"""/debug/pprof-style introspection endpoints for component servers.

Ref: every reference binary mounts net/http/pprof (`/debug/pprof`) —
goroutine dumps and CPU profiles are the standard tools for "why is the
scheduler slow".  Python equivalents served here:

- /debug/pprof/stacks   — all-thread stack dump (goroutine profile analog)
- /debug/pprof/profile?seconds=N — statistical CPU profile: samples every
  thread's frame stack at ~100Hz for N seconds (py-spy style), reports
  aggregated (function, file:line) self/cumulative counts as text.

Shared by MetricsServer, the apiserver, and the kubelet server so one
implementation backs every component (the reference gets this for free
from net/http/pprof).
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter
from typing import Optional, Tuple

MAX_PROFILE_SECONDS = 60.0

# The sampler burns a thread at 100Hz — cap concurrent profiles so the
# endpoint cannot be used to pile up samplers (429 beyond the cap).
_profile_slots = threading.BoundedSemaphore(2)


def handle_debug(path: str, query: dict) -> Optional[Tuple[int, str, bytes]]:
    """Serve a /debug/pprof request. Returns (status, content-type, body)
    or None when the path is not a debug path."""
    if not path.startswith("/debug/pprof"):
        return None
    leaf = path[len("/debug/pprof"):].strip("/")
    if leaf in ("", "index"):
        body = (b"ktpu pprof analog\n"
                b"  /debug/pprof/stacks\n"
                b"  /debug/pprof/profile?seconds=N\n")
        return 200, "text/plain", body
    if leaf == "stacks":
        return 200, "text/plain", dump_stacks().encode()
    if leaf == "profile":
        raw = query.get("seconds", "1")
        if isinstance(raw, (list, tuple)):
            raw = raw[0] if raw else "1"
        try:
            seconds = float(raw)
        except (TypeError, ValueError):
            return 400, "text/plain", b"bad seconds\n"
        seconds = max(0.05, min(MAX_PROFILE_SECONDS, seconds))
        if not _profile_slots.acquire(blocking=False):
            return 429, "text/plain", b"profiler busy\n"
        try:
            return 200, "text/plain", sample_profile(seconds).encode()
        finally:
            _profile_slots.release()
    return 404, "text/plain", b"unknown debug path\n"


def dump_stacks() -> str:
    """Stack of every live thread (the goroutine-dump analog)."""
    names = {th.ident: th.name for th in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {ident} ({names.get(ident, '?')}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


def sample_profile(seconds: float, hz: float = 100.0) -> str:
    """Statistical profile: sample all thread stacks at `hz` for `seconds`,
    aggregate self and cumulative hits per (function, file:line)."""
    interval = 1.0 / hz
    me = threading.get_ident()
    self_hits: Counter = Counter()
    cum_hits: Counter = Counter()
    samples = 0
    deadline = time.perf_counter() + seconds
    while time.perf_counter() < deadline:
        for ident, frame in list(sys._current_frames().items()):
            if ident == me:
                continue
            samples += 1
            seen = set()
            f, leaf = frame, True
            while f is not None:
                code = f.f_code
                key = f"{code.co_name} ({code.co_filename}:{code.co_firstlineno})"
                if leaf:
                    self_hits[key] += 1
                    leaf = False
                if key not in seen:
                    cum_hits[key] += 1
                    seen.add(key)
                f = f.f_back
        time.sleep(interval)
    lines = [f"samples: {samples} over {seconds:.2f}s @ {hz:.0f}Hz",
             f"{'self':>6} {'cum':>6}  location"]
    for key, cum in cum_hits.most_common(60):
        lines.append(f"{self_hits.get(key, 0):6d} {cum:6d}  {key}")
    return "\n".join(lines) + "\n"
