"""Component metrics: counters/gauges/histograms with Prometheus text output.

Ref: the reference's prometheus client usage (scheduler metrics/, kubelet
metrics/ — incl. the fork's DevicePluginAllocationLatency observed at
devicemanager/manager.go:231).  Histograms keep a bounded sample reservoir
so p50/p90/p99 are queryable in-process (bench.py reads them directly).
"""

from __future__ import annotations

import bisect
import random
import threading
from .logutil import RateLimitedReporter
from typing import Dict, List, Optional


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._v += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._v

    def render(self) -> str:
        return f"# TYPE {self.name} counter\n{self.name} {self.value}\n"


class Gauge(Counter):
    def set(self, v: float):
        with self._lock:
            self._v = v

    def render(self) -> str:
        return f"# TYPE {self.name} gauge\n{self.name} {self.value}\n"


class Histogram:
    """Reservoir-sampled histogram with exact quantiles over the reservoir."""

    def __init__(self, name: str, help_: str = "", reservoir: int = 10000):
        self.name = name
        self.help = help_
        self._samples: List[float] = []
        self._count = 0
        self._sum = 0.0
        self._max_reservoir = reservoir
        self._lock = threading.Lock()

    def observe(self, v: float):
        with self._lock:
            self._count += 1
            self._sum += v
            if len(self._samples) < self._max_reservoir:
                bisect.insort(self._samples, v)
            else:
                idx = random.randrange(self._count)
                if idx < self._max_reservoir:
                    del self._samples[random.randrange(len(self._samples))]
                    bisect.insort(self._samples, v)

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._samples:
                return None
            idx = min(len(self._samples) - 1, int(q * len(self._samples)))
            return self._samples[idx]

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def render(self) -> str:
        lines = [f"# TYPE {self.name} summary"]
        for q in (0.5, 0.9, 0.99):
            v = self.quantile(q)
            if v is not None:
                lines.append(f'{self.name}{{quantile="{q}"}} {v:.6f}')
        lines.append(f"{self.name}_sum {self.sum:.6f}")
        lines.append(f"{self.name}_count {self.count}")
        return "\n".join(lines) + "\n"


class Registry:
    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def register(self, metric):
        with self._lock:
            self._metrics[metric.name] = metric
        return metric

    def counter(self, name: str, help_: str = "") -> Counter:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = Counter(name, help_)
            return self._metrics[name]  # type: ignore[return-value]

    def gauge(self, name: str, help_: str = "") -> Gauge:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = Gauge(name, help_)
            return self._metrics[name]  # type: ignore[return-value]

    def histogram(self, name: str, help_: str = "") -> Histogram:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = Histogram(name, help_)
            return self._metrics[name]  # type: ignore[return-value]

    def render(self) -> str:
        with self._lock:
            return "".join(m.render() for m in self._metrics.values())  # type: ignore[attr-defined]


global_registry = Registry()


class MetricsServer:
    """Tiny /metrics + /healthz HTTP server for a component process (ref:
    every reference binary serves prometheus on its own port — scheduler
    :10251, kubelet :10250/metrics, controller-manager :10252)."""

    def __init__(self, registry: Registry, host: str = "127.0.0.1",
                 port: int = 0, extra: Optional[Dict[str, callable]] = None,
                 debug: Optional[bool] = None):
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        registry_ref = registry
        extra_fns = dict(extra or {})  # name -> () -> float, appended as gauges
        # a permanently-broken gauge fn must not print once per scrape
        gauge_err_reporter = RateLimitedReporter("metrics", window=60.0)
        # /debug/pprof exposes thread stacks and a CPU sampler; the apiserver
        # authorizes it per-request, this bare server cannot — so default to
        # loopback-only (None = auto) unless the caller opts in explicitly
        if debug is None:
            debug = host in ("127.0.0.1", "localhost", "::1")
        debug_enabled = debug

        class _H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: D102
                pass

            def do_GET(self):
                if self.path.startswith("/debug/pprof") and debug_enabled:
                    from urllib.parse import parse_qs, urlsplit

                    from .debug import handle_debug

                    parts = urlsplit(self.path)
                    res = handle_debug(parts.path, parse_qs(parts.query))
                    status, ctype, body = res or (404, "text/plain", b"")
                    self.send_response(status)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path == "/healthz":
                    body = _json.dumps({"status": "ok"}).encode()
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    text = registry_ref.render()
                    for name, fn in extra_fns.items():
                        try:
                            text += f"# TYPE {name} gauge\n{name} {float(fn())}\n"
                        except Exception as e:  # noqa: BLE001 — one bad gauge must not kill /metrics
                            gauge_err_reporter.report(
                                f"extra gauge {name}: {e}")
                    body = text.encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), _H)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="metrics-server",
        )
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
