"""Component metrics: counters/gauges/histograms with Prometheus text output.

Ref: the reference's prometheus client usage (scheduler metrics/, kubelet
metrics/ — incl. the fork's DevicePluginAllocationLatency observed at
devicemanager/manager.go:231).  Histograms keep a bounded sample reservoir
so p50/p90/p99 are queryable in-process (bench.py reads them directly), AND
cumulative `_bucket` counters so a real Prometheus can aggregate across
scrapes/instances (reservoir quantiles can't be summed; buckets can).

Labels: every metric doubles as a family — `counter(name).labels(phase=
"bind")` returns a child carrying that label set, rendered as
`name{phase="bind"} v` under one TYPE header (the prometheus_client
parent/child shape).
"""

from __future__ import annotations

import bisect
import random
import threading
from .logutil import RateLimitedReporter
from typing import Dict, List, Optional, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_str(labels: Optional[_LabelKey], extra: str = "") -> str:
    """'{a="b",c="d"}' (optionally merged with an extra 'k="v"' pair)."""
    parts = [f'{k}="{v}"' for k, v in (labels or ())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class Counter:
    def __init__(self, name: str, help_: str = "",
                 labels: Optional[_LabelKey] = None):
        self.name = name
        self.help = help_
        self._v = 0.0
        self._labels = labels
        self._children: Dict[_LabelKey, "Counter"] = {}
        # hot leaf lock (taken on every inc/observe); plain threading — the
        # runtime sanitizer tracking would tax every metric update
        self._lock = threading.Lock()  # ktpulint: ignore[KTPU007] hot leaf metric lock

    def _make_child(self, key: _LabelKey) -> "Counter":
        return type(self)(self.name, self.help, labels=key)

    def labels(self, **kv: object) -> "Counter":
        """Child metric for this label set (created on first use)."""
        key = tuple(sorted((k, str(v)) for k, v in kv.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child(key)
        return child

    def _children_snapshot(self) -> List["Counter"]:
        with self._lock:
            return [self._children[k] for k in sorted(self._children)]

    def remove_labels(self, **kv: object):
        """Drop every labeled child whose label set contains all the given
        pairs — a deleted owner's series must not render forever."""
        match = {(k, str(v)) for k, v in kv.items()}
        with self._lock:
            for key in [k for k in self._children
                        if match.issubset(set(k))]:
                del self._children[key]

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._v += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._v

    TYPE = "counter"

    def _sample_lines(self) -> List[str]:
        return [f"{self.name}{_label_str(self._labels)} {self.value}"]

    def render(self) -> str:
        children = self._children_snapshot()
        lines = [f"# TYPE {self.name} {self.TYPE}"]
        # the bare (unlabeled) series renders unless this is purely a
        # family handle for labeled children
        if not children or self._touched():
            lines.extend(self._sample_lines())
        for child in children:
            lines.extend(child._sample_lines())
        return "\n".join(lines) + "\n"

    def _touched(self) -> bool:
        return self.value != 0.0


class Gauge(Counter):
    TYPE = "gauge"

    def set(self, v: float):
        with self._lock:
            self._v = v


# Default latency buckets (seconds) — the prometheus client defaults plus a
# 30/60s tail for pod-startup SLIs (the GenAI-inference studies' regime).
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class Histogram:
    """Reservoir-sampled histogram: exact quantiles over the reservoir for
    in-process readers, plus cumulative `_bucket` counters for scrapers."""

    def __init__(self, name: str, help_: str = "", reservoir: int = 10000,
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                 labels: Optional[_LabelKey] = None):
        self.name = name
        self.help = help_
        self._samples: List[float] = []
        self._count = 0
        self._sum = 0.0
        self._max_reservoir = reservoir
        self.buckets = tuple(sorted(buckets))
        self._bucket_counts = [0] * len(self.buckets)
        self._labels = labels
        self._children: Dict[_LabelKey, "Histogram"] = {}
        # hot leaf lock (every observe) — see Counter._lock
        self._lock = threading.Lock()  # ktpulint: ignore[KTPU007] hot leaf metric lock

    def _make_child(self, key: _LabelKey) -> "Histogram":
        return Histogram(self.name, self.help, reservoir=self._max_reservoir,
                         buckets=self.buckets, labels=key)

    def labels(self, **kv: object) -> "Histogram":
        key = tuple(sorted((k, str(v)) for k, v in kv.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child(key)
        return child

    def _children_snapshot(self) -> List["Histogram"]:
        with self._lock:
            return [self._children[k] for k in sorted(self._children)]

    def remove_labels(self, **kv: object):
        """See Counter.remove_labels."""
        match = {(k, str(v)) for k, v in kv.items()}
        with self._lock:
            for key in [k for k in self._children
                        if match.issubset(set(k))]:
                del self._children[key]

    def observe(self, v: float):
        with self._lock:
            self._count += 1
            self._sum += v
            idx = bisect.bisect_left(self.buckets, v)
            if idx < len(self._bucket_counts):
                self._bucket_counts[idx] += 1
            if len(self._samples) < self._max_reservoir:
                bisect.insort(self._samples, v)
            else:
                idx = random.randrange(self._count)
                if idx < self._max_reservoir:
                    del self._samples[random.randrange(len(self._samples))]
                    bisect.insort(self._samples, v)

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._samples:
                return None
            idx = min(len(self._samples) - 1, int(q * len(self._samples)))
            return self._samples[idx]

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def _sample_lines(self) -> List[str]:
        lines = []
        for q in (0.5, 0.9, 0.99):
            v = self.quantile(q)
            if v is not None:
                lines.append("%s%s %.6f" % (
                    self.name,
                    _label_str(self._labels, 'quantile="%s"' % q), v))
        with self._lock:
            cum, counts = 0, list(self._bucket_counts)
            count, total = self._count, self._sum
        for le, n in zip(self.buckets, counts):
            cum += n
            lines.append("%s_bucket%s %d" % (
                self.name, _label_str(self._labels, 'le="%s"' % le), cum))
        lines.append("%s_bucket%s %d" % (
            self.name, _label_str(self._labels, 'le="+Inf"'), count))
        lines.append("%s_sum%s %.6f" % (
            self.name, _label_str(self._labels), total))
        lines.append("%s_count%s %d" % (
            self.name, _label_str(self._labels), count))
        return lines

    def render(self) -> str:
        children = self._children_snapshot()
        lines = [f"# TYPE {self.name} histogram"]
        if not children or self.count:
            lines.extend(self._sample_lines())
        for child in children:
            lines.extend(child._sample_lines())
        return "\n".join(lines) + "\n"


class Registry:
    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()  # ktpulint: ignore[KTPU007] leaf registry lock

    def register(self, metric):
        with self._lock:
            existing = self._metrics.get(metric.name)
            if existing is not None and existing is not metric:
                raise ValueError(
                    f"metric {metric.name!r} already registered as "
                    f"{type(existing).__name__}")
            self._metrics[metric.name] = metric
        return metric

    def _get_or_create(self, name: str, cls, help_: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help_)
            elif type(m) is not cls:
                # a silent wrong-type return here sent .observe() calls to a
                # Counter once — fail loudly at registration instead
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_create(name, Counter, help_)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help_)

    def histogram(self, name: str, help_: str = "") -> Histogram:
        return self._get_or_create(name, Histogram, help_)

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        return "".join(m.render() for m in metrics)  # type: ignore[attr-defined]


global_registry = Registry()


class MetricsServer:
    """Tiny /metrics + /healthz + /readyz (+ /debug/*) HTTP server for a
    component process (ref: every reference binary serves prometheus on its
    own port — scheduler :10251, kubelet :10250/metrics, controller-manager
    :10252).

    `ready_fn` backs /readyz: None means ready-when-serving (same as
    /healthz); a callable gates readiness on component state (informers
    synced, leader lease held, ...) and a falsy/raising callable answers
    503.  `spans` (a utils.spans.SpanCollector) backs /debug/traces."""

    def __init__(self, registry: Registry, host: str = "127.0.0.1",
                 port: int = 0, extra: Optional[Dict[str, callable]] = None,
                 debug: Optional[bool] = None, ready_fn=None, spans=None):
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        registry_ref = registry
        extra_fns = dict(extra or {})  # name -> () -> float, appended as gauges
        # a permanently-broken gauge fn must not print once per scrape
        gauge_err_reporter = RateLimitedReporter("metrics", window=60.0)
        # /debug/pprof exposes thread stacks and a CPU sampler; the apiserver
        # authorizes it per-request, this bare server cannot — so default to
        # loopback-only (None = auto) unless the caller opts in explicitly
        if debug is None:
            debug = host in ("127.0.0.1", "localhost", "::1")
        debug_enabled = debug
        ready_ref = ready_fn
        spans_ref = spans

        class _H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: D102
                pass

            def do_GET(self):
                from urllib.parse import parse_qs, urlsplit

                parts = urlsplit(self.path)
                if parts.path == "/debug/flightrecorder" and debug_enabled:
                    from . import flightrec

                    q = parse_qs(parts.query)
                    body = flightrec.to_json(
                        (q.get("component") or [""])[0])
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if parts.path == "/debug/traces" and debug_enabled \
                        and spans_ref is not None:
                    q = parse_qs(parts.query)
                    trace = (q.get("trace") or [""])[0]
                    body = spans_ref.to_json(trace)
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path.startswith("/debug/pprof") and debug_enabled:
                    from .debug import handle_debug

                    res = handle_debug(parts.path, parse_qs(parts.query))
                    status, ctype, body = res or (404, "text/plain", b"")
                    self.send_response(status)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path == "/healthz":
                    body = _json.dumps({"status": "ok"}).encode()
                    ctype = "application/json"
                elif self.path == "/readyz":
                    ready = True
                    if ready_ref is not None:
                        try:
                            ready = bool(ready_ref())
                        except Exception:  # noqa: BLE001 — a broken check reads as unready
                            ready = False
                    body = _json.dumps(
                        {"status": "ok" if ready else "unready"}).encode()
                    if not ready:
                        self.send_response(503)
                        self.send_header("Content-Type", "application/json")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    text = registry_ref.render()
                    for name, fn in extra_fns.items():
                        try:
                            text += f"# TYPE {name} gauge\n{name} {float(fn())}\n"
                        except Exception as e:  # noqa: BLE001 — one bad gauge must not kill /metrics
                            gauge_err_reporter.report(
                                f"extra gauge {name}: {e}")
                    body = text.encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), _H)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="metrics-server",
        )
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
