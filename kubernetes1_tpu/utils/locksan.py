"""Runtime lock-order + hold-time sanitizer for the threaded control plane.

Every hand-rolled thread+lock component (scheduler cache/queue, informers,
workqueues, kubelet managers, prober, eviction) creates its locks through
this factory.  With `KTPU_LOCKSAN` unset (production) the factory returns
plain `threading.Lock`/`RLock`/`Condition` objects — zero overhead, zero
behavior change.  With `KTPU_LOCKSAN=1` (the test suite turns it on in
`tests/conftest.py`) every acquisition is tracked:

- **Lock-order cycles.**  Locks are grouped into classes by NAME (the
  lockdep model: "SchedulerCache._lock" is one class across every
  instance).  A per-thread stack records what each thread holds; each
  acquisition adds held-class -> acquired-class edges to a global graph.
  An edge that closes a cycle means two threads can interleave into a
  deadlock — `LockOrderViolation` is raised at acquire time, with the
  cycle, while both stacks still exist, instead of a silent freeze in
  production at 3am.
- **Hold-time budget.**  A lock held longer than `KTPU_LOCKSAN_BUDGET`
  seconds (default 10) raises `HoldTimeViolation` at release.  A lock
  held across a blocking call is the #1 way orchestration-layer stalls
  tax accelerator goodput: every thread that needs the lock (heartbeats,
  admission, binding) convoys behind the holder.

`threading.Condition.wait()` cooperates for free: waiting releases the
underlying (wrapped) lock through the factory lock's own release/acquire
path, so blocked-in-wait time is never charged as hold time.

The factories are also where `utils/schedsan.py` plants its lock-edge
preemption points: with `KTPU_SCHEDSAN=<seed>` active the wrappers are
installed even when locksan itself is off, and every acquire (before
the inner acquire — widening the contention window) and every release
(after the inner release — widening the handoff window) draws from the
lock class's seeded stream.  Schedules created AFTER activation get
points; racesweep activates schedsan before building a topology.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Set

from . import loopsan
from . import schedsan


class LockSanError(RuntimeError):
    """Base class for sanitizer findings."""


class LockOrderViolation(LockSanError):
    """Acquiring this lock here can deadlock against another thread."""


class HoldTimeViolation(LockSanError):
    """A lock was held longer than the configured budget."""


def enabled() -> bool:
    return os.environ.get("KTPU_LOCKSAN", "") not in ("", "0")


def hold_budget() -> float:
    try:
        return float(os.environ.get("KTPU_LOCKSAN_BUDGET", "10.0"))
    except ValueError:
        return 10.0


class _OrderGraph:
    """Global directed graph over lock classes: edge A->B means some
    thread acquired B while holding A.  A path B..->A at the moment a
    thread holding A acquires B is a potential deadlock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._edges: Dict[str, Set[str]] = {}

    def check_and_add(self, frm: str, to: str) -> Optional[List[str]]:
        """Add edge frm->to; return the cycle path if it closes one."""
        if frm == to:
            # same class, different instances, nested: A(1)->A(2) in one
            # thread deadlocks against A(2)->A(1) in another
            return [frm, to]
        with self._lock:
            if to in self._edges.get(frm, ()):
                return None
            path = self._path(to, frm)
            if path is not None:
                return [frm] + path
            self._edges.setdefault(frm, set()).add(to)
        return None

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def reset(self):
        with self._lock:
            self._edges.clear()


_graph = _OrderGraph()
_tls = threading.local()


def _held_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def reset_order_graph():
    """Tests only: forget learned ordering between cases."""
    _graph.reset()


_owners_lock = threading.Lock()


class _SanBase:
    """Shared acquire/release tracking for Lock and RLock wrappers."""

    _reentrant = False

    def __init__(self, inner, name: str, budget: Optional[float]):
        self._inner = inner
        self.name = name
        self._budget = budget
        # schedsan site names precomputed: the acquire hook runs on every
        # lock operation under the sanitizer and must not allocate there
        self._ss_acq = "lock.acquire:" + name
        self._ss_rel = "lock.release:" + name
        # live acquisitions of THIS instance as (holder_stack, entry) pairs,
        # so a release from a different thread (legal Lock handoff pattern)
        # can still find and retire the acquirer's stack entry instead of
        # leaking it into false held-class edges forever
        self._owners: List[tuple] = []

    # ------------------------------------------------------------- tracking

    def _before_acquire(self, blocking: bool = True):
        stack = _held_stack()
        for entry in list(stack):
            if entry[0] is self:
                if self._reentrant or not blocking:
                    # RLock re-entry is legal; a non-blocking re-acquire
                    # just returns False
                    return
                # blocking re-acquire of a non-reentrant lock this thread
                # already holds: a GUARANTEED deadlock — report it instead
                # of freezing, which is the sanitizer's whole job
                raise LockOrderViolation(
                    f"self-deadlock: thread re-acquiring non-reentrant "
                    f"lock {self.name!r} it already holds")
        checked: Set[str] = set()
        for entry in list(stack):
            lock = entry[0]
            if lock.name in checked:
                continue
            checked.add(lock.name)
            cycle = _graph.check_and_add(lock.name, self.name)
            if cycle is not None:
                raise LockOrderViolation(
                    f"lock-order cycle: acquiring {self.name!r} while "
                    f"holding {lock.name!r} closes the cycle "
                    f"{' -> '.join(cycle)} (another thread acquires these "
                    f"in the opposite order)")

    def _after_acquire(self):
        stack = _held_stack()
        entry = (self, time.monotonic())
        stack.append(entry)
        with _owners_lock:
            self._owners.append((stack, entry))

    def _retire_mine(self):
        """Pop THIS thread's most recent live entry for this lock.  Must
        run BEFORE the inner release: once the inner lock is free, a
        contending waiter's _after_acquire appends its own entry and a
        blind LIFO pop would retire the WAITER's entry — leaving a stale
        held-state on the releaser (false lock-order edges) and charging
        two threads' hold time to one release."""
        my_stack = _held_stack()
        with _owners_lock:
            for i in range(len(self._owners) - 1, -1, -1):
                stack, entry = self._owners[i]
                if stack is my_stack:
                    del self._owners[i]
                    stack.remove(entry)
                    return entry
        return None

    def _retire_oldest(self):
        """Cross-thread handoff (acquire in A, release in B): retire the
        OLDEST live entry.  Runs after the inner release; popping from the
        front is immune to the waiter-append race (appends go to the
        end)."""
        with _owners_lock:
            if not self._owners:
                return None
            stack, entry = self._owners.pop(0)
        try:
            stack.remove(entry)
        except ValueError:
            pass  # holder's stack already unwound
        return entry

    def _check_budget(self, entry, check: bool = True):
        if entry is None or not check:
            return
        held = time.monotonic() - entry[1]
        budget = self._budget if self._budget is not None else hold_budget()
        if held > budget:
            raise HoldTimeViolation(
                f"{self.name!r} held for {held:.3f}s "
                f"(budget {budget:.3f}s) — a blocking call under "
                f"this lock convoys every other thread")

    # --------------------------------------------------------- lock protocol

    def acquire(self, blocking: bool = True, timeout: float = -1):
        # Trylocks are exempt from ordering (the lockdep rule): a
        # non-blocking acquire cannot deadlock its caller, and recording
        # its edges would poison the graph against the deadlock-AVOIDANCE
        # pattern trylock exists for.
        schedsan.preempt(self._ss_acq)
        if blocking:
            self._before_acquire(blocking)
        if blocking and loopsan.active() and loopsan.on_dispatcher():
            # dispatcher-side waits are loopsan's business: a bounded
            # leaf lock is legal, but the measured wait feeds the stall
            # telemetry (and the flight recorder past the threshold)
            t0 = time.monotonic()
            got = self._inner.acquire(blocking, timeout)
            loopsan.note_lock_wait(self.name, time.monotonic() - t0)
        else:
            got = self._inner.acquire(blocking, timeout)
        if got:
            self._after_acquire()
        return got

    def release(self):
        entry = self._retire_mine()
        self._inner.release()  # raises on erroneous release, as the inner does
        schedsan.preempt(self._ss_rel)
        if entry is None:
            entry = self._retire_oldest()  # legal cross-thread handoff
        self._check_budget(entry)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        entry = self._retire_mine()
        self._inner.release()
        schedsan.preempt(self._ss_rel)
        if entry is None:
            entry = self._retire_oldest()
        # When the critical section is already unwinding an exception, a
        # HoldTimeViolation raised here would REPLACE it and hide the real
        # failure — stay silent and let the original propagate.
        self._check_budget(entry, check=exc_type is None)
        return False

    def locked(self):
        return self._inner.locked() if hasattr(self._inner, "locked") else None

    def __repr__(self):
        return f"<{type(self).__name__} {self.name!r} wrapping {self._inner!r}>"


class SanLock(_SanBase):
    pass


class SanRLock(_SanBase):
    """RLock wrapper.  The _release_save/_acquire_restore/_is_owned trio
    lets threading.Condition fully release a multiply-acquired RLock while
    waiting; tracking hooks keep hold-time honest across the wait."""

    _reentrant = True

    def _release_save(self):
        # Retire BEFORE the inner release (see _retire_mine), and with no
        # hold-time check: raising here would leave Condition.wait's
        # caller releasing an already-released lock, and the interesting
        # hold time (post-wakeup critical section) is charged by the
        # normal release.  The inner RLock releases ALL recursion levels
        # at once, so every one of this thread's entries must retire with
        # it — a partial retire would leave pre-wait timestamps behind and
        # charge the whole wait as hold time at the final release.
        levels = 0
        while self._retire_mine() is not None:
            levels += 1
        return (self._inner._release_save(), levels)

    def _acquire_restore(self, state):
        # Condition-wait wakeup re-acquire: the window between notify and
        # the waiter retaking the lock is a classic lost-wakeup race site
        schedsan.preempt(self._ss_acq)
        inner_state, levels = state
        self._before_acquire()
        self._inner._acquire_restore(inner_state)
        for _ in range(max(levels, 1)):  # fresh post-wakeup timestamps
            self._after_acquire()

    def _is_owned(self):
        return self._inner._is_owned()


def make_lock(name: str, hold_budget: Optional[float] = None):
    """A named Lock: plain threading.Lock when both sanitizers are off.
    An active schedsan schedule forces the wrapper too — its preemption
    points live on the wrapper's acquire/release path."""
    if not (enabled() or schedsan.active() or loopsan.active()):
        return threading.Lock()
    return SanLock(threading.Lock(), name, hold_budget)


def make_rlock(name: str, hold_budget: Optional[float] = None):
    if not (enabled() or schedsan.active() or loopsan.active()):
        return threading.RLock()
    return SanRLock(threading.RLock(), name, hold_budget)


def make_condition(lock=None, name: str = "", hold_budget: Optional[float] = None):
    """A Condition whose underlying lock goes through the sanitizer.
    Waiting releases the wrapped lock via its own release path, so time
    blocked in wait() is not charged against the hold budget."""
    if not (enabled() or schedsan.active() or loopsan.active()):
        return threading.Condition(lock)
    if lock is None:
        lock = make_rlock(name or "condition", hold_budget)
    return threading.Condition(lock)
