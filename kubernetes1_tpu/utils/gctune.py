"""Server-process GC tuning.

The control plane's allocation profile is pathological for CPython's
default GC thresholds: every API object is a tree of dataclasses that
LIVES (the MVCC store + its watch-history ring hold them), so the young
generation fills every ~700 allocations, each collection promotes
everything, and periodic full collections rescan a monotonically growing
heap — measured at ~15% of total control-plane CPU at 1000-node density
(261 vs 225 pods/s with collection off).

The reference tunes its runtime GC for the same reason (kube sets GOGC
for the apiserver).  Tuning here:
- freeze() the boot-time heap out of the collector's sight,
- widen gen0 ~70x so young-object churn is batched,
- leave automatic full collections enabled (threshold2 stays default, and
  CPython's long-lived-25% rule already spaces them out) but batch the
  middle generation harder.

True cycles (exception tracebacks, closures) still get collected — this
is tuning, not gc.disable()'s leak-forever trade.
"""

from __future__ import annotations

import gc

_tuned = False


def tune_for_server() -> None:
    """Idempotent; call at long-lived component start (apiserver, store,
    scheduler, controller-manager, kubelet)."""
    global _tuned
    if _tuned:
        return
    _tuned = True
    gc.freeze()
    gc.set_threshold(200000, 50, 50)
