"""Runtime shared-object mutation sanitizer (KTPU_MUTSAN).

The control plane's correctness rests on a convention stock Kubernetes
also only enforces by discipline: **objects handed out by a cache are
immutable snapshots**.  An informer's `get()/list()` and the apiserver
watch cache's `get_raw()/list_raw()` return THE stored object — one
in-place mutation silently corrupts what every other consumer (and,
since the read path caches serialized bytes per `(uid, resourceVersion)`,
every LIST/watch response) sees for that revision.  The bug class is
invisible in tests that don't race and catastrophic under load.

This module is the runtime half of the mutation-safety layer (the static
half is ktpulint KTPU008/KTPU009).  With `KTPU_MUTSAN` unset (production)
`freeze()` is the identity function — zero overhead, zero behavior
change.  With `KTPU_MUTSAN=1` (the test suite turns it on in
`tests/conftest.py`, like KTPU_LOCKSAN) cache handouts are wrapped in
recursively freezing proxies:

- attribute assignment, item assignment, and mutating container methods
  (`append`, `update`, `setdefault`, …) raise `SharedObjectMutationError`
  carrying BOTH sites: the mutation site (the raised traceback) and the
  acquisition site (where the shared object was handed out), so the fix —
  `clone()` at the acquisition site — is one hop away.
- reads recurse: `pod.spec.containers[0].resources.requests` is frozen
  at every level, so deep aliasing cannot escape the sanitizer.
- the sanctioned escape hatch is `KObject.clone()` (machinery/meta.py):
  a deep copy that is yours to mutate.  `copy.deepcopy` of a frozen
  proxy likewise returns an unfrozen deep copy.
- attributes prefixed `_ktpu_` write through to the target: they are the
  blessed memoization slots (scheduler request-size memos) — derived,
  never serialized, and replaced together with the object on update.

Design note: proxies, not flags.  Freezing by flipping a bit on the
object would require a `__setattr__` hook on every dataclass AND could
not catch `pod.metadata.annotations["x"] = ...` (dict mutation).  The
proxy wraps lazily on access instead, so freezing is O(1) per handout
and containers are snapshotted (a frozen dict/list holds its own entry
array — concurrent resyncs can never invalidate an iteration).
"""

from __future__ import annotations

import copy
import dataclasses
import os
import traceback
from typing import Any

__all__ = [
    "SharedObjectMutationError",
    "enabled",
    "freeze",
    "unwrap",
]

_MEMO_PREFIX = "_ktpu_"  # sanctioned write-through memoization slots


class SharedObjectMutationError(RuntimeError):
    """In-place mutation of a shared cache object.  The traceback of this
    exception is the MUTATION site; the message carries the ACQUISITION
    site (where the shared snapshot was handed out) and the fix."""


def enabled() -> bool:
    return os.environ.get("KTPU_MUTSAN", "") not in ("", "0")


def _acquisition_site() -> str:
    """file:line of the frame that asked for the freeze — the cache
    boundary handing out the shared object."""
    for frame in reversed(traceback.extract_stack(limit=8)[:-2]):
        if not frame.filename.endswith("mutsan.py"):
            return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


def _mutation_error(origin: str, what: str) -> SharedObjectMutationError:
    return SharedObjectMutationError(
        f"in-place mutation of a shared cache object: {what} "
        f"(object acquired at {origin}); this object is a shared snapshot "
        f"— clone() it (KObject.clone / copy.deepcopy) before mutating"
    )


def unwrap(value: Any) -> Any:
    """The raw object behind a FrozenObject proxy (identity otherwise).
    Frozen containers are snapshots, not views — they have no single
    backing object to return and are handled by their own __deepcopy__."""
    return getattr(value, "_mutsan_target_", value)


def freeze(value: Any, origin: str = "") -> Any:
    """Frozen view of `value` when the sanitizer is on; `value` itself
    otherwise.  Dataclass instances wrap lazily (reads freeze on access);
    dicts/lists snapshot their entries at freeze time."""
    if not enabled():
        return value
    return _freeze(value, origin or _acquisition_site())


def _freeze(value: Any, origin: str) -> Any:
    if isinstance(value, (FrozenObject, FrozenDict, FrozenList)):
        return value
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        return FrozenObject(value, origin)
    # Unstructured (CRD objects) duck-typed by its KIND class attr: not a
    # dataclass, but every bit as shared when an informer caches it
    if getattr(type(value), "KIND", None) is not None:
        return FrozenObject(value, origin)
    # exact types only: subclasses may carry behavior a blind snapshot
    # would drop, and the wire model uses plain dict/list everywhere
    if type(value) is dict:
        return FrozenDict(value, origin)
    if type(value) is list:
        return FrozenList(value, origin)
    if type(value) is tuple:
        return tuple(_freeze(v, origin) for v in value)
    return value


class FrozenObject:
    """Read-only proxy over a dataclass instance.  Field reads return
    frozen views; writes raise.  Methods resolve on the target — the API
    model's methods are read-only accessors (`key()`, `clone()`), and
    `clone()` on the raw target is exactly the sanctioned escape."""

    __slots__ = ("_mutsan_target_", "_mutsan_origin_")

    def __init__(self, target: Any, origin: str):
        object.__setattr__(self, "_mutsan_target_", target)
        object.__setattr__(self, "_mutsan_origin_", origin)

    # reads ---------------------------------------------------------------
    def __getattr__(self, name: str) -> Any:
        value = getattr(self._mutsan_target_, name)
        if (dataclasses.is_dataclass(value) and not isinstance(value, type)) \
                or type(value) in (dict, list, tuple):
            return _freeze(value, self._mutsan_origin_)
        return value  # str/int/bound read-only method/...

    @property  # isinstance(frozen_pod, Pod) must keep working
    def __class__(self):  # noqa: D105
        return type(self._mutsan_target_)

    def __repr__(self) -> str:
        return f"<frozen {self._mutsan_target_!r}>"

    def __eq__(self, other: Any) -> bool:
        return self._mutsan_target_ == unwrap(other)

    def __ne__(self, other: Any) -> bool:
        return self._mutsan_target_ != unwrap(other)

    def __deepcopy__(self, memo) -> Any:
        return copy.deepcopy(self._mutsan_target_, memo)

    # writes --------------------------------------------------------------
    def __setattr__(self, name: str, value: Any):
        if name.startswith(_MEMO_PREFIX):
            setattr(self._mutsan_target_, name, value)
            return
        raise _mutation_error(
            self._mutsan_origin_,
            f"setattr {type(self._mutsan_target_).__name__}.{name}")

    def __delattr__(self, name: str):
        raise _mutation_error(
            self._mutsan_origin_,
            f"delattr {type(self._mutsan_target_).__name__}.{name}")


def _frozen_dict_mutator(name: str):
    def fail(self, *a, **kw):
        raise _mutation_error(self._mutsan_origin_, f"dict.{name}()")
    fail.__name__ = name
    return fail


class FrozenDict(dict):
    """Read-only dict SNAPSHOT: entries are copied in at freeze time (an
    iteration can never be invalidated by a concurrent resync) and value
    reads freeze lazily.  Still a real dict, so json.dumps and isinstance
    checks keep working."""

    __slots__ = ("_mutsan_origin_",)

    def __init__(self, src: dict, origin: str):
        dict.__init__(self, src)
        self._mutsan_origin_ = origin

    # reads wrap lazily
    def __getitem__(self, key):
        return _freeze(dict.__getitem__(self, key), self._mutsan_origin_)

    def get(self, key, default=None):
        if dict.__contains__(self, key):
            return self[key]
        return default

    def values(self):
        return [self[k] for k in dict.keys(self)]

    def items(self):
        return [(k, self[k]) for k in dict.keys(self)]

    def copy(self):  # explicit copy = explicit unfreeze (shallow, raw)
        return {k: dict.__getitem__(self, k) for k in dict.keys(self)}

    def __deepcopy__(self, memo):
        return {copy.deepcopy(k, memo): copy.deepcopy(dict.__getitem__(self, k), memo)
                for k in dict.keys(self)}

    def __reduce__(self):
        return (dict, (self.copy(),))

    # writes raise
    __setitem__ = _frozen_dict_mutator("__setitem__")
    __delitem__ = _frozen_dict_mutator("__delitem__")
    clear = _frozen_dict_mutator("clear")
    pop = _frozen_dict_mutator("pop")
    popitem = _frozen_dict_mutator("popitem")
    setdefault = _frozen_dict_mutator("setdefault")
    update = _frozen_dict_mutator("update")
    __ior__ = _frozen_dict_mutator("__ior__")


def _frozen_list_mutator(name: str):
    def fail(self, *a, **kw):
        raise _mutation_error(self._mutsan_origin_, f"list.{name}()")
    fail.__name__ = name
    return fail


class FrozenList(list):
    """Read-only list SNAPSHOT (see FrozenDict)."""

    __slots__ = ("_mutsan_origin_",)

    def __init__(self, src: list, origin: str):
        list.__init__(self, src)
        self._mutsan_origin_ = origin

    def __getitem__(self, idx):
        item = list.__getitem__(self, idx)
        if isinstance(idx, slice):
            return [_freeze(v, self._mutsan_origin_) for v in item]
        return _freeze(item, self._mutsan_origin_)

    def __iter__(self):
        origin = self._mutsan_origin_
        for item in list.__iter__(self):
            yield _freeze(item, origin)

    def copy(self):
        return list(list.__iter__(self))

    def __deepcopy__(self, memo):
        return [copy.deepcopy(v, memo) for v in list.__iter__(self)]

    def __reduce__(self):
        return (list, (self.copy(),))

    # writes raise
    __setitem__ = _frozen_list_mutator("__setitem__")
    __delitem__ = _frozen_list_mutator("__delitem__")
    __iadd__ = _frozen_list_mutator("__iadd__")
    __imul__ = _frozen_list_mutator("__imul__")
    append = _frozen_list_mutator("append")
    extend = _frozen_list_mutator("extend")
    insert = _frozen_list_mutator("insert")
    remove = _frozen_list_mutator("remove")
    pop = _frozen_list_mutator("pop")
    clear = _frozen_list_mutator("clear")
    sort = _frozen_list_mutator("sort")
    reverse = _frozen_list_mutator("reverse")
