"""invariants: cheap runtime probes for the standing invariants.

The ROADMAP's "Standing invariants" section is the repo's real spec, and
until now every dynamic clause in it was enforced by reviewer memory.
This module makes the four that guard the thread-and-lock code
MECHANICAL — each probe is a one-call assert a hot path can afford:

  ``rev_monotonic(site, shard, rev)``
      per-shard revision monotonicity at every fan-out layer (store
      watcher delivery, cacher apply, informer resume): a revision that
      moves backwards within one (site, shard) stream is a lost-update
      or replay bug, full stop.
  ``no_double_alloc(site, key, holder, prior)``
      the device-claim ledger never holds one chip for two live pods —
      the registry calls it at every claim insert/confirm.
  ``dispatch_superset(site, expected, delivered)``
      indexed dispatch ⊇ the brute-force re-check (the both-buckets
      rule from PR 13): a watcher the full scan says should see an
      event must be in the index's delivery set.
  ``composite_sticky(site, old_rv, new_rv)``
      composite (``"shard.counter"``) resume points are never
      overwritten by a bare single-int revision (PR 11's rule).

Arming: probes are identity no-ops (one module-global check) unless
  - a schedsan schedule is active (``KTPU_SCHEDSAN=<seed>``), or
  - a faultline injector is active (chaos runs), or
  - ``KTPU_INVARIANTS=1`` (opt-in for sanitizer A/B runs), or
  - ``arm()`` was called programmatically (racesweep does, scoped to
    the scenario: ``arm()`` returns the prior state to restore).

Stream keys: long-lived fan-out objects (stores, cachers, watchers) get
their ledger stream from :func:`stream_of`, never ``id()`` — CPython
recycles addresses, so an id-keyed stream would hand a dead cacher's
revision history to whatever instance is allocated on top of it and
false-trip on the newcomer's first (smaller) revision.

A violation raises :class:`InvariantViolation` carrying the flight
recorder's per-component timelines (``.flightrecorder``) and, in the
message, the reproducing ``schedsan`` / ``faultline`` seeds — a red run
ships its own black box AND the schedule that produced it.

State (the monotonicity ledger, the claim mirror) accrues only while
armed; ``reset()`` clears it between seeds so one scenario's revision
history can't poison the next's.
"""

from __future__ import annotations

import itertools
import os
import threading
from typing import Dict, Iterable, Optional, Tuple

from . import faultline, flightrec, schedsan

ENV_VAR = "KTPU_INVARIANTS"

_forced = os.environ.get(ENV_VAR, "") not in ("", "0")

# leaf lock: guards the probe ledgers (touched from every fan-out
# thread while armed; never held across user code)
_lock = threading.Lock()  # ktpulint: ignore[KTPU007] leaf lock around probe ledger dict ops; only taken while probes are armed
_last_rev: Dict[Tuple[str, object], object] = {}


class InvariantViolation(AssertionError):
    """A machine-checked standing invariant failed.  Carries the flight
    recorder dump (``.flightrecorder``) and stamps the active schedsan /
    faultline seeds into the message so the failing schedule is
    reproducible from the artifact alone."""

    def __init__(self, site: str, detail: str):
        self.site = site
        self.schedsan_seed = schedsan.seed()
        inj = faultline._injector
        self.faults_seed = inj.seed if inj is not None else None
        self.flightrecorder = flightrec.dump()["components"]
        super().__init__(
            f"invariant[{site}]: {detail} "
            f"(schedsan_seed={self.schedsan_seed}, "
            f"faults_seed={self.faults_seed}; replay with "
            f"KTPU_SCHEDSAN={self.schedsan_seed})")


def armed() -> bool:
    """Fast path for callers whose EXPECTED-value computation is itself
    expensive (the cacher's brute-force dispatch re-check): skip the
    work entirely when no probe would look at it."""
    return (_forced or schedsan.active() or faultline.active())


def arm(on: bool = True) -> bool:
    """Programmatic arming (racesweep; tests).  Does not clear state —
    call :func:`reset` when starting a fresh scenario.  Returns the
    PRIOR state so a scoped caller can restore it on the way out
    (leaving probes force-armed after a sweep would hand every later
    test an accruing ledger it never asked for)."""
    global _forced
    prior = _forced
    _forced = bool(on)
    return prior


def reset() -> None:
    """Drop accrued probe state (the per-(site, shard) revision ledger).
    Each racesweep seed and each chaos schedule starts from a clean
    ledger — revisions restart when a scenario rebuilds its store."""
    with _lock:
        _last_rev.clear()


_stream_seq = itertools.count()


def stream_of(obj: object, label: str) -> str:
    """Stable per-instance stream key for the monotonicity ledger.
    ``id()`` is NOT usable here: CPython recycles addresses, so a dead
    instance's ledger entry would be inherited by whatever object is
    allocated on top of it — a false "moved backwards" the first time
    the newcomer stamps its (smaller) revision.  Minted once, memoized
    on the instance (``_ktpu_``-prefixed: writes through mutsan's
    frozen proxies like other blessed derived slots).  Two threads
    racing the first mint may split one instance across two streams for
    a single call — harmless: monotonicity within each stream still
    holds."""
    tok = getattr(obj, "_ktpu_invariant_stream", None)
    if tok is None:
        tok = f"{label}#{next(_stream_seq)}"
        try:
            obj._ktpu_invariant_stream = tok
        except AttributeError:  # __slots__ instance: no memo slot
            return f"{label}@{id(obj)}"
    return tok


def _violate(site: str, detail: str) -> None:
    flightrec.note("invariants", flightrec.INVARIANT_VIOLATION,
                   site=site, detail=detail)
    raise InvariantViolation(site, detail)


def rev_monotonic(site: str, shard: object, rev: object) -> None:
    """Assert ``rev`` does not move backwards within the (site, shard)
    stream.  Equal revisions are allowed (idempotent redelivery after a
    resume is legal); a strictly smaller one is a lost update."""
    if not (_forced or schedsan.active() or faultline.active()):
        return
    key = (site, shard)
    with _lock:
        last = _last_rev.get(key)
        _last_rev[key] = rev
    # raise OUTSIDE the ledger lock: InvariantViolation construction
    # dumps the flight recorder, and no probe lock may be held across
    # another subsystem's code
    if last is not None and _lt(rev, last):
        _violate(site, f"revision moved backwards on shard "
                       f"{shard!r}: {last!r} -> {rev!r}")


def _lt(a: object, b: object) -> bool:
    """``a < b`` across the repo's two revision spellings (bare ints and
    ``"shard.counter"`` composites) without raising on a mix — a mixed
    comparison is itself suspicious but belongs to composite_sticky."""
    try:
        return a < b  # type: ignore[operator]
    except TypeError:
        return False


def no_double_alloc(site: str, key: object, holder: object,
                    prior: object) -> None:
    """Assert a device-claim ledger slot is free or already ours:
    ``prior`` is the live holder currently in the ledger (None when the
    slot is free or the old claim expired)."""
    if not (_forced or schedsan.active() or faultline.active()):
        return
    if prior is not None and prior != holder:
        _violate(site, f"double allocation of {key!r}: held by {prior!r}, "
                       f"claimed by {holder!r}")


def dispatch_superset(site: str, expected: Iterable[object],
                      delivered: Iterable[object]) -> None:
    """Assert indexed dispatch delivered to AT LEAST the watchers the
    brute-force re-check says must see the event (missing one is a lost
    event; extras are legal — dispatch may over-approximate)."""
    if not (_forced or schedsan.active() or faultline.active()):
        return
    missing = set(expected) - set(delivered)
    if missing:
        _violate(site, f"indexed dispatch missed {len(missing)} "
                       f"watcher(s) the re-check requires: "
                       f"{sorted(map(repr, missing))[:4]}")


def composite_sticky(site: str, old_rv: object, new_rv: object) -> None:
    """Assert a composite (``"shard.counter"``) resume point was not
    overwritten by a bare single-int revision — the informer's resume
    guard must have held."""
    if not (_forced or schedsan.active() or faultline.active()):
        return
    if "." in str(old_rv) and new_rv is not None \
            and "." not in str(new_rv):
        _violate(site, f"composite resume point {old_rv!r} overwritten "
                       f"by single-int revision {new_rv!r}")
