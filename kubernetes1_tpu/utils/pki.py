"""Cluster PKI: real x509 certificates for every wire in the system.

Ref: cmd/kubeadm/app/phases/certs/certs.go:37 (CreatePKIAssets: CA,
apiserver serving cert, component client certs), pkiutil (NewCertAndKey),
and the kubelet TLS-bootstrap flow (CSR in, signed client cert out —
pkg/controller/certificates/signer).

Design notes (TPU-first, not a Go translation):
- EC P-256 keys everywhere: handshake + issuance are ~10x faster than RSA
  on the wimpy control-plane hosts that sit next to TPU pods, and every
  TLS stack in the image speaks it.
- One dual-EKU node certificate (clientAuth + serverAuth, SANs for the
  node's addresses) instead of kubeadm's separate kubelet client/serving
  pair: the kubelet both dials the apiserver and serves :10250, and a
  single CSR round-trip keeps `ktpu join` one-shot.
- CA "hash" for join-time discovery pinning is sha256 over the CA cert
  DER (kubeadm pins the SPKI; whole-cert pinning is strictly stronger
  and one line).
"""

from __future__ import annotations

import datetime
import hashlib
import ipaddress
import os
from typing import Iterable, List, Optional, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

_ONE_DAY = datetime.timedelta(days=1)


def _new_key():
    return ec.generate_private_key(ec.SECP256R1())


def _key_pem(key) -> str:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    ).decode()


def _load_key(key_pem: str):
    return serialization.load_pem_private_key(key_pem.encode(), password=None)


def _load_cert(cert_pem: str) -> x509.Certificate:
    return x509.load_pem_x509_certificate(cert_pem.encode())


def _subject(cn: str, orgs: Iterable[str]) -> x509.Name:
    parts = [x509.NameAttribute(NameOID.COMMON_NAME, cn)]
    parts += [x509.NameAttribute(NameOID.ORGANIZATION_NAME, o) for o in orgs]
    return x509.Name(parts)


def _san_list(dns_sans: Iterable[str], ip_sans: Iterable[str]) -> List:
    sans: List = [x509.DNSName(d) for d in dns_sans]
    for ip in ip_sans:
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(ip)))
        except ValueError:
            sans.append(x509.DNSName(ip))  # hostname slipped into ip list
    return sans


def create_ca(cn: str = "ktpu-ca", days: int = 3650) -> Tuple[str, str]:
    """Self-signed CA. Returns (cert_pem, key_pem)."""
    key = _new_key()
    now = datetime.datetime.now(datetime.timezone.utc)
    name = _subject(cn, [])
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - _ONE_DAY)
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True, key_cert_sign=True, crl_sign=True,
                content_commitment=False, key_encipherment=False,
                data_encipherment=False, key_agreement=False,
                encipher_only=False, decipher_only=False),
            critical=True)
        .sign(key, hashes.SHA256())
    )
    return cert.public_bytes(serialization.Encoding.PEM).decode(), _key_pem(key)


def issue_cert(
    ca_cert_pem: str,
    ca_key_pem: str,
    cn: str,
    orgs: Iterable[str] = (),
    dns_sans: Iterable[str] = (),
    ip_sans: Iterable[str] = (),
    client: bool = False,
    server: bool = False,
    days: int = 365,
) -> Tuple[str, str]:
    """Issue a leaf cert + fresh key. Returns (cert_pem, key_pem)."""
    key = _new_key()
    cert_pem = _build_leaf(
        ca_cert_pem, ca_key_pem, key.public_key(), _subject(cn, orgs),
        dns_sans, ip_sans, client, server, days)
    return cert_pem, _key_pem(key)


def _build_leaf(ca_cert_pem, ca_key_pem, public_key, subject,
                dns_sans, ip_sans, client, server, days) -> str:
    ca_cert = _load_cert(ca_cert_pem)
    ca_key = _load_key(ca_key_pem)
    now = datetime.datetime.now(datetime.timezone.utc)
    ekus = []
    if client:
        ekus.append(ExtendedKeyUsageOID.CLIENT_AUTH)
    if server:
        ekus.append(ExtendedKeyUsageOID.SERVER_AUTH)
    b = (
        x509.CertificateBuilder()
        .subject_name(subject)
        .issuer_name(ca_cert.subject)
        .public_key(public_key)
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - _ONE_DAY)
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                       critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True, key_encipherment=False,
                content_commitment=False, data_encipherment=False,
                key_agreement=False, key_cert_sign=False, crl_sign=False,
                encipher_only=False, decipher_only=False),
            critical=True)
    )
    if ekus:
        b = b.add_extension(x509.ExtendedKeyUsage(ekus), critical=False)
    sans = _san_list(dns_sans, ip_sans)
    if sans:
        b = b.add_extension(x509.SubjectAlternativeName(sans), critical=False)
    cert = b.sign(ca_key, hashes.SHA256())
    return cert.public_bytes(serialization.Encoding.PEM).decode()


def create_csr(
    cn: str,
    orgs: Iterable[str] = (),
    dns_sans: Iterable[str] = (),
    ip_sans: Iterable[str] = (),
) -> Tuple[str, str]:
    """PEM CSR + its private key (the kubelet's side of TLS bootstrap)."""
    key = _new_key()
    b = x509.CertificateSigningRequestBuilder().subject_name(_subject(cn, orgs))
    sans = _san_list(dns_sans, ip_sans)
    if sans:
        b = b.add_extension(x509.SubjectAlternativeName(sans), critical=False)
    csr = b.sign(key, hashes.SHA256())
    return csr.public_bytes(serialization.Encoding.PEM).decode(), _key_pem(key)


def csr_identity(csr_pem: str) -> Tuple[str, List[str]]:
    """(CN, organizations) a CSR asks for — the approver checks these
    against the requesting user before the signer ever runs."""
    csr = x509.load_pem_x509_csr(csr_pem.encode())
    cn = ""
    orgs: List[str] = []
    for attr in csr.subject:
        if attr.oid == NameOID.COMMON_NAME:
            cn = str(attr.value)
        elif attr.oid == NameOID.ORGANIZATION_NAME:
            orgs.append(str(attr.value))
    return cn, orgs


def sign_csr(
    ca_cert_pem: str,
    ca_key_pem: str,
    csr_pem: str,
    client: bool = False,
    server: bool = False,
    days: int = 365,
) -> str:
    """Sign a PEM CSR with the cluster CA, preserving subject + SANs.
    The CSR's signature is verified first (proof-of-possession)."""
    csr = x509.load_pem_x509_csr(csr_pem.encode())
    if not csr.is_signature_valid:
        raise ValueError("CSR signature invalid")
    dns_sans: List[str] = []
    ip_sans: List[str] = []
    try:
        san = csr.extensions.get_extension_for_class(
            x509.SubjectAlternativeName).value
        dns_sans = san.get_values_for_type(x509.DNSName)
        ip_sans = [str(ip) for ip in san.get_values_for_type(x509.IPAddress)]
    except x509.ExtensionNotFound:
        pass
    return _build_leaf(ca_cert_pem, ca_key_pem, csr.public_key(),
                       csr.subject, dns_sans, ip_sans, client, server, days)


def cert_identity(cert_pem: str) -> Tuple[str, List[str]]:
    """(CN, organizations) of a leaf cert — the x509 authn mapping
    (CN=username, O=groups; staging authenticator/request/x509)."""
    cert = _load_cert(cert_pem)
    cn = ""
    orgs: List[str] = []
    for attr in cert.subject:
        if attr.oid == NameOID.COMMON_NAME:
            cn = str(attr.value)
        elif attr.oid == NameOID.ORGANIZATION_NAME:
            orgs.append(str(attr.value))
    return cn, orgs


def is_pem_csr(data: str) -> bool:
    return "-----BEGIN CERTIFICATE REQUEST-----" in (data or "")


def ca_cert_hash(ca_cert_pem: str) -> str:
    """`sha256:<hex>` pin for join-time discovery (kubeadm's
    --discovery-token-ca-cert-hash role)."""
    der = _load_cert(ca_cert_pem).public_bytes(serialization.Encoding.DER)
    return "sha256:" + hashlib.sha256(der).hexdigest()


def write_pki(dir_path: str, name: str, cert_pem: str,
              key_pem: Optional[str] = None) -> Tuple[str, str]:
    """Write <name>.crt (+ <name>.key, 0600). Returns their paths."""
    os.makedirs(dir_path, exist_ok=True)
    cert_path = os.path.join(dir_path, f"{name}.crt")
    with open(cert_path, "w") as f:  # ktpulint: ignore[KTPU012] bootstrap-time cert material for the operator — written once before any component serves; a failure here aborts startup loudly, there is no recovery path to chaos-test
        f.write(cert_pem)
    key_path = ""
    if key_pem is not None:
        key_path = os.path.join(dir_path, f"{name}.key")
        fd = os.open(key_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(key_pem)
    return cert_path, key_path
