"""Shared entrypoint helpers for the component binaries."""

from __future__ import annotations

import os
import threading


def bounded_exit(delay: float = 5.0, code: int = 1) -> threading.Timer:
    """Arm a daemon timer that hard-exits if graceful shutdown hangs (a
    dead apiserver must not leave a binary wedged in informer-retry joins
    forever).  Daemonized so a CLEAN stop is not padded by the timeout;
    callers may .cancel() after their stop() returns.  Exits NONZERO: a
    truncated shutdown is a failure a supervisor (Restart=on-failure) must
    see, not a clean stop."""
    timer = threading.Timer(delay, lambda: os._exit(code))
    timer.daemon = True
    timer.start()
    return timer


def read_key(path: str, default: str) -> str:
    """Key-file flag helper: file content when a path is given, else the
    development default."""
    return open(path).read().strip() if path else default


def add_client_args(ap) -> None:
    """The shared client-connection flag set every component binary takes
    (ref: each cmd/* --kubeconfig): --kubeconfig overrides the individual
    --server/--token/--ca-file/--client-{cert,key}-file flags."""
    ap.add_argument("--kubeconfig", default="",
                    help='JSON {"server","token"?,"ca"?,"cert"?,"key"?}')
    ap.add_argument("--ca-file", default="",
                    help="CA bundle to verify the apiserver's TLS cert")
    ap.add_argument("--client-cert-file", default="",
                    help="x509 client cert (CN=user, O=groups)")
    ap.add_argument("--client-key-file", default="")


def clientset_from_args(args):
    """Build the component's Clientset from add_client_args flags."""
    from ..client import Clientset

    if getattr(args, "kubeconfig", ""):
        return Clientset.from_config(args.kubeconfig)
    return Clientset(args.server, token=args.token,
                     ca_file=getattr(args, "ca_file", ""),
                     cert_file=getattr(args, "client_cert_file", ""),
                     key_file=getattr(args, "client_key_file", ""))
