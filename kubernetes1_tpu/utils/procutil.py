"""Shared entrypoint helpers for the component binaries."""

from __future__ import annotations

import os
import threading


def bounded_exit(delay: float = 5.0, code: int = 1) -> threading.Timer:
    """Arm a daemon timer that hard-exits if graceful shutdown hangs (a
    dead apiserver must not leave a binary wedged in informer-retry joins
    forever).  Daemonized so a CLEAN stop is not padded by the timeout;
    callers may .cancel() after their stop() returns.  Exits NONZERO: a
    truncated shutdown is a failure a supervisor (Restart=on-failure) must
    see, not a clean stop."""
    timer = threading.Timer(delay, lambda: os._exit(code))
    timer.daemon = True
    timer.start()
    return timer


def read_key(path: str, default: str) -> str:
    """Key-file flag helper: file content when a path is given, else the
    development default."""
    return open(path).read().strip() if path else default
