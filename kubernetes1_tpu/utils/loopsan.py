"""loopsan: dispatcher-blocking sanitizer — the runtime twin of KTPU016.

The static pass (tools/ktpulint/callgraph.py) proves "no blocking
primitive is REACHABLE from dispatcher-run code" over the call graph it
can resolve.  What it cannot see — callbacks built at runtime, dynamic
dispatch it declined to guess, lag from plain CPU hogging — this module
catches live: the dispatcher thread is marked, the blocking primitives
the classifier knows (``time.sleep``, blocking socket I/O,
``queue.Queue.get``, ``Future.result``) are patched to RAISE
``BlockingOnDispatcherError`` when invoked on that thread, and the
error carries the callback's REGISTRATION SITE (who scheduled this
callback, from where) plus the live call stack — turning "the loop got
slow" into a one-line attribution.

Two hazards are measured rather than raised:

- lock waits: a dispatcher callback acquiring a transiently contended
  leaf lock is legal (the static pass sanctions bounded leaf locks);
  locksan's acquire path reports the measured wait here, and waits over
  the stall threshold land in the flight recorder;
- dispatcher lag: the event loop reports timer fire lag here, and lag
  over the threshold (``KTPU_LOOPSAN_STALL_S``, default 0.25s) notes a
  ``DISPATCHER_STALL`` flight-recorder event (rate-limited) — the black
  box shows WHEN the loop fell behind even if no primitive raised.

Family contract (schedsan/mutsan shape):
  - ``KTPU_LOOPSAN=1`` in the environment arms at import (how tier-1
    arms it via conftest, subprocesses inherit with zero plumbing);
  - ``activate()`` / ``deactivate()`` arm programmatically (racesweep,
    chaos schedules, cluster_life);
  - identity when inactive: the loop marks its thread unconditionally
    (one set-add per loop LIFETIME), everything else is behind one
    ``active()`` test and the primitives are only patched while armed.

Deliberate perturbation is exempt: sleeps issued from schedsan (seeded
preemption), faultline (injected delay), and locksan's own machinery are
the sanitizers talking, not product blocking — same frames the static
pass exempts.  Non-blocking sockets (``gettimeout() == 0``) never stall
by construction and pass through, which is exactly why _WatchConn's
recv/send are statically pragma'd AND runtime-clean.
"""

from __future__ import annotations

import os
import socket as _socket_mod
import threading
import time
import traceback
from collections import deque
from typing import Callable, Dict, List, Optional

from . import flightrec

ENV_VAR = "KTPU_LOOPSAN"
STALL_ENV_VAR = "KTPU_LOOPSAN_STALL_S"
DEFAULT_STALL_S = 0.25

_VIOLATION_CAP = 256  # bounded: the sanitizer must never OOM on telemetry

# frames whose sleeps are the sanitizers' own perturbation, not product
# blocking (mirrors callgraph._EXEMPT_MODULE_SUFFIXES)
_EXEMPT_FILES = (f"{os.sep}schedsan.py", f"{os.sep}faultline.py",
                 f"{os.sep}locksan.py")


class BlockingOnDispatcherError(RuntimeError):
    """A blocking primitive ran on the marked dispatcher thread.

    Attributes carry the attribution the error message renders:
    ``primitive`` (what blocked), ``registration_site`` (file:line that
    scheduled the callback being run, '' when the callback predates
    arming), ``callback`` (its name), ``stack`` (formatted call stack at
    the blocking call)."""

    def __init__(self, primitive: str, registration_site: str,
                 callback: str, stack: str):
        self.primitive = primitive
        self.registration_site = registration_site
        self.callback = callback
        self.stack = stack
        where = (f"callback {callback!r} registered at {registration_site}"
                 if registration_site else
                 "a callback registered before loopsan was armed")
        super().__init__(
            f"{primitive} on the shared dispatcher thread ({where}) — "
            f"blocking work goes through eventloop.shared_pool(); the "
            f"dispatcher runs non-blocking state machines only.\n"
            f"stack at the blocking call:\n{stack}")


# Dispatcher idents are tracked UNCONDITIONALLY (set-add once per loop
# lifetime): arming mid-run — racesweep activates after the shared loop
# already started — must still know which thread is the dispatcher.
_dispatcher_idents: set = set()

# registration attribution for the callback currently running on each
# thread (set by the wrapper wrap_callback installs)
_tls = threading.local()


def mark_dispatcher() -> None:
    """Called by EventLoop._run on entry, on the loop thread."""
    _dispatcher_idents.add(threading.get_ident())


def unmark_dispatcher() -> None:
    _dispatcher_idents.discard(threading.get_ident())


def on_dispatcher() -> bool:
    return threading.get_ident() in _dispatcher_idents


class _State:
    """One armed session: violation ring + stall telemetry + the saved
    originals of every patched primitive."""

    def __init__(self, stall_threshold_s: float):
        self.stall_threshold_s = stall_threshold_s
        self._lock = threading.Lock()  # ktpulint: ignore[KTPU007] leaf lock inside the sanitizer itself; locksan's factory routes back here when loopsan arms it
        self.violation_ring: "deque[Dict[str, str]]" = deque(
            maxlen=_VIOLATION_CAP)
        self.violation_count = 0
        self.max_stall_s = 0.0
        self.stall_count = 0
        self._last_note = 0.0
        self.originals: Dict[str, Callable] = {}

    def record_violation(self, err: BlockingOnDispatcherError) -> None:
        with self._lock:
            self.violation_count += 1
            self.violation_ring.append({
                "primitive": err.primitive,
                "registration_site": err.registration_site,
                "callback": err.callback,
                "stack": err.stack,
            })

    def record_stall(self, source: str, seconds: float) -> None:
        note = False
        with self._lock:
            if seconds > self.max_stall_s:
                self.max_stall_s = seconds
            if seconds >= self.stall_threshold_s:
                self.stall_count += 1
                now = time.monotonic()
                if now - self._last_note >= 1.0:  # rate-limit the ring
                    self._last_note = now
                    note = True
        if note:
            flightrec.note("eventloop", flightrec.DISPATCHER_STALL,
                           source=source, stall_s=round(seconds, 4))


_state: Optional[_State] = None


def active() -> bool:
    return _state is not None


enabled = active  # locksan spells the question enabled(); keep both


def stats() -> Dict[str, object]:
    """The scorecard-facing summary: zeroes when inactive (the
    cluster_life ``loopsan`` block's keys never disappear)."""
    s = _state
    if s is None:
        return {"violations": 0, "max_stall_s": 0.0, "stalls": 0}
    with s._lock:
        return {"violations": s.violation_count,
                "max_stall_s": round(s.max_stall_s, 4),
                "stalls": s.stall_count}


def violations() -> List[Dict[str, str]]:
    """Recorded violation details (newest-bounded ring) — what the
    injected-blocking regression asserts registration sites against."""
    s = _state
    if s is None:
        return []
    with s._lock:
        return list(s.violation_ring)


# ------------------------------------------------------------ attribution


def wrap_callback(fn: Callable, kind: str) -> Callable:
    """Wrap a callback at REGISTRATION time (EventLoop does this while
    loopsan is active): capture the registering frame now, and publish it
    in thread-local state while the callback runs, so a primitive that
    raises mid-callback can name who scheduled it."""
    site = _registration_site()
    name = getattr(fn, "__name__", repr(fn))

    def _loopsan_wrapped():
        prev = getattr(_tls, "reg", None)
        _tls.reg = (site, f"{kind}:{name}")
        try:
            fn()
        finally:
            _tls.reg = prev

    _loopsan_wrapped.__name__ = name  # keep _guard's error reports readable
    return _loopsan_wrapped


def wrap_io_callback(fn: Callable, kind: str) -> Callable:
    """Same as wrap_callback for selector callbacks (they take the ready
    mask as an argument)."""
    site = _registration_site()
    name = getattr(fn, "__name__", repr(fn))

    def _loopsan_wrapped(mask):
        prev = getattr(_tls, "reg", None)
        _tls.reg = (site, f"{kind}:{name}")
        try:
            fn(mask)
        finally:
            _tls.reg = prev

    _loopsan_wrapped.__name__ = name
    return _loopsan_wrapped


def _registration_site() -> str:
    """file:line of the first stack frame outside the loop machinery —
    the code that asked for this callback to run."""
    here = os.path.dirname(os.path.abspath(__file__))
    skip = (os.path.join(here, "loopsan.py"),
            os.path.join(here, "eventloop.py"))
    for frame in traceback.extract_stack()[::-1]:
        if frame.filename not in skip:
            return f"{os.path.basename(frame.filename)}:{frame.lineno}"
    return ""


def _current_registration() -> tuple:
    reg = getattr(_tls, "reg", None)
    return reg if reg is not None else ("", "")


# ------------------------------------------------------------- enforcement


def _violate(primitive: str) -> None:
    site, cb = _current_registration()
    stack = "".join(traceback.format_stack()[-8:-1])
    err = BlockingOnDispatcherError(primitive, site, cb, stack)
    s = _state
    if s is not None:
        s.record_violation(err)
    raise err


def _caller_exempt() -> bool:
    """True when the blocking call was issued by sanitizer machinery
    (schedsan preemption sleeps, faultline injected delays)."""
    for frame in traceback.extract_stack()[-4:-1]:
        if frame.filename.endswith(_EXEMPT_FILES):
            return True
    return False


def note_lag(lag_s: float) -> None:
    """EventLoop reports each timer's fire lag here (one call per timer
    fire, behind the caller's active() test)."""
    s = _state
    if s is not None:
        s.record_stall("timer_lag", lag_s)


def note_lock_wait(lock_name: str, waited_s: float) -> None:
    """locksan reports a measured dispatcher-side lock wait.  Contended
    leaf locks are LEGAL (briefly) — this records the stall instead of
    raising, and the flight recorder catches the pathological ones."""
    s = _state
    if s is not None and waited_s > 0.0:
        s.record_stall(f"lock_wait:{lock_name}", waited_s)


# Patched primitives.  Each guard answers three questions in order: is
# this the dispatcher thread?  would this call actually block?  is the
# caller exempt machinery?  Only then it raises.


def _patched_sleep(orig):
    def sleep(seconds):
        if on_dispatcher() and seconds and not _caller_exempt():
            _violate(f"time.sleep({seconds!r})")
        return orig(seconds)

    return sleep


def _patched_queue_get(orig):
    def get(self, block=True, timeout=None):
        if on_dispatcher() and block and timeout != 0:
            _violate("queue.Queue.get(block=True)")
        return orig(self, block, timeout)

    return get


def _patched_future_result(orig):
    def result(self, timeout=None):
        if on_dispatcher() and timeout != 0 and not self.done():
            _violate("Future.result() on an unfinished future")
        return orig(self, timeout)

    return result


def _patched_sock(orig, label):
    def method(self, *args, **kwargs):
        if on_dispatcher() and self.gettimeout() != 0:
            # a non-blocking socket (timeout 0) returns or raises
            # BlockingIOError — it cannot stall the loop
            _violate(f"blocking socket.{label}")
        return orig(self, *args, **kwargs)

    return method


_SOCKET_PATCHES = ("send", "sendall", "recv", "recv_into", "accept",
                   "connect")


def activate(stall_threshold_s: Optional[float] = None) -> None:
    """Arm process-wide: patch the blocking primitives and start
    recording.  Idempotent (re-arming keeps the existing session)."""
    global _state
    if _state is not None:
        return
    if stall_threshold_s is None:
        stall_threshold_s = float(
            os.environ.get(STALL_ENV_VAR, "") or DEFAULT_STALL_S)
    s = _State(stall_threshold_s)
    s.originals["time.sleep"] = time.sleep
    time.sleep = _patched_sleep(time.sleep)
    import queue as _queue

    s.originals["queue.Queue.get"] = _queue.Queue.get
    _queue.Queue.get = _patched_queue_get(_queue.Queue.get)
    from concurrent.futures import Future as _Future

    s.originals["Future.result"] = _Future.result
    _Future.result = _patched_future_result(_Future.result)
    for name in _SOCKET_PATCHES:
        orig = getattr(_socket_mod.socket, name)
        s.originals[f"socket.{name}"] = orig
        # socket.socket is the Python subclass of the C _socket.socket:
        # setting the attribute installs a Python-level override without
        # touching the C type
        setattr(_socket_mod.socket, name, _patched_sock(orig, name))
    _state = s


def deactivate() -> None:
    """Disarm and restore every patched primitive."""
    global _state
    s = _state
    if s is None:
        return
    _state = None
    time.sleep = s.originals["time.sleep"]
    import queue as _queue

    _queue.Queue.get = s.originals["queue.Queue.get"]
    from concurrent.futures import Future as _Future

    _Future.result = s.originals["Future.result"]
    for name in _SOCKET_PATCHES:
        setattr(_socket_mod.socket, name, s.originals[f"socket.{name}"])


if os.environ.get(ENV_VAR, "") not in ("", "0"):
    activate()
