"""Pod-startup SLIs: watch-driven decomposition of create→Running.

Fleet-scale TPU operations care about end-to-end goodput, not
per-component averages ("ML Productivity Goodput", PAPERS.md), and the
Kubernetes GenAI-inference literature treats pod-startup latency as THE
primary SLI.  This tracker turns the control plane's phase stamps into
per-phase Prometheus histograms:

  phase="scheduled"          created-at     → scheduled-at   (algorithm)
  phase="bind"               scheduled-at   → bound-at       (bind commit)
  phase="admitted"           bound-at       → admitted-at    (kubelet +
                                              device plugin AdmitPod)
  phase="running"            admitted-at    → Running observed
  phase="device_allocation"  scheduled-at   → admitted-at    (TPU pods:
                 scheduler's device-ID pick through the kubelet/plugin
                 allocation that injects /dev/accel*; only observed for
                 pods requesting extended resources)
  phase="total"              created-at     → Running observed

The stamps are wall-clock annotations written by the component that owns
each transition (see api/types.py SLO annotations); "Running observed" is
this tracker's own watch-event receipt, so the total includes watch fanout
— exactly what a user-facing "my pod is up" SLI should count.  Stamps from
different processes assume one machine's clock (the localcluster/bench
topology); cross-host deployments inherit NTP skew like any SLI pipeline.

Metrics land in a Registry (labeled histogram with cumulative `_bucket`
series, utils/metrics.py) exported on an optional MetricsServer at
`/metrics`; bench.py reads `report()` in-process.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ..api import types as t
from . import locksan
from .metrics import MetricsServer, Registry

PHASE_METRIC = "ktpu_pod_startup_phase_seconds"

# (phase label, start stamp key, end stamp key); None = Running observation
_PHASES = (
    ("scheduled", t.CREATED_AT_ANNOTATION, t.SCHEDULED_AT_ANNOTATION),
    ("bind", t.SCHEDULED_AT_ANNOTATION, t.BOUND_AT_ANNOTATION),
    ("admitted", t.BOUND_AT_ANNOTATION, t.ADMITTED_AT_ANNOTATION),
    ("running", t.ADMITTED_AT_ANNOTATION, None),
    ("total", t.CREATED_AT_ANNOTATION, None),
)


def _stamp(pod, key: str) -> Optional[float]:
    raw = (pod.metadata.annotations or {}).get(key)
    if not raw:
        return None
    try:
        return float(raw)
    except (TypeError, ValueError):
        return None


class StartupSLITracker:
    """Watches pods and feeds the per-phase startup histograms.

    Runs anywhere a Clientset reaches the apiserver — inside the
    localcluster (wired by LocalCluster), beside bench.py, or as its own
    process.  Pods already Running (or already bound with no created-at
    stamp) at first sight are ignored: their transitions predate this
    tracker and observation time would fabricate latencies."""

    def __init__(self, clientset, registry: Optional[Registry] = None,
                 metrics_port: Optional[int] = None):
        from ..client import SharedInformer

        self.registry = registry or Registry()
        self.phase_seconds = self.registry.histogram(
            PHASE_METRIC,
            "pod-startup latency decomposed per phase (label phase=...)")
        self.pods_started = self.registry.counter(
            "ktpu_pods_started_total",
            "pods observed reaching Running with full SLI decomposition")
        self.informer = SharedInformer(clientset.pods)
        self._lock = locksan.make_lock("StartupSLITracker._lock")
        self._seen: Dict[str, dict] = {}  # uid -> {"done": bool, ...}
        self.metrics_server: Optional[MetricsServer] = None
        if metrics_port is not None:
            self.metrics_server = MetricsServer(
                self.registry, port=metrics_port,
                ready_fn=self.informer.has_synced)

    # ---------------------------------------------------------------- wiring

    def start(self) -> "StartupSLITracker":
        self.informer.add_handler(
            on_add=self._on_event,
            on_update=lambda _old, pod: self._on_event(pod),
            on_delete=self._on_delete,
        )
        self.informer.start()
        if self.metrics_server is not None:
            self.metrics_server.start()
        return self

    def stop(self):
        self.informer.stop()
        if self.metrics_server is not None:
            self.metrics_server.stop()

    # ------------------------------------------------------------- recording

    def _on_event(self, pod):
        self.record(pod, now=time.time())  # ktpulint: ignore[KTPU005] compared against wall-clock SLI stamps

    def _on_delete(self, pod):
        with self._lock:
            self._seen.pop(pod.metadata.uid, None)

    def record(self, pod, now: float):
        """Observe one watch event for `pod` at wall time `now`.  Pure
        state-machine + histogram math — tests drive it directly."""
        uid = pod.metadata.uid
        running = pod.status.phase == t.POD_RUNNING
        with self._lock:
            rec = self._seen.get(uid)
            if rec is None:
                # replayed history: a pod that reaches us already Running
                # (or mid-flight with no creation stamp) can't be decomposed
                ignore = running or (bool(pod.spec.node_name)
                                     and _stamp(pod, t.CREATED_AT_ANNOTATION)
                                     is None)
                rec = self._seen[uid] = {"done": ignore}
            if rec["done"] or not running:
                return
            rec["done"] = True
        stamps = {key: _stamp(pod, key)
                  for _, key, _ in _PHASES if key is not None}
        complete = True
        for phase, start_key, end_key in _PHASES:
            start = stamps.get(start_key)
            end = now if end_key is None else _stamp(pod, end_key)
            if start is None or end is None or end < start:
                complete = False
                continue
            self.phase_seconds.labels(phase=phase).observe(end - start)
        if pod.spec.extended_resources:
            start = _stamp(pod, t.SCHEDULED_AT_ANNOTATION)
            end = _stamp(pod, t.ADMITTED_AT_ANNOTATION)
            if start is not None and end is not None and end >= start:
                self.phase_seconds.labels(
                    phase="device_allocation").observe(end - start)
        if complete:
            self.pods_started.inc()

    # -------------------------------------------------------------- readouts

    def report(self) -> dict:
        """Per-phase summary for bench.py: {phase: {count, p50_s, p99_s}}."""
        out = {}
        phases = [p for p, _, _ in _PHASES] + ["device_allocation"]
        for phase in phases:
            h = self.phase_seconds.labels(phase=phase)
            if not h.count:
                continue
            out[phase] = {
                "count": h.count,
                "p50_s": round(h.quantile(0.5) or 0.0, 4),
                "p99_s": round(h.quantile(0.99) or 0.0, 4),
            }
        return out
