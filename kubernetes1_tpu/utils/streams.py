"""Bidirectional streaming over an upgraded HTTP connection.

Ref: the reference streams exec/attach/port-forward over SPDY channels
(pkg/kubelet/server/remotecommand, client-go/tools/remotecommand) or
WebSocket.  The TPU-native wire form here is a minimal channel-framed
protocol over a hijacked socket:

    client:  GET/POST <path> HTTP/1.1
             Connection: Upgrade
             Upgrade: ktpu-stream
    server:  HTTP/1.1 101 Switching Protocols  (then raw frames both ways)

frame  = channel(1 byte) | length(4 bytes big-endian) | payload
channels mirror SPDY's: 0 stdin, 1 stdout, 2 stderr, 3 error/status,
4 resize.  A zero-length frame on a stream channel means EOF for that
channel.  The error channel carries one UTF-8 JSON object
{"exitCode": N, "error": "..."} and closes the stream.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Optional, Tuple

from . import faultline

UPGRADE_PROTO = "ktpu-stream"

STDIN, STDOUT, STDERR, ERROR, RESIZE = 0, 1, 2, 3, 4

_HEADER = struct.Struct(">BI")
MAX_FRAME = 1 << 20


def write_frame(sock: socket.socket, channel: int, payload: bytes):
    sock.sendall(_HEADER.pack(channel, len(payload)) + payload)


def read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def read_frame(sock: socket.socket) -> Optional[Tuple[int, bytes]]:
    """(channel, payload) or None on EOF/garbage."""
    header = read_exact(sock, _HEADER.size)
    if header is None:
        return None
    channel, length = _HEADER.unpack(header)
    if length > MAX_FRAME:
        return None
    if length == 0:
        return channel, b""
    payload = read_exact(sock, length)
    if payload is None:
        return None
    return channel, payload


def send_status(sock: socket.socket, exit_code: int, error: str = ""):
    try:
        write_frame(sock, ERROR, json.dumps(
            {"exitCode": exit_code, "error": error}).encode())
    except OSError:
        pass


def quiet_connection_errors(httpd):
    """Peer-gone noise (a watcher hanging up mid-stream, a plaintext probe
    or wrong-CA handshake on a TLS port, a scanner) is routine on any
    server socket — drop it instead of stack-tracing to stderr."""
    import ssl as _ssl
    import sys as _sys

    orig = httpd.handle_error

    def handle_error(request, client_address):
        exc = _sys.exc_info()[1]
        if isinstance(exc, (_ssl.SSLError, ConnectionError, TimeoutError)):
            return
        orig(request, client_address)

    httpd.handle_error = handle_error


# back-compat alias (TLS servers were the first callers)
quiet_tls_errors = quiet_connection_errors


class UpgradeRefused(ConnectionError):
    """The server answered the Upgrade handshake with a real HTTP status
    instead of 101 — it is alive but does not serve this stream (an older
    apiserver's 404, an authz 403).  `status` carries the code (0 when
    the head was unparseable) so callers can distinguish does-not-speak
    (stick to the fallback path) from transient transport failure."""

    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = status


def upgrade_request(host: str, port: int, path: str, headers: dict,
                    timeout: float = 30.0, ssl_context=None,
                    proto: str = UPGRADE_PROTO) -> socket.socket:
    """Open a socket (TLS when ssl_context is given), perform the Upgrade
    handshake, return the socket ready for frames.  Raises UpgradeRefused
    (a ConnectionError) on a non-101 response."""
    # stream.upgrade: the exec/attach/port-forward dial leg (client->
    # apiserver and apiserver->kubelet both ride this helper); FaultInjected
    # is a ConnectionError, which every caller already classifies
    faultline.check("stream.upgrade")
    sock = socket.create_connection((host, port), timeout=timeout)
    if ssl_context is not None:
        sock = ssl_context.wrap_socket(sock, server_hostname=host)
    try:
        lines = [f"GET {path} HTTP/1.1", f"Host: {host}:{port}",
                 "Connection: Upgrade", f"Upgrade: {proto}"]
        for k, v in headers.items():
            lines.append(f"{k}: {v}")
        sock.sendall(("\r\n".join(lines) + "\r\n\r\n").encode())
        status = _read_http_head(sock)
        if " 101 " not in status.split("\r\n", 1)[0] + " ":
            body = status.split("\r\n\r\n", 1)[-1][:300]
            first = status.splitlines()[0] if status else "EOF"
            try:
                code = int(first.split(" ", 2)[1])
            except (IndexError, ValueError):
                code = 0
            raise UpgradeRefused(
                f"upgrade refused: {first}" + (f" — {body}" if body else ""),
                status=code)
        sock.settimeout(None)
        return sock
    except BaseException:
        sock.close()
        raise


def _read_http_head(sock: socket.socket) -> str:
    """Read up to the end of the HTTP response head (and any tiny error
    body that arrives with it)."""
    data = b""
    while b"\r\n\r\n" not in data:
        chunk = sock.recv(4096)
        if not chunk:
            break
        data += chunk
        if len(data) > 65536:
            break
    return data.decode(errors="replace")


def accept_upgrade(handler, proto: str = UPGRADE_PROTO) -> Optional[socket.socket]:
    """Server side: validate the Upgrade header on a BaseHTTPRequestHandler,
    send 101, and return the hijacked socket (caller owns it afterwards)."""
    if handler.headers.get("Upgrade", "").lower() != proto:
        return None
    handler.send_response(101, "Switching Protocols")
    handler.send_header("Upgrade", proto)
    handler.send_header("Connection", "Upgrade")
    handler.end_headers()
    handler.wfile.flush()
    sock = handler.connection
    handler.close_connection = True
    return sock


def splice(a: socket.socket, b: socket.socket):
    """Raw byte relay both directions until either side closes — the
    apiserver's proxy hop (it terminates the handshake on each side and
    then has no need to reframe)."""
    import threading

    def pump(src, dst):
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

    t = threading.Thread(target=pump, args=(b, a), daemon=True)
    t.start()
    pump(a, b)
    t.join(timeout=5.0)
