"""Deduplicating, rate-limited work queues for controllers.

Ref: client-go util/workqueue/{queue,delaying_queue,default_rate_limiters}.go.
Semantics preserved from the reference:
- an item added while queued is coalesced (dedup on dirty set);
- an item added while being processed is re-queued when done() is called;
- RateLimitingQueue.add_rate_limited applies per-item exponential backoff,
  forget() resets it — this is what gives controllers retry-with-backoff.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Any, Hashable, Optional

from . import locksan, schedsan


class WorkQueue:
    def __init__(self):
        self._cond = locksan.make_condition(name="WorkQueue._cond")
        self._queue: list = []
        self._dirty: set = set()
        self._processing: set = set()
        self._shutdown = False

    def add(self, item: Hashable):
        # dedup races (add-while-queued vs add-while-processing) live in
        # the window before the condition lock — widen it under schedsan
        schedsan.preempt("workqueue.add")
        with self._cond:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return
            self._queue.append(item)
            self._cond.notify()

    def get(self, timeout: Optional[float] = None):
        """Blocks; returns None on shutdown or timeout."""
        schedsan.preempt("workqueue.get")
        with self._cond:
            deadline = time.monotonic() + timeout if timeout is not None else None
            while not self._queue and not self._shutdown:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                self._cond.wait(remaining)
            if self._shutdown and not self._queue:
                return None
            item = self._queue.pop(0)
            self._processing.add(item)
            self._dirty.discard(item)
            return item

    def done(self, item: Hashable):
        with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    def len(self) -> int:
        with self._cond:
            return len(self._queue)

    def shut_down(self):
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    @property
    def shutting_down(self) -> bool:
        with self._cond:
            return self._shutdown


class DelayingQueue(WorkQueue):
    """WorkQueue plus add_after(item, delay)."""

    def __init__(self):
        super().__init__()
        self._heap: list = []  # (ready_at, seq, item)
        self._seq = 0
        self._timer_cond = locksan.make_condition(name="DelayingQueue._timer_cond")
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def add_after(self, item: Hashable, delay: float):
        if delay <= 0:
            self.add(item)
            return
        with self._timer_cond:
            heapq.heappush(self._heap, (time.monotonic() + delay, self._seq, item))
            self._seq += 1
            self._timer_cond.notify()

    def _loop(self):
        while True:
            with self._timer_cond:
                if self.shutting_down:
                    return
                now = time.monotonic()
                ready = []
                while self._heap and self._heap[0][0] <= now:
                    ready.append(heapq.heappop(self._heap)[2])
                wait = (self._heap[0][0] - now) if self._heap else 0.5
            for item in ready:
                self.add(item)
            with self._timer_cond:
                self._timer_cond.wait(min(wait, 0.5))

    def shut_down(self):
        super().shut_down()
        with self._timer_cond:
            self._timer_cond.notify_all()


class RateLimitingQueue(DelayingQueue):
    """Per-item exponential backoff (5ms base doubling to 1000s by default —
    the reference's DefaultControllerRateLimiter)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        super().__init__()
        self._base = base_delay
        self._max = max_delay
        self._failures: dict = {}
        self._fail_lock = locksan.make_lock("RateLimitingQueue._fail_lock")

    def add_rate_limited(self, item: Hashable):
        with self._fail_lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        delay = min(self._base * (2 ** n), self._max)
        self.add_after(item, delay)

    def forget(self, item: Hashable):
        with self._fail_lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Hashable) -> int:
        with self._fail_lock:
            return self._failures.get(item, 0)
