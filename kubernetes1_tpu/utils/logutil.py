"""Small logging helpers for best-effort paths.

`RateLimitedReporter` is the shared shape for "count every drop, emit at
most one summary line per window": best-effort subsystems (event sink,
DNS receive loop, audit webhook) must not be silent about failures, but
a per-occurrence print turns an outage or a packet flood into a stderr
flood exactly when the operator is reading the logs.
"""

from __future__ import annotations

import sys
import time


class RateLimitedReporter:
    """Count occurrences; print one `<prefix>: dropped N (<detail>)`
    summary line per `window` seconds.  The first occurrence after a
    quiet period reports immediately, so a single failure is never
    silent.  Intended for use from one thread at a time (each subsystem's
    own loop); a lost increment under rare concurrent use only undercounts
    a log line."""

    def __init__(self, prefix: str, window: float = 5.0, stream=None):
        self.prefix = prefix
        self.window = window
        self.stream = stream
        self._count = 0
        self._last = 0.0

    def report(self, detail: str, n: int = 1):
        self._count += n
        now = time.monotonic()
        if now - self._last >= self.window:
            print(f"{self.prefix}: dropped {self._count} ({detail})",
                  file=self.stream or sys.stderr)
            self._count = 0
            self._last = now
