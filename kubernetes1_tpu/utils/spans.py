"""Cross-component request tracing: span contexts, propagation, collection.

Ref: the reference's Audit-ID request correlation (apiserver/pkg/endpoints/
filters/request_info + audit) and the utiltrace step logs it feeds — plus
the OpenTelemetry-shaped tracing kubernetes later grew (apiserver
--tracing-config).  Here the wire format is deliberately tiny:

- an `X-Ktpu-Trace: <trace-id>/<span-id>` header rides every client
  request (client/rest.py injects it from the thread's active span, or
  mints a fresh root context so every request is traceable);
- the apiserver extracts it, wraps request handling in a span, and stamps
  the trace id into created pods' metadata annotations
  (`trace.ktpu.io/trace-id`), so the id survives the watch path into the
  scheduler and kubelet — which open their own spans under the same
  trace id;
- finished spans land in a bounded per-component SpanCollector served as
  JSON at `/debug/traces` on each component's HTTP surface.

One pod's journey — apiserver create, scheduler algorithm, bind,
kubelet device admission, container start — is then a single trace id
queryable on three components, instead of five logs to grep.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, NamedTuple, Optional

from . import locksan

# Header carrying "<trace-id>/<span-id>" on every client request.  The
# annotation the apiserver stamps the trace id under lives with the other
# wire constants: api/types.py TRACE_ID_ANNOTATION.
HEADER = "X-Ktpu-Trace"


class SpanContext(NamedTuple):
    trace_id: str
    span_id: str


def new_id() -> str:
    return os.urandom(8).hex()


def format_context(ctx: SpanContext) -> str:
    return f"{ctx.trace_id}/{ctx.span_id}"


def parse_header(value: str) -> Optional[SpanContext]:
    """SpanContext from an X-Ktpu-Trace header value; None when absent or
    malformed (a bad header must never fail the request it rides on)."""
    if not value or "/" not in value:
        return None
    trace_id, _, span_id = value.partition("/")
    trace_id, span_id = trace_id.strip(), span_id.strip()
    if not trace_id or not span_id:
        return None
    return SpanContext(trace_id, span_id)


# ------------------------------------------------------------ active span

_tls = threading.local()


def current_span() -> Optional["Span"]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def current_trace_id() -> str:
    sp = current_span()
    return sp.trace_id if sp is not None else ""


def inject_header() -> str:
    """Header value for an outgoing request: the active span's context, or
    a fresh root context so even un-instrumented callers get a trace id."""
    sp = current_span()
    if sp is not None:
        return format_context(sp.context())
    return format_context(SpanContext(new_id(), new_id()))


class Span:
    """One timed operation within a trace.  Context-manager use activates
    it on the thread (so Trace objects and outgoing requests attach);
    exit finishes it into its collector, recording an in-flight exception
    as `error=<ExcType>`."""

    __slots__ = ("name", "component", "trace_id", "span_id", "parent_id",
                 "fields", "logs", "error", "start_wall", "_t0",
                 "_collector", "_finished")

    def __init__(self, name: str, component: str = "",
                 trace_id: str = "", parent_id: str = "",
                 collector: Optional["SpanCollector"] = None, **fields):
        self.name = name
        self.component = component
        self.trace_id = trace_id or new_id()
        self.span_id = new_id()
        self.parent_id = parent_id
        self.fields: Dict[str, object] = dict(fields)
        self.logs: List[tuple] = []  # (elapsed_s, msg)
        self.error = ""
        self.start_wall = time.time()  # ktpulint: ignore[KTPU005] user-visible span start timestamp
        self._t0 = time.perf_counter()
        self._collector = collector
        self._finished = False

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def annotate(self, **fields):
        self.fields.update(fields)

    def log(self, msg: str):
        self.logs.append((time.perf_counter() - self._t0, msg))

    def finish(self, error: str = ""):
        if self._finished:
            return
        self._finished = True
        if error:
            self.error = error
        if self._collector is not None:
            self._collector.add(self)

    def to_dict(self) -> dict:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "name": self.name,
            "component": self.component,
            "start": round(self.start_wall, 6),
            "durationMs": round((time.perf_counter() - self._t0) * 1000, 3),
            "fields": {k: str(v) for k, v in self.fields.items()},
            "logs": [f"[{at * 1000:.1f}ms] {msg}" for at, msg in self.logs],
            "error": self.error,
        }

    # -- context manager / thread activation --------------------------------

    def __enter__(self) -> "Span":
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        elif stack and self in stack:  # defensive: out-of-order exit
            stack.remove(self)
        self.finish(error=exc_type.__name__ if exc_type is not None else "")
        return False


class SpanCollector:
    """Bounded in-process store of finished spans, served at
    /debug/traces.  One per component; the deque keeps the newest
    `capacity` spans (forensics wants the recent tail, not history)."""

    def __init__(self, component: str = "", capacity: int = 1024):
        self.component = component
        self._spans: deque = deque(maxlen=capacity)
        self._lock = locksan.make_lock("SpanCollector._lock")

    def start_span(self, name: str, parent=None, trace_id: str = "",
                   **fields) -> Span:
        """New span under this collector.  `parent` may be a SpanContext,
        a Span, or None; an explicit trace_id (e.g. from a pod annotation)
        wins when no parent context is available."""
        parent_id = ""
        if isinstance(parent, Span):
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif isinstance(parent, SpanContext):
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif parent is None and not trace_id:
            active = current_span()
            if active is not None:
                trace_id, parent_id = active.trace_id, active.span_id
        return Span(name, component=self.component, trace_id=trace_id,
                    parent_id=parent_id, collector=self, **fields)

    def add(self, span: Span):
        with self._lock:
            self._spans.append(span.to_dict())

    def spans(self, trace_id: str = "") -> List[dict]:
        with self._lock:
            out = list(self._spans)
        if trace_id:
            out = [s for s in out if s["traceId"] == trace_id]
        return out

    def to_json(self, trace_id: str = "") -> bytes:
        return json.dumps({
            "component": self.component,
            "spans": self.spans(trace_id),
        }, separators=(",", ":")).encode()
