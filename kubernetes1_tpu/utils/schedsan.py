"""schedsan: seeded, deterministic thread-interleaving sanitizer.

Chaos for the GIL.  CPython's scheduler hides most interleaving bugs:
the interpreter switches threads every few milliseconds, so the narrow
windows — between a lock release and the next acquire, between an
enqueue and the leader-election test that decides who drains it —
almost never see a context switch under test.  They see one in
production, at 3am, once.

This module plants *preemption points* at every concurrency-sensitive
site the framework owns (locksan factory acquire/release, every
faultline site check, the store's group-commit leader election, the
cacher's ``_cond`` apply, workqueue get/put).  When activated, each
point draws from a seeded per-site RNG stream and decides to either
proceed, yield the GIL (``time.sleep(0)``), or take a jittered
micro-sleep — widening exactly the windows real schedulers hit, in a
schedule that is REPLAYABLE by seed.

Activation (either):
  - environment: ``KTPU_SCHEDSAN=<seed>`` (parsed at import, so spawned
    server subprocesses inherit the schedule with zero plumbing);
  - programmatic: ``schedsan.activate(seed)`` / ``deactivate()`` (what
    scripts/racesweep.py uses in-process).

Determinism contract (tests/test_schedsan.py pins it):
  - same seed ⇒ same per-site decision sequence — each site's stream is
    ``random.Random((seed << 32) ^ crc32(site))`` (the faultline trick),
    so one site's schedule never shifts another's;
  - per-site independence: interleaving calls at site B does not change
    the decisions site A sees;
  - identity when inactive: one module-global ``is None`` test on the
    hot path — no locks, no RNG, no allocation (faultline's shape).

Tuning: ``activate(seed, yield_prob=, sleep_prob=, max_sleep_s=)``.
Defaults (10% yield, 2% micro-sleep ≤ 2ms) keep a racesweep scenario
inside tens of milliseconds of added wall time while still forcing
thousands of adversarial switch points per run.
"""

from __future__ import annotations

import os
import random
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

ENV_VAR = "KTPU_SCHEDSAN"

# actions a preemption point can take (recorded in the trace)
PROCEED = "proceed"
YIELD = "yield"
SLEEP = "sleep"

_TRACE_CAP = 8192  # bounded: a sweep must never OOM on its own telemetry


class _Site:
    """One named preemption point: its own seeded RNG stream (decision
    sequences are a pure function of (seed, site)) and action counters."""

    __slots__ = ("name", "rng", "counts")

    def __init__(self, name: str, seed: int):
        self.name = name
        self.rng = random.Random((seed << 32) ^ zlib.crc32(name.encode()))
        self.counts = {PROCEED: 0, YIELD: 0, SLEEP: 0}


class Sampler:
    """The active schedule: per-site streams + a bounded decision trace."""

    def __init__(self, seed: int, yield_prob: float = 0.10,
                 sleep_prob: float = 0.02, max_sleep_s: float = 0.002):
        self.seed = int(seed)
        self.yield_prob = float(yield_prob)
        self.sleep_prob = float(sleep_prob)
        self.max_sleep_s = float(max_sleep_s)
        self._sites: Dict[str, _Site] = {}
        # leaf lock: serializes RNG draws + trace appends (Random is not
        # thread-safe for seeded use); held for nanoseconds, never while
        # sleeping — the sleep happens AFTER release so a preemption at
        # one site cannot serialize every other site behind it
        self._lock = threading.Lock()  # ktpulint: ignore[KTPU007] leaf lock inside the sanitizer itself; taken only when schedsan is ACTIVE
        self._trace: List[Tuple[str, str]] = []
        self._dropped = 0

    def decide(self, site_name: str) -> Tuple[str, float]:
        """(action, sleep_seconds) for the next decision at this site.
        Pure function of (seed, site, decision index) — the draw order
        within a site is the site's own; other sites never perturb it."""
        with self._lock:
            site = self._sites.get(site_name)
            if site is None:
                site = self._sites[site_name] = _Site(site_name, self.seed)
            r = site.rng.random()
            if r < self.yield_prob:
                action, dur = YIELD, 0.0
            elif r < self.yield_prob + self.sleep_prob:
                # jitter drawn under the SAME per-site stream: the sleep
                # duration is part of the replayable schedule
                action = SLEEP
                dur = site.rng.uniform(self.max_sleep_s / 40.0,
                                       self.max_sleep_s)
            else:
                action, dur = PROCEED, 0.0
            site.counts[action] += 1
            if len(self._trace) < _TRACE_CAP:
                self._trace.append((site_name, action))
            else:
                self._dropped += 1
            return action, dur

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {name: dict(s.counts) for name, s in self._sites.items()}

    def trace(self, site: Optional[str] = None) -> List[Tuple[str, str]]:
        with self._lock:
            if site is None:
                return list(self._trace)
            return [t for t in self._trace if t[0] == site]


_sampler: Optional[Sampler] = None


def active() -> bool:
    return _sampler is not None


# locksan spells the same question enabled(); keep both names working so
# each caller reads naturally next to its sibling sanitizer's check
enabled = active


def current() -> Optional[Sampler]:
    return _sampler


def seed() -> Optional[int]:
    """The active schedule's seed (None when inactive) — invariant
    violations stamp it into their report so the schedule that produced
    a race is reproducible from the failure artifact alone."""
    s = _sampler
    return s.seed if s is not None else None


def activate(seed: int, yield_prob: float = 0.10, sleep_prob: float = 0.02,
             max_sleep_s: float = 0.002) -> Sampler:
    """Install a schedule process-wide (replacing any active one)."""
    global _sampler
    s = Sampler(int(seed), yield_prob=yield_prob, sleep_prob=sleep_prob,
                max_sleep_s=max_sleep_s)
    _sampler = s
    return s


def deactivate() -> None:
    global _sampler
    _sampler = None


def stats() -> Dict[str, Dict[str, int]]:
    """Per-site action counts (empty when inactive) — racesweep's proof
    that a scenario actually crossed its preemption points."""
    s = _sampler
    return s.stats() if s is not None else {}


def trace(site: Optional[str] = None) -> List[Tuple[str, str]]:
    """The bounded (site, action) decision trace — what the determinism
    regression tests compare across replays of one seed."""
    s = _sampler
    return s.trace(site) if s is not None else []


def preempt(site: str) -> None:
    """The preemption point.  No-op when inactive (one ``is None`` test);
    when a schedule is active, draws the site's next decision and yields
    or micro-sleeps accordingly.  The sleep happens OUTSIDE the
    sampler's internal lock so one site's preemption never serializes
    the rest of the process behind it."""
    s = _sampler
    if s is None:
        return
    action, dur = s.decide(site)
    if action is PROCEED:
        return
    time.sleep(dur if action is SLEEP else 0.0)


_env = os.environ.get(ENV_VAR, "")
if _env:
    try:
        _seed = int(_env)
    except ValueError as e:
        raise ValueError(
            f"{ENV_VAR} must be an integer seed, got {_env!r}") from e
    activate(_seed)
    del _seed
