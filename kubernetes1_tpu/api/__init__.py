"""API types + scheme registration (ref: pkg/apis/core/install)."""

from ..machinery.scheme import global_scheme
from . import types as t  # noqa: F401
from .types import *  # noqa: F401,F403

_REGISTRY = [
    # (class, plural, namespaced)
    (t.Pod, "pods", True),
    (t.Node, "nodes", False),
    (t.Binding, "bindings", True),
    (t.Namespace, "namespaces", False),
    (t.Event, "events", True),
    (t.Lease, "leases", True),
    (t.Job, "jobs", True),
    (t.ReplicaSet, "replicasets", True),
    (t.Deployment, "deployments", True),
    (t.DaemonSet, "daemonsets", True),
    (t.StatefulSet, "statefulsets", True),
    (t.CronJob, "cronjobs", True),
    (t.Service, "services", True),
    (t.Endpoints, "endpoints", True),
    (t.ConfigMap, "configmaps", True),
    (t.PriorityClass, "priorityclasses", False),
    (t.Secret, "secrets", True),
    (t.ServiceAccount, "serviceaccounts", True),
    (t.ResourceQuota, "resourcequotas", True),
    (t.LimitRange, "limitranges", True),
    (t.HorizontalPodAutoscaler, "horizontalpodautoscalers", True),
    (t.PodDisruptionBudget, "poddisruptionbudgets", True),
    (t.Eviction, "evictions", True),
    (t.PersistentVolume, "persistentvolumes", False),
    (t.PersistentVolumeClaim, "persistentvolumeclaims", True),
    (t.CertificateSigningRequest, "certificatesigningrequests", False),
    (t.CustomResourceDefinition, "customresourcedefinitions", False),
    (t.MutatingWebhookConfiguration, "mutatingwebhookconfigurations", False),
    (t.ValidatingWebhookConfiguration, "validatingwebhookconfigurations", False),
    (t.APIService, "apiservices", False),
    (t.PodMetrics, "podmetrics", True),
    (t.NodeMetrics, "nodemetrics", False),
    (t.Role, "roles", True),
    (t.ClusterRole, "clusterroles", False),
    (t.RoleBinding, "rolebindings", True),
    (t.ClusterRoleBinding, "clusterrolebindings", False),
]

for cls, plural, namespaced in _REGISTRY:
    global_scheme.register(cls, plural, namespaced)

scheme = global_scheme
