"""API types + scheme registration (ref: pkg/apis/core/install)."""

from ..machinery.scheme import global_scheme
from . import types as t  # noqa: F401
from .types import *  # noqa: F401,F403

_REGISTRY = [
    # (class, plural, namespaced)
    (t.Pod, "pods", True),
    (t.Node, "nodes", False),
    (t.Binding, "bindings", True),
    (t.Namespace, "namespaces", False),
    (t.Event, "events", True),
    (t.Lease, "leases", True),
    (t.Job, "jobs", True),
    (t.ReplicaSet, "replicasets", True),
    (t.Deployment, "deployments", True),
    (t.DaemonSet, "daemonsets", True),
    (t.StatefulSet, "statefulsets", True),
    (t.CronJob, "cronjobs", True),
    (t.Service, "services", True),
    (t.Endpoints, "endpoints", True),
    (t.ConfigMap, "configmaps", True),
    (t.PriorityClass, "priorityclasses", False),
    (t.Secret, "secrets", True),
    (t.ServiceAccount, "serviceaccounts", True),
    (t.ResourceQuota, "resourcequotas", True),
    (t.LimitRange, "limitranges", True),
    (t.HorizontalPodAutoscaler, "horizontalpodautoscalers", True),
    (t.PodDisruptionBudget, "poddisruptionbudgets", True),
    (t.Eviction, "evictions", True),
    (t.PersistentVolume, "persistentvolumes", False),
    (t.PersistentVolumeClaim, "persistentvolumeclaims", True),
    (t.StorageClass, "storageclasses", False),
    (t.CertificateSigningRequest, "certificatesigningrequests", False),
    (t.CustomResourceDefinition, "customresourcedefinitions", False),
    (t.PodPreset, "podpresets", True),
    (t.MutatingWebhookConfiguration, "mutatingwebhookconfigurations", False),
    (t.ValidatingWebhookConfiguration, "validatingwebhookconfigurations", False),
    (t.APIService, "apiservices", False),
    (t.PodMetrics, "podmetrics", True),
    (t.NodeMetrics, "nodemetrics", False),
    (t.PodCustomMetrics, "podcustommetrics", True),
    (t.PodSecurityPolicy, "podsecuritypolicies", False),
    (t.Role, "roles", True),
    (t.ClusterRole, "clusterroles", False),
    (t.RoleBinding, "rolebindings", True),
    (t.ClusterRoleBinding, "clusterrolebindings", False),
]

for cls, plural, namespaced in _REGISTRY:
    global_scheme.register(cls, plural, namespaced)


# ---- multi-version serving (ref: runtime.Scheme conversion funcs;
# the reference serves Deployment at both extensions/v1beta1 and apps/*,
# with generated Convert_* functions between versions and the internal
# hub form — staging/src/k8s.io/api has both trees).


def _deployment_v1beta1_from_internal(d: dict) -> dict:
    """apps/v1 (hub) -> extensions/v1beta1: same shape; v1beta1 never
    requires a selector, so one defaulted from the template labels is
    elided on the way out."""
    out = dict(d)
    spec = dict(out.get("spec") or {})
    tmpl_labels = (((spec.get("template") or {}).get("metadata") or {})
                   .get("labels") or {})
    sel = spec.get("selector") or {}
    # elide ONLY a pure matchLabels selector equal to the template labels —
    # a selector carrying matchExpressions must round-trip intact
    if set(sel.keys()) == {"matchLabels"} and sel["matchLabels"] == tmpl_labels:
        spec.pop("selector", None)
    out["spec"] = spec
    return out


def _deployment_v1beta1_to_internal(d: dict) -> dict:
    """extensions/v1beta1 -> apps/v1 (hub): default the optional selector
    from template labels (v1beta1 semantics) and drop rollbackTo (the
    deprecated imperative rollback field has no internal representation)."""
    out = dict(d)
    out["apiVersion"] = t.Deployment.API_VERSION
    spec = dict(out.get("spec") or {})
    spec.pop("rollbackTo", None)
    # v1beta1 defaulting applies only when the selector is entirely unset —
    # a matchExpressions-only selector is a real selector, not an absence
    if not spec.get("selector"):
        tmpl_labels = (((spec.get("template") or {}).get("metadata") or {})
                       .get("labels") or {})
        if tmpl_labels:
            spec["selector"] = {"matchLabels": dict(tmpl_labels)}
    out["spec"] = spec
    return out


def _identity_version(to_version: str):
    def from_internal(d: dict) -> dict:
        return dict(d)

    def to_internal(d: dict, _hub=to_version) -> dict:
        out = dict(d)
        out["apiVersion"] = _hub
        return out

    return from_internal, to_internal


global_scheme.register_conversion(
    "Deployment", "extensions/v1beta1",
    _deployment_v1beta1_from_internal, _deployment_v1beta1_to_internal)
# batch/v1beta1 CronJob is shape-identical to the hub version (as in 1.9,
# where v1beta1 vs v2alpha1 differ only in defaults we don't carry)
_cj_from, _cj_to = _identity_version(t.CronJob.API_VERSION)
global_scheme.register_conversion("CronJob", "batch/v1beta1", _cj_from, _cj_to)

scheme = global_scheme
