"""Core API types — the L3 equivalent of the reference's pkg/apis/core +
staging/src/k8s.io/api (Pod/Node/Binding/workloads), including the fork's
ExtendedResources v2 device model re-pointed at TPU.

Reference anchors (for parity checking, NOT copied):
- Pod/Container/Node: staging/src/k8s.io/api/core/v1/types.go
- fork ExtendedResources: types.go:2633-2637 (ResourceSelector/Affinity),
  :2885 (PodSpec.ExtendedResources), :3848-3850 (NodeStatus.ExtendedResources),
  :4018-4060 (Binding/ExtendedResourceMap/Domain/Device), :2202-2204
  (Container.ExtendedResourceRequests)
- Job: pkg/apis/batch/types.go — extended here with completionMode=Indexed and
  gang scheduling policy, the two capabilities SURVEY.md flags as reference
  gaps that multi-host TPU slices require.

Differences from the reference, by design (TPU-first):
- Devices carry free-form string attributes with the `google.com/tpu/` prefix
  (topology, slice id, host index, chip coords) instead of NVIDIA attrs.
- PodSpec.scheduling_gang names a gang; all pods of a gang bind atomically.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..machinery.meta import KObject, ListMeta, ObjectMeta, OwnerReference

# ----------------------------------------------------------------- constants

POD_PENDING = "Pending"
POD_RUNNING = "Running"
POD_SUCCEEDED = "Succeeded"
POD_FAILED = "Failed"
POD_UNKNOWN = "Unknown"

DEVICE_HEALTHY = "Healthy"
DEVICE_UNHEALTHY = "Unhealthy"

NODE_READY = "Ready"

# Well-known TPU attribute keys (vendor-prefixed like the reference's
# nvidia.com/gpu/memory convention).
ATTR_TPU_TYPE = "google.com/tpu/type"            # e.g. v5e, v5p
ATTR_TPU_TOPOLOGY = "google.com/tpu/topology"    # e.g. 2x2x1, 4x4x8
ATTR_TPU_SLICE = "google.com/tpu/slice"          # slice/ICI-domain id
ATTR_TPU_HOST_INDEX = "google.com/tpu/host-index"
ATTR_TPU_CHIP_COORDS = "google.com/tpu/coords"   # x,y,z within slice
ATTR_TPU_CORES_PER_CHIP = "google.com/tpu/cores-per-chip"

# Annotation carrying the scheduler's nominated node during preemption
# (ref: scheduler.go NominatedNodeAnnotationKey).
NOMINATED_NODE_ANNOTATION = "scheduler.ktpu.io/nominated-node"
# Marker prefix on the Conflict message the apiserver answers when a bind
# would double-allocate a chip another scheduler shard just claimed
# (apiserver/registry.py device-claim guard).  The scheduler matches on it
# to re-queue the loser with a refreshed cache instead of treating the
# Conflict as "this pod is already bound" (terminal).  A message marker —
# not a new error class — so it crosses old/new client-server pairs as a
# plain 409.
DEVICE_CLAIM_CONFLICT = "device claim conflict"
# Job completion index annotation+env (reference gap; needed for TPU worker id)
COMPLETION_INDEX_ANNOTATION = "batch.ktpu.io/completion-index"
JOB_NAME_LABEL = "batch.ktpu.io/job-name"
# Gang attempt: an ICI slice is all-or-nothing on the FAILURE path too —
# when a gang member dies the Job controller tears the whole gang down and
# recreates it as a fresh attempt.  The counter lives as an annotation on
# the Job (current attempt) and as this label on every member pod, so a
# restarted controller reconstructs attempt membership from the API alone.
GANG_ATTEMPT_LABEL = "batch.ktpu.io/gang-attempt"
# Mirror pods: static-manifest pods the kubelet itself publishes to the
# apiserver (ref: kubetypes.ConfigMirrorAnnotationKey). NodeRestriction
# admission only lets a node credential create pods carrying this marker.
STATIC_POD_ANNOTATION = "kubelet.ktpu.io/static"

# Request tracing: the apiserver stamps the creating request's trace id on
# pods so scheduler/kubelet spans correlate across the watch path
# (utils/spans; the k8s Audit-ID analog made durable on the object).
TRACE_ID_ANNOTATION = "trace.ktpu.io/trace-id"

# Watch-lag SLI (obs plane): lag-stamp BOOKMARK frames carry the
# monotonic commit timestamp(s) of the just-delivered batch under this
# annotation, as space-separated "<shard>:<ts>" tokens — one per shard
# the batch advanced.  Opt-in per watch (?lagStamps=1); informers parse
# it into ktpu_informer_lag_seconds{shard=...}.
COMMITTED_AT_ANNOTATION = "obs.ktpu.io/committed-at"
# Pod-startup SLI phase stamps (utils/slo): wall-clock seconds as "%.6f"
# strings, written by the component that owns each transition —
#   created-at    apiserver, at pod admission into the registry
#   scheduled-at  scheduler, when the placement algorithm picked node+chips
#                 (carried on the Binding, merged into the pod at bind)
#   bound-at      apiserver registry, when the binding commits
#   admitted-at   kubelet, when device admission (incl. plugin AdmitPod)
#                 accepted the pod on its node
# running is observed from the watch stream by the SLI tracker itself.
CREATED_AT_ANNOTATION = "slo.ktpu.io/created-at"
SCHEDULED_AT_ANNOTATION = "slo.ktpu.io/scheduled-at"
BOUND_AT_ANNOTATION = "slo.ktpu.io/bound-at"
ADMITTED_AT_ANNOTATION = "slo.ktpu.io/admitted-at"

# --------------------------------------------------------------- shared bits


@dataclass
class LabelSelectorRequirement:
    key: str = ""
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist
    values: List[str] = field(default_factory=list)


@dataclass
class LabelSelector:
    match_labels: Dict[str, str] = field(default_factory=dict)
    match_expressions: List[LabelSelectorRequirement] = field(default_factory=list)


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = ""  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty matches all effects
    toleration_seconds: Optional[int] = None


# ----------------------------------------------------- extended resources v2


@dataclass
class ResourceSelectorRequirement:
    """One attribute-affinity term (ref: types.go ResourceSelector)."""

    key: str = ""  # e.g. google.com/tpu/type
    operator: str = "In"  # In | NotIn | Exists | Gt | Lt
    values: List[str] = field(default_factory=list)


@dataclass
class ResourceAffinity:
    required: List[ResourceSelectorRequirement] = field(default_factory=list)


@dataclass
class PodExtendedResource:
    """A pod-level device request (ref: types.go PodExtendedResource).

    `assigned` is filled by the scheduler at bind time and is the durable
    record of which chip IDs the pod owns — the fork's restart-safe
    "checkpoint in the API object" design (storage.go:186).
    """

    name: str = ""  # unique within pod; containers reference it
    resource: str = ""  # e.g. google.com/tpu
    quantity: int = 0
    affinity: Optional[ResourceAffinity] = None
    assigned: List[str] = field(default_factory=list)


@dataclass
class ExtendedResourceDevice:
    id: str = ""
    health: str = DEVICE_HEALTHY
    attributes: Dict[str, str] = field(default_factory=dict)


# NodeStatus.extended_resources: {resource name: [devices]}
ExtendedResourceMap = Dict[str, List[ExtendedResourceDevice]]


# ------------------------------------------------------------------ pod spec


@dataclass
class ConfigMapKeySelector:
    name: str = ""
    key: str = ""
    optional: bool = False


@dataclass
class SecretKeySelector:
    name: str = ""
    key: str = ""
    optional: bool = False


@dataclass
class ObjectFieldSelector:
    """Downward API (ref: pkg/fieldpath/fieldpath.go) — supported paths:
    metadata.name, metadata.namespace, metadata.uid, metadata.labels['k'],
    metadata.annotations['k'], spec.nodeName, spec.serviceAccountName,
    status.podIP, status.hostIP."""

    field_path: str = ""


@dataclass
class EnvVarSource:
    config_map_key_ref: Optional[ConfigMapKeySelector] = None
    secret_key_ref: Optional[SecretKeySelector] = None
    field_ref: Optional[ObjectFieldSelector] = None


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""
    value_from: Optional[EnvVarSource] = None


@dataclass
class ConfigMapEnvSource:
    name: str = ""
    optional: bool = False


@dataclass
class SecretEnvSource:
    name: str = ""
    optional: bool = False


@dataclass
class EnvFromSource:
    """envFrom: import a whole ConfigMap/Secret as env vars
    (ref: kubelet_pods.go:591 makeEnvironmentVariables)."""

    prefix: str = ""
    config_map_ref: Optional[ConfigMapEnvSource] = None
    secret_ref: Optional[SecretEnvSource] = None


@dataclass
class ContainerPort:
    name: str = ""
    container_port: int = 0
    host_port: int = 0
    protocol: str = "TCP"


@dataclass
class VolumeMount:
    name: str = ""
    mount_path: str = ""
    read_only: bool = False
    sub_path: str = ""


@dataclass
class HostPathVolumeSource:
    path: str = ""


@dataclass
class EmptyDirVolumeSource:
    medium: str = ""


@dataclass
class KeyToPath:
    key: str = ""
    path: str = ""


@dataclass
class ConfigMapVolumeSource:
    name: str = ""
    items: List[KeyToPath] = field(default_factory=list)  # empty = all keys
    optional: bool = False


@dataclass
class SecretVolumeSource:
    secret_name: str = ""
    items: List[KeyToPath] = field(default_factory=list)
    optional: bool = False


@dataclass
class PersistentVolumeClaimVolumeSource:
    claim_name: str = ""


@dataclass
class DownwardAPIVolumeFile:
    path: str = ""
    field_ref: Optional[ObjectFieldSelector] = None


@dataclass
class DownwardAPIVolumeSource:
    items: List[DownwardAPIVolumeFile] = field(default_factory=list)


@dataclass
class Volume:
    name: str = ""
    host_path: Optional[HostPathVolumeSource] = None
    empty_dir: Optional[EmptyDirVolumeSource] = None
    config_map: Optional[ConfigMapVolumeSource] = None
    secret: Optional[SecretVolumeSource] = None
    persistent_volume_claim: Optional[PersistentVolumeClaimVolumeSource] = None
    downward_api: Optional[DownwardAPIVolumeSource] = None


@dataclass
class ResourceRequirements:
    limits: Dict[str, Any] = field(default_factory=dict)
    requests: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ExecAction:
    command: List[str] = field(default_factory=list)


@dataclass
class HTTPGetAction:
    path: str = "/"
    port: int = 0
    host: str = ""


@dataclass
class TCPSocketAction:
    port: int = 0
    host: str = ""


@dataclass
class Probe:
    exec_action: Optional[ExecAction] = None
    http_get: Optional[HTTPGetAction] = None
    tcp_socket: Optional[TCPSocketAction] = None
    initial_delay_seconds: int = 0
    period_seconds: int = 10
    timeout_seconds: int = 1
    failure_threshold: int = 3
    success_threshold: int = 1


@dataclass
class SecurityContext:
    """Per-container security settings (ref: core/v1 SecurityContext +
    pkg/securitycontext): who the process runs as and whether it may touch
    privileged host resources (/dev/accel* hostPaths on a TPU host)."""

    run_as_user: Optional[int] = None
    run_as_group: Optional[int] = None
    run_as_non_root: Optional[bool] = None
    privileged: Optional[bool] = None


@dataclass
class PodSecurityContext:
    """Pod-level defaults every container inherits unless it overrides
    (ref: core/v1 PodSecurityContext; DetermineEffectiveSecurityContext)."""

    run_as_user: Optional[int] = None
    run_as_group: Optional[int] = None
    run_as_non_root: Optional[bool] = None


@dataclass
class Container:
    name: str = ""
    image: str = ""
    image_pull_policy: str = ""  # "" = IfNotPresent default | Always | Never
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    working_dir: str = ""
    env: List[EnvVar] = field(default_factory=list)
    env_from: List[EnvFromSource] = field(default_factory=list)
    ports: List[ContainerPort] = field(default_factory=list)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    volume_mounts: List[VolumeMount] = field(default_factory=list)
    liveness_probe: Optional[Probe] = None
    readiness_probe: Optional[Probe] = None
    security_context: Optional[SecurityContext] = None
    # Names of PodSpec.extended_resources entries this container consumes
    # (ref: types.go:2202-2204).
    extended_resource_requests: List[str] = field(default_factory=list)


@dataclass
class NodeSelectorRequirement:
    key: str = ""
    operator: str = "In"
    values: List[str] = field(default_factory=list)


@dataclass
class NodeAffinityTerm:
    match_expressions: List[NodeSelectorRequirement] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    """Ref: core/v1 PodAffinityTerm (types.go) — co-locate (or anti-) with
    pods matching `label_selector` within one `topology_key` domain.

    TPU-native topology keys beyond node labels:
    - kubernetes.io/hostname    -> the node itself
    - google.com/tpu-slice      -> the ICI slice the node's chips belong to
      (resolved from device attributes, so a trainer can require
      co-location with its parameter-server on the same slice)."""

    label_selector: Optional[LabelSelector] = None
    topology_key: str = "kubernetes.io/hostname"
    namespaces: List[str] = field(default_factory=list)  # empty = pod's own


@dataclass
class PreferredSchedulingTerm:
    """Ref: core/v1 PreferredSchedulingTerm — a weighted soft node-affinity
    preference (preferredDuringSchedulingIgnoredDuringExecution)."""

    weight: int = 1  # 1-100
    preference: NodeAffinityTerm = field(default_factory=NodeAffinityTerm)


@dataclass
class Affinity:
    # required node affinity terms are ORed; expressions within a term ANDed
    node_affinity_required: List[NodeAffinityTerm] = field(default_factory=list)
    # soft preferences scored by the NodeAffinity priority
    # (priorities/node_affinity.go)
    node_affinity_preferred: List[PreferredSchedulingTerm] = field(default_factory=list)
    # requiredDuringSchedulingIgnoredDuringExecution pod (anti-)affinity:
    # every term must be satisfied (ref predicates.go:1036-1044)
    pod_affinity_required: List[PodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity_required: List[PodAffinityTerm] = field(default_factory=list)


@dataclass
class PodSpec:
    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)
    volumes: List[Volume] = field(default_factory=list)
    restart_policy: str = "Always"  # Always | OnFailure | Never
    node_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: List[Toleration] = field(default_factory=list)
    priority: int = 0
    priority_class_name: str = ""
    scheduler_name: str = "default-scheduler"
    termination_grace_period_seconds: int = 30
    active_deadline_seconds: Optional[int] = None
    host_network: bool = False
    service_account_name: str = ""
    security_context: Optional[PodSecurityContext] = None
    # fork v2: pod-level device requests with attribute affinity
    extended_resources: List[PodExtendedResource] = field(default_factory=list)
    # gang scheduling (TPU multi-host slices): pods sharing
    # (namespace, scheduling_gang) bind all-or-nothing over gang_size pods.
    scheduling_gang: str = ""
    gang_size: int = 0


@dataclass
class ContainerStateRunning:
    started_at: str = ""


@dataclass
class ContainerStateTerminated:
    exit_code: int = 0
    reason: str = ""
    message: str = ""
    started_at: str = ""
    finished_at: str = ""


@dataclass
class ContainerStateWaiting:
    reason: str = ""
    message: str = ""


@dataclass
class ContainerState:
    waiting: Optional[ContainerStateWaiting] = None
    running: Optional[ContainerStateRunning] = None
    terminated: Optional[ContainerStateTerminated] = None


@dataclass
class ContainerStatus:
    name: str = ""
    state: ContainerState = field(default_factory=ContainerState)
    ready: bool = False
    restart_count: int = 0
    image: str = ""
    container_id: str = ""


@dataclass
class PodCondition:
    type: str = ""  # PodScheduled | Ready | Initialized | ContainersReady
    status: str = ""  # True | False | Unknown
    reason: str = ""
    message: str = ""
    last_transition_time: str = ""


@dataclass
class PodStatus:
    phase: str = POD_PENDING
    conditions: List[PodCondition] = field(default_factory=list)
    message: str = ""
    reason: str = ""
    host_ip: str = ""
    pod_ip: str = ""
    start_time: str = ""
    container_statuses: List[ContainerStatus] = field(default_factory=list)


@dataclass
class Pod(KObject):
    KIND = "Pod"
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


# --------------------------------------------------------------------- node


@dataclass
class NodeSystemInfo:
    machine_id: str = ""
    kernel_version: str = ""
    os_image: str = ""
    container_runtime_version: str = ""
    kubelet_version: str = ""
    architecture: str = ""


@dataclass
class NodeCondition:
    type: str = ""  # Ready | MemoryPressure | DiskPressure | TPUUnhealthy
    status: str = ""
    reason: str = ""
    message: str = ""
    last_heartbeat_time: str = ""
    last_transition_time: str = ""


@dataclass
class NodeAddress:
    type: str = ""  # InternalIP | Hostname
    address: str = ""


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: List[Taint] = field(default_factory=list)
    pod_cidr: str = ""
    provider_id: str = ""


@dataclass
class NodeStatus:
    capacity: Dict[str, Any] = field(default_factory=dict)
    allocatable: Dict[str, Any] = field(default_factory=dict)
    conditions: List[NodeCondition] = field(default_factory=list)
    addresses: List[NodeAddress] = field(default_factory=list)
    node_info: NodeSystemInfo = field(default_factory=NodeSystemInfo)
    # fork: per-device inventory with attributes (types.go:3848-3850),
    # published by kubelet from the device manager's store
    # (kubelet_node_status.go:552-621)
    extended_resources: Dict[str, List[ExtendedResourceDevice]] = field(default_factory=dict)
    images: List[str] = field(default_factory=list)


@dataclass
class Node(KObject):
    KIND = "Node"
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)


# ------------------------------------------------------------------- binding


@dataclass
class Binding(KObject):
    """Scheduler -> apiserver: bind pod to node + carry assigned device IDs
    (ref: types.go:4493-4495, registry/core/pod/storage/storage.go:138-195).

    `extended_resource_assignments` maps PodExtendedResource.name -> chip IDs.
    """

    KIND = "Binding"
    target_node: str = ""
    extended_resource_assignments: Dict[str, List[str]] = field(default_factory=dict)


# --------------------------------------------------------------- namespaces


@dataclass
class NamespaceStatus:
    phase: str = "Active"  # Active | Terminating


@dataclass
class Namespace(KObject):
    KIND = "Namespace"
    status: NamespaceStatus = field(default_factory=NamespaceStatus)


# ------------------------------------------------------------------- events


@dataclass
class ObjectReference:
    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""


@dataclass
class Event(KObject):
    KIND = "Event"
    involved_object: ObjectReference = field(default_factory=ObjectReference)
    reason: str = ""
    message: str = ""
    type: str = "Normal"  # Normal | Warning
    count: int = 1
    source_component: str = ""
    first_timestamp: str = ""
    last_timestamp: str = ""


# -------------------------------------------------------------------- lease


@dataclass
class Lease(KObject):
    """Leader-election resource lock (ref: client-go tools/leaderelection)."""

    KIND = "Lease"
    holder_identity: str = ""
    lease_duration_seconds: int = 15
    acquire_time: str = ""
    renew_time: str = ""
    lease_transitions: int = 0


# ---------------------------------------------------------------- workloads


@dataclass
class JobSpec:
    parallelism: Optional[int] = None
    completions: Optional[int] = None
    backoff_limit: int = 6
    active_deadline_seconds: Optional[int] = None
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    # Indexed completion mode (reference gap — SURVEY.md §2.1 job row):
    # each pod gets a stable completion index 0..completions-1, exposed as
    # annotation + TPU_WORKER_ID env; required for multi-host TPU workers.
    completion_mode: str = "NonIndexed"  # NonIndexed | Indexed
    # Gang scheduling: all pods of the job bind atomically (TPU slices).
    gang_scheduling: bool = False
    # Cleanup of finished jobs (upstream ttlafterfinished design; absent in
    # the 1.9 reference where finished Jobs accumulate forever).
    ttl_seconds_after_finished: Optional[int] = None


@dataclass
class JobCondition:
    type: str = ""  # Complete | Failed
    status: str = ""
    reason: str = ""
    message: str = ""
    last_transition_time: str = ""


@dataclass
class JobStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    start_time: str = ""
    completion_time: str = ""
    conditions: List[JobCondition] = field(default_factory=list)
    # Indexed mode: which indexes have succeeded, as a compact string "0-3,7"
    completed_indexes: str = ""


@dataclass
class Job(KObject):
    KIND = "Job"
    API_VERSION = "batch/v1"
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)


@dataclass
class ReplicaSetSpec:
    replicas: Optional[int] = None
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    min_ready_seconds: int = 0


@dataclass
class ReplicaSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    available_replicas: int = 0
    fully_labeled_replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicaSet(KObject):
    KIND = "ReplicaSet"
    API_VERSION = "apps/v1"
    spec: ReplicaSetSpec = field(default_factory=ReplicaSetSpec)
    status: ReplicaSetStatus = field(default_factory=ReplicaSetStatus)


@dataclass
class RollingUpdateDeployment:
    max_unavailable: Any = 1  # int or "25%"
    max_surge: Any = 1


@dataclass
class DeploymentStrategy:
    type: str = "RollingUpdate"  # RollingUpdate | Recreate
    rolling_update: RollingUpdateDeployment = field(default_factory=RollingUpdateDeployment)


@dataclass
class DeploymentSpec:
    replicas: Optional[int] = None
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    strategy: DeploymentStrategy = field(default_factory=DeploymentStrategy)
    revision_history_limit: int = 10
    paused: bool = False


@dataclass
class DeploymentStatus:
    observed_generation: int = 0
    replicas: int = 0
    updated_replicas: int = 0
    ready_replicas: int = 0
    available_replicas: int = 0
    unavailable_replicas: int = 0


@dataclass
class Deployment(KObject):
    KIND = "Deployment"
    API_VERSION = "apps/v1"
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)


@dataclass
class DaemonSetSpec:
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class DaemonSetStatus:
    current_number_scheduled: int = 0
    desired_number_scheduled: int = 0
    number_ready: int = 0
    number_misscheduled: int = 0
    observed_generation: int = 0


@dataclass
class DaemonSet(KObject):
    KIND = "DaemonSet"
    API_VERSION = "apps/v1"
    spec: DaemonSetSpec = field(default_factory=DaemonSetSpec)
    status: DaemonSetStatus = field(default_factory=DaemonSetStatus)


# ----------------------------------------------------------------- services


@dataclass
class RollingUpdateStatefulSetStrategy:
    partition: int = 0


@dataclass
class StatefulSetUpdateStrategy:
    type: str = "RollingUpdate"  # RollingUpdate | OnDelete
    rolling_update: Optional[RollingUpdateStatefulSetStrategy] = None


@dataclass
class StatefulSetSpec:
    replicas: Optional[int] = None
    selector: Optional[LabelSelector] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    service_name: str = ""
    # OrderedReady: create/delete one ordinal at a time; Parallel: all at once.
    pod_management_policy: str = "OrderedReady"  # OrderedReady | Parallel
    update_strategy: StatefulSetUpdateStrategy = field(
        default_factory=StatefulSetUpdateStrategy
    )


@dataclass
class StatefulSetStatus:
    observed_generation: int = 0
    replicas: int = 0
    ready_replicas: int = 0
    current_replicas: int = 0
    updated_replicas: int = 0
    current_revision: str = ""
    update_revision: str = ""


@dataclass
class StatefulSet(KObject):
    """Stable-identity workload (ref: pkg/apis/apps/types.go StatefulSet;
    controller at pkg/controller/statefulset/stateful_set.go)."""

    KIND = "StatefulSet"
    API_VERSION = "apps/v1"
    spec: StatefulSetSpec = field(default_factory=StatefulSetSpec)
    status: StatefulSetStatus = field(default_factory=StatefulSetStatus)


@dataclass
class JobTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSpec = field(default_factory=JobSpec)


@dataclass
class CronJobSpec:
    schedule: str = ""  # 5-field cron, local time
    suspend: bool = False
    concurrency_policy: str = "Allow"  # Allow | Forbid | Replace
    starting_deadline_seconds: Optional[int] = None
    job_template: JobTemplateSpec = field(default_factory=JobTemplateSpec)
    successful_jobs_history_limit: int = 3
    failed_jobs_history_limit: int = 1


@dataclass
class CronJobStatus:
    active: List["ObjectReference"] = field(default_factory=list)
    last_schedule_time: str = ""


@dataclass
class CronJob(KObject):
    """Scheduled Jobs (ref: pkg/apis/batch/types.go CronJob; controller at
    pkg/controller/cronjob/cronjob_controller.go)."""

    KIND = "CronJob"
    API_VERSION = "batch/v1"
    spec: CronJobSpec = field(default_factory=CronJobSpec)
    status: CronJobStatus = field(default_factory=CronJobStatus)


@dataclass
class ServicePort:
    name: str = ""
    port: int = 0
    target_port: int = 0
    node_port: int = 0
    protocol: str = "TCP"


@dataclass
class ServiceSpec:
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)
    cluster_ip: str = ""  # allocated from 10.96/16; "None" = headless
    type: str = "ClusterIP"  # ClusterIP | NodePort
    session_affinity: str = ""  # "" | ClientIP


@dataclass
class Service(KObject):
    KIND = "Service"
    spec: ServiceSpec = field(default_factory=ServiceSpec)


@dataclass
class EndpointAddress:
    ip: str = ""
    node_name: str = ""
    # the pod this address IS (real k8s: a full ObjectReference; the
    # name suffices here).  In-process clusters assign every pod the
    # loopback ip, so pod IDENTITY — not ip — is what an L7 resolver
    # keys its backend registry on.
    target_ref: str = ""


@dataclass
class EndpointPort:
    name: str = ""
    port: int = 0
    protocol: str = "TCP"


@dataclass
class EndpointSubset:
    addresses: List[EndpointAddress] = field(default_factory=list)
    # matching pods that must NOT receive new traffic but may still be
    # finishing in-flight work: terminating (deletion_timestamp set) or
    # Running-but-not-Ready.  The explicit drain signal: an L7 balancer
    # keeps their open responses alive while picking only `addresses`.
    not_ready_addresses: List[EndpointAddress] = field(default_factory=list)
    ports: List[EndpointPort] = field(default_factory=list)


@dataclass
class Endpoints(KObject):
    KIND = "Endpoints"
    subsets: List[EndpointSubset] = field(default_factory=list)


@dataclass
class ConfigMap(KObject):
    KIND = "ConfigMap"
    data: Dict[str, str] = field(default_factory=dict)


@dataclass
class PriorityClass(KObject):
    KIND = "PriorityClass"
    API_VERSION = "scheduling/v1"
    value: int = 0
    global_default: bool = False
    description: str = ""


# ----------------------------------------------------- secrets / identities


@dataclass
class Secret(KObject):
    """Ref: core/v1 Secret (types.go). Values are stored as plain strings
    (`stringData` semantics) — there is no base64 layer to shed."""

    KIND = "Secret"
    type: str = "Opaque"  # Opaque | kubernetes.io/service-account-token | bootstrap.kubernetes.io/token
    data: Dict[str, str] = field(default_factory=dict)


@dataclass
class ServiceAccount(KObject):
    """Ref: core/v1 ServiceAccount; token secrets minted by the token
    controller (pkg/controller/serviceaccount)."""

    KIND = "ServiceAccount"
    secrets: List[ObjectReference] = field(default_factory=list)
    automount_service_account_token: bool = True


# -------------------------------------------------------- quota and limits


@dataclass
class ResourceQuotaSpec:
    hard: Dict[str, str] = field(default_factory=dict)  # "pods", "requests.cpu", "google.com/tpu", ...


@dataclass
class ResourceQuotaStatus:
    hard: Dict[str, str] = field(default_factory=dict)
    used: Dict[str, str] = field(default_factory=dict)


@dataclass
class ResourceQuota(KObject):
    """Ref: core/v1 ResourceQuota; enforced by admission, recalculated by the
    resourcequota controller (pkg/controller/resourcequota)."""

    KIND = "ResourceQuota"
    spec: ResourceQuotaSpec = field(default_factory=ResourceQuotaSpec)
    status: ResourceQuotaStatus = field(default_factory=ResourceQuotaStatus)


@dataclass
class LimitRangeItem:
    type: str = "Container"  # Container | Pod
    max: Dict[str, str] = field(default_factory=dict)
    min: Dict[str, str] = field(default_factory=dict)
    default: Dict[str, str] = field(default_factory=dict)          # default limits
    default_request: Dict[str, str] = field(default_factory=dict)  # default requests


@dataclass
class LimitRangeSpec:
    limits: List[LimitRangeItem] = field(default_factory=list)


@dataclass
class LimitRange(KObject):
    """Ref: core/v1 LimitRange, applied by the LimitRanger admission plugin
    (plugin/pkg/admission/limitranger)."""

    KIND = "LimitRange"
    spec: LimitRangeSpec = field(default_factory=LimitRangeSpec)


# ---------------------------------------------------------------- autoscaling


@dataclass
class CrossVersionObjectReference:
    kind: str = ""  # Deployment | ReplicaSet | StatefulSet
    name: str = ""
    api_version: str = ""


@dataclass
class ResourceMetricSource:
    """autoscaling/v2 Resource metric: utilization of a container-requested
    resource (PodMetrics ÷ requests), percent."""

    name: str = "cpu"
    target_average_utilization: Optional[int] = None


@dataclass
class PodsMetricSource:
    """autoscaling/v2 Pods metric: a named sample scraped off each pod's
    /metrics endpoint (PodCustomMetrics), averaged across the target's
    pods and compared against `target_average_value`."""

    metric_name: str = ""
    target_average_value: float = 0.0


@dataclass
class MetricSpec:
    """One scaling signal (ref: autoscaling/v2 MetricSpec).  The HPA
    computes a desired replica count per entry and takes the MAX."""

    type: str = ""  # Resource | Pods
    resource: Optional[ResourceMetricSource] = None
    pods: Optional[PodsMetricSource] = None


@dataclass
class HorizontalPodAutoscalerSpec:
    scale_target_ref: CrossVersionObjectReference = field(
        default_factory=CrossVersionObjectReference
    )
    min_replicas: int = 1
    max_replicas: int = 1
    target_cpu_utilization_percentage: Optional[int] = None
    # v2-style metric specs; when non-empty they are the scaling signals
    # (target_cpu_utilization_percentage above is the v1 shorthand and
    # keeps working unchanged when `metrics` is empty)
    metrics: List[MetricSpec] = field(default_factory=list)
    # behavior stabilization windows (ref: autoscaling/v2
    # HPAScalingRules.stabilizationWindowSeconds): a scale-up takes the
    # MIN recommendation of the up-window, a scale-down the MAX of the
    # down-window — 0 (default) reacts instantly, exactly the v1 behavior
    scale_up_stabilization_seconds: float = 0.0
    scale_down_stabilization_seconds: float = 0.0


@dataclass
class HorizontalPodAutoscalerStatus:
    observed_generation: int = 0
    last_scale_time: str = ""
    current_replicas: int = 0
    desired_replicas: int = 0
    current_cpu_utilization_percentage: Optional[int] = None
    # observed per-metric averages last cycle (metric name -> value);
    # free-form map — metric names are workload-defined
    current_metric_values: Dict[str, float] = field(default_factory=dict)


@dataclass
class HorizontalPodAutoscaler(KObject):
    """Ref: autoscaling/v1 HPA; reconciled by pkg/controller/podautoscaler
    against the resource-metrics pipeline (Summary API here)."""

    KIND = "HorizontalPodAutoscaler"
    API_VERSION = "autoscaling/v1"
    spec: HorizontalPodAutoscalerSpec = field(default_factory=HorizontalPodAutoscalerSpec)
    status: HorizontalPodAutoscalerStatus = field(
        default_factory=HorizontalPodAutoscalerStatus
    )


# -------------------------------------------------------------- disruption


@dataclass
class PodDisruptionBudgetSpec:
    selector: Optional[LabelSelector] = None
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None


@dataclass
class PodDisruptionBudgetStatus:
    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0
    observed_generation: int = 0


@dataclass
class PodDisruptionBudget(KObject):
    """Ref: policy/v1beta1 PDB + pkg/controller/disruption; consulted by the
    eviction subresource and `ktpu drain`."""

    KIND = "PodDisruptionBudget"
    API_VERSION = "policy/v1"
    spec: PodDisruptionBudgetSpec = field(default_factory=PodDisruptionBudgetSpec)
    status: PodDisruptionBudgetStatus = field(default_factory=PodDisruptionBudgetStatus)


@dataclass
class Eviction(KObject):
    """Eviction subresource payload (ref: policy/v1beta1 Eviction,
    pkg/registry/core/pod/storage/eviction.go:57): POST to
    /pods/<name>/eviction deletes the pod only if no matching
    PodDisruptionBudget would be violated; 429 otherwise."""

    KIND = "Eviction"
    API_VERSION = "policy/v1"
    grace_period_seconds: Optional[int] = None


# ------------------------------------------------------------------ volumes


@dataclass
class PersistentVolumeSpec:
    capacity: Dict[str, str] = field(default_factory=dict)  # {"storage": "10Gi"}
    access_modes: List[str] = field(default_factory=list)  # ReadWriteOnce | ReadOnlyMany | ReadWriteMany
    host_path: Optional[HostPathVolumeSource] = None
    storage_class_name: str = ""
    persistent_volume_reclaim_policy: str = "Retain"  # Retain | Delete | Recycle
    claim_ref: Optional[ObjectReference] = None


@dataclass
class PersistentVolumeStatus:
    phase: str = "Available"  # Available | Bound | Released | Failed


@dataclass
class PersistentVolume(KObject):
    """Ref: core/v1 PV + pkg/controller/volume/persistentvolume binder."""

    KIND = "PersistentVolume"
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)
    status: PersistentVolumeStatus = field(default_factory=PersistentVolumeStatus)


@dataclass
class PersistentVolumeClaimSpec:
    access_modes: List[str] = field(default_factory=list)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    volume_name: str = ""
    storage_class_name: str = ""


@dataclass
class PersistentVolumeClaimStatus:
    phase: str = "Pending"  # Pending | Bound | Lost
    capacity: Dict[str, str] = field(default_factory=dict)
    access_modes: List[str] = field(default_factory=list)


@dataclass
class PersistentVolumeClaim(KObject):
    KIND = "PersistentVolumeClaim"
    spec: PersistentVolumeClaimSpec = field(default_factory=PersistentVolumeClaimSpec)
    status: PersistentVolumeClaimStatus = field(
        default_factory=PersistentVolumeClaimStatus
    )


@dataclass
class StorageClass(KObject):
    """Ref: pkg/apis/storage/types.go:28 — names a provisioner so PVCs can
    ask for storage that doesn't exist yet (dynamic provisioning) instead
    of binding only to pre-created PVs.

    volumeBindingMode (storage/types.go VolumeBindingMode):
      Immediate            — provision/bind as soon as the PVC appears
      WaitForFirstConsumer — hold the PVC Pending until a pod consuming it
                             is scheduled; on a TPU cluster this keeps a
                             checkpoint volume's hostPath on the node the
                             gang actually landed on."""

    KIND = "StorageClass"
    API_VERSION = "storage.k8s.io/v1"
    provisioner: str = ""
    reclaim_policy: str = "Delete"   # Delete | Retain
    volume_binding_mode: str = "Immediate"
    parameters: Dict[str, str] = field(default_factory=dict)


# -------------------------------------------------------------- certificates


@dataclass
class CertificateSigningRequestSpec:
    request: str = ""  # CSR payload (PEM in the reference; opaque string here)
    usages: List[str] = field(default_factory=list)
    username: str = ""
    groups: List[str] = field(default_factory=list)


@dataclass
class CSRCondition:
    type: str = ""  # Approved | Denied
    reason: str = ""
    message: str = ""
    last_update_time: str = ""


@dataclass
class CertificateSigningRequestStatus:
    conditions: List[CSRCondition] = field(default_factory=list)
    certificate: str = ""


@dataclass
class CertificateSigningRequest(KObject):
    """Ref: certificates/v1beta1 CSR + pkg/controller/certificates (signer
    issues on Approved condition; kubelet TLS bootstrap client flow)."""

    KIND = "CertificateSigningRequest"
    API_VERSION = "certificates/v1"
    spec: CertificateSigningRequestSpec = field(
        default_factory=CertificateSigningRequestSpec
    )
    status: CertificateSigningRequestStatus = field(
        default_factory=CertificateSigningRequestStatus
    )


# ------------------------------------------------------------ extensibility


@dataclass
class CRDNames:
    plural: str = ""
    singular: str = ""
    kind: str = ""


@dataclass
class CustomResourceDefinitionSpec:
    group: str = ""
    version: str = "v1"
    names: CRDNames = field(default_factory=CRDNames)
    scope: str = "Namespaced"  # Namespaced | Cluster


@dataclass
class CustomResourceDefinitionStatus:
    accepted_names: CRDNames = field(default_factory=CRDNames)
    conditions: List[str] = field(default_factory=list)


@dataclass
class CustomResourceDefinition(KObject):
    """Ref: apiextensions-apiserver CustomResourceDefinition — registers a
    dynamic REST resource served straight from the store."""

    KIND = "CustomResourceDefinition"
    API_VERSION = "apiextensions/v1"
    spec: CustomResourceDefinitionSpec = field(
        default_factory=CustomResourceDefinitionSpec
    )
    status: CustomResourceDefinitionStatus = field(
        default_factory=CustomResourceDefinitionStatus
    )


@dataclass
class APIServiceSpec:
    group: str = ""
    version: str = ""
    service_namespace: str = ""  # backing Service for delegation
    service_name: str = ""
    service_port: int = 443
    group_priority_minimum: int = 1000


@dataclass
class APIServiceStatus:
    available: bool = False
    message: str = ""


# --------------------------------------------------------------------- rbac


@dataclass
class PolicyRule:
    """Ref: rbac/v1 PolicyRule (staging/src/k8s.io/api/rbac/v1/types.go).
    api_groups are omitted — the flat registry has no group dimension."""

    verbs: List[str] = field(default_factory=list)       # get|list|watch|create|update|patch|delete|*
    resources: List[str] = field(default_factory=list)   # plural names or *
    resource_names: List[str] = field(default_factory=list)


@dataclass
class Subject:
    kind: str = "User"  # User | Group | ServiceAccount
    name: str = ""
    namespace: str = ""


@dataclass
class RoleRef:
    kind: str = "Role"  # Role | ClusterRole
    name: str = ""


@dataclass
class Role(KObject):
    KIND = "Role"
    API_VERSION = "rbac/v1"
    rules: List[PolicyRule] = field(default_factory=list)


@dataclass
class ClusterRole(KObject):
    KIND = "ClusterRole"
    API_VERSION = "rbac/v1"
    rules: List[PolicyRule] = field(default_factory=list)


@dataclass
class RoleBinding(KObject):
    KIND = "RoleBinding"
    API_VERSION = "rbac/v1"
    subjects: List[Subject] = field(default_factory=list)
    role_ref: RoleRef = field(default_factory=RoleRef)


@dataclass
class ClusterRoleBinding(KObject):
    KIND = "ClusterRoleBinding"
    API_VERSION = "rbac/v1"
    subjects: List[Subject] = field(default_factory=list)
    role_ref: RoleRef = field(default_factory=RoleRef)


@dataclass
class KubeletConfiguration(KObject):
    """ComponentConfig for the kubelet (ref: pkg/apis/componentconfig +
    pkg/kubelet/kubeletconfig/controller.go:81 — dynamic reconfiguration
    from a ConfigMap with validation and last-known-good rollback).

    Stored as the `kubelet` key (JSON) of a kube-system ConfigMap named
    kubelet-config-<node> (per-node) or kubelet-config (cluster-wide);
    the kubelet live-applies the dynamic fields below."""

    KIND = "KubeletConfiguration"
    API_VERSION = "kubelet.config.ktpu.io/v1"
    sync_interval_seconds: Optional[float] = None
    heartbeat_interval_seconds: Optional[float] = None
    pleg_interval_seconds: Optional[float] = None
    max_pods: Optional[int] = None
    eviction_thresholds: Dict[str, float] = field(default_factory=dict)
    volume_refresh_interval_seconds: Optional[float] = None


# ------------------------------------------------------------------ metrics


@dataclass
class ContainerMetrics:
    name: str = ""
    usage: Dict[str, str] = field(default_factory=dict)  # {"cpu": "250m", "memory": "64Mi"}


@dataclass
class PodMetrics(KObject):
    """Ref: staging/src/k8s.io/metrics pod metrics, fed here by each kubelet
    directly (the cadvisor → Summary API → metrics-server pipeline collapsed
    into one hop; HPA reads these)."""

    KIND = "PodMetrics"
    API_VERSION = "metrics.k8s.io/v1"
    timestamp: str = ""
    containers: List[ContainerMetrics] = field(default_factory=list)


@dataclass
class NodeMetrics(KObject):
    KIND = "NodeMetrics"
    API_VERSION = "metrics.k8s.io/v1"
    timestamp: str = ""
    usage: Dict[str, str] = field(default_factory=dict)


@dataclass
class MetricSample:
    """One named sample scraped off a pod /metrics endpoint.  `labels`
    carries the sample's own label set (a labeled child series); HPA
    Pods-metric matching is by bare `name`."""

    name: str = ""
    value: float = 0.0
    type: str = ""  # counter | gauge | rate (scrape-derived counter rate)
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class PodCustomMetrics(KObject):
    """Workload SLIs scraped off an annotated pod's /metrics endpoint by
    its node's kubelet (the custom.metrics.k8s.io pipeline collapsed into
    one hop, exactly like PodMetrics above).  `stale=True` means the last
    scrape failed and `samples` is the LAST-GOOD snapshot — consumers
    (the HPA) must treat stale samples as missing, never as fresh truth.
    The kubelet copies the pod's labels onto this object so selector
    reads work on the metrics collection directly."""

    KIND = "PodCustomMetrics"
    API_VERSION = "custom.metrics.k8s.io/v1"
    timestamp: str = ""
    stale: bool = False
    samples: List[MetricSample] = field(default_factory=list)


@dataclass
class PodPresetSpec:
    """Ref: settings.k8s.io/v1alpha1 PodPresetSpec — what to inject into
    pods matching the selector (env, envFrom, volumes, volumeMounts)."""

    selector: Optional[LabelSelector] = None
    env: List[EnvVar] = field(default_factory=list)
    env_from: List[EnvFromSource] = field(default_factory=list)
    volumes: List[Volume] = field(default_factory=list)
    volume_mounts: List[VolumeMount] = field(default_factory=list)


@dataclass
class PodPreset(KObject):
    """Ref: staging settings.k8s.io PodPreset + the PodPreset admission
    plugin (1.9 alpha) — declarative injection of config into pods at
    admission time; TPU use: one preset gives every training pod the
    checkpoint volume + coordinator env without touching Job templates."""

    KIND = "PodPreset"
    API_VERSION = "settings/v1alpha1"
    spec: PodPresetSpec = field(default_factory=PodPresetSpec)


@dataclass
class WebhookRule:
    """Which (operations x resources) a webhook intercepts (ref:
    admissionregistration/v1beta1 RuleWithOperations)."""

    operations: List[str] = field(default_factory=lambda: ["CREATE", "UPDATE"])
    resources: List[str] = field(default_factory=list)  # plurals; ["*"] = all


@dataclass
class Webhook:
    """One webhook endpoint (ref: admissionregistration Webhook).  The
    client config is a plain URL (no CA bundle layer here); the response
    `patch` is an RFC 7386 merge-patch object rather than upstream's
    base64 JSONPatch — consistent with this API server's PATCH support."""

    name: str = ""
    url: str = ""
    rules: List[WebhookRule] = field(default_factory=list)
    failure_policy: str = "Fail"  # Fail | Ignore
    timeout_seconds: float = 10.0


@dataclass
class MutatingWebhookConfiguration(KObject):
    """Ref: staging admissionregistration MutatingWebhookConfiguration —
    dynamic admission: matching requests POST an AdmissionReview to each
    webhook, which may return a patch to apply."""

    KIND = "MutatingWebhookConfiguration"
    API_VERSION = "admissionregistration/v1"
    webhooks: List[Webhook] = field(default_factory=list)


@dataclass
class ValidatingWebhookConfiguration(KObject):
    KIND = "ValidatingWebhookConfiguration"
    API_VERSION = "admissionregistration/v1"
    webhooks: List[Webhook] = field(default_factory=list)


@dataclass
class APIService(KObject):
    """Ref: kube-aggregator APIService — requests under /apis/<group>/<ver>
    proxy to the backing service's endpoints."""

    KIND = "APIService"
    API_VERSION = "apiregistration/v1"
    spec: APIServiceSpec = field(default_factory=APIServiceSpec)
    status: APIServiceStatus = field(default_factory=APIServiceStatus)


# ------------------------------------------------------- pod security policy

@dataclass
class PodSecurityPolicySpec:
    """Ref: pkg/apis/policy PodSecurityPolicySpec (the subset with teeth on
    a shared TPU host): may pods run privileged, which hostPath prefixes
    are mountable, and must they run as non-root."""

    privileged: bool = False
    # path PREFIXES a hostPath volume may use; empty = any path
    allowed_host_paths: List[str] = field(default_factory=list)
    # RunAsAny | MustRunAsNonRoot (ref RunAsUserStrategyOptions)
    run_as_user_rule: str = "RunAsAny"


@dataclass
class PodSecurityPolicy(KObject):
    """Ref: pkg/security/podsecuritypolicy + its admission plugin: a
    cluster-scoped policy every pod must satisfy (any one matching policy
    admits the pod)."""

    KIND = "PodSecurityPolicy"
    API_VERSION = "policy/v1beta1"
    spec: PodSecurityPolicySpec = field(default_factory=PodSecurityPolicySpec)


def effective_security_context(pod: "Pod", container: "Container") -> SecurityContext:
    """Container overrides pod (ref pkg/securitycontext
    DetermineEffectiveSecurityContext)."""
    psc = pod.spec.security_context
    csc = container.security_context
    out = SecurityContext()
    if psc is not None:
        out.run_as_user = psc.run_as_user
        out.run_as_group = psc.run_as_group
        out.run_as_non_root = psc.run_as_non_root
    if csc is not None:
        if csc.run_as_user is not None:
            out.run_as_user = csc.run_as_user
        if csc.run_as_group is not None:
            out.run_as_group = csc.run_as_group
        if csc.run_as_non_root is not None:
            out.run_as_non_root = csc.run_as_non_root
        out.privileged = csc.privileged
    return out
