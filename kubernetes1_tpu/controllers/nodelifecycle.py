"""Node lifecycle controller: heartbeat-based failure detection + eviction.

Ref: pkg/controller/node/node_controller.go with the reference's defaults
(options.go:96-97): a node whose Ready heartbeat is older than
monitor_grace goes NotReady; after eviction_timeout its pods are deleted so
their controllers recreate them elsewhere — the elastic-restart primitive
for preemptible TPU slices (a reclaimed v5e host's workers re-form on new
hosts via the Job controller's index-preserving recreate).
"""

from __future__ import annotations

import threading
import time
import traceback
from ..api import types as t
from ..client import Clientset, EventRecorder, InformerFactory
from ..machinery import ApiError, now_iso
from ..machinery.meta import parse_iso


class NodeLifecycleController:
    name = "node-lifecycle-controller"

    def __init__(
        self,
        clientset: Clientset,
        factory: InformerFactory,
        monitor_grace: float = 40.0,
        eviction_timeout: float = 300.0,
        monitor_interval: float = 5.0,
    ):
        self.cs = clientset
        self.factory = factory
        self.nodes = factory.informer("nodes")
        self.pods = factory.informer("pods")
        self.recorder = EventRecorder(clientset, self.name)
        self.monitor_grace = monitor_grace
        self.eviction_timeout = eviction_timeout
        self.monitor_interval = monitor_interval
        self._not_ready_since: dict = {}
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.wait(self.monitor_interval):
            try:
                self._monitor()
            except Exception:  # noqa: BLE001
                traceback.print_exc()

    def _ready_condition(self, node: t.Node):
        for cond in node.status.conditions:
            if cond.type == t.NODE_READY:
                return cond
        return None

    def _monitor(self):
        now = time.time()
        for node in self.nodes.list():
            name = node.metadata.name
            cond = self._ready_condition(node)
            stale = True
            if cond and cond.last_heartbeat_time:
                try:
                    stale = (now - parse_iso(cond.last_heartbeat_time)) > self.monitor_grace
                except ValueError:
                    stale = True
            if not stale and cond and cond.status == "True":
                self._not_ready_since.pop(name, None)
                continue
            # node is failing: mark NotReady (if kubelet isn't doing it) and
            # start the eviction clock
            since = self._not_ready_since.setdefault(name, now)
            if stale and cond and cond.status == "True":
                self._mark_not_ready(node)
            if now - since > self.eviction_timeout:
                self._evict_pods(node)

    def _mark_not_ready(self, node: t.Node):
        try:
            fresh = self.cs.nodes.get(node.metadata.name, "")
            cond = self._ready_condition(fresh)
            if cond is None:
                cond = t.NodeCondition(type=t.NODE_READY)
                fresh.status.conditions.append(cond)
            if cond.status != "Unknown":
                cond.status = "Unknown"
                cond.reason = "NodeStatusUnknown"
                cond.message = "kubelet stopped posting node status"
                cond.last_transition_time = now_iso()
                self.cs.nodes.update_status(fresh)
                self.recorder.event(
                    fresh, "Warning", "NodeNotReady",
                    f"node {node.metadata.name} heartbeat stale",
                )
        except ApiError:
            pass

    def _evict_pods(self, node: t.Node):
        for pod in self.pods.list():
            if pod.spec.node_name != node.metadata.name:
                continue
            if pod.status.phase in (t.POD_SUCCEEDED, t.POD_FAILED):
                continue  # finished pods hold no resources; leave the record
            if pod.metadata.deletion_timestamp:
                # kubelet is gone and can't finalize: force delete so the
                # controller can replace the pod
                try:
                    self.cs.pods.delete(
                        pod.metadata.name, pod.metadata.namespace, grace_seconds=0
                    )
                except ApiError:
                    pass
                continue
            try:
                self.cs.pods.delete(pod.metadata.name, pod.metadata.namespace)
                self.recorder.event(
                    pod, "Warning", "NodeEviction",
                    f"evicted: node {node.metadata.name} unreachable",
                )
            except ApiError:
                pass
