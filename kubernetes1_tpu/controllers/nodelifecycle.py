"""Node lifecycle controller: heartbeat-based failure detection + eviction.

Ref: pkg/controller/node/node_controller.go with the reference's defaults
(options.go:96-97): a node whose Ready heartbeat is older than
monitor_grace goes NotReady; after eviction_timeout its pods are deleted so
their controllers recreate them elsewhere — the elastic-restart primitive
for preemptible TPU slices (a reclaimed v5e host's workers re-form on new
hosts via the Job controller's index-preserving recreate).
"""

from __future__ import annotations

import threading
import time
import traceback
from ..api import types as t
from ..client import Clientset, EventRecorder, InformerFactory
from ..machinery import ApiError, now_iso
from ..machinery.meta import parse_iso


class NodeLifecycleController:
    name = "node-lifecycle-controller"

    def __init__(
        self,
        clientset: Clientset,
        factory: InformerFactory,
        monitor_grace: float = 40.0,
        eviction_timeout: float = 300.0,
        monitor_interval: float = 5.0,
    ):
        self.cs = clientset
        self.factory = factory
        self.nodes = factory.informer("nodes")
        self.pods = factory.informer("pods")
        self.recorder = EventRecorder(clientset, self.name)
        self.monitor_grace = monitor_grace
        self.eviction_timeout = eviction_timeout
        self.monitor_interval = monitor_interval
        self._not_ready_since: dict = {}
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.wait(self.monitor_interval):
            try:
                self._monitor()
            except Exception:  # noqa: BLE001
                traceback.print_exc()

    def _ready_condition(self, node: t.Node):
        for cond in node.status.conditions:
            if cond.type == t.NODE_READY:
                return cond
        return None

    def _monitor(self):
        now = time.time()  # ktpulint: ignore[KTPU005] vs heartbeat API timestamps
        for node in self.nodes.list():
            name = node.metadata.name
            cond = self._ready_condition(node)
            stale = True
            if cond and cond.last_heartbeat_time:
                try:
                    stale = (now - parse_iso(cond.last_heartbeat_time)) > self.monitor_grace
                except ValueError:
                    stale = True
            if not stale and cond and cond.status == "True":
                self._not_ready_since.pop(name, None)
                # removal keys off the LISTED node's taints, not in-memory
                # state — a restarted controller must still untaint
                # recovered nodes (and skip the GET when no taint shows)
                if any(tt.key == self.NOT_READY_TAINT for tt in node.spec.taints):
                    self._remove_not_ready_taint(node)
                continue
            # node is failing: mark NotReady (if kubelet isn't doing it) and
            # start the eviction clock
            since = self._not_ready_since.setdefault(name, now)
            if stale and cond and cond.status == "True":
                self._mark_not_ready(node)
            from ..utils.features import gates

            if gates.enabled("TaintBasedEvictions"):
                # taint-based path REPLACES the flat timer: the NoExecute
                # taint keeps new pods off, and each pod's own
                # tolerationSeconds (DefaultTolerationSeconds injects 300s)
                # decides when it falls
                if not any(tt.key == self.NOT_READY_TAINT
                           for tt in node.spec.taints):
                    self._apply_not_ready_taint(node)
                self._evict_by_toleration(node, now - since)
            elif now - since > self.eviction_timeout:
                self._evict_pods(node)

    NOT_READY_TAINT = "node.kubernetes.io/not-ready"

    def _apply_not_ready_taint(self, node: t.Node):
        """TaintBasedEvictions (feature-gated, alpha in the reference): a
        failing node gets the not-ready:NoExecute taint — the effect the
        DefaultTolerationSeconds tolerations actually match."""
        try:
            fresh = self.cs.nodes.get(node.metadata.name, "")
            if any(tt.key == self.NOT_READY_TAINT for tt in fresh.spec.taints):
                return
            fresh.spec.taints.append(
                t.Taint(key=self.NOT_READY_TAINT, effect="NoExecute"))
            self.cs.nodes.update(fresh)
        except ApiError:
            pass

    def _remove_not_ready_taint(self, node: t.Node):
        try:
            fresh = self.cs.nodes.get(node.metadata.name, "")
            kept = [tt for tt in fresh.spec.taints
                    if tt.key != self.NOT_READY_TAINT]
            if len(kept) != len(fresh.spec.taints):
                fresh.spec.taints = kept
                self.cs.nodes.update(fresh)
        except ApiError:
            pass

    def _evict_by_toleration(self, node: t.Node, not_ready_for: float):
        """NoExecute semantics (ref: the taint manager): a pod with no
        matching toleration falls immediately; tolerationSeconds=N falls
        after N; an unbounded toleration rides out the outage."""
        taint = t.Taint(key=self.NOT_READY_TAINT, effect="NoExecute")
        from ..scheduler.predicates import _tolerates

        for pod in self.pods.list():
            if pod.spec.node_name != node.metadata.name:
                continue
            if pod.status.phase in (t.POD_SUCCEEDED, t.POD_FAILED):
                continue
            matching = [tol for tol in pod.spec.tolerations
                        if _tolerates(tol, taint)]
            if matching:
                seconds = [tol.toleration_seconds for tol in matching]
                if any(s is None for s in seconds):
                    continue  # tolerates indefinitely
                if not_ready_for <= max(s for s in seconds):
                    continue  # still within its grace window
            if pod.metadata.deletion_timestamp:
                try:  # kubelet is gone; force-finalize so it reschedules
                    self.cs.pods.delete(
                        pod.metadata.name, pod.metadata.namespace, grace_seconds=0)
                except ApiError:
                    pass
                continue
            try:
                self.cs.pods.delete(pod.metadata.name, pod.metadata.namespace)
                self.recorder.event(
                    pod, "Warning", "TaintEviction",
                    f"evicted: node {node.metadata.name} not-ready past "
                    f"the pod's toleration",
                )
            except ApiError:
                pass

    def _mark_not_ready(self, node: t.Node):
        try:
            fresh = self.cs.nodes.get(node.metadata.name, "")
            cond = self._ready_condition(fresh)
            if cond is None:
                cond = t.NodeCondition(type=t.NODE_READY)
                fresh.status.conditions.append(cond)
            if cond.status != "Unknown":
                cond.status = "Unknown"
                cond.reason = "NodeStatusUnknown"
                cond.message = "kubelet stopped posting node status"
                cond.last_transition_time = now_iso()
                self.cs.nodes.update_status(fresh)
                self.recorder.event(
                    fresh, "Warning", "NodeNotReady",
                    f"node {node.metadata.name} heartbeat stale",
                )
        except ApiError:
            pass

    def _evict_pods(self, node: t.Node):
        for pod in self.pods.list():
            if pod.spec.node_name != node.metadata.name:
                continue
            if pod.status.phase in (t.POD_SUCCEEDED, t.POD_FAILED):
                continue  # finished pods hold no resources; leave the record
            if pod.metadata.deletion_timestamp:
                # kubelet is gone and can't finalize: force delete so the
                # controller can replace the pod
                try:
                    self.cs.pods.delete(
                        pod.metadata.name, pod.metadata.namespace, grace_seconds=0
                    )
                except ApiError:
                    pass
                continue
            try:
                self.cs.pods.delete(pod.metadata.name, pod.metadata.namespace)
                self.recorder.event(
                    pod, "Warning", "NodeEviction",
                    f"evicted: node {node.metadata.name} unreachable",
                )
            except ApiError:
                pass
