"""Node lifecycle controller: heartbeat-based failure detection + eviction.

Ref: pkg/controller/node/node_controller.go with the reference's defaults
(options.go:96-97): a node whose Ready heartbeat is older than
monitor_grace goes NotReady; after eviction_timeout its pods are deleted so
their controllers recreate them elsewhere — the elastic-restart primitive
for preemptible TPU slices (a reclaimed v5e host's workers re-form on new
hosts via the Job controller's gang failure policy).

Every API mutation routes through client/retry's shared policy (standing
invariant): transient failures — link faults, overload sheds, 5xx — back
off with full jitter and retry in place; Conflict re-runs the
read-modify-write closure; errors that outlive the budget are COUNTED
(errors_total) and retried by the next monitor pass, which recomputes the
world from the informer (the loop is level-triggered, so a dropped write
is delayed, never lost).  Evictions are counted exactly once per pod
(evictions_total) with an Event on each — the chaos tier's
NotReady→eviction-fires-exactly-once verdict reads these counters.
"""

from __future__ import annotations

import threading
import time
import traceback
from ..api import types as t
from ..client import Clientset, EventRecorder, InformerFactory
from ..client import retry as _retry
from ..machinery import ApiError, Conflict, NotFound, now_iso
from ..machinery.meta import parse_iso
from ..utils.metrics import Counter


class NodeLifecycleController:
    name = "node-lifecycle-controller"

    def __init__(
        self,
        clientset: Clientset,
        factory: InformerFactory,
        monitor_grace: float = 40.0,
        eviction_timeout: float = 300.0,
        monitor_interval: float = 5.0,
    ):
        self.cs = clientset
        self.factory = factory
        self.nodes = factory.informer("nodes")
        self.pods = factory.informer("pods")
        self.recorder = EventRecorder(clientset, self.name)
        self.monitor_grace = monitor_grace
        self.eviction_timeout = eviction_timeout
        self.monitor_interval = monitor_interval
        self._not_ready_since: dict = {}
        # uids whose eviction was already counted+evented: the informer may
        # not deliver the deletion_timestamp before the next monitor pass,
        # and the exactly-once contract must not ride on watch latency.
        # Pruned against the live pod list each pass (a gone pod can never
        # be re-evicted), so it stays bounded under churn.
        self._evicted_uids: set = set()
        self._stop = threading.Event()
        self._thread = None
        # instance-level counters (not Registry-bound): scraped by
        # bench.py/scripts/chaos.py for exactly-once verdicts
        self.evictions_total = Counter(
            "ktpu_node_evictions_total", "pods evicted off failed nodes")
        self.errors_total = Counter(
            "ktpu_nodelifecycle_errors_total",
            "API errors surviving the retry budget + monitor-pass crashes")
        self.not_ready_total = Counter(
            "ktpu_node_not_ready_transitions_total",
            "Ready->Unknown transitions this controller marked")

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.wait(self.monitor_interval):
            try:
                self._monitor()
            except Exception:  # noqa: BLE001 — monitor must survive; counted + next pass retries
                self.errors_total.inc()
                traceback.print_exc()

    def _mutate(self, closure):
        """One read-modify-write through the shared retry policy: transient
        failures back off with jitter, Conflict re-runs the closure against
        a fresh read.  Returns the closure's result, or None once the
        budget runs out / the object is gone — the next monitor pass
        recomputes and retries, so None is a delay, not a loss."""
        try:
            return _retry.call_with_retries(
                lambda: _retry.retry_on_conflict(closure),
                steps=3, reason="nodelifecycle")
        except NotFound:
            return None  # already gone: the desired state holds
        except Conflict:
            return None  # persistent write race: next pass re-reads
        except (ApiError, ConnectionError, TimeoutError, OSError):
            self.errors_total.inc()
            return None

    def _delete_pod(self, pod: t.Pod, grace_seconds=None) -> bool:
        def op():
            if grace_seconds is None:
                self.cs.pods.delete(pod.metadata.name, pod.metadata.namespace)
            else:
                self.cs.pods.delete(pod.metadata.name, pod.metadata.namespace,
                                    grace_seconds=grace_seconds)
            return True

        return bool(self._mutate(op))

    def _delete_pods_batched(self, pods, grace_seconds=None,
                             reason="nodelifecycle"):
        """Batch leg of _delete_pod: one pods/delete:batch per pass
        instead of a round-trip per evicted pod (a dead 110-pod node's
        eviction storm is the hot case).  Returns one bool per pod —
        True only when THIS pass's delete landed (NotFound = already
        gone = False, same as the singleton path's exactly-once
        accounting); transient per-item failures are counted and left to
        the next monitor pass."""
        from ..machinery import NotFound as _NotFound
        from .base import delete_pods_batch

        if not pods:
            return []
        out = []
        for err in delete_pods_batch(self.cs, pods,
                                     grace_seconds=grace_seconds,
                                     reason=reason):
            if err is None:
                out.append(True)
            elif isinstance(err, (_NotFound, Conflict)):
                out.append(False)  # already gone / write race: next pass
            else:
                self.errors_total.inc()
                out.append(False)
        return out

    def _ready_condition(self, node: t.Node):
        for cond in node.status.conditions:
            if cond.type == t.NODE_READY:
                return cond
        return None

    def _monitor(self):
        now = time.time()  # ktpulint: ignore[KTPU005] vs heartbeat API timestamps
        if self._evicted_uids:
            self._evicted_uids &= {p.metadata.uid for p in self.pods.list()}
        for node in self.nodes.list():
            name = node.metadata.name
            cond = self._ready_condition(node)
            stale = True
            if cond and cond.last_heartbeat_time:
                try:
                    stale = (now - parse_iso(cond.last_heartbeat_time)) > self.monitor_grace
                except ValueError:
                    stale = True
            if not stale and cond and cond.status == "True":
                self._not_ready_since.pop(name, None)
                # removal keys off the LISTED node's taints, not in-memory
                # state — a restarted controller must still untaint
                # recovered nodes (and skip the GET when no taint shows)
                if any(tt.key == self.NOT_READY_TAINT for tt in node.spec.taints):
                    self._remove_not_ready_taint(node)
                continue
            # node is failing: mark NotReady (if kubelet isn't doing it) and
            # start the eviction clock
            since = self._not_ready_since.setdefault(name, now)
            if stale and cond and cond.status == "True":
                self._mark_not_ready(node)
            from ..utils.features import gates

            if gates.enabled("TaintBasedEvictions"):
                # taint-based path REPLACES the flat timer: the NoExecute
                # taint keeps new pods off, and each pod's own
                # tolerationSeconds (DefaultTolerationSeconds injects 300s)
                # decides when it falls
                if not any(tt.key == self.NOT_READY_TAINT
                           for tt in node.spec.taints):
                    self._apply_not_ready_taint(node)
                self._evict_by_toleration(node, now - since)
            elif now - since > self.eviction_timeout:
                self._evict_pods(node)

    NOT_READY_TAINT = "node.kubernetes.io/not-ready"

    def _apply_not_ready_taint(self, node: t.Node):
        """TaintBasedEvictions (feature-gated, alpha in the reference): a
        failing node gets the not-ready:NoExecute taint — the effect the
        DefaultTolerationSeconds tolerations actually match."""
        name = node.metadata.name

        def apply():
            fresh = self.cs.nodes.get(name, "")
            if any(tt.key == self.NOT_READY_TAINT for tt in fresh.spec.taints):
                return False
            fresh.spec.taints.append(
                t.Taint(key=self.NOT_READY_TAINT, effect="NoExecute"))
            self.cs.nodes.update(fresh)
            return True

        self._mutate(apply)

    def _remove_not_ready_taint(self, node: t.Node):
        name = node.metadata.name

        def remove():
            fresh = self.cs.nodes.get(name, "")
            kept = [tt for tt in fresh.spec.taints
                    if tt.key != self.NOT_READY_TAINT]
            if len(kept) != len(fresh.spec.taints):
                fresh.spec.taints = kept
                self.cs.nodes.update(fresh)
            return True

        self._mutate(remove)

    def _evict_by_toleration(self, node: t.Node, not_ready_for: float):
        """NoExecute semantics (ref: the taint manager): a pod with no
        matching toleration falls immediately; tolerationSeconds=N falls
        after N; an unbounded toleration rides out the outage."""
        taint = t.Taint(key=self.NOT_READY_TAINT, effect="NoExecute")
        from ..scheduler.predicates import _tolerates

        finalize, fresh = [], []
        for pod in self.pods.list():
            if pod.spec.node_name != node.metadata.name:
                continue
            if pod.status.phase in (t.POD_SUCCEEDED, t.POD_FAILED):
                continue
            matching = [tol for tol in pod.spec.tolerations
                        if _tolerates(tol, taint)]
            if matching:
                seconds = [tol.toleration_seconds for tol in matching]
                if any(s is None for s in seconds):
                    continue  # tolerates indefinitely
                if not_ready_for <= max(s for s in seconds):
                    continue  # still within its grace window
            if pod.metadata.deletion_timestamp:
                # kubelet is gone; force-finalize so it reschedules — the
                # eviction was already counted when the first delete landed
                finalize.append(pod)
                continue
            if pod.metadata.uid in self._evicted_uids:
                continue  # counted; waiting on the watch to show the delete
            fresh.append(pod)
        self._delete_pods_batched(finalize, grace_seconds=0,
                                  reason="nodelifecycle_finalize")
        for pod, landed in zip(fresh, self._delete_pods_batched(
                fresh, reason="nodelifecycle_taint_evict")):
            if landed:
                # the delete stamps deletion_timestamp, so later passes take
                # the force-finalize branch above: exactly one count + Event
                # per evicted pod
                self._evicted_uids.add(pod.metadata.uid)
                self.evictions_total.inc()
                self.recorder.event(
                    pod, "Warning", "TaintEviction",
                    f"evicted: node {node.metadata.name} not-ready past "
                    f"the pod's toleration",
                )

    def _mark_not_ready(self, node: t.Node):
        name = node.metadata.name

        def mark():
            fresh = self.cs.nodes.get(name, "")
            cond = self._ready_condition(fresh)
            if cond is None:
                cond = t.NodeCondition(type=t.NODE_READY)
                fresh.status.conditions.append(cond)
            if cond.status == "Unknown":
                return None  # someone (or a prior pass) already marked it
            cond.status = "Unknown"
            cond.reason = "NodeStatusUnknown"
            cond.message = "kubelet stopped posting node status"
            cond.last_transition_time = now_iso()
            self.cs.nodes.update_status(fresh)
            return fresh

        fresh = self._mutate(mark)
        if fresh is not None:
            self.not_ready_total.inc()
            self.recorder.event(
                fresh, "Warning", "NodeNotReady",
                f"node {name} heartbeat stale",
            )

    def _evict_pods(self, node: t.Node):
        finalize, fresh = [], []
        for pod in self.pods.list():
            if pod.spec.node_name != node.metadata.name:
                continue
            if pod.status.phase in (t.POD_SUCCEEDED, t.POD_FAILED):
                continue  # finished pods hold no resources; leave the record
            if pod.metadata.deletion_timestamp:
                # kubelet is gone and can't finalize: force delete so the
                # controller can replace the pod (not a new eviction — it
                # was counted when the graceful delete landed)
                finalize.append(pod)
                continue
            if pod.metadata.uid in self._evicted_uids:
                continue  # counted; waiting on the watch to show the delete
            fresh.append(pod)
        # a dead node's whole pod set evicts/finalizes as TWO batch
        # requests (graceful + grace-0) instead of a round-trip per pod
        self._delete_pods_batched(finalize, grace_seconds=0,
                                  reason="nodelifecycle_finalize")
        for pod, landed in zip(fresh, self._delete_pods_batched(
                fresh, reason="nodelifecycle_evict")):
            if landed:
                self._evicted_uids.add(pod.metadata.uid)
                self.evictions_total.inc()
                self.recorder.event(
                    pod, "Warning", "NodeEviction",
                    f"evicted: node {node.metadata.name} unreachable",
                )
