"""Pod garbage collector (ref: pkg/controller/podgc/gc_controller.go):
(1) deletes pods bound to nodes that no longer exist (orphaned pods — the
elastic-recovery path after a TPU host is replaced), (2) caps the number of
terminated pods kept around for inspection."""

from __future__ import annotations

from ..api import types as t
from .base import Controller, delete_pods_batch

RESYNC = 5.0  # the reference's gcCheckPeriod is 20s


class PodGCController(Controller):
    name = "podgc-controller"

    def __init__(
        self,
        clientset,
        factory,
        terminated_pod_threshold: int = 100,
        quarantine: float = 2 * RESYNC,
        workers: int = 1,
    ):
        super().__init__(clientset, factory, workers)
        self.terminated_pod_threshold = terminated_pod_threshold
        # A node must be missing this long before its pods are deleted — the
        # pods and nodes informers are independent watch streams, so a
        # just-registered node can briefly be absent from our cache while its
        # first bound pod is already present (upstream quarantines likewise).
        self.quarantine = quarantine
        self._missing_since: dict = {}  # node_name -> monotonic first-seen-missing
        self._tick_key = "podgc/tick"

    def setup(self):
        self.pods = self.factory.informer("pods")
        self.nodes = self.factory.informer("nodes")
        self.queue.add(self._tick_key)

    def sync(self, key: str):
        try:
            self._gc_orphaned()
            self._gc_terminated()
        finally:
            self.enqueue_after(self._tick_key, RESYNC)

    def _gc_orphaned(self):
        import time

        if not self.nodes.has_synced():
            return
        node_names = {n.metadata.name for n in self.nodes.list()}
        now = time.monotonic()
        for known in [n for n in self._missing_since if n in node_names]:
            del self._missing_since[known]
        doomed = []
        for p in self.pods.list():
            node = p.spec.node_name
            if not node or node in node_names or p.metadata.deletion_timestamp:
                continue
            first = self._missing_since.setdefault(node, now)
            if now - first < self.quarantine:
                continue
            doomed.append(p)
        # the whole orphan sweep finalizes through ONE delete:batch group
        # commit (a replaced TPU host orphans its pods all at once)
        for p, err in zip(doomed, delete_pods_batch(
                self.cs, doomed, grace_seconds=0, reason="podgc_orphaned")):
            if err is None:
                self.recorder.event(
                    p, "Normal", "PodGC",
                    f"deleted orphaned pod bound to missing node "
                    f"{p.spec.node_name}",
                )

    def _gc_terminated(self):
        terminated = [
            p for p in self.pods.list()
            if p.status.phase in (t.POD_SUCCEEDED, t.POD_FAILED)
            and not p.metadata.deletion_timestamp
            and not p.metadata.owner_references  # keep controller-owned history
        ]
        excess = len(terminated) - self.terminated_pod_threshold
        if excess <= 0:
            return
        terminated.sort(key=lambda p: p.metadata.creation_timestamp)
        # one batch for the whole cap sweep (outcomes ignored: the next
        # resync re-lists and retries anything that didn't land)
        delete_pods_batch(self.cs, terminated[:excess], grace_seconds=0,
                          reason="podgc_terminated")
