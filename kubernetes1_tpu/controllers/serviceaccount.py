"""ServiceAccount + token controllers (ref: pkg/controller/serviceaccount/
serviceaccounts_controller.go + tokens_controller.go): every namespace gets a
'default' ServiceAccount; every ServiceAccount gets a signed token Secret
referenced from .secrets. Tokens are HMAC-signed with the cluster's service
account key (the reference signs JWTs with the --service-account-key-file
RSA key; the construction here is the same shape without an x509 stack)."""

from __future__ import annotations

import base64
import hashlib
import hmac
import json

from ..api import types as t
from ..machinery import AlreadyExists, ApiError, NotFound
from .base import Controller

TOKEN_SECRET_TYPE = "kubernetes.io/service-account-token"


def sign_token(key: str, namespace: str, name: str, uid: str) -> str:
    """Compact HMAC token: base64(payload).base64(hmac)."""
    payload = json.dumps(
        {"sub": f"system:serviceaccount:{namespace}:{name}", "uid": uid},
        sort_keys=True, separators=(",", ":"),
    ).encode()
    mac = hmac.new(key.encode(), payload, hashlib.sha256).digest()
    return (
        base64.urlsafe_b64encode(payload).rstrip(b"=").decode()
        + "."
        + base64.urlsafe_b64encode(mac).rstrip(b"=").decode()
    )


def verify_token(key: str, token: str):
    """Return the subject dict or None."""
    try:
        p64, m64 = token.split(".", 1)
        pad = lambda s: s + "=" * (-len(s) % 4)  # noqa: E731
        payload = base64.urlsafe_b64decode(pad(p64))
        mac = base64.urlsafe_b64decode(pad(m64))
        want = hmac.new(key.encode(), payload, hashlib.sha256).digest()
        if not hmac.compare_digest(mac, want):
            return None
        return json.loads(payload)
    except (ValueError, json.JSONDecodeError):
        return None


class ServiceAccountController(Controller):
    name = "serviceaccount-controller"

    def __init__(self, clientset, factory, signing_key: str = "ktpu-sa-key", workers: int = 1):
        super().__init__(clientset, factory, workers)
        self.signing_key = signing_key

    def setup(self):
        self.namespaces = self.factory.informer("namespaces")
        self.serviceaccounts = self.factory.informer("serviceaccounts")
        self.namespaces.add_handler(
            on_add=self.enqueue, on_update=lambda _o, n: self.enqueue(n)
        )
        self.serviceaccounts.add_handler(
            on_add=self._sa_event,
            on_update=lambda _o, n: self._sa_event(n),
            on_delete=self._sa_event,
        )

    def _sa_event(self, sa: t.ServiceAccount):
        ns = self.namespaces.get(sa.metadata.namespace)
        if ns is not None:
            self.enqueue(ns)

    def sync(self, key: str):
        ns = self.namespaces.get(key)
        if ns is None or ns.status.phase == "Terminating":
            return
        nsname = ns.metadata.name
        try:
            sa = self.cs.serviceaccounts.get("default", nsname)
        except NotFound:
            sa = t.ServiceAccount()
            sa.metadata.name = "default"
            sa.metadata.namespace = nsname
            try:
                sa = self.cs.serviceaccounts.create(sa, nsname)
            except AlreadyExists:
                sa = self.cs.serviceaccounts.get("default", nsname)
        self._ensure_token(sa)
        # tokens for any other ServiceAccounts in this namespace
        for other in self.serviceaccounts.list():
            if other.metadata.namespace == nsname and other.metadata.name != "default":
                self._ensure_token(other)

    def _ensure_token(self, sa: t.ServiceAccount):
        """Token controller half: mint the token Secret and link it."""
        if sa.secrets:
            return
        secret = t.Secret(type=TOKEN_SECRET_TYPE)
        secret.metadata.name = f"{sa.metadata.name}-token"
        secret.metadata.namespace = sa.metadata.namespace
        secret.data = {
            "token": sign_token(
                self.signing_key, sa.metadata.namespace, sa.metadata.name,
                sa.metadata.uid,
            ),
            "namespace": sa.metadata.namespace,
        }
        try:
            self.cs.secrets.create(secret, sa.metadata.namespace)
        except AlreadyExists:
            pass
        try:
            fresh = self.cs.serviceaccounts.get(sa.metadata.name, sa.metadata.namespace)
            if not fresh.secrets:
                fresh.secrets = [
                    t.ObjectReference(
                        kind="Secret", namespace=sa.metadata.namespace,
                        name=secret.metadata.name,
                    )
                ]
                self.cs.serviceaccounts.update(fresh)
        except ApiError:
            pass  # requeue via event
