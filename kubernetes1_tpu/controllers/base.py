"""Controller base: informer-fed, workqueue-driven reconcilers.

Ref: the universal controller shape in pkg/controller/ — informer events
enqueue keys into a rate-limited workqueue; N workers pop and call a
level-triggered sync that compares desired vs actual and writes the
difference through the API (never acting on the event payload itself).
"""

from __future__ import annotations

import threading
import traceback
from typing import List, Optional

from ..client import Clientset, EventRecorder, InformerFactory
from ..machinery.scheme import to_dict
from ..utils.workqueue import RateLimitingQueue


def delete_pods_batch(cs: Clientset, pods, grace_seconds=None,
                      reason: str = "pod_delete_batch"):
    """Delete N pods through ONE pods/delete:batch request per namespace
    (the deletion half of the group-commit write path) — the shared leg
    for every hot delete caller (gang teardown, replicaset scale-down,
    podgc sweeps, node-lifecycle eviction).

    Per-pod outcomes come back aligned with `pods`: None on success or
    the ApiError that sank that member (NotFound comes back as the error
    so exactly-once accounting callers can tell "I deleted it" from
    "already gone").  An ENVELOPE-level failure (transport fault, an apiserver
    without the batch leg) falls back to singleton deletes through the
    shared retry policy, so a controller on a degraded wire degrades to
    exactly the pre-batch behavior instead of dropping the pass."""
    from ..client import retry as _retry
    from ..machinery import ApiError

    if not pods:
        return []
    outcomes = [None] * len(pods)
    by_ns = {}
    for i, p in enumerate(pods):
        by_ns.setdefault(p.metadata.namespace, []).append(i)
    for ns, idxs in by_ns.items():
        items = [{"name": pods[i].metadata.name} for i in idxs]
        try:
            results = cs.delete_batch(ns, items, grace_seconds=grace_seconds)
            if len(results) != len(idxs):
                raise ApiError(
                    f"malformed delete:batch response: {len(results)} "
                    f"results for {len(items)} items")
        except (ApiError, ConnectionError, TimeoutError, OSError):
            # envelope failed: per-pod fallback (idempotent — a delete
            # that DID land answers NotFound, which is success here)
            for i in idxs:
                p = pods[i]
                try:
                    _retry.call_with_retries(
                        lambda p=p: cs.pods.delete(
                            p.metadata.name, p.metadata.namespace,
                            grace_seconds=grace_seconds),
                        steps=3, reason=reason)
                except (ApiError, ConnectionError, TimeoutError, OSError) as e:
                    outcomes[i] = e  # NotFound included: caller decides
            continue
        for i, err in zip(idxs, results):
            outcomes[i] = err
    return outcomes


def write_status_if_changed(client, obj, mutate) -> bool:
    """Apply mutate(obj.status) and PUT the status subresource only when it
    actually changed. A no-op status write still bumps resourceVersion and
    fires a MODIFIED event, which re-triggers the writing controller's own
    informer (an infinite write storm) and conflicts every other writer out
    of its get→update window — the replicaset/deployment livelock."""
    before = to_dict(obj.status)
    mutate(obj.status)
    if to_dict(obj.status) == before:
        return False
    client.update_status(obj)
    return True


class Controller:
    name = "controller"

    def __init__(self, clientset: Clientset, factory: InformerFactory, workers: int = 2):
        self.cs = clientset
        self.factory = factory
        self.queue = RateLimitingQueue()
        self.workers = workers
        self.recorder = EventRecorder(clientset, self.name)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # subclasses wire informer handlers in setup() and implement sync(key)

    def setup(self):
        raise NotImplementedError

    def sync(self, key: str):
        raise NotImplementedError

    def enqueue(self, obj):
        self.queue.add(obj.key())

    def enqueue_after(self, key: str, delay: float):
        self.queue.add_after(key, delay)

    def start_workers(self):
        for i in range(self.workers):
            th = threading.Thread(
                target=self._worker, daemon=True, name=f"{self.name}-{i}"
            )
            th.start()
            self._threads.append(th)
        return self

    def start(self):
        self.setup()
        return self.start_workers()

    def stop(self):
        self._stop.set()
        self.queue.shut_down()

    def _worker(self):
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.5)
            if key is None:
                continue
            try:
                self.sync(key)
                self.queue.forget(key)
            except Exception:  # noqa: BLE001
                traceback.print_exc()
                self.queue.add_rate_limited(key)
            finally:
                self.queue.done(key)
