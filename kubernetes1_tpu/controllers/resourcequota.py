"""ResourceQuota controller (ref: pkg/controller/resourcequota/
resource_quota_controller.go): recalculates each quota's status.used from the
authoritative object lists so observers (CLI, admission failure messages)
see current consumption. Enforcement itself happens in the apiserver's
ResourceQuota admission plugin."""

from __future__ import annotations

from ..api import types as t
from ..apiserver.admission import compute_namespace_usage
from ..machinery import ApiError, Conflict, NotFound
from .base import Controller


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else f"{v:g}"


class ResourceQuotaController(Controller):
    name = "resourcequota-controller"

    # Quota usage is LEVEL-recomputed: sync() re-derives status.used from
    # authoritative LISTs, so any missed edge (a resource kind this
    # controller has no informer for — services, configmaps, PVCs all
    # count against quota) self-heals on the next delivery.  The resync
    # period is that backstop's cadence: the shared quota informer
    # redelivers every cached quota locally (SharedInformer.resync_period
    # — no API traffic, NOT a relist), and each redelivery enqueues a
    # recompute.  Event-driven requeues (pod churn below) stay the fast
    # path; this bounds staleness for everything they can't see.
    resync_period = 10.0

    def setup(self):
        self.quotas = self.factory.informer(
            "resourcequotas", resync_period=self.resync_period)
        self.pods = self.factory.informer("pods")
        self.quotas.add_handler(
            on_add=self.enqueue, on_update=lambda _o, n: self.enqueue(n)
        )
        # pod churn is what moves usage; requeue the namespace's quotas
        self.pods.add_handler(
            on_add=self._pod_event,
            on_update=lambda _o, n: self._pod_event(n),
            on_delete=self._pod_event,
        )

    def _pod_event(self, pod: t.Pod):
        for q in self.quotas.list():
            if q.metadata.namespace == pod.metadata.namespace:
                self.enqueue(q)

    def _usage(self, namespace: str) -> dict:
        def lister(resource, ns):
            try:
                return self.cs.resource(resource).list(namespace=ns)[0]
            except ApiError:
                return []

        return compute_namespace_usage(lister, namespace)

    def sync(self, key: str):
        quota = self.quotas.get(key)
        if quota is None:
            return
        usage = self._usage(quota.metadata.namespace)
        used = {res: _fmt(usage.get(res, 0.0)) for res in quota.spec.hard}
        if quota.status.used == used and quota.status.hard == quota.spec.hard:
            return
        try:
            fresh = self.cs.resourcequotas.get(
                quota.metadata.name, quota.metadata.namespace
            )
            fresh.status.hard = dict(quota.spec.hard)
            fresh.status.used = used
            self.cs.resourcequotas.update_status(fresh)
        except (NotFound, Conflict):
            pass  # requeued by the next event / resync
