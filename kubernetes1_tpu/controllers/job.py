"""Job controller: run-to-completion workloads — the TPU training primitive.

Ref: pkg/controller/job/job_controller.go (syncJob :425, manageJob :633-700,
completion counting :523-545), extended with the two capabilities SURVEY.md
§2.1 identifies as reference gaps that multi-host TPU training requires:

1. **Indexed completion mode** — each pod carries a stable completion index
   0..completions-1 (annotation batch.ktpu.io/completion-index and pod name
   suffix "<job>-<index>"), which the TPU device plugin turns into
   TPU_WORKER_ID.  A v5p-32 slice Job runs as 8 indexed workers whose JAX
   processes learn their coordinates from the index.
2. **Gang scheduling** — spec.gang_scheduling=True stamps every pod with
   (scheduling_gang=<job uid>, gang_size=parallelism) so the scheduler binds
   the whole worker set atomically on one ICI slice.

The controller also injects the multi-host bootstrap annotations the plugin
consumes: worker id, coordinator address (index-0 worker), and the full
worker hostname list.

Elastic restart (the preemptible v5e-256 config): failed/deleted worker
pods are recreated with the SAME completion index until backoff_limit, so a
preempted slice re-forms and training resumes from the job's own
checkpoints.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Set

from ..api import types as t
from ..client import Clientset, InformerFactory
from ..deviceplugin.tpu_plugin import (
    ANN_COORDINATOR,
    ANN_WORKER_ID,
    ANN_WORKER_HOSTNAMES,
)
from ..machinery import AlreadyExists, ApiError, NotFound, now_iso
from ..machinery.labels import label_selector_matches
from ..machinery.scheme import from_dict, to_dict
from .base import Controller, write_status_if_changed

COORDINATOR_PORT = 8476


def format_indexes(indexes: Set[int]) -> str:
    """{0,1,2,5} -> '0-2,5' (compact completedIndexes form)."""
    if not indexes:
        return ""
    xs = sorted(indexes)
    parts, start, prev = [], xs[0], xs[0]
    for x in xs[1:]:
        if x == prev + 1:
            prev = x
            continue
        parts.append(f"{start}-{prev}" if prev > start else str(start))
        start = prev = x
    parts.append(f"{start}-{prev}" if prev > start else str(start))
    return ",".join(parts)


class JobController(Controller):
    name = "job-controller"

    def setup(self):
        self.jobs = self.factory.informer("jobs")
        self.pods = self.factory.informer("pods")
        self.jobs.add_handler(
            on_add=self.enqueue,
            on_update=lambda _o, n: self.enqueue(n),
            on_delete=self.enqueue,
        )
        self.pods.add_handler(
            on_add=self._pod_event,
            on_update=lambda _o, n: self._pod_event(n),
            on_delete=self._pod_event,
        )

    def _pod_event(self, pod: t.Pod):
        job_name = pod.metadata.labels.get(t.JOB_NAME_LABEL)
        if job_name:
            self.queue.add(f"{pod.metadata.namespace}/{job_name}")

    # ------------------------------------------------------------------ sync

    def sync(self, key: str):
        job = self.jobs.get(key)
        if job is None:
            return
        if self._finished(job):
            return
        ns = job.metadata.namespace
        pods = [
            p
            for p in self.pods.list()
            if p.metadata.namespace == ns
            and label_selector_matches(job.spec.selector, p.metadata.labels)
        ]
        active = [p for p in pods if not self._pod_finished(p) and not p.metadata.deletion_timestamp]
        succeeded = [p for p in pods if p.status.phase == t.POD_SUCCEEDED]
        failed = [p for p in pods if p.status.phase == t.POD_FAILED]

        indexed = job.spec.completion_mode == "Indexed"
        completions = job.spec.completions
        parallelism = job.spec.parallelism or 1

        if indexed:
            self._manage_indexed(job, active, succeeded, failed)
        else:
            self._manage_nonindexed(job, active, succeeded, failed)
        self._update_status(job, active, succeeded, failed)

    @staticmethod
    def _pod_finished(pod: t.Pod) -> bool:
        return pod.status.phase in (t.POD_SUCCEEDED, t.POD_FAILED)

    @staticmethod
    def _finished(job: t.Job) -> bool:
        return any(
            c.type in ("Complete", "Failed") and c.status == "True"
            for c in job.status.conditions
        )

    # ------------------------------------------------------------- indexed

    def _pod_index(self, pod: t.Pod) -> Optional[int]:
        raw = pod.metadata.annotations.get(t.COMPLETION_INDEX_ANNOTATION)
        try:
            return int(raw) if raw is not None else None
        except ValueError:
            return None

    def _manage_indexed(self, job: t.Job, active, succeeded, failed):
        completions = job.spec.completions or job.spec.parallelism or 1
        have: Set[int] = set()
        for p in active:
            idx = self._pod_index(p)
            if idx is not None:
                have.add(idx)
        done: Set[int] = set()
        for p in succeeded:
            idx = self._pod_index(p)
            if idx is not None:
                done.add(idx)
        if len(failed) > job.spec.backoff_limit:
            return  # status update will mark Failed
        missing = [
            i for i in range(completions) if i not in have and i not in done
        ]
        # cap concurrency at parallelism
        budget = (job.spec.parallelism or completions) - len(active)
        for idx in missing[: max(0, budget)]:
            self._create_indexed_pod(job, idx, completions)

    def _create_indexed_pod(self, job: t.Job, index: int, completions: int):
        pod = self._pod_from_template(job)
        pod.metadata.name = f"{job.metadata.name}-{index}"
        pod.metadata.generate_name = ""
        pod.metadata.annotations[t.COMPLETION_INDEX_ANNOTATION] = str(index)
        # TPU multi-host bootstrap (consumed by the device plugin)
        pod.metadata.annotations[ANN_WORKER_ID] = str(index)
        coordinator = f"{job.metadata.name}-0.{job.metadata.namespace}"
        pod.metadata.annotations[ANN_COORDINATOR] = f"{coordinator}:{COORDINATOR_PORT}"
        pod.metadata.annotations[ANN_WORKER_HOSTNAMES] = ",".join(
            f"{job.metadata.name}-{i}.{job.metadata.namespace}"
            for i in range(completions)
        )
        if job.spec.gang_scheduling:
            pod.spec.scheduling_gang = f"job-{job.metadata.uid}"
            pod.spec.gang_size = completions
        try:
            self.cs.pods.create(pod)
            self.recorder.event(
                job, "Normal", "SuccessfulCreate", f"created pod {pod.metadata.name}"
            )
        except AlreadyExists:
            pass

    # ---------------------------------------------------------- nonindexed

    def _manage_nonindexed(self, job: t.Job, active, succeeded, failed):
        parallelism = job.spec.parallelism or 1
        completions = job.spec.completions
        if len(failed) > job.spec.backoff_limit:
            return
        if completions is not None:
            want_active = min(parallelism, max(0, completions - len(succeeded)))
        else:
            want_active = parallelism
        need = want_active - len(active)
        for _ in range(max(0, need)):
            pod = self._pod_from_template(job)
            pod.metadata.generate_name = f"{job.metadata.name}-"
            if job.spec.gang_scheduling:
                pod.spec.scheduling_gang = f"job-{job.metadata.uid}"
                pod.spec.gang_size = parallelism
            try:
                self.cs.pods.create(pod)
            except ApiError:
                break
        for pod in active[: max(0, -need)]:
            try:
                self.cs.pods.delete(pod.metadata.name, pod.metadata.namespace)
            except ApiError:
                pass

    def _pod_from_template(self, job: t.Job) -> t.Pod:
        tmpl = job.spec.template
        pod = t.Pod()
        pod.metadata.namespace = job.metadata.namespace
        pod.metadata.labels = dict(tmpl.metadata.labels)
        pod.metadata.labels.setdefault(t.JOB_NAME_LABEL, job.metadata.name)
        pod.metadata.annotations = dict(tmpl.metadata.annotations)
        pod.metadata.owner_references = [
            t.OwnerReference(
                api_version=job.API_VERSION,
                kind="Job",
                name=job.metadata.name,
                uid=job.metadata.uid,
                controller=True,
            )
        ]
        pod.spec = from_dict(t.PodSpec, to_dict(tmpl.spec))  # deep copy
        if not pod.spec.restart_policy or pod.spec.restart_policy == "Always":
            pod.spec.restart_policy = "Never"  # job pods must terminate
        return pod

    # --------------------------------------------------------------- status

    def _update_status(self, job: t.Job, active, succeeded, failed):
        completions = job.spec.completions
        indexed = job.spec.completion_mode == "Indexed"
        done_indexes: Set[int] = set()
        if indexed:
            for p in succeeded:
                idx = self._pod_index(p)
                if idx is not None:
                    done_indexes.add(idx)

        fresh = self.cs.jobs.get(job.metadata.name, job.metadata.namespace)

        complete = False
        if indexed:
            want = completions or job.spec.parallelism or 1
            complete = len(done_indexes) >= want
        elif completions is not None:
            complete = len(succeeded) >= completions
        else:
            complete = len(succeeded) > 0 and len(active) == 0
        newly_complete = complete and not self._finished(fresh)
        newly_failed = (
            not newly_complete
            and len(failed) > job.spec.backoff_limit
            and not self._finished(fresh)
        )

        def apply(st):
            st.active = len(active)
            st.succeeded = len(succeeded)
            st.failed = len(failed)
            if not st.start_time:
                st.start_time = now_iso()
            if indexed:
                st.completed_indexes = format_indexes(done_indexes)
            if newly_complete:
                st.completion_time = now_iso()
                st.conditions.append(
                    t.JobCondition(
                        type="Complete", status="True",
                        last_transition_time=now_iso(),
                    )
                )
            elif newly_failed:
                st.conditions.append(
                    t.JobCondition(
                        type="Failed", status="True",
                        reason="BackoffLimitExceeded",
                        last_transition_time=now_iso(),
                    )
                )

        try:
            write_status_if_changed(self.cs.jobs, fresh, apply)
        except NotFound:
            return
        if newly_complete:
            self.recorder.event(job, "Normal", "Completed", "job completed")
        elif newly_failed:
            self.recorder.event(
                job, "Warning", "BackoffLimitExceeded",
                f"{len(failed)} failed pods exceed backoffLimit={job.spec.backoff_limit}",
            )
