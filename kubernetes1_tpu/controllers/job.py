"""Job controller: run-to-completion workloads — the TPU training primitive.

Ref: pkg/controller/job/job_controller.go (syncJob :425, manageJob :633-700,
completion counting :523-545), extended with the two capabilities SURVEY.md
§2.1 identifies as reference gaps that multi-host TPU training requires:

1. **Indexed completion mode** — each pod carries a stable completion index
   0..completions-1 (annotation batch.ktpu.io/completion-index and pod name
   suffix "<job>-<index>"), which the TPU device plugin turns into
   TPU_WORKER_ID.  A v5p-32 slice Job runs as 8 indexed workers whose JAX
   processes learn their coordinates from the index.
2. **Gang scheduling** — spec.gang_scheduling=True stamps every pod with
   (scheduling_gang=<job uid>, gang_size=parallelism) so the scheduler binds
   the whole worker set atomically on one ICI slice.

The controller also injects the multi-host bootstrap annotations the plugin
consumes: worker id, coordinator address (index-0 worker), and the full
worker hostname list.

Elastic restart (the preemptible v5e-256 config): failed/deleted worker
pods are recreated with the SAME completion index until backoff_limit, so a
preempted slice re-forms and training resumes from the job's own
checkpoints.

Gang failure policy (node & slice failure domain): for gang-scheduled jobs
the slice is all-or-nothing on the FAILURE path too, not just at
placement.  When any member of the current gang attempt dies — pod Failed
(chip gone unhealthy, pressure eviction), deletion (node-lifecycle
eviction), or vanishing outright (force finalize off a dead node) — the
controller tears down EVERY member, waits a capped exponential backoff,
and recreates the whole gang as a new attempt (GANG_ATTEMPT_LABEL on the
pods, the same key as an annotation on the Job) whose fresh scheduling_gang
id makes the scheduler re-place it as a unit on healthy devices.
backoff_limit caps ATTEMPTS for gang jobs (counting failed pods is
meaningless when teardown deletes the evidence).  The
ktpu_gang_recovery_seconds histogram measures member-death to
all-members-Running MTTR — the goodput denominator.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Set

from ..api import types as t
from ..client import Clientset, InformerFactory
from ..client import retry as _retry
from ..deviceplugin.tpu_plugin import (
    ANN_COORDINATOR,
    ANN_WORKER_ID,
    ANN_WORKER_HOSTNAMES,
)
from ..machinery import AlreadyExists, ApiError, NotFound, now_iso
from ..machinery.labels import label_selector_matches
from ..machinery.scheme import from_dict, to_dict
from ..utils import flightrec
from ..utils.metrics import Counter, Histogram
from .base import Controller, delete_pods_batch, write_status_if_changed

COORDINATOR_PORT = 8476

# Gang recovery MTTR: first observation of a member death -> every member
# of the replacement attempt Running.  Module-level (the client/retry
# retries_total pattern) so one process-wide distribution aggregates every
# controller instance; the apiserver's /metrics renders it, and bench.py /
# scripts/chaos.py snapshot counts for per-phase deltas.
gang_recovery_seconds = Histogram(
    "ktpu_gang_recovery_seconds",
    "gang member death to all-members-Running recovery time",
    buckets=(0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 120.0, 300.0),
)
gang_attempts_total = Counter(
    "ktpu_gang_attempts_total", "whole-gang recreate attempts")


def gang_recovery_snapshot() -> dict:
    """{recoveries, attempts} — process-cumulative; per-phase reporters
    (bench.py, scripts/chaos.py) diff against their entry snapshot."""
    return {"recoveries": gang_recovery_seconds.count,
            "attempts": int(gang_attempts_total.value)}


def format_indexes(indexes: Set[int]) -> str:
    """{0,1,2,5} -> '0-2,5' (compact completedIndexes form)."""
    if not indexes:
        return ""
    xs = sorted(indexes)
    parts, start, prev = [], xs[0], xs[0]
    for x in xs[1:]:
        if x == prev + 1:
            prev = x
            continue
        parts.append(f"{start}-{prev}" if prev > start else str(start))
        start = prev = x
    parts.append(f"{start}-{prev}" if prev > start else str(start))
    return ",".join(parts)


class JobController(Controller):
    name = "job-controller"
    # capped exponential backoff between gang recreate attempts (class
    # attrs so tests/chaos can retune an instance before setup())
    gang_backoff_base = 1.0
    gang_backoff_cap = 30.0

    def setup(self):
        # gang bookkeeping (all reconstructible from the API after a
        # controller restart; only the MTTR window and the live backoff
        # deadline are in-memory best-effort)
        self._gang_broken_at: Dict[str, float] = {}  # job key -> monotonic
        self._gang_retry_at: Dict[str, float] = {}
        self._gang_notified: Set[str] = set()
        self.jobs = self.factory.informer("jobs")
        self.pods = self.factory.informer("pods")
        self.jobs.add_handler(
            on_add=self.enqueue,
            on_update=lambda _o, n: self.enqueue(n),
            on_delete=self.enqueue,
        )
        self.pods.add_handler(
            on_add=self._pod_event,
            on_update=lambda _o, n: self._pod_event(n),
            on_delete=self._pod_event,
        )

    def _pod_event(self, pod: t.Pod):
        job_name = pod.metadata.labels.get(t.JOB_NAME_LABEL)
        if job_name:
            self.queue.add(f"{pod.metadata.namespace}/{job_name}")

    # ------------------------------------------------------------------ sync

    def sync(self, key: str):
        job = self.jobs.get(key)
        if job is None or self._finished(job):
            self._gang_forget(key)
            return
        ns = job.metadata.namespace
        pods = [
            p
            for p in self.pods.list()
            if p.metadata.namespace == ns
            and label_selector_matches(job.spec.selector, p.metadata.labels)
        ]
        if job.spec.gang_scheduling and self._gang_policy_on():
            self._sync_gang(job, pods)
            return
        active = [p for p in pods if not self._pod_finished(p) and not p.metadata.deletion_timestamp]
        succeeded = [p for p in pods if p.status.phase == t.POD_SUCCEEDED]
        failed = [p for p in pods if p.status.phase == t.POD_FAILED]

        indexed = job.spec.completion_mode == "Indexed"
        completions = job.spec.completions
        parallelism = job.spec.parallelism or 1

        if indexed:
            self._manage_indexed(job, active, succeeded, failed)
        else:
            self._manage_nonindexed(job, active, succeeded, failed)
        self._update_status(job, active, succeeded, failed)

    @staticmethod
    def _pod_finished(pod: t.Pod) -> bool:
        return pod.status.phase in (t.POD_SUCCEEDED, t.POD_FAILED)

    @staticmethod
    def _finished(job: t.Job) -> bool:
        return any(
            c.type in ("Complete", "Failed") and c.status == "True"
            for c in job.status.conditions
        )

    # ------------------------------------------------------------- indexed

    def _pod_index(self, pod: t.Pod) -> Optional[int]:
        raw = pod.metadata.annotations.get(t.COMPLETION_INDEX_ANNOTATION)
        try:
            return int(raw) if raw is not None else None
        except ValueError:
            return None

    def _manage_indexed(self, job: t.Job, active, succeeded, failed):
        completions = job.spec.completions or job.spec.parallelism or 1
        have: Set[int] = set()
        for p in active:
            idx = self._pod_index(p)
            if idx is not None:
                have.add(idx)
        done: Set[int] = set()
        for p in succeeded:
            idx = self._pod_index(p)
            if idx is not None:
                done.add(idx)
        if len(failed) > job.spec.backoff_limit:
            return  # status update will mark Failed
        missing = [
            i for i in range(completions) if i not in have and i not in done
        ]
        # cap concurrency at parallelism
        budget = (job.spec.parallelism or completions) - len(active)
        for idx in missing[: max(0, budget)]:
            self._create_indexed_pod(job, idx, completions)

    def _create_indexed_pod(self, job: t.Job, index: int, completions: int,
                            attempt: int = 0):
        pod = self._pod_from_template(job)
        pod.metadata.name = f"{job.metadata.name}-{index}"
        pod.metadata.generate_name = ""
        pod.metadata.annotations[t.COMPLETION_INDEX_ANNOTATION] = str(index)
        # TPU multi-host bootstrap (consumed by the device plugin)
        pod.metadata.annotations[ANN_WORKER_ID] = str(index)
        coordinator = f"{job.metadata.name}-0.{job.metadata.namespace}"
        pod.metadata.annotations[ANN_COORDINATOR] = f"{coordinator}:{COORDINATOR_PORT}"
        pod.metadata.annotations[ANN_WORKER_HOSTNAMES] = ",".join(
            f"{job.metadata.name}-{i}.{job.metadata.namespace}"
            for i in range(completions)
        )
        if job.spec.gang_scheduling:
            self._stamp_gang_member(job, pod, completions, attempt)
        try:
            self.cs.pods.create(pod)
            self.recorder.event(
                job, "Normal", "SuccessfulCreate", f"created pod {pod.metadata.name}"
            )
        except AlreadyExists:
            pass

    # ---------------------------------------------------------- nonindexed

    def _manage_nonindexed(self, job: t.Job, active, succeeded, failed):
        parallelism = job.spec.parallelism or 1
        completions = job.spec.completions
        if len(failed) > job.spec.backoff_limit:
            return
        if completions is not None:
            want_active = min(parallelism, max(0, completions - len(succeeded)))
        else:
            want_active = parallelism
        need = want_active - len(active)
        for _ in range(max(0, need)):
            pod = self._pod_from_template(job)
            pod.metadata.generate_name = f"{job.metadata.name}-"
            if job.spec.gang_scheduling:
                # gate-off path: members place independently, the stamp is
                # membership metadata only (attempt stays 0)
                self._stamp_gang_member(job, pod, parallelism, 0)
            try:
                self.cs.pods.create(pod)
            except ApiError:
                break
        for pod in active[: max(0, -need)]:
            try:
                self.cs.pods.delete(pod.metadata.name, pod.metadata.namespace)
            except ApiError:
                pass

    def _pod_from_template(self, job: t.Job) -> t.Pod:
        tmpl = job.spec.template
        pod = t.Pod()
        pod.metadata.namespace = job.metadata.namespace
        pod.metadata.labels = dict(tmpl.metadata.labels)
        pod.metadata.labels.setdefault(t.JOB_NAME_LABEL, job.metadata.name)
        pod.metadata.annotations = dict(tmpl.metadata.annotations)
        pod.metadata.owner_references = [
            t.OwnerReference(
                api_version=job.API_VERSION,
                kind="Job",
                name=job.metadata.name,
                uid=job.metadata.uid,
                controller=True,
            )
        ]
        pod.spec = from_dict(t.PodSpec, to_dict(tmpl.spec))  # deep copy
        if not pod.spec.restart_policy or pod.spec.restart_policy == "Always":
            pod.spec.restart_policy = "Never"  # job pods must terminate
        return pod

    # ------------------------------------------------------- gang lifecycle

    @staticmethod
    def _gang_policy_on() -> bool:
        from ..utils.features import gates

        return gates.enabled("GangScheduling")

    def _gang_forget(self, key: str):
        self._gang_broken_at.pop(key, None)
        self._gang_retry_at.pop(key, None)
        self._gang_notified.discard(key)

    @staticmethod
    def _gang_id(job: t.Job, attempt: int) -> str:
        # a fresh id per attempt: the scheduler sees each recreate as a new
        # gang, so stale first-seen state and any straggler pods of a prior
        # attempt can never satisfy (or starve) the replacement's placement
        return f"job-{job.metadata.uid}-a{attempt}"

    def _stamp_gang_member(self, job: t.Job, pod: t.Pod, size: int,
                           attempt: int):
        pod.metadata.labels[t.GANG_ATTEMPT_LABEL] = str(attempt)
        pod.spec.scheduling_gang = self._gang_id(job, attempt)
        pod.spec.gang_size = size

    def _gang_size(self, job: t.Job) -> int:
        if job.spec.completion_mode == "Indexed":
            return job.spec.completions or job.spec.parallelism or 1
        return job.spec.parallelism or 1

    @staticmethod
    def _attempt_of(obj_meta_map: Optional[Dict[str, str]]) -> int:
        raw = (obj_meta_map or {}).get(t.GANG_ATTEMPT_LABEL)
        try:
            return int(raw) if raw else 0
        except ValueError:
            return 0

    def _sync_gang(self, job: t.Job, pods: List[t.Pod]):
        """All-or-nothing failure handling for one gang job (see module
        docstring).  Level-triggered: every decision is recomputed from the
        listed pods, so a controller restart resumes mid-recovery."""
        key = job.key()
        attempt = self._attempt_of(job.metadata.annotations)
        size = self._gang_size(job)
        indexed = job.spec.completion_mode == "Indexed"
        cur = [p for p in pods
               if self._attempt_of(p.metadata.labels) == attempt]
        stale = [p for p in pods
                 if self._attempt_of(p.metadata.labels) != attempt]
        # previous attempts tear down unconditionally — a broken gang's
        # survivors hold the chips the replacement needs
        self._force_delete_many(stale)

        succeeded = [p for p in cur if p.status.phase == t.POD_SUCCEEDED]
        failed = [p for p in cur if p.status.phase == t.POD_FAILED]
        active = [p for p in cur if not self._pod_finished(p)
                  and not p.metadata.deletion_timestamp]
        deleting = [p for p in cur if p.metadata.deletion_timestamp
                    and not self._pod_finished(p)]
        bound = [p for p in cur if p.spec.node_name]

        broken = ""
        if failed:
            broken = (f"member {failed[0].metadata.name} failed: "
                      f"{failed[0].status.reason or failed[0].status.message or 'unknown'}")
        elif deleting:
            broken = f"member {deleting[0].metadata.name} is being deleted"
        elif bound and len(cur) < size:
            # a bound member proves the gang was fully created and placed
            # (placement is all-or-nothing), so a missing member was
            # force-finalized — node eviction's end state
            broken = f"{size - len(cur)} member(s) vanished"
        if broken:
            self._gang_broken(job, attempt, cur, broken)
            return

        # recovery bookkeeping: a previously-broken gang whose replacement
        # attempt is fully Running closes the MTTR window
        if (key in self._gang_broken_at and len(active) == size
                and all(p.status.phase == t.POD_RUNNING for p in active)):
            dt = time.monotonic() - self._gang_broken_at.pop(key)
            self._gang_retry_at.pop(key, None)
            self._gang_notified.discard(key)
            gang_recovery_seconds.observe(dt)
            self.recorder.event(
                job, "Normal", "GangRecovered",
                f"gang attempt {attempt}: all {size} members Running "
                f"{dt:.2f}s after member death")

        if stale:
            # old-attempt teardown still finalizing: its chips aren't free
            # yet, so re-check shortly instead of racing the replacement
            self.enqueue_after(key, 0.2)
            self._update_status(job, active, succeeded, failed,
                                fail_override=False)
            return
        retry_at = self._gang_retry_at.get(key)
        if retry_at is not None and len(cur) < size:
            now = time.monotonic()
            if now < retry_at:  # capped-backoff window before the recreate
                self.enqueue_after(key, retry_at - now)
                self._update_status(job, active, succeeded, failed,
                                    fail_override=False)
                return
        if len(cur) < size:
            # About to create members from a view that may be STALE in the
            # worst way: the exhausted path force-deletes the survivors and
            # commits the Failed verdict, and those very deletion events
            # re-enqueue this sync — if it runs before the Failed status
            # event is delivered, cur is empty, nothing looks broken (no
            # bound member left to prove a vanish), and the create loop
            # would resurrect the gang as attempt-N pods no sync will ever
            # manage again (observed: orphaned Running pods holding chips
            # forever).  The verdict was committed through our own
            # apiserver, so ONE authoritative read closes the window.
            try:
                fresh = self.cs.jobs.get(job.metadata.name,
                                         job.metadata.namespace)
            except NotFound:
                self._gang_forget(key)
                return
            except (ApiError, ConnectionError, TimeoutError, OSError):
                self.enqueue_after(key, 0.5)  # transient: re-judge shortly
                return
            if self._finished(fresh):
                self._gang_forget(key)
                return
        if indexed:
            have: Set[int] = set()
            for p in active:
                idx = self._pod_index(p)
                if idx is not None:
                    have.add(idx)
            done: Set[int] = set()
            for p in succeeded:
                idx = self._pod_index(p)
                if idx is not None:
                    done.add(idx)
            for idx in [i for i in range(size)
                        if i not in have and i not in done]:
                self._create_indexed_pod(job, idx, size, attempt=attempt)
        else:
            for _ in range(max(0, size - len(active) - len(succeeded))):
                pod = self._pod_from_template(job)
                pod.metadata.generate_name = f"{job.metadata.name}-"
                self._stamp_gang_member(job, pod, size, attempt)
                try:
                    self.cs.pods.create(pod)
                    self.recorder.event(job, "Normal", "SuccessfulCreate",
                                        f"created pod (gang attempt {attempt})")
                except ApiError:
                    break
        self._update_status(job, active, succeeded, failed,
                            fail_override=False)

    def _gang_broken(self, job: t.Job, attempt: int, cur: List[t.Pod],
                     why: str):
        """One member died: tear the whole attempt down, then either give
        up (attempts exhausted) or schedule the recreate behind a capped
        exponential backoff."""
        key = job.key()
        self._gang_broken_at.setdefault(key, time.monotonic())
        if key not in self._gang_notified:
            self._gang_notified.add(key)
            self.recorder.event(
                job, "Warning", "GangMemberFailed",
                f"gang attempt {attempt}: {why}; tearing down all "
                f"{len(cur)} member(s)")
        if attempt + 1 > job.spec.backoff_limit:
            # exhausted: kill the remains (a broken slice's survivors hold
            # chips) but keep finished pod records for debugging
            self._force_delete_many(
                [p for p in cur if not self._pod_finished(p)])
            active = [p for p in cur if not self._pod_finished(p)
                      and not p.metadata.deletion_timestamp]
            succeeded = [p for p in cur if p.status.phase == t.POD_SUCCEEDED]
            failed = [p for p in cur if p.status.phase == t.POD_FAILED]
            self._update_status(
                job, active, succeeded, failed, fail_override=True,
                fail_reason="GangBackoffLimitExceeded",
                fail_message=(f"gang attempt {attempt} broken ({why}) with "
                              f"all backoff_limit={job.spec.backoff_limit} "
                              f"recreate attempts used"))
            self._gang_retry_at.pop(key, None)
            return
        delay = min(self.gang_backoff_base * (2 ** attempt),
                    self.gang_backoff_cap)
        self._gang_retry_at[key] = time.monotonic() + delay
        nxt = attempt + 1
        try:
            # persist the attempt on the Job FIRST: the bump is what moves
            # every old member into the stale sweep, so a controller crash
            # right here resumes with teardown, never a half-recreate
            self.cs.jobs.patch(
                job.metadata.name,
                {"metadata": {"annotations": {t.GANG_ATTEMPT_LABEL: str(nxt)}}},
                namespace=job.metadata.namespace)
        except NotFound:
            self._gang_forget(key)
            return
        except (ApiError, ConnectionError, TimeoutError, OSError):
            # transient: the next sync re-detects the broken gang and
            # retries the bump (broken_at/notified are idempotent)
            self.enqueue_after(key, 0.5)
            return
        gang_attempts_total.inc()
        flightrec.note("job-controller", flightrec.GANG_ATTEMPT,
                       job=job.metadata.name, attempt=nxt, why=why,
                       backoff_s=round(delay, 2))
        self.recorder.event(
            job, "Normal", "GangRecreate",
            f"recreating gang as attempt {nxt} after {delay:.1f}s backoff")
        # the patch's MODIFIED event re-enqueues this job; that sync's
        # stale sweep tears the old attempt down and creation waits out
        # the backoff window

    def _force_delete(self, pod: t.Pod):
        """Grace-0 delete through the shared retry policy: gang teardown
        must finalize members on DEAD nodes too — no kubelet will ever
        acknowledge a graceful delete there."""
        flightrec.note("job-controller", flightrec.GANG_TEARDOWN,
                       pod=pod.metadata.name,
                       gang=pod.spec.scheduling_gang or "")
        try:
            _retry.call_with_retries(
                lambda: self.cs.pods.delete(
                    pod.metadata.name, pod.metadata.namespace,
                    grace_seconds=0),
                steps=3, reason="gang_teardown")
        except NotFound:
            pass
        except (ApiError, ConnectionError, TimeoutError, OSError):
            pass  # level-triggered: the next sync retries the survivors

    def _force_delete_many(self, pods: List[t.Pod]):
        """Whole-gang teardown as ONE pods/delete:batch request: a gang's
        members die together by policy, so their deletes should commit as
        one store group commit, not N round-trips.  Same semantics as
        _force_delete per member — grace 0, errors left to the next
        level-triggered sync."""
        if not pods:
            return
        if len(pods) == 1:
            self._force_delete(pods[0])
            return
        for p in pods:
            flightrec.note("job-controller", flightrec.GANG_TEARDOWN,
                           pod=p.metadata.name,
                           gang=p.spec.scheduling_gang or "")
        delete_pods_batch(self.cs, pods, grace_seconds=0,
                          reason="gang_teardown")

    # --------------------------------------------------------------- status

    def _update_status(self, job: t.Job, active, succeeded, failed,
                       fail_override: Optional[bool] = None,
                       fail_reason: str = "BackoffLimitExceeded",
                       fail_message: str = ""):
        """fail_override: gang jobs count ATTEMPTS, not failed pods (the
        teardown deletes them) — None keeps the failed-pod-count rule,
        True/False forces the verdict."""
        completions = job.spec.completions
        indexed = job.spec.completion_mode == "Indexed"
        done_indexes: Set[int] = set()
        if indexed:
            for p in succeeded:
                idx = self._pod_index(p)
                if idx is not None:
                    done_indexes.add(idx)

        fresh = self.cs.jobs.get(job.metadata.name, job.metadata.namespace)

        complete = False
        if indexed:
            want = completions or job.spec.parallelism or 1
            complete = len(done_indexes) >= want
        elif completions is not None:
            complete = len(succeeded) >= completions
        else:
            complete = len(succeeded) > 0 and len(active) == 0
        newly_complete = complete and not self._finished(fresh)
        newly_failed = (
            not newly_complete
            and not self._finished(fresh)
            and (fail_override if fail_override is not None
                 else len(failed) > job.spec.backoff_limit)
        )

        def apply(st):
            st.active = len(active)
            st.succeeded = len(succeeded)
            st.failed = len(failed)
            if not st.start_time:
                st.start_time = now_iso()
            if indexed:
                st.completed_indexes = format_indexes(done_indexes)
            if newly_complete:
                st.completion_time = now_iso()
                st.conditions.append(
                    t.JobCondition(
                        type="Complete", status="True",
                        last_transition_time=now_iso(),
                    )
                )
            elif newly_failed:
                st.conditions.append(
                    t.JobCondition(
                        type="Failed", status="True",
                        reason=fail_reason,
                        last_transition_time=now_iso(),
                    )
                )

        try:
            write_status_if_changed(self.cs.jobs, fresh, apply)
        except NotFound:
            return
        if newly_complete:
            self.recorder.event(job, "Normal", "Completed", "job completed")
        elif newly_failed:
            self.recorder.event(
                job, "Warning", fail_reason,
                fail_message or f"{len(failed)} failed pods exceed "
                                f"backoffLimit={job.spec.backoff_limit}",
            )
