"""CronJob controller (ref: pkg/controller/cronjob/cronjob_controller.go):
creates Jobs on a cron schedule with concurrency policy and history limits.

Unlike most controllers this one is clock-driven: each sync computes the
next fire time and re-arms itself via the delaying workqueue (the
reference polls syncAll every 10s; the workqueue re-arm is the
level-triggered equivalent without the global poll).
"""

from __future__ import annotations

import datetime
from typing import List, Optional

from ..api import types as t
from ..machinery import AlreadyExists, ApiError, NotFound
from ..machinery.meta import parse_iso
from ..machinery.scheme import from_dict, to_dict
from ..utils.cron import next_fire, unmet_times
from .base import Controller, write_status_if_changed


def _utc(ts: float) -> datetime.datetime:
    return datetime.datetime.fromtimestamp(ts, datetime.timezone.utc)


class CronJobController(Controller):
    name = "cronjob-controller"

    def __init__(self, *args, clock=None, **kwargs):
        super().__init__(*args, **kwargs)
        import time as _time

        self.clock = clock or _time.time

    def setup(self):
        self.cronjobs = self.factory.informer("cronjobs")
        self.jobs = self.factory.informer("jobs")
        self.cronjobs.add_handler(
            on_add=self.enqueue,
            on_update=lambda _o, n: self.enqueue(n),
            on_delete=self.enqueue,
        )
        self.jobs.add_handler(
            on_add=self._job_event,
            on_update=lambda _o, n: self._job_event(n),
            on_delete=self._job_event,
        )

    def _job_event(self, job: t.Job):
        for ref in job.metadata.owner_references:
            if ref.kind == "CronJob" and ref.controller:
                self.queue.add(f"{job.metadata.namespace}/{ref.name}")

    def _owned_jobs(self, cj: t.CronJob) -> List[t.Job]:
        return [
            j
            for j in self.jobs.list()
            if j.metadata.namespace == cj.metadata.namespace
            and any(
                r.kind == "CronJob" and r.uid == cj.metadata.uid and r.controller
                for r in j.metadata.owner_references
            )
        ]

    @staticmethod
    def _finished(job: t.Job) -> str:
        for c in job.status.conditions:
            if c.type in ("Complete", "Failed") and c.status == "True":
                return c.type
        return ""

    def _new_job(self, cj: t.CronJob, fire: datetime.datetime) -> t.Job:
        job = t.Job()
        # name encodes the scheduled minute so a missed double-create is an
        # AlreadyExists no-op (ref: getJobName, scheduledTimeHash)
        job.metadata.name = f"{cj.metadata.name}-{int(fire.timestamp()) // 60}"
        job.metadata.namespace = cj.metadata.namespace
        job.metadata.labels = dict(cj.spec.job_template.metadata.labels)
        job.metadata.annotations = dict(cj.spec.job_template.metadata.annotations)
        job.metadata.owner_references = [
            t.OwnerReference(
                api_version=cj.API_VERSION, kind="CronJob",
                name=cj.metadata.name, uid=cj.metadata.uid, controller=True,
            )
        ]
        job.spec = from_dict(t.JobSpec, to_dict(cj.spec.job_template.spec))
        return job

    def sync(self, key: str):
        cj = self.cronjobs.get(key)
        if cj is None or cj.metadata.deletion_timestamp:
            return
        now = _utc(self.clock())
        jobs = self._owned_jobs(cj)
        active = [j for j in jobs if not self._finished(j)]
        self._prune_history(cj, jobs)
        self._reconcile_active(cj, active)

        if not cj.spec.suspend:
            earliest = (
                _utc(parse_iso(cj.status.last_schedule_time))
                if cj.status.last_schedule_time
                else _utc(parse_iso(cj.metadata.creation_timestamp))
            )
            times, truncated = unmet_times(cj.spec.schedule, earliest, now)
            if truncated:
                # Too many missed starts (controller down for a long time):
                # start nothing for the stale backlog — firing times[-1]
                # would trigger a catch-up storm — and advance
                # lastScheduleTime to now so the controller recovers.
                self.recorder.event(
                    cj, "Warning", "TooManyMissedTimes",
                    f"too many missed start times since {earliest}; "
                    "skipping backlog",
                )
                self._record_schedule_time(cj, now, None, active)
            elif times:
                fire = times[-1]  # only the most recent unmet time is acted on
                deadline_ok = (
                    cj.spec.starting_deadline_seconds is None
                    or (now - fire).total_seconds()
                    <= cj.spec.starting_deadline_seconds
                )
                if deadline_ok and self._concurrency_allows(cj, active):
                    if cj.spec.concurrency_policy == "Replace":
                        active = []  # the previous jobs were just deleted
                    self._start_job(cj, fire, active)

        # re-arm for the next scheduled minute
        try:
            nxt = next_fire(cj.spec.schedule, now)
            self.enqueue_after(key, max(1.0, (nxt - now).total_seconds()))
        except ValueError:
            pass

    def _concurrency_allows(self, cj: t.CronJob, active: List[t.Job]) -> bool:
        if not active or cj.spec.concurrency_policy == "Allow":
            return True
        if cj.spec.concurrency_policy == "Forbid":
            self.recorder.event(
                cj, "Normal", "JobAlreadyActive",
                "skipping schedule: previous job still active",
            )
            return False
        # Replace: kill the running jobs, then start fresh
        for j in active:
            try:
                self.cs.jobs.delete(j.metadata.name, j.metadata.namespace)
            except ApiError:
                pass
        return True

    @staticmethod
    def _job_ref(job: t.Job) -> t.ObjectReference:
        return t.ObjectReference(kind="Job", namespace=job.metadata.namespace,
                                 name=job.metadata.name, uid=job.metadata.uid)

    def _reconcile_active(self, cj: t.CronJob, active: List[t.Job]):
        """Drop finished/deleted jobs from status.active (the reference
        prunes active each sync; without this, completed jobs linger)."""
        want = sorted((r.uid for r in map(self._job_ref, active)))
        have = sorted(r.uid for r in cj.status.active)
        if want == have:
            return
        self._record_schedule_time(cj, None, None, active)

    def _record_schedule_time(
        self,
        cj: t.CronJob,
        schedule_time: Optional[datetime.datetime],
        new_job: Optional[t.Job],
        active: List[t.Job],
    ):
        try:
            fresh = self.cs.cronjobs.get(cj.metadata.name, cj.metadata.namespace)
        except NotFound:
            return
        refs = [self._job_ref(j) for j in active]
        if new_job is not None:
            refs.insert(0, self._job_ref(new_job))

        def apply(st):
            if schedule_time is not None:
                st.last_schedule_time = (
                    schedule_time.strftime("%Y-%m-%dT%H:%M:%S") + "Z"
                )
            st.active = refs

        try:
            write_status_if_changed(self.cs.cronjobs, fresh, apply)
        except ApiError:
            pass

    def _start_job(self, cj: t.CronJob, fire: datetime.datetime, active: List[t.Job]):
        job = self._new_job(cj, fire)
        try:
            created = self.cs.jobs.create(job)
        except AlreadyExists:
            return
        except ApiError:
            return
        self.recorder.event(cj, "Normal", "SuccessfulCreate",
                            f"created job {created.metadata.name}")
        self._record_schedule_time(cj, fire, created, active)

    def _prune_history(self, cj: t.CronJob, jobs: List[t.Job]):
        for kind, limit in (
            ("Complete", cj.spec.successful_jobs_history_limit),
            ("Failed", cj.spec.failed_jobs_history_limit),
        ):
            done = sorted(
                (j for j in jobs if self._finished(j) == kind),
                key=lambda j: j.metadata.creation_timestamp,
            )
            for j in done[: max(0, len(done) - limit)]:
                try:
                    self.cs.jobs.delete(j.metadata.name, j.metadata.namespace)
                except ApiError:
                    pass
