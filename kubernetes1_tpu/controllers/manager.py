"""Controller manager (ref: cmd/kube-controller-manager/app/
controllermanager.go:334-363): runs every control loop over one shared
informer factory, optionally under leader election."""

from __future__ import annotations

import threading
from typing import List, Optional

from ..client import Clientset, InformerFactory, LeaderElector
from .certificates import CertificateController
from .cronjob import CronJobController
from .daemonset import DaemonSetController
from .deployment import DeploymentController
from .disruption import DisruptionController
from .endpoints import EndpointsController
from .job import JobController
from .namespace import GarbageCollector, NamespaceController
from .nodelifecycle import NodeLifecycleController
from .podautoscaler import HorizontalPodAutoscalerController
from .podgc import PodGCController
from .provisioner import HostPathProvisioner
from .replicaset import ReplicaSetController
from .resourcequota import ResourceQuotaController
from .serviceaccount import ServiceAccountController
from .statefulset import StatefulSetController
from .ttl import TTLAfterFinishedController
from .volumebinder import PersistentVolumeBinder


class ControllerManager:
    def __init__(
        self,
        clientset: Clientset,
        leader_elect: bool = False,
        identity: str = "kcm-0",
        monitor_grace: float = 40.0,
        eviction_timeout: float = 300.0,
        ca_key: str = "ktpu-ca-key",
        ca_cert_pem: str = "",
        sa_signing_key: str = "ktpu-sa-key",
        pv_base_dir: str = "/var/lib/ktpu/pv",
        endpoints_coalesce_window: float = 0.0,  # s; 0 = write per event
    ):
        self.cs = clientset
        self.factory = InformerFactory(clientset)
        self.controllers = [
            JobController(clientset, self.factory),
            ReplicaSetController(clientset, self.factory),
            DeploymentController(clientset, self.factory),
            DaemonSetController(clientset, self.factory),
            StatefulSetController(clientset, self.factory),
            CronJobController(clientset, self.factory),
            NamespaceController(clientset, self.factory),
            GarbageCollector(clientset, self.factory),
            EndpointsController(clientset, self.factory,
                                coalesce_window=endpoints_coalesce_window),
            ResourceQuotaController(clientset, self.factory),
            ServiceAccountController(clientset, self.factory,
                                     signing_key=sa_signing_key),
            HorizontalPodAutoscalerController(clientset, self.factory),
            DisruptionController(clientset, self.factory),
            PodGCController(clientset, self.factory),
            TTLAfterFinishedController(clientset, self.factory),
            CertificateController(clientset, self.factory, ca_key=ca_key,
                                  ca_cert_pem=ca_cert_pem),
            PersistentVolumeBinder(clientset, self.factory),
            HostPathProvisioner(clientset, self.factory,
                                base_dir=pv_base_dir),
        ]
        self.node_lifecycle = NodeLifecycleController(
            clientset,
            self.factory,
            monitor_grace=monitor_grace,
            eviction_timeout=eviction_timeout,
        )
        self.leader_elect = leader_elect
        self.identity = identity
        self._elector: Optional[LeaderElector] = None
        self._started = threading.Event()

    def _run(self):
        if self._started.is_set():
            return
        self._started.set()
        for c in self.controllers:
            c.setup()
        self.factory.start_all()
        self.factory.wait_for_sync()
        for c in self.controllers:
            c.start_workers()
        self.node_lifecycle.start()

    def start(self):
        from ..utils.gctune import tune_for_server

        tune_for_server()
        if self.leader_elect:
            self._elector = LeaderElector(
                self.cs,
                "ktpu-controller-manager",
                self.identity,
                on_started_leading=self._run,
            )
            self._elector.start()
        else:
            self._run()
        return self

    def stop(self):
        if self._elector:
            self._elector.stop()
        for c in self.controllers:
            c.stop()
        self.node_lifecycle.stop()
        self.factory.stop_all()
