"""Dynamic volume provisioner (ref: pkg/controller/volume/persistentvolume/
pv_controller.go provisionClaim + the external-provisioner contract;
StorageClass: pkg/apis/storage/types.go:28).

A Pending PVC naming a StorageClass whose provisioner is ours gets a
hostPath PV created on demand (pvc-<uid> under base_dir), pre-bound via
claim_ref so the binder's resume path completes the bind.  On a TPU
training cluster this is the checkpoint-volume path: a Job's PVC
provisions storage the moment it's needed, and the data outlives pod
restarts (reclaim Retain) or is cleaned with the claim (Delete).

volumeBindingMode=WaitForFirstConsumer (storage/types.go
VolumeBindingWaitForFirstConsumer) is honored as API behavior: the PVC
stays Pending until a pod that consumes it has been SCHEDULED, so
provisioning happens where (and only when) the workload actually lands.
"""

from __future__ import annotations

import os
import shutil

from ..api import types as t
from ..machinery import AlreadyExists, ApiError, NotFound
from .base import Controller
from .volumeutil import has_scheduled_consumer, pod_claim_keys

HOSTPATH_PROVISIONER = "ktpu.io/hostpath"
PROVISIONED_BY = "pv.kubernetes.io/provisioned-by"
HOSTPATH_DIR_ANNOTATION = "ktpu.io/hostpath-dir"


class HostPathProvisioner(Controller):
    name = "hostpath-provisioner"

    def __init__(self, clientset, factory, workers: int = 2,
                 base_dir: str = "/var/lib/ktpu/pv",
                 provisioner_name: str = HOSTPATH_PROVISIONER):
        super().__init__(clientset, factory, workers)
        self.base_dir = base_dir
        self.provisioner_name = provisioner_name

    def setup(self):
        self.pvcs = self.factory.informer("persistentvolumeclaims")
        self.pvs = self.factory.informer("persistentvolumes")
        self.classes = self.factory.informer("storageclasses")
        self.pods = self.factory.informer("pods")
        self.pvcs.add_handler(
            on_add=self.enqueue, on_update=lambda _o, n: self.enqueue(n),
            on_delete=self._claim_deleted)
        # a StorageClass created after its PVCs must un-stick them
        self.classes.add_handler(on_add=self._class_event)
        # WaitForFirstConsumer trigger: a pod landing on a node makes its
        # claims provisionable
        self.pods.add_handler(
            on_add=self._pod_event, on_update=lambda _o, n: self._pod_event(n))
        # reclaim: deleting a PV we provisioned removes its directory
        self.pvs.add_handler(on_delete=self._pv_deleted)

    def _class_event(self, sc):
        for pvc in self.pvcs.list():
            if pvc.spec.storage_class_name == sc.metadata.name:
                self.enqueue(pvc)

    def _pod_event(self, pod: t.Pod):
        if not pod.spec.node_name:
            return
        for key in pod_claim_keys(pod):
            self.queue.add(key)

    def _claim_deleted(self, pvc: t.PersistentVolumeClaim):
        """A claim deleted BEFORE the binder finished leaves our pre-bound
        PV orphaned (never Bound, so the binder's release path skips it):
        delete it here, which also reclaims the directory via _pv_deleted."""
        pv_name = f"pvc-{pvc.metadata.uid}"
        pv = self.pvs.get(pv_name)
        if pv is None or pv.status.phase == "Bound" \
                or pv.metadata.annotations.get(PROVISIONED_BY) != \
                self.provisioner_name:
            return
        try:
            self.cs.persistentvolumes.delete(pv_name, "")
        except (NotFound, ApiError):
            pass

    def _pv_deleted(self, pv: t.PersistentVolume):
        if pv.metadata.annotations.get(PROVISIONED_BY) != \
                self.provisioner_name:
            return
        # Retain means what it says: deleting the PV OBJECT must not touch
        # the data (upstream semantics); only Delete reclaims the directory
        if pv.spec.persistent_volume_reclaim_policy != "Delete":
            return
        path = pv.metadata.annotations.get(HOSTPATH_DIR_ANNOTATION, "")
        # only ever remove directories we created, under our base_dir
        base = os.path.realpath(self.base_dir)
        real = os.path.realpath(path) if path else ""
        if real and real.startswith(base + os.sep):
            shutil.rmtree(real, ignore_errors=True)

    # ------------------------------------------------------------------ sync

    def sync(self, key: str):
        pvc = self.pvcs.get(key)
        if pvc is None or pvc.status.phase == "Bound" \
                or pvc.spec.volume_name:
            return
        if not pvc.spec.storage_class_name:
            return  # static binding only
        sc = self.classes.get(pvc.spec.storage_class_name)
        if sc is None or sc.provisioner != self.provisioner_name:
            return  # not ours (an external provisioner's class, or typo)
        if sc.volume_binding_mode == "WaitForFirstConsumer" \
                and not has_scheduled_consumer(self.pods, pvc):
            return  # re-enqueued by _pod_event when a consumer lands
        pv_name = f"pvc-{pvc.metadata.uid}"
        if self.pvs.get(pv_name) is not None:
            return  # already provisioned (informer lag: binder will finish)
        path = os.path.join(self.base_dir, pv_name)
        os.makedirs(path, exist_ok=True)
        pv = t.PersistentVolume()
        pv.metadata.name = pv_name
        pv.metadata.annotations = {
            PROVISIONED_BY: self.provisioner_name,
            HOSTPATH_DIR_ANNOTATION: path,
        }
        pv.spec.capacity = {
            "storage": pvc.spec.resources.requests.get("storage", "1Gi")}
        pv.spec.access_modes = list(pvc.spec.access_modes) or [
            "ReadWriteOnce"]
        pv.spec.host_path = t.HostPathVolumeSource(path=path)
        pv.spec.storage_class_name = sc.metadata.name
        pv.spec.persistent_volume_reclaim_policy = sc.reclaim_policy
        # pre-bound: the binder's resume path (claim_ref match) completes
        # the PVC side — the same crash-safe handoff a half-finished static
        # bind uses
        pv.spec.claim_ref = t.ObjectReference(
            kind="PersistentVolumeClaim",
            namespace=pvc.metadata.namespace or "default",
            name=pvc.metadata.name,
            uid=pvc.metadata.uid,
        )
        try:
            self.cs.persistentvolumes.create(pv, "")
        except AlreadyExists:
            return
        except ApiError:
            self.enqueue_after(key, 0.5)
            return
        self.recorder.event(
            pvc, "Normal", "ProvisioningSucceeded",
            f"provisioned volume {pv_name} ({self.provisioner_name})")
