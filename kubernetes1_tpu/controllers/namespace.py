"""Namespace + garbage controllers.

NamespaceController (ref: pkg/controller/namespace/): Terminating
namespaces get emptied of every namespaced resource, then finalized.

GarbageCollector (ref: pkg/controller/garbagecollector/): objects whose
controller owner reference no longer resolves are deleted — how pods die
when their Job/ReplicaSet is removed.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Dict, List

from ..api import types as t
from ..client import Clientset, InformerFactory
from ..machinery import ApiError, NotFound
from .base import Controller

NAMESPACED_RESOURCES = (
    "pods", "jobs", "cronjobs", "replicasets", "deployments", "daemonsets",
    "statefulsets", "services", "endpoints", "configmaps", "events", "leases",
)


class NamespaceController(Controller):
    name = "namespace-controller"

    def setup(self):
        self.namespaces = self.factory.informer("namespaces")
        self.namespaces.add_handler(
            on_add=self.enqueue,
            on_update=lambda _o, n: self.enqueue(n),
        )

    def sync(self, key: str):
        ns = self.namespaces.get(key)
        if ns is None or ns.status.phase != "Terminating":
            return
        remaining = 0
        for resource in NAMESPACED_RESOURCES:
            items, _ = self.cs.resource(resource).list(namespace=ns.metadata.name)
            for obj in items:
                remaining += 1
                try:
                    self.cs.resource(resource).delete(
                        obj.metadata.name, ns.metadata.name,
                        grace_seconds=0 if resource == "pods" else None,
                    )
                except ApiError:
                    pass
        if remaining == 0:
            try:
                self.cs.namespaces.delete(ns.metadata.name, "", grace_seconds=0)
            except ApiError:
                pass
        else:
            self.enqueue_after(key, 0.5)


OWNED_RESOURCES = ("pods", "replicasets", "jobs")
OWNER_RESOURCES = ("jobs", "replicasets", "deployments", "daemonsets",
                   "statefulsets", "cronjobs")


class GarbageCollector(Controller):
    name = "garbage-collector"

    OWNER_RESOURCE = {
        "Job": "jobs",
        "ReplicaSet": "replicasets",
        "Deployment": "deployments",
        "DaemonSet": "daemonsets",
        "StatefulSet": "statefulsets",
        "CronJob": "cronjobs",
    }

    def setup(self):
        self.informers: Dict[str, object] = {}
        for resource in set(OWNED_RESOURCES + OWNER_RESOURCES):
            self.informers[resource] = self.factory.informer(resource)
        for resource in OWNED_RESOURCES:
            inf = self.informers[resource]
            inf.add_handler(
                on_add=lambda o, r=resource: self.queue.add(f"{r}|{o.key()}")
            )
        # owner deletions re-scan owned kinds
        for owner in OWNER_RESOURCES:
            self.informers[owner].add_handler(
                on_delete=lambda o: self._rescan()
            )

    def _rescan(self):
        for resource in OWNED_RESOURCES:
            for obj in self.informers[resource].list():
                self.queue.add(f"{resource}|{obj.key()}")

    def sync(self, key: str):
        resource, obj_key = key.split("|", 1)
        obj = self.informers[resource].get(obj_key)
        if obj is None or obj.metadata.deletion_timestamp:
            return
        for ref in obj.metadata.owner_references:
            owner_resource = self.OWNER_RESOURCE.get(ref.kind)
            if owner_resource is None:
                continue
            try:
                owner = self.cs.resource(owner_resource).get(
                    ref.name, obj.metadata.namespace
                )
                if owner.metadata.uid != ref.uid:
                    raise NotFound("uid changed")
            except NotFound:
                try:
                    self.cs.resource(resource).delete(
                        obj.metadata.name, obj.metadata.namespace,
                        grace_seconds=0 if resource == "pods" else None,
                    )
                except ApiError:
                    pass
                return
