"""Namespace + garbage controllers.

NamespaceController (ref: pkg/controller/namespace/): Terminating
namespaces get emptied of every namespaced resource, then finalized.

GarbageCollector (ref: pkg/controller/garbagecollector/): objects whose
controller owner reference no longer resolves are deleted — how pods die
when their Job/ReplicaSet is removed.
"""

from __future__ import annotations

import time
import traceback
from typing import Dict, List

from ..api import types as t
from ..client import Clientset, InformerFactory
from ..machinery import ApiError, NotFound
from .base import Controller
from ..utils import locksan

NAMESPACED_RESOURCES = (
    "pods", "jobs", "cronjobs", "replicasets", "deployments", "daemonsets",
    "statefulsets", "services", "endpoints", "configmaps", "events", "leases",
    "secrets", "serviceaccounts", "persistentvolumeclaims",
    "resourcequotas", "limitranges", "horizontalpodautoscalers",
    "poddisruptionbudgets", "podpresets", "roles", "rolebindings",
)


class NamespaceController(Controller):
    name = "namespace-controller"

    def setup(self):
        self.namespaces = self.factory.informer("namespaces")
        self.namespaces.add_handler(
            on_add=self.enqueue,
            on_update=lambda _o, n: self.enqueue(n),
        )

    def sync(self, key: str):
        ns = self.namespaces.get(key)
        if ns is None or ns.status.phase != "Terminating":
            return
        remaining = 0
        for resource in NAMESPACED_RESOURCES:
            items, _ = self.cs.resource(resource).list(namespace=ns.metadata.name)
            for obj in items:
                remaining += 1
                try:
                    self.cs.resource(resource).delete(
                        obj.metadata.name, ns.metadata.name,
                        grace_seconds=0 if resource == "pods" else None,
                    )
                except ApiError:
                    pass
        if remaining == 0:
            try:
                self.cs.namespaces.delete(ns.metadata.name, "", grace_seconds=0)
            except ApiError:
                pass
        else:
            self.enqueue_after(key, 0.5)


# The reference's GC is fully KIND-GENERIC (graph built from every
# resource's ownerReferences, pkg/controller/garbagecollector); this
# covers the kinds that participate in ownership in practice — the
# controller-owned chain plus the config/service kinds users hang off
# their workloads (a ConfigMap owned by its Job dies with the Job).
OWNED_RESOURCES = ("pods", "replicasets", "jobs", "configmaps", "secrets",
                   "services", "persistentvolumeclaims")
OWNER_RESOURCES = ("jobs", "replicasets", "deployments", "daemonsets",
                   "statefulsets", "cronjobs", "pods", "configmaps",
                   "services", "secrets")


class GarbageCollector(Controller):
    name = "garbage-collector"

    OWNER_RESOURCE = {
        "Job": "jobs",
        "ReplicaSet": "replicasets",
        "Deployment": "deployments",
        "DaemonSet": "daemonsets",
        "StatefulSet": "statefulsets",
        "CronJob": "cronjobs",
        "Pod": "pods",
        "ConfigMap": "configmaps",
        "Service": "services",
        "Secret": "secrets",
    }

    def setup(self):
        import threading

        self.informers: Dict[str, object] = {}
        # owner uid -> owned "<resource>|<key>"s: an owner's deletion
        # enqueues exactly its dependents (the reference's GC builds the
        # same dependency graph, pkg/controller/garbagecollector/graph.go)
        # — a full-cluster rescan per delete would be O(deletes x objects)
        # at 30k-pod density
        self._by_owner: Dict[str, set] = {}
        self._owner_lock = locksan.make_lock("GarbageCollector._owner_lock")
        for resource in set(OWNED_RESOURCES + OWNER_RESOURCES):
            self.informers[resource] = self.factory.informer(resource)
        for resource in OWNED_RESOURCES:
            inf = self.informers[resource]
            inf.add_handler(
                on_add=lambda o, r=resource: self._owned_added(r, o),
                on_delete=lambda o, r=resource: self._owned_removed(r, o),
            )
        for owner in OWNER_RESOURCES:
            self.informers[owner].add_handler(
                on_delete=self._owner_deleted
            )

    def _owned_added(self, resource: str, obj):
        key = f"{resource}|{obj.key()}"
        with self._owner_lock:
            for ref in obj.metadata.owner_references:
                self._by_owner.setdefault(ref.uid, set()).add(key)
        self.queue.add(key)

    def _owned_removed(self, resource: str, obj):
        key = f"{resource}|{obj.key()}"
        with self._owner_lock:
            for ref in obj.metadata.owner_references:
                deps = self._by_owner.get(ref.uid)
                if deps is not None:
                    deps.discard(key)
                    if not deps:
                        del self._by_owner[ref.uid]

    def _owner_deleted(self, obj):
        with self._owner_lock:
            deps = self._by_owner.pop(obj.metadata.uid, ())
        for key in deps:
            self.queue.add(key)

    def sync(self, key: str):
        resource, obj_key = key.split("|", 1)
        obj = self.informers[resource].get(obj_key)
        if obj is None or obj.metadata.deletion_timestamp:
            return
        for ref in obj.metadata.owner_references:
            owner_resource = self.OWNER_RESOURCE.get(ref.kind)
            if owner_resource is None:
                continue
            try:
                owner = self.cs.resource(owner_resource).get(
                    ref.name, obj.metadata.namespace
                )
                if owner.metadata.uid != ref.uid:
                    raise NotFound("uid changed")
            except NotFound:
                try:
                    self.cs.resource(resource).delete(
                        obj.metadata.name, obj.metadata.namespace,
                        grace_seconds=0 if resource == "pods" else None,
                    )
                except ApiError:
                    pass
                return
