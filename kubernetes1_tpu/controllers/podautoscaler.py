"""Horizontal pod autoscaler (ref: pkg/controller/podautoscaler/
horizontal.go): periodically compares observed metrics against the HPA's
targets and rescales the target workload.

Per metric spec:   desired_m = ceil(current * observed / target)
                   (inside a ±10% tolerance band: desired_m = current)
Across metrics:    desired = max(desired_m)    (autoscaling/v2 rule — any
                   one saturated signal is enough to need the replicas)
then clamped to [minReplicas, maxReplicas] and run through the behavior
stabilization windows (scale-up takes the MIN recommendation of its
window, scale-down the MAX of its — v2 HPAScalingRules shape; window 0 =
instant, the v1 behavior).

Metric sources:

- Resource/cpu (and the v1 ``targetCPUUtilizationPercentage`` shorthand):
  PodMetrics ÷ container requests, percent — consumed from an INFORMER
  snapshot, never one live GET per pod per 2s cycle;
- Pods: a named sample scraped off each pod's /metrics endpoint
  (PodCustomMetrics, the kubelet scrape pipeline), averaged across the
  target's pods against ``targetAverageValue``.  Samples marked STALE
  (the owning kubelet's scrape is failing) count as missing.

Missing metrics skip the cycle (the reference's rule): with no usable
signal the HPA HOLDS the current scale — a scrape outage must read as
"no new information", never as "load went to zero".
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..api import types as t
from ..client.retry import retry_on_conflict
from ..machinery import ApiError, NotFound, now_iso
from ..machinery.labels import label_selector_matches
from ..obs.appmetrics import sample_value
from ..utils import flightrec, locksan
from ..utils.logutil import RateLimitedReporter
from ..utils.metrics import Counter, Gauge, Histogram
from ..utils.quantity import parse_quantity
from .base import Controller

TOLERANCE = 0.1
SYNC_PERIOD = 2.0  # the reference uses 30s; scaled for in-process clusters

# Module-level metric families (the retries_total contract): every HPA
# instance in a process shares them; rendered by the apiserver's
# render_client_metrics gate and the controllers __main__ registry, so a
# fleet merge sees the whole scaling loop exactly once per process.
hpa_observed_value = Gauge(
    "ktpu_hpa_observed_value",
    "last observed average per (hpa, metric) — cpu in percent, Pods "
    "metrics in the sample's own unit")
hpa_desired_replicas = Gauge(
    "ktpu_hpa_desired_replicas", "last desired replica count per hpa")
hpa_current_replicas = Gauge(
    "ktpu_hpa_current_replicas", "target's current replica count per hpa")
hpa_rescales_total = Counter(
    "ktpu_hpa_rescales_total", "rescales issued, by direction")
hpa_missing_metric_cycles_total = Counter(
    "ktpu_hpa_missing_metric_cycles_total",
    "cycles skipped because no metric produced a usable value")
hpa_reaction_seconds = Histogram(
    "ktpu_hpa_reaction_seconds",
    "first out-of-tolerance observation -> rescale write landed")


def rescales_snapshot() -> float:
    """Total rescales across directions (bench/chaos delta helper — the
    family's value lives on labeled children)."""
    return sum(c.value for c in
               hpa_rescales_total._children_snapshot()) \
        + hpa_rescales_total.value


class HorizontalPodAutoscalerController(Controller):
    name = "horizontal-pod-autoscaler"

    def setup(self):
        self.hpas = self.factory.informer("horizontalpodautoscalers")
        self.pods = self.factory.informer("pods")
        # metric pipelines ride informers too: one watch each, zero API
        # round-trips per sync cycle (the old shape issued one live
        # podmetrics GET per pod per 2s cycle — N×RTT of pure overhead).
        # LAZY: the PodMetrics collection churns with every kubelet
        # heartbeat (one object per pod), so a controller manager with
        # ZERO HPAs must not subscribe to that fan-out — the informers
        # spin up on the first reconcile that needs them.
        self._podmetrics = None
        self._podcustommetrics = None
        self._metric_inf_lock = locksan.make_lock(
            "podautoscaler.metric_informers")
        self.hpas.add_handler(
            on_add=self._schedule, on_update=lambda _o, n: self._schedule(n)
        )
        # per-HPA recommendation history (behavior stabilization windows)
        # and the first-out-of-band stamp feeding the reaction-time SLI
        self._recommendations: Dict[str, Deque[Tuple[float, int]]] = {}
        self._out_of_band_since: Dict[str, float] = {}
        self._status_err_reporter = RateLimitedReporter(
            self.name, window=30.0)

    def _schedule(self, hpa):
        self.enqueue(hpa)

    def _lazy_informer(self, attr: str, resource: str):
        inf = getattr(self, attr)
        if inf is not None:
            inf.wait_for_sync(10.0)  # instant once synced
            return inf
        with self._metric_inf_lock:
            inf = getattr(self, attr)
            if inf is None:
                inf = self.factory.informer(resource)
                # created after the factory's start_all (first HPA seen
                # mid-run): start it here — SharedInformer.start is
                # guarded, and this lock serializes racing workers
                inf.start()
                setattr(self, attr, inf)
        inf.wait_for_sync(10.0)
        return inf

    @property
    def podmetrics(self):
        return self._lazy_informer("_podmetrics", "podmetrics")

    @property
    def podcustommetrics(self):
        return self._lazy_informer("_podcustommetrics", "podcustommetrics")

    def _target_client(self, kind: str):
        return {
            "Deployment": self.cs.deployments,
            "ReplicaSet": self.cs.replicasets,
            "StatefulSet": self.cs.statefulsets,
        }.get(kind)

    def sync(self, key: str):
        hpa = self.hpas.get(key)
        if hpa is None:
            self._recommendations.pop(key, None)
            self._out_of_band_since.pop(key, None)
            # the deleted HPA's labeled gauge children must not render
            # (or feed the fleet scaling view) forever
            for fam in (hpa_observed_value, hpa_desired_replicas,
                        hpa_current_replicas):
                fam.remove_labels(hpa=key)
            return
        try:
            self._reconcile(hpa)
        finally:
            # periodic resync regardless of outcome (metrics move on their own)
            self.enqueue_after(key, SYNC_PERIOD)

    # ----------------------------------------------------------- evaluation

    def _metric_specs(self, hpa: t.HorizontalPodAutoscaler,
                      ) -> List[t.MetricSpec]:
        """spec.metrics, or the v1 CPU shorthand lifted into one Resource
        entry — one evaluation path for both API shapes."""
        if hpa.spec.metrics:
            return hpa.spec.metrics
        if hpa.spec.target_cpu_utilization_percentage:
            return [t.MetricSpec(type="Resource", resource=t.ResourceMetricSource(
                name="cpu",
                target_average_utilization=hpa.spec.target_cpu_utilization_percentage,
            ))]
        return []

    def _evaluate(self, hpa, pods) -> List[Tuple[str, float, float]]:
        """[(metric name, observed average, observed/target ratio)] —
        one entry per metric spec that produced a value this cycle."""
        out = []
        for ms in self._metric_specs(hpa):
            if ms.type == "Resource" and ms.resource is not None \
                    and ms.resource.name == "cpu" \
                    and ms.resource.target_average_utilization:
                util = self._cpu_utilization(pods)
                if util is not None:
                    out.append(("cpu", util, util / float(
                        ms.resource.target_average_utilization)))
            elif ms.type == "Pods" and ms.pods is not None \
                    and ms.pods.metric_name \
                    and ms.pods.target_average_value > 0:
                avg = self._pods_metric(pods, ms.pods.metric_name)
                if avg is not None:
                    out.append((ms.pods.metric_name, avg,
                                avg / ms.pods.target_average_value))
        return out

    def _reconcile(self, hpa: t.HorizontalPodAutoscaler):
        client = self._target_client(hpa.spec.scale_target_ref.kind)
        if client is None:
            return
        ns = hpa.metadata.namespace
        key = hpa.key()
        try:
            target = client.get(hpa.spec.scale_target_ref.name, ns)
        except NotFound:
            return
        current = target.spec.replicas or 0
        if current == 0:
            return  # scaled to zero — autoscaling disabled by convention
        selector = target.spec.selector
        pods = [
            p for p in self.pods.list()
            if p.metadata.namespace == ns
            and not p.metadata.deletion_timestamp
            and p.status.phase == t.POD_RUNNING
            and selector is not None
            and label_selector_matches(selector, p.metadata.labels)
        ]
        specs = self._metric_specs(hpa)
        evaluations = self._evaluate(hpa, pods)
        some_missing = bool(specs) and len(evaluations) < len(specs)
        held_for_missing = False
        if specs and not evaluations:
            # missing-metrics-skips-cycle: no usable signal this round —
            # hold the current scale (a scraping outage is not zero
            # load).  The hold still runs the [min,max] clamp and the
            # status write (the seed's v1 behavior, byte-identical) but
            # skips the stabilization/reaction bookkeeping below: a
            # blip's `current` sample in the up-window would suppress a
            # pending scale-up for the whole window, and popping the
            # reaction stamp would make the SLI measure from the last
            # blip instead of the first out-of-tolerance observation.
            hpa_missing_metric_cycles_total.inc()
            held_for_missing = True
        desired = current
        if evaluations:
            # max-of-metrics, tolerance applied per metric (v2 rule)
            per_metric = []
            for _name, _avg, ratio in evaluations:
                if abs(ratio - 1.0) > TOLERANCE:
                    per_metric.append(int(math.ceil(current * ratio)))
                else:
                    per_metric.append(current)
            desired = max(per_metric)
            if some_missing and desired < current:
                # a PARTIAL outage blocks scale-down (the reference's
                # rule): the missing metric might be the saturated one —
                # max-of-metrics means its vote can only RAISE desired,
                # so acting on the readable subset is safe upward but a
                # drain on stale information downward
                hpa_missing_metric_cycles_total.inc()
                desired = current
                held_for_missing = True
        desired = max(hpa.spec.min_replicas or 1,
                      min(hpa.spec.max_replicas, desired))
        if not held_for_missing:
            # arm the reaction stamp on the PRE-stabilization want: the
            # SLI is "first out-of-tolerance observation -> rescale
            # landed", and a stabilization window holding the
            # recommendation is exactly the reaction time the histogram
            # must capture, not elide.  A missing-metric hold skips the
            # bookkeeping like the total-outage skip above.
            self._note_reaction_window(key, desired, current)
            desired = self._stabilize(hpa, key, desired, current)

        utilization = None
        for name, avg, _ratio in evaluations:
            hpa_observed_value.labels(hpa=key, metric=name).set(avg)
            if name == "cpu":
                utilization = avg
        hpa_current_replicas.labels(hpa=key).set(current)
        hpa_desired_replicas.labels(hpa=key).set(desired)

        if desired != current:
            def rescale():
                fresh = client.get(hpa.spec.scale_target_ref.name, ns)
                fresh.spec.replicas = desired
                return client.update(fresh)

            try:
                retry_on_conflict(rescale)
            except ApiError:
                return
            direction = "up" if desired > current else "down"
            hpa_rescales_total.labels(direction=direction).inc()
            flightrec.note("hpa", flightrec.HPA_RESCALE, hpa=key,
                           target=f"{hpa.spec.scale_target_ref.kind}"
                                  f"/{hpa.spec.scale_target_ref.name}",
                           from_replicas=current, to_replicas=desired,
                           direction=direction)
            since = self._out_of_band_since.pop(key, None)
            if since is not None:
                hpa_reaction_seconds.observe(time.monotonic() - since)
            self.recorder.event(
                hpa, "Normal", "SuccessfulRescale",
                f"scaled {hpa.spec.scale_target_ref.kind.lower()}"
                f"/{hpa.spec.scale_target_ref.name} from {current} to {desired}",
            )
        self._update_status(hpa, current, desired, utilization, evaluations)

    # --------------------------------------------------------- stabilization

    def _stabilize(self, hpa, key: str, recommendation: int,
                   current: int) -> int:
        """Behavior stabilization (ref: v2 stabilizationWindowSeconds):
        a scale-up acts on the MIN recommendation of the up-window (one
        spike must not add replicas), a scale-down on the MAX of the
        down-window (replicas drain only after the need has been gone
        for the whole window).  Windows of 0 pass through untouched."""
        up_w = hpa.spec.scale_up_stabilization_seconds or 0.0
        down_w = hpa.spec.scale_down_stabilization_seconds or 0.0
        now = time.monotonic()
        dq = self._recommendations.setdefault(key, deque())
        dq.append((now, recommendation))
        horizon = now - max(up_w, down_w, SYNC_PERIOD)
        while dq and dq[0][0] < horizon:
            dq.popleft()
        if recommendation > current and up_w > 0:
            floor = now - up_w
            stabilized = min(r for ts, r in dq if ts >= floor)
            return max(stabilized, current)
        if recommendation < current and down_w > 0:
            floor = now - down_w
            stabilized = max(r for ts, r in dq if ts >= floor)
            return min(stabilized, current)
        return recommendation

    def _note_reaction_window(self, key: str, desired: int, current: int):
        """Arm the reaction-time stamp the first cycle a rescale becomes
        wanted; disarm when the want goes away without a rescale."""
        if desired != current:
            self._out_of_band_since.setdefault(key, time.monotonic())
        else:
            self._out_of_band_since.pop(key, None)

    # --------------------------------------------------------- metric reads

    def _cpu_utilization(self, pods):
        """Mean of (usage / request) across pods, percent; None if no pod
        has both a request and a metrics sample (the reference treats
        missing metrics as 'skip this cycle').  PodMetrics come from the
        informer snapshot — zero API round-trips per cycle."""
        ratios = []
        inf = self.podmetrics  # one sync wait per cycle, not per pod
        for p in pods:
            requests = {
                c.name: parse_quantity(c.resources.requests.get("cpu"))
                for c in p.spec.containers
            }
            if not any(requests.values()):
                continue
            pm = inf.get(p.key())
            if pm is None:
                continue
            usage = sum(parse_quantity(c.usage.get("cpu")) for c in pm.containers)
            request = sum(requests.values())
            if request > 0:
                ratios.append(100.0 * usage / request)
        if not ratios:
            return None
        return sum(ratios) / len(ratios)

    def _pods_metric(self, pods, metric_name: str) -> Optional[float]:
        """Average of a scraped sample across the target's pods; stale
        PodCustomMetrics (owning kubelet's scrape failing) and pods
        without the sample are missing, not zero.  None when NO pod has
        a fresh sample — the skip-cycle signal."""
        values = []
        inf = self.podcustommetrics  # one sync wait per cycle, not per pod
        for p in pods:
            pcm = inf.get(p.key())
            if pcm is None or pcm.stale:
                continue
            v = sample_value(pcm, metric_name)
            if v is not None:
                values.append(v)
        if not values:
            return None
        return sum(values) / len(values)

    # --------------------------------------------------------------- status

    def _update_status(self, hpa, current, desired, utilization,
                       evaluations):
        def attempt():
            try:
                fresh = self.cs.horizontalpodautoscalers.get(
                    hpa.metadata.name, hpa.metadata.namespace
                )
            except NotFound:
                return
            st = fresh.status
            util = int(round(utilization)) if utilization is not None \
                else st.current_cpu_utilization_percentage
            metric_values = {name: round(avg, 4)
                             for name, avg, _r in evaluations
                             if name != "cpu"}
            if (
                st.current_replicas == current
                and st.desired_replicas == desired
                and st.current_cpu_utilization_percentage == util
                and st.current_metric_values == metric_values
                and st.observed_generation == fresh.metadata.generation
            ):
                return  # unchanged — writing anyway would re-trigger our own informer
            st.current_replicas = current
            st.desired_replicas = desired
            st.current_cpu_utilization_percentage = util
            st.current_metric_values = metric_values
            if desired != current:
                st.last_scale_time = now_iso()
            st.observed_generation = fresh.metadata.generation
            self.cs.horizontalpodautoscalers.update_status(fresh)

        try:
            # Conflict = a concurrent writer bumped the rv between our
            # get and update: re-read and retry through the shared
            # policy.  Anything else is logged, never swallowed — a
            # permanently failing status write must be visible.
            retry_on_conflict(attempt)
        except NotFound:
            return  # HPA deleted mid-write: nothing to record
        except ApiError as e:
            self._status_err_reporter.report(
                f"status update {hpa.key()}: {e}")
