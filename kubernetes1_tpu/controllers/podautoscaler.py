"""Horizontal pod autoscaler (ref: pkg/controller/podautoscaler/
horizontal.go): periodically compares observed CPU utilization (PodMetrics ÷
container requests) against the HPA target and rescales the target workload.

desiredReplicas = ceil(currentReplicas * currentUtilization / targetUtilization)
with a tolerance band (±10%) to prevent thrashing, clamped to
[minReplicas, maxReplicas] (the reference's computeReplicasForCPUUtilization)."""

from __future__ import annotations

import math

from ..api import types as t
from ..client.retry import retry_on_conflict
from ..machinery import ApiError, NotFound, now_iso
from ..machinery.labels import label_selector_matches
from ..utils.quantity import parse_quantity
from .base import Controller

TOLERANCE = 0.1
SYNC_PERIOD = 2.0  # the reference uses 30s; scaled for in-process clusters


class HorizontalPodAutoscalerController(Controller):
    name = "horizontal-pod-autoscaler"

    def setup(self):
        self.hpas = self.factory.informer("horizontalpodautoscalers")
        self.pods = self.factory.informer("pods")
        self.hpas.add_handler(
            on_add=self._schedule, on_update=lambda _o, n: self._schedule(n)
        )

    def _schedule(self, hpa):
        self.enqueue(hpa)

    def _target_client(self, kind: str):
        return {
            "Deployment": self.cs.deployments,
            "ReplicaSet": self.cs.replicasets,
            "StatefulSet": self.cs.statefulsets,
        }.get(kind)

    def sync(self, key: str):
        hpa = self.hpas.get(key)
        if hpa is None:
            return
        try:
            self._reconcile(hpa)
        finally:
            # periodic resync regardless of outcome (metrics move on their own)
            self.enqueue_after(key, SYNC_PERIOD)

    def _reconcile(self, hpa: t.HorizontalPodAutoscaler):
        client = self._target_client(hpa.spec.scale_target_ref.kind)
        if client is None:
            return
        ns = hpa.metadata.namespace
        try:
            target = client.get(hpa.spec.scale_target_ref.name, ns)
        except NotFound:
            return
        current = target.spec.replicas or 0
        if current == 0:
            return  # scaled to zero — autoscaling disabled by convention
        selector = target.spec.selector
        pods = [
            p for p in self.pods.list()
            if p.metadata.namespace == ns
            and not p.metadata.deletion_timestamp
            and p.status.phase == t.POD_RUNNING
            and selector is not None
            and label_selector_matches(selector, p.metadata.labels)
        ]
        utilization = self._cpu_utilization(pods)
        desired = current
        tgt = hpa.spec.target_cpu_utilization_percentage
        if tgt and utilization is not None:
            ratio = utilization / float(tgt)
            if abs(ratio - 1.0) > TOLERANCE:
                desired = int(math.ceil(current * ratio))
        desired = max(hpa.spec.min_replicas or 1, min(hpa.spec.max_replicas, desired))

        if desired != current:
            def rescale():
                fresh = client.get(hpa.spec.scale_target_ref.name, ns)
                fresh.spec.replicas = desired
                return client.update(fresh)

            try:
                retry_on_conflict(rescale)
                self.recorder.event(
                    hpa, "Normal", "SuccessfulRescale",
                    f"scaled {hpa.spec.scale_target_ref.kind.lower()}"
                    f"/{hpa.spec.scale_target_ref.name} from {current} to {desired}",
                )
            except ApiError:
                return
        self._update_status(hpa, current, desired, utilization)

    def _cpu_utilization(self, pods):
        """Mean of (usage / request) across pods, percent; None if no pod has
        both a request and a metrics sample (the reference treats missing
        metrics as 'skip this cycle')."""
        ratios = []
        for p in pods:
            requests = {
                c.name: parse_quantity(c.resources.requests.get("cpu"))
                for c in p.spec.containers
            }
            if not any(requests.values()):
                continue
            try:
                pm = self.cs.podmetrics.get(p.metadata.name, p.metadata.namespace)
            except ApiError:
                continue
            usage = sum(parse_quantity(c.usage.get("cpu")) for c in pm.containers)
            request = sum(requests.values())
            if request > 0:
                ratios.append(100.0 * usage / request)
        if not ratios:
            return None
        return sum(ratios) / len(ratios)

    def _update_status(self, hpa, current, desired, utilization):
        try:
            fresh = self.cs.horizontalpodautoscalers.get(
                hpa.metadata.name, hpa.metadata.namespace
            )
        except NotFound:
            return
        st = fresh.status
        util = int(round(utilization)) if utilization is not None else st.current_cpu_utilization_percentage
        if (
            st.current_replicas == current
            and st.desired_replicas == desired
            and st.current_cpu_utilization_percentage == util
            and st.observed_generation == fresh.metadata.generation
        ):
            return  # unchanged — writing anyway would re-trigger our own informer
        st.current_replicas = current
        st.desired_replicas = desired
        st.current_cpu_utilization_percentage = util
        if desired != current:
            st.last_scale_time = now_iso()
        st.observed_generation = fresh.metadata.generation
        try:
            self.cs.horizontalpodautoscalers.update_status(fresh)
        except ApiError:
            pass
