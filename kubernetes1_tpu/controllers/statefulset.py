"""StatefulSet controller (ref: pkg/controller/statefulset/
stateful_set.go + stateful_set_control.go): stable pod identity
`<name>-<ordinal>`, ordered scale-up/down, partitioned rolling updates.

TPU relevance: stable ordinals give multi-host workers persistent
identities across restarts (same role as Indexed Jobs, but for
long-running serving/parameter-server shapes).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

from ..api import types as t
from ..machinery import ApiError, NotFound
from ..machinery.scheme import from_dict, to_dict
from .base import Controller, write_status_if_changed
from .deployment import template_hash

POD_NAME_LABEL = "statefulset.kubernetes.io/pod-name"
REVISION_LABEL = "controller-revision-hash"

_ORDINAL_RE = re.compile(r"^(.*)-(\d+)$")


def ordinal_of(pod_name: str, parent: str) -> Optional[int]:
    m = _ORDINAL_RE.match(pod_name)
    if m and m.group(1) == parent:
        return int(m.group(2))
    return None


def is_ready(pod: t.Pod) -> bool:
    return pod.status.phase == t.POD_RUNNING and any(
        c.type == "Ready" and c.status == "True" for c in pod.status.conditions
    )


class StatefulSetController(Controller):
    name = "statefulset-controller"

    def setup(self):
        self.ssets = self.factory.informer("statefulsets")
        self.pods = self.factory.informer("pods")
        self.ssets.add_handler(
            on_add=self.enqueue,
            on_update=lambda _o, n: self.enqueue(n),
            on_delete=self.enqueue,
        )
        self.pods.add_handler(
            on_add=self._pod_event,
            on_update=lambda _o, n: self._pod_event(n),
            on_delete=self._pod_event,
        )

    def _pod_event(self, pod: t.Pod):
        for ref in pod.metadata.owner_references:
            if ref.kind == "StatefulSet" and ref.controller:
                self.queue.add(f"{pod.metadata.namespace}/{ref.name}")

    def _owned_pods(self, ss: t.StatefulSet) -> Dict[int, t.Pod]:
        out: Dict[int, t.Pod] = {}
        for p in self.pods.list():
            if p.metadata.namespace != ss.metadata.namespace:
                continue
            if not any(
                r.kind == "StatefulSet" and r.uid == ss.metadata.uid and r.controller
                for r in p.metadata.owner_references
            ):
                continue
            o = ordinal_of(p.metadata.name, ss.metadata.name)
            if o is not None:
                out[o] = p
        return out

    def _new_pod(self, ss: t.StatefulSet, ordinal: int, revision: str) -> t.Pod:
        pod = t.Pod()
        pod.metadata.name = f"{ss.metadata.name}-{ordinal}"
        pod.metadata.namespace = ss.metadata.namespace
        pod.metadata.labels = dict(ss.spec.template.metadata.labels)
        pod.metadata.labels[POD_NAME_LABEL] = pod.metadata.name
        pod.metadata.labels[REVISION_LABEL] = revision
        pod.metadata.annotations = dict(ss.spec.template.metadata.annotations)
        pod.metadata.owner_references = [
            t.OwnerReference(
                api_version=ss.API_VERSION, kind="StatefulSet",
                name=ss.metadata.name, uid=ss.metadata.uid, controller=True,
            )
        ]
        pod.spec = from_dict(t.PodSpec, to_dict(ss.spec.template.spec))
        return pod

    def sync(self, key: str):
        ss = self.ssets.get(key)
        if ss is None or ss.metadata.deletion_timestamp:
            return
        want = ss.spec.replicas if ss.spec.replicas is not None else 1
        update_rev = template_hash(ss.spec.template)
        pods = self._owned_pods(ss)
        ordered = ss.spec.pod_management_policy == "OrderedReady"

        # Replace failed/succeeded pods first (the controller always
        # recreates a dead stateful pod under the same identity).
        for o, p in sorted(pods.items()):
            if o < want and p.status.phase in (t.POD_FAILED, t.POD_SUCCEEDED):
                self._delete(p)
                return  # re-sync after the delete is observed

        # Scale up: fill missing ordinals ascending.
        for o in range(want):
            p = pods.get(o)
            if p is None or p.metadata.deletion_timestamp:
                if p is None:
                    try:
                        self.cs.pods.create(self._new_pod(ss, o, update_rev))
                    except ApiError:
                        pass
                if ordered:
                    self._update_status(ss, pods, want, update_rev)
                    return
                continue
            if ordered and not is_ready(p):
                # OrderedReady: wait for this ordinal before touching higher ones
                self._update_status(ss, pods, want, update_rev)
                return

        # Scale down: remove highest ordinals first, one at a time if ordered.
        excess = sorted((o for o in pods if o >= want), reverse=True)
        for o in excess:
            if not pods[o].metadata.deletion_timestamp:
                self._delete(pods[o])
                if ordered:
                    self._update_status(ss, pods, want, update_rev)
                    return

        # Rolling update: delete out-of-date pods with ordinal >= partition,
        # highest first, one at a time. Readiness-gated regardless of
        # podManagementPolicy (the policy only governs scaling): the next
        # delete waits until every current pod is back Running+Ready.
        if ss.spec.update_strategy.type == "RollingUpdate" and not excess:
            current = [p for o, p in pods.items() if o < want]
            all_ready = len(current) == want and all(
                is_ready(p) and not p.metadata.deletion_timestamp for p in current
            )
            ru = ss.spec.update_strategy.rolling_update
            partition = ru.partition if ru else 0
            for o in sorted((o for o in pods if o < want), reverse=True):
                p = pods[o]
                if o < partition or p.metadata.deletion_timestamp:
                    continue
                if p.metadata.labels.get(REVISION_LABEL) != update_rev:
                    if all_ready:
                        self._delete(p)
                    break  # one at a time

        self._update_status(ss, pods, want, update_rev)

    def _delete(self, pod: t.Pod):
        try:
            self.cs.pods.delete(pod.metadata.name, pod.metadata.namespace)
        except ApiError:
            pass

    def _update_status(
        self, ss: t.StatefulSet, pods: Dict[int, t.Pod], want: int, update_rev: str
    ):
        try:
            fresh = self.cs.statefulsets.get(ss.metadata.name, ss.metadata.namespace)
        except NotFound:
            return
        alive = [
            p for o, p in pods.items()
            if o < want and not p.metadata.deletion_timestamp
            and p.status.phase not in (t.POD_FAILED, t.POD_SUCCEEDED)
        ]
        def apply(st):
            st.replicas = len(alive)
            st.ready_replicas = sum(1 for p in alive if is_ready(p))
            st.updated_replicas = sum(
                1 for p in alive if p.metadata.labels.get(REVISION_LABEL) == update_rev
            )
            st.current_replicas = st.updated_replicas
            st.update_revision = update_rev
            if st.updated_replicas == len(alive):
                st.current_revision = update_rev
            st.observed_generation = fresh.metadata.generation

        try:
            write_status_if_changed(self.cs.statefulsets, fresh, apply)
        except ApiError:
            pass
