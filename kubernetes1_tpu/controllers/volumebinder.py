"""PersistentVolume binder (ref: pkg/controller/volume/persistentvolume/
pv_controller.go): matches Pending claims to Available volumes (capacity ≥
request, access modes ⊆ volume's, storage class equal), binds both sides,
and releases volumes whose claim is gone (Retain → Released, Delete →
deleted). JAX checkpoint/dataset volumes ride this path."""

from __future__ import annotations

from ..api import types as t
from ..machinery import ApiError, NotFound
from ..utils.quantity import parse_quantity
from .base import Controller
from .volumeutil import has_scheduled_consumer, pod_claim_keys


class PersistentVolumeBinder(Controller):
    name = "persistentvolume-binder"

    def setup(self):
        self.pvs = self.factory.informer("persistentvolumes")
        self.pvcs = self.factory.informer("persistentvolumeclaims")
        self.classes = self.factory.informer("storageclasses")
        self.pods = self.factory.informer("pods")
        self.pvcs.add_handler(
            on_add=self.enqueue, on_update=lambda _o, n: self.enqueue(n),
            on_delete=self._claim_deleted,
        )
        self.pvs.add_handler(
            on_add=self._pv_event, on_update=lambda _o, n: self._pv_event(n)
        )
        # WaitForFirstConsumer: a pod landing on a node unblocks binding
        self.pods.add_handler(
            on_add=self._pod_event, on_update=lambda _o, n: self._pod_event(n)
        )

    def _pod_event(self, pod: t.Pod):
        if not pod.spec.node_name:
            return
        for key in pod_claim_keys(pod):
            self.queue.add(key)

    def _must_wait_for_consumer(self, pvc: t.PersistentVolumeClaim) -> bool:
        """StorageClass volumeBindingMode=WaitForFirstConsumer (ref
        storage/types.go): hold binding until a pod consuming the claim is
        scheduled — applies to pre-created PVs exactly as to dynamic ones."""
        if not pvc.spec.storage_class_name:
            return False
        sc = self.classes.get(pvc.spec.storage_class_name)
        if sc is None or sc.volume_binding_mode != "WaitForFirstConsumer":
            return False
        return not has_scheduled_consumer(self.pods, pvc)

    def _pv_event(self, pv: t.PersistentVolume):
        # a new/updated volume may satisfy a pending claim; also reconcile
        # release of bound volumes whose claim vanished
        for pvc in self.pvcs.list():
            if pvc.status.phase == "Pending":
                self.enqueue(pvc)
        self._maybe_release(pv)

    def _claim_deleted(self, pvc: t.PersistentVolumeClaim):
        for pv in self.pvs.list():
            self._maybe_release(pv)

    def _maybe_release(self, pv: t.PersistentVolume):
        ref = pv.spec.claim_ref
        if ref is None or pv.status.phase != "Bound":
            return
        if self.pvcs.get(f"{ref.namespace}/{ref.name}") is not None:
            return
        try:
            if pv.spec.persistent_volume_reclaim_policy == "Delete":
                self.cs.persistentvolumes.delete(pv.metadata.name, "")
                return
            fresh = self.cs.persistentvolumes.get(pv.metadata.name, "")
            fresh.status.phase = "Released"
            self.cs.persistentvolumes.update_status(fresh)
        except (NotFound, ApiError):
            pass

    @staticmethod
    def _matches(pv: t.PersistentVolume, pvc: t.PersistentVolumeClaim) -> bool:
        if pv.spec.claim_ref is not None or pv.status.phase != "Available":
            return False
        if pv.spec.storage_class_name != pvc.spec.storage_class_name:
            return False
        if not set(pvc.spec.access_modes) <= set(pv.spec.access_modes):
            return False
        want = parse_quantity(pvc.spec.resources.requests.get("storage"))
        have = parse_quantity(pv.spec.capacity.get("storage"))
        return have >= want

    def sync(self, key: str):
        pvc = self.pvcs.get(key)
        if pvc is None or pvc.status.phase == "Bound":
            return
        if pvc.spec.volume_name:
            self._finish_bind(pvc, pvc.spec.volume_name)
            return
        # a previous pass may have claimed a PV but crashed before finishing —
        # resume that bind instead of claiming a second volume (the dynamic
        # provisioner's pre-bound PVs ride the same path).  The uid must
        # match: a same-name claim RECREATED after a delete is a different
        # claim, and handing it a stale pre-bound volume would serve it the
        # old claim's data with the old claim's class/size.
        for pv in self.pvs.list():
            ref = pv.spec.claim_ref
            if (
                ref is not None
                and ref.namespace == pvc.metadata.namespace
                and ref.name == pvc.metadata.name
                and (not ref.uid or ref.uid == pvc.metadata.uid)
            ):
                self._finish_bind(pvc, pv.metadata.name)
                return
        if self._must_wait_for_consumer(pvc):
            return  # _pod_event re-enqueues when a consumer is scheduled
        # smallest satisfying volume wins (reference's findBestMatchForClaim)
        candidates = [pv for pv in self.pvs.list() if self._matches(pv, pvc)]
        if not candidates:
            return  # requeued when a PV appears
        best = min(candidates, key=lambda pv: parse_quantity(pv.spec.capacity.get("storage")))
        try:
            fresh_pv = self.cs.persistentvolumes.get(best.metadata.name, "")
            if fresh_pv.spec.claim_ref is not None:
                self.enqueue_after(key, 0.5)  # raced with another binder pass
                return
            fresh_pv.spec.claim_ref = t.ObjectReference(
                kind="PersistentVolumeClaim",
                namespace=pvc.metadata.namespace,
                name=pvc.metadata.name,
                uid=pvc.metadata.uid,
            )
            fresh_pv = self.cs.persistentvolumes.update(fresh_pv)
            fresh_pv.status.phase = "Bound"
            self.cs.persistentvolumes.update_status(fresh_pv)
        except ApiError:
            self.enqueue_after(key, 0.5)
            return
        self._finish_bind(pvc, best.metadata.name)

    def _finish_bind(self, pvc: t.PersistentVolumeClaim, pv_name: str):
        try:
            pv = self.cs.persistentvolumes.get(pv_name, "")
            fresh = self.cs.persistentvolumeclaims.get(
                pvc.metadata.name, pvc.metadata.namespace
            )
            if not fresh.spec.volume_name:
                fresh.spec.volume_name = pv_name
                fresh = self.cs.persistentvolumeclaims.update(fresh)
            fresh.status.phase = "Bound"
            fresh.status.capacity = dict(pv.spec.capacity)
            fresh.status.access_modes = list(pv.spec.access_modes)
            self.cs.persistentvolumeclaims.update_status(fresh)
            if pv.status.phase != "Bound":
                pv.status.phase = "Bound"
                self.cs.persistentvolumes.update_status(pv)
        except ApiError:
            self.enqueue_after(pvc.key(), 0.5)
