"""TTL-after-finished controller: deletes finished Jobs (and their pods via
the garbage collector) once spec.ttl_seconds_after_finished elapses.

The reference's pkg/controller/ttl manages node annotations; run-to-completion
cleanup did not exist in 1.9 (jobs piled up forever). For a TPU cluster that
churns through training Jobs this is table stakes, so the controller follows
the later upstream ttlafterfinished design instead."""

from __future__ import annotations

import datetime

from ..machinery import ApiError, NotFound
from .base import Controller


def _parse_iso(ts: str):
    try:
        return datetime.datetime.fromisoformat(ts.replace("Z", "+00:00"))
    except ValueError:
        return None


class TTLAfterFinishedController(Controller):
    name = "ttl-after-finished-controller"

    def __init__(self, clientset, factory, clock=None, workers: int = 1):
        super().__init__(clientset, factory, workers)
        self._now = clock or (lambda: datetime.datetime.now(datetime.timezone.utc))

    def setup(self):
        self.jobs = self.factory.informer("jobs")
        self.jobs.add_handler(
            on_add=self.enqueue, on_update=lambda _o, n: self.enqueue(n)
        )

    def sync(self, key: str):
        job = self.jobs.get(key)
        if job is None or job.spec.ttl_seconds_after_finished is None:
            return
        finished_at = None
        for cond in job.status.conditions:
            if cond.type in ("Complete", "Failed") and cond.status == "True":
                finished_at = _parse_iso(cond.last_transition_time) or self._now()
        if finished_at is None:
            return
        expiry = finished_at + datetime.timedelta(
            seconds=job.spec.ttl_seconds_after_finished
        )
        remaining = (expiry - self._now()).total_seconds()
        if remaining > 0:
            self.enqueue_after(key, min(remaining, 30.0))
            return
        try:
            self.cs.jobs.delete(job.metadata.name, job.metadata.namespace)
        except (NotFound, ApiError):
            pass
