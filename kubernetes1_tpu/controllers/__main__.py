"""Standalone controller-manager entrypoint (ref: cmd/kube-controller-manager).

    python -m kubernetes1_tpu.controllers --server http://127.0.0.1:8001 [--leader-elect]
"""

import argparse
import signal
import threading

from .manager import ControllerManager


def main():
    ap = argparse.ArgumentParser(description="ktpu controller manager")
    ap.add_argument("--feature-gates", default="", help="Name=true|false list (one shared gate map; utils/features.py)")
    ap.add_argument("--server", default="http://127.0.0.1:8001")
    ap.add_argument("--token", default="")
    ap.add_argument("--leader-elect", action="store_true")
    ap.add_argument("--identity", default="kcm-0")
    ap.add_argument("--metrics-port", type=int, default=-1,
                    help="/metrics + /healthz port (0 = ephemeral, -1 = off);"
                         " exports the gang failure-domain surface "
                         "(ktpu_gang_recovery_seconds MTTR, attempts, node "
                         "eviction counters) from a standalone controller "
                         "manager — in-process topologies read them off the "
                         "apiserver's /metrics instead")
    ap.add_argument("--node-monitor-grace", type=float, default=40.0)
    ap.add_argument("--pod-eviction-timeout", type=float, default=300.0)
    ap.add_argument("--endpoints-coalesce-ms", type=float, default=0.0,
                    help="endpoints fan-out coalesce window in ms (0 = one "
                         "Endpoints write per pod event — today's wire); "
                         ">0 batches a service's churn into one write per "
                         "window")
    ap.add_argument("--ca-key-file", default="", help="CSR signing key")
    ap.add_argument("--ca-cert-file", default="",
                    help="cluster CA cert (enables x509 CSR signing)")
    ap.add_argument("--sa-key-file", default="", help="SA token signing key")
    from ..utils.procutil import add_client_args, clientset_from_args, read_key

    add_client_args(ap)
    args = ap.parse_args()
    if args.feature_gates:
        from ..utils.features import gates
        gates.apply(args.feature_gates)

    cs = clientset_from_args(args)
    cm = ControllerManager(
        cs,
        leader_elect=args.leader_elect,
        identity=args.identity,
        monitor_grace=args.node_monitor_grace,
        eviction_timeout=args.pod_eviction_timeout,
        ca_key=read_key(args.ca_key_file, "ktpu-ca-key"),
        ca_cert_pem=read_key(args.ca_cert_file, ""),
        sa_signing_key=read_key(args.sa_key_file, "ktpu-sa-key"),
        endpoints_coalesce_window=args.endpoints_coalesce_ms / 1000.0,
    )
    cm.start()
    metrics_server = None
    if args.metrics_port >= 0:
        from ..utils.metrics import MetricsServer, Registry
        from . import job as _job

        reg = Registry()
        reg.register(_job.gang_recovery_seconds)
        reg.register(_job.gang_attempts_total)
        from . import endpoints as _eps

        reg.register(_eps.endpoints_writes_total)
        reg.register(_eps.endpoints_coalesced_total)
        reg.register(_eps.endpoints_propagation_seconds)
        reg.register(cm.node_lifecycle.evictions_total)
        reg.register(cm.node_lifecycle.errors_total)
        reg.register(cm.node_lifecycle.not_ready_total)
        from . import podautoscaler as _hpa

        reg.register(_hpa.hpa_observed_value)
        reg.register(_hpa.hpa_desired_replicas)
        reg.register(_hpa.hpa_current_replicas)
        reg.register(_hpa.hpa_rescales_total)
        reg.register(_hpa.hpa_missing_metric_cycles_total)
        reg.register(_hpa.hpa_reaction_seconds)
        # process-entrypoint registration (see scheduler/__main__): a
        # controller-manager PROCESS exports the informer/retry families
        # its control loops bump; in-process deployments leave this to
        # the apiserver's render
        from ..client import informer as _informer
        from ..client import retry as _retry

        reg.register(_retry.retries_total)
        reg.register(_informer.informer_relists_total)
        reg.register(_informer.informer_reconnects_total)
        reg.register(_informer.informer_relist_bytes_total)
        reg.register(_informer.informer_lag_seconds)
        try:
            metrics_server = MetricsServer(reg, port=args.metrics_port).start()
            print(f"controller manager metrics on {metrics_server.url}",
                  flush=True)
        except OSError as e:
            # a busy port must not take down the control loops (the
            # scheduler entrypoint makes the same call)
            print(f"controller manager: metrics endpoint unavailable "
                  f"(port {args.metrics_port}): {e}", flush=True)
    print("controller manager running", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    from ..utils.procutil import bounded_exit

    bounded_exit(5.0)
    if metrics_server is not None:
        metrics_server.stop()
    cm.stop()


if __name__ == "__main__":
    main()
