"""Deployment controller (ref: pkg/controller/deployment/): rollout via
template-hashed ReplicaSets — RollingUpdate scales the new RS up and old
ones down within maxSurge/maxUnavailable; Recreate kills old first."""

from __future__ import annotations

import hashlib
import json
from typing import List, Optional

from ..api import types as t
from ..machinery import AlreadyExists, ApiError, NotFound
from ..machinery.scheme import from_dict, to_dict
from .base import Controller, write_status_if_changed

HASH_LABEL = "pod-template-hash"


REVISION_ANNOTATION = "deployment.ktpu.io/revision"


def revision_of(rs) -> int:
    """The RS's stamped rollout revision; 0 = not yet stamped by the
    controller (shared by the controller and `ktpu rollout`)."""
    try:
        return int((rs.metadata.annotations or {})
                   .get(REVISION_ANNOTATION, "0"))
    except ValueError:
        return 0


def template_hash(spec: t.PodTemplateSpec) -> str:
    canon = json.dumps(to_dict(spec), sort_keys=True)
    return hashlib.sha1(canon.encode()).hexdigest()[:10]


def resolve_portion(value, total: int, round_up: bool) -> int:
    if isinstance(value, str) and value.endswith("%"):
        frac = float(value[:-1]) / 100.0
        import math

        return math.ceil(frac * total) if round_up else math.floor(frac * total)
    return int(value)


class DeploymentController(Controller):
    name = "deployment-controller"

    def setup(self):
        self.deployments = self.factory.informer("deployments")
        self.rsets = self.factory.informer("replicasets")
        self.deployments.add_handler(
            on_add=self.enqueue,
            on_update=lambda _o, n: self.enqueue(n),
            on_delete=self.enqueue,
        )
        self.rsets.add_handler(
            on_add=self._rs_event,
            on_update=lambda _o, n: self._rs_event(n),
            on_delete=self._rs_event,
        )

    def _rs_event(self, rs: t.ReplicaSet):
        for ref in rs.metadata.owner_references:
            if ref.kind == "Deployment" and ref.controller:
                self.queue.add(f"{rs.metadata.namespace}/{ref.name}")

    def _owned_rsets(self, dep: t.Deployment) -> List[t.ReplicaSet]:
        return [
            rs
            for rs in self.rsets.list()
            if rs.metadata.namespace == dep.metadata.namespace
            and any(
                ref.kind == "Deployment" and ref.uid == dep.metadata.uid
                for ref in rs.metadata.owner_references
            )
        ]

    def sync(self, key: str):
        dep = self.deployments.get(key)
        if dep is None or dep.spec.paused:
            return
        want_hash = template_hash(dep.spec.template)
        owned = self._owned_rsets(dep)
        new_rs = next(
            (rs for rs in owned if rs.metadata.labels.get(HASH_LABEL) == want_hash),
            None,
        )
        old = [rs for rs in owned if rs is not new_rs]
        replicas = dep.spec.replicas if dep.spec.replicas is not None else 1

        if new_rs is None:
            new_rs = self._create_rs(dep, want_hash, initial=0 if old else replicas)
            if new_rs is None:
                return
        new_rs = self._ensure_revision(new_rs, old)

        if dep.spec.strategy.type == "Recreate":
            if any((rs.spec.replicas or 0) > 0 for rs in old):
                for rs in old:
                    self._scale(rs, 0)
                return
            self._scale(new_rs, replicas)
        else:
            self._rolling(dep, new_rs, old, replicas)
        self._cleanup_old(dep, old)
        self._update_status(dep, new_rs, owned)

    def _ensure_revision(self, new_rs: t.ReplicaSet,
                         old: List[t.ReplicaSet]) -> t.ReplicaSet:
        """Revision bookkeeping (ref: deployment_util.go maxRevision/
        SetNewReplicaSetAnnotations): the active RS always carries the
        highest revision — a rollback reuses an OLD RS, which then gets a
        fresh max+1 number rather than its historical one."""
        max_old = max([revision_of(rs) for rs in old] or [0])
        if revision_of(new_rs) > max_old:
            return new_rs
        # a failed stamp must propagate: the worker requeues with backoff,
        # so the active RS never silently stays at revision 0
        return self.cs.replicasets.patch(
            new_rs.metadata.name,
            {"metadata": {"annotations": {
                REVISION_ANNOTATION: str(max_old + 1)}}},
            new_rs.metadata.namespace)

    def _create_rs(self, dep: t.Deployment, hash_: str, initial: int) -> Optional[t.ReplicaSet]:
        rs = t.ReplicaSet()
        rs.metadata.name = f"{dep.metadata.name}-{hash_}"
        rs.metadata.namespace = dep.metadata.namespace
        rs.metadata.labels = {**dep.spec.template.metadata.labels, HASH_LABEL: hash_}
        rs.metadata.owner_references = [
            t.OwnerReference(
                api_version=dep.API_VERSION, kind="Deployment",
                name=dep.metadata.name, uid=dep.metadata.uid, controller=True,
            )
        ]
        rs.spec.replicas = initial
        sel = from_dict(t.LabelSelector, to_dict(dep.spec.selector)) if dep.spec.selector else t.LabelSelector()
        sel.match_labels = {**sel.match_labels, HASH_LABEL: hash_}
        rs.spec.selector = sel
        rs.spec.template = from_dict(t.PodTemplateSpec, to_dict(dep.spec.template))
        rs.spec.template.metadata.labels = dict(rs.metadata.labels)
        try:
            return self.cs.replicasets.create(rs)
        except AlreadyExists:
            try:
                return self.cs.replicasets.get(rs.metadata.name, rs.metadata.namespace)
            except NotFound:
                return None

    def _scale(self, rs: t.ReplicaSet, replicas: int):
        if (rs.spec.replicas or 0) == replicas:
            return
        from ..client.retry import retry_on_conflict

        def attempt():
            fresh = self.cs.replicasets.get(rs.metadata.name, rs.metadata.namespace)
            fresh.spec.replicas = replicas
            return self.cs.replicasets.update(fresh)

        try:
            retry_on_conflict(attempt)
        except ApiError:
            pass  # re-enqueued by the next RS event

    def _rolling(self, dep, new_rs, old: List[t.ReplicaSet], replicas: int):
        ru = dep.spec.strategy.rolling_update
        max_surge = resolve_portion(ru.max_surge, replicas, round_up=True)
        max_unavail = resolve_portion(ru.max_unavailable, replicas, round_up=False)
        if max_surge == 0 and max_unavail == 0:
            max_unavail = 1
        old_total = sum(rs.spec.replicas or 0 for rs in old)
        new_want = rs_replicas = new_rs.spec.replicas or 0

        # scale new up within surge budget
        total_allowed = replicas + max_surge
        headroom = total_allowed - (old_total + rs_replicas)
        if headroom > 0 and rs_replicas < replicas:
            self._scale(new_rs, min(replicas, rs_replicas + headroom))
            return  # next event continues the rollout
        # scale old down within availability budget (ready count proxies
        # availability; informer status lags one beat, acceptable here)
        new_ready = (self.rsets.get(new_rs.key()) or new_rs).status.ready_replicas
        min_available = replicas - max_unavail
        can_remove = (new_ready + old_total) - min_available
        if can_remove > 0:
            for rs in sorted(old, key=lambda r: r.metadata.creation_timestamp):
                cur = rs.spec.replicas or 0
                if cur == 0:
                    continue
                step = min(cur, can_remove)
                self._scale(rs, cur - step)
                break

    def _cleanup_old(self, dep, old: List[t.ReplicaSet]):
        zeroed = [
            rs
            for rs in old
            if (rs.spec.replicas or 0) == 0 and rs.status.replicas == 0
        ]
        keep = dep.spec.revision_history_limit
        for rs in zeroed[: max(0, len(zeroed) - keep)]:
            try:
                self.cs.replicasets.delete(rs.metadata.name, rs.metadata.namespace)
            except ApiError:
                pass

    def _update_status(self, dep, new_rs, owned):
        try:
            fresh = self.cs.deployments.get(dep.metadata.name, dep.metadata.namespace)
        except NotFound:
            return
        live = [self.rsets.get(rs.key()) or rs for rs in owned]
        new_live = self.rsets.get(new_rs.key()) or new_rs

        def apply(st):
            st.replicas = sum(rs.status.replicas for rs in live)
            st.ready_replicas = sum(rs.status.ready_replicas for rs in live)
            st.available_replicas = st.ready_replicas
            st.updated_replicas = new_live.status.replicas
            st.unavailable_replicas = max(
                0, (fresh.spec.replicas or 1) - st.ready_replicas
            )
            st.observed_generation = fresh.metadata.generation

        try:
            write_status_if_changed(self.cs.deployments, fresh, apply)
        except ApiError:
            pass
