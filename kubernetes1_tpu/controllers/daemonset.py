"""DaemonSet controller (ref: pkg/controller/daemon/): one pod per eligible
node — how the TPU device plugin and metrics exporter roll out to hosts."""

from __future__ import annotations

from ..api import types as t
from ..machinery import ApiError
from ..machinery.labels import label_selector_matches, match_labels
from ..machinery.scheme import from_dict, to_dict
from .base import Controller, write_status_if_changed


class DaemonSetController(Controller):
    name = "daemonset-controller"

    def setup(self):
        self.daemonsets = self.factory.informer("daemonsets")
        self.pods = self.factory.informer("pods")
        self.nodes = self.factory.informer("nodes")
        self.daemonsets.add_handler(
            on_add=self.enqueue,
            on_update=lambda _o, n: self.enqueue(n),
            on_delete=self.enqueue,
        )
        self.nodes.add_handler(
            on_add=lambda n: self._all(),
            on_update=lambda _o, n: self._all(),
            on_delete=lambda n: self._all(),
        )
        self.pods.add_handler(
            on_add=self._pod_event,
            on_update=lambda _o, n: self._pod_event(n),
            on_delete=self._pod_event,
        )

    def _all(self):
        for ds in self.daemonsets.list():
            self.enqueue(ds)

    def _pod_event(self, pod: t.Pod):
        for ref in pod.metadata.owner_references:
            if ref.kind == "DaemonSet" and ref.controller:
                self.queue.add(f"{pod.metadata.namespace}/{ref.name}")

    def _node_eligible(self, ds: t.DaemonSet, node: t.Node) -> bool:
        if node.spec.unschedulable:
            return False
        sel = ds.spec.template.spec.node_selector
        if sel and not match_labels(sel, node.metadata.labels):
            return False
        return True

    def sync(self, key: str):
        ds = self.daemonsets.get(key)
        if ds is None:
            return
        ns = ds.metadata.namespace
        owned = [
            p
            for p in self.pods.list()
            if p.metadata.namespace == ns
            and not p.metadata.deletion_timestamp
            and any(
                r.kind == "DaemonSet" and r.uid == ds.metadata.uid
                for r in p.metadata.owner_references
            )
        ]
        by_node = {}
        for p in owned:
            by_node.setdefault(p.spec.node_name, []).append(p)
        eligible = [
            n for n in self.nodes.list() if self._node_eligible(ds, n)
        ]
        eligible_names = {n.metadata.name for n in eligible}
        for node in eligible:
            if node.metadata.name not in by_node:
                self._create_pod(ds, node.metadata.name)
        # remove pods on nodes no longer eligible + extra duplicates
        for node_name, pods in by_node.items():
            doomed = pods[1:] if node_name in eligible_names else pods
            for p in doomed:
                try:
                    self.cs.pods.delete(p.metadata.name, ns)
                except ApiError:
                    pass
        self._update_status(ds, owned, eligible)

    def _create_pod(self, ds: t.DaemonSet, node_name: str):
        pod = t.Pod()
        pod.metadata.namespace = ds.metadata.namespace
        pod.metadata.generate_name = f"{ds.metadata.name}-"
        pod.metadata.labels = dict(ds.spec.template.metadata.labels)
        pod.metadata.owner_references = [
            t.OwnerReference(
                api_version=ds.API_VERSION, kind="DaemonSet",
                name=ds.metadata.name, uid=ds.metadata.uid, controller=True,
            )
        ]
        pod.spec = from_dict(t.PodSpec, to_dict(ds.spec.template.spec))
        # daemon pods bypass the scheduler: direct placement + tolerate all
        pod.spec.node_name = node_name
        pod.spec.tolerations.append(t.Toleration(operator="Exists"))
        try:
            self.cs.pods.create(pod)
        except ApiError:
            pass

    def _update_status(self, ds, owned, eligible):
        try:
            fresh = self.cs.daemonsets.get(ds.metadata.name, ds.metadata.namespace)
        except ApiError:
            return
        eligible_names = {n.metadata.name for n in eligible}

        def apply(st):
            st.desired_number_scheduled = len(eligible)
            st.current_number_scheduled = len(
                {p.spec.node_name for p in owned if p.spec.node_name in eligible_names}
            )
            st.number_misscheduled = len(
                [p for p in owned if p.spec.node_name not in eligible_names]
            )
            st.number_ready = len(
                [p for p in owned if p.status.phase == t.POD_RUNNING]
            )
            st.observed_generation = fresh.metadata.generation

        try:
            write_status_if_changed(self.cs.daemonsets, fresh, apply)
        except ApiError:
            pass
