"""Endpoints controller (ref: pkg/controller/endpoint/): services select
ready pods into Endpoints objects — the discovery substrate kube-proxy and
the TPU coordinator bootstrap resolve against.

Churn fan-out (the endpointslice-batching analog): by default every pod
event touching a service's selector triggers one full Endpoints rewrite —
under actor-swarm churn that is one write per service per pod event, and
the writes (each a full-object PUT bumping resourceVersion) become the
dominant control-plane load.  With ``coalesce_window > 0`` the controller
keeps a per-service DIRTY set instead: the first event arms one delayed
flush, every further event inside the window is absorbed
(``ktpu_endpoints_coalesced_total``), and the flush recomputes the object
from the informers — level-triggered, so the final object always equals
what the uncoalesced controller would have written.  ``coalesce_window=0``
(the default) keeps today's immediate enqueue byte-for-byte.

The propagation-lag SLI (``ktpu_endpoints_propagation_seconds``) measures
the OLDEST unserved pod event to the Endpoints write that folds it in —
the staleness a consumer resolving the service can actually observe; it
is measured at window 0 too, so a coalescing A/B compares like for like.
"""

from __future__ import annotations

import time
from typing import Dict

from ..api import types as t
from ..machinery import AlreadyExists, ApiError, NotFound
from ..machinery.labels import match_labels
from ..utils import locksan
from ..utils.metrics import Counter, Histogram
from .base import Controller

# Module-level (the client/retry retries_total pattern): one process-wide
# surface regardless of controller instances; the co-located apiserver
# renders them (render_client_metrics) and a standalone controller
# manager exports them from its own /metrics.
endpoints_writes_total = Counter(
    "ktpu_endpoints_writes_total",
    "Endpoints object writes (update/create) committed")
endpoints_coalesced_total = Counter(
    "ktpu_endpoints_coalesced_total",
    "pod churn events absorbed by an already-armed coalesced flush")
endpoints_propagation_seconds = Histogram(
    "ktpu_endpoints_propagation_seconds",
    "oldest unserved pod event to the Endpoints write folding it in",
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
             10.0))


class EndpointsController(Controller):
    name = "endpoints-controller"

    def __init__(self, clientset, factory, workers: int = 2,
                 coalesce_window: float = 0.0):
        super().__init__(clientset, factory, workers)
        # seconds one service's flush waits to absorb more churn; 0 =
        # immediate enqueue (today's wire, byte-identical)
        self.coalesce_window = max(0.0, float(coalesce_window))
        self._dirty_lock = locksan.make_lock(
            "EndpointsController._dirty_lock")
        # svc key -> monotonic time of the OLDEST event not yet folded
        # into a committed write (the propagation-lag numerator)
        self._dirty_since: Dict[str, float] = {}
        # svc keys with a delayed flush armed (window > 0 only): events
        # landing while armed are the coalesced ones
        self._armed: set = set()

    def setup(self):
        self.services = self.factory.informer("services")
        self.pods = self.factory.informer("pods")
        self.services.add_handler(
            on_add=self.enqueue,
            on_update=lambda _o, n: self.enqueue(n),
            on_delete=self._service_deleted,
        )
        self.pods.add_handler(
            on_add=self._pod_event,
            on_update=lambda _o, n: self._pod_event(n),
            on_delete=self._pod_event,
        )

    def _service_deleted(self, svc: t.Service):
        key = svc.key()
        with self._dirty_lock:
            self._armed.discard(key)
            self._dirty_since.pop(key, None)
        try:
            self.cs.endpoints.delete(svc.metadata.name, svc.metadata.namespace)
        except ApiError:
            pass

    def _pod_event(self, pod: t.Pod):
        for svc in self.services.list():
            if svc.metadata.namespace == pod.metadata.namespace and match_labels(
                svc.spec.selector, pod.metadata.labels
            ):
                self._mark_dirty(svc)

    def _mark_dirty(self, svc: t.Service):
        key = svc.key()
        with self._dirty_lock:
            self._dirty_since.setdefault(key, time.monotonic())
            if self.coalesce_window > 0:
                if key in self._armed:
                    # a flush is already armed for this window: this
                    # event rides it — one write absorbs N churn events
                    endpoints_coalesced_total.inc()
                    return
                self._armed.add(key)
        if self.coalesce_window > 0:
            self.enqueue_after(key, self.coalesce_window)
        else:
            self.queue.add(key)

    def sync(self, key: str):
        with self._dirty_lock:
            self._armed.discard(key)
            dirty_t0 = self._dirty_since.pop(key, None)
        svc = self.services.get(key)
        if svc is None:
            return
        if not svc.spec.selector:
            # selector-less service: endpoints are managed manually
            # (ref: endpoints_controller.go skips services w/o selector)
            return
        selected = [
            p
            for p in self.pods.list()
            if p.metadata.namespace == svc.metadata.namespace
            and match_labels(svc.spec.selector, p.metadata.labels)
            and p.status.phase == t.POD_RUNNING
        ]
        ready_pods = [
            p
            for p in selected
            if not p.metadata.deletion_timestamp
            and any(
                c.type == "Ready" and c.status == "True" for c in p.status.conditions
            )
        ]
        # the DRAIN signal, made explicit: terminating or not-Ready pods
        # leave `addresses` (no new traffic) but stay visible in
        # `not_ready_addresses` so an L7 balancer can tell "draining"
        # from "gone" and keep in-flight responses alive
        ready_names = {p.metadata.name for p in ready_pods}
        draining_pods = [p for p in selected
                         if p.metadata.name not in ready_names]
        subset = t.EndpointSubset(
            addresses=[
                t.EndpointAddress(ip=p.status.pod_ip or p.status.host_ip, node_name=p.spec.node_name,
                                  target_ref=p.metadata.name)
                for p in sorted(ready_pods, key=lambda p: p.metadata.name)
            ],
            not_ready_addresses=[
                t.EndpointAddress(ip=p.status.pod_ip or p.status.host_ip, node_name=p.spec.node_name,
                                  target_ref=p.metadata.name)
                for p in sorted(draining_pods, key=lambda p: p.metadata.name)
            ],
            ports=[
                t.EndpointPort(name=sp.name, port=sp.target_port or sp.port, protocol=sp.protocol)
                for sp in svc.spec.ports
            ],
        )
        eps = t.Endpoints(subsets=[subset] if subset.addresses
                          or subset.not_ready_addresses else [])
        eps.metadata.name = svc.metadata.name
        eps.metadata.namespace = svc.metadata.namespace
        wrote = True
        try:
            try:
                existing = self.cs.endpoints.get(svc.metadata.name, svc.metadata.namespace)
                eps.metadata.resource_version = existing.metadata.resource_version
                eps.metadata.uid = existing.metadata.uid
                eps.metadata.creation_timestamp = existing.metadata.creation_timestamp
                self.cs.endpoints.update(eps)
            except NotFound:
                try:
                    self.cs.endpoints.create(eps, svc.metadata.namespace)
                except AlreadyExists:
                    # a PEER's create landed, not ours: no write to
                    # count, and its content may predate our dirty
                    # event — re-sync to fold it in
                    wrote = False
        except Exception:
            # failed write: the informer state is still dirty — restore
            # the stamp so the retry's eventual write reports the true
            # (longer) propagation lag instead of dropping the sample
            if dirty_t0 is not None:
                with self._dirty_lock:
                    cur = self._dirty_since.get(key)
                    self._dirty_since[key] = (
                        dirty_t0 if cur is None else min(cur, dirty_t0))
            raise
        if not wrote:
            if dirty_t0 is not None:
                with self._dirty_lock:
                    cur = self._dirty_since.get(key)
                    self._dirty_since[key] = (
                        dirty_t0 if cur is None else min(cur, dirty_t0))
            self.queue.add(key)
            return
        endpoints_writes_total.inc()
        if dirty_t0 is not None:
            endpoints_propagation_seconds.observe(
                time.monotonic() - dirty_t0)
