"""Endpoints controller (ref: pkg/controller/endpoint/): services select
ready pods into Endpoints objects — the discovery substrate kube-proxy and
the TPU coordinator bootstrap resolve against."""

from __future__ import annotations

from ..api import types as t
from ..machinery import AlreadyExists, ApiError, NotFound
from ..machinery.labels import match_labels
from .base import Controller


class EndpointsController(Controller):
    name = "endpoints-controller"

    def setup(self):
        self.services = self.factory.informer("services")
        self.pods = self.factory.informer("pods")
        self.services.add_handler(
            on_add=self.enqueue,
            on_update=lambda _o, n: self.enqueue(n),
            on_delete=self._service_deleted,
        )
        self.pods.add_handler(
            on_add=self._pod_event,
            on_update=lambda _o, n: self._pod_event(n),
            on_delete=self._pod_event,
        )

    def _service_deleted(self, svc: t.Service):
        try:
            self.cs.endpoints.delete(svc.metadata.name, svc.metadata.namespace)
        except ApiError:
            pass

    def _pod_event(self, pod: t.Pod):
        for svc in self.services.list():
            if svc.metadata.namespace == pod.metadata.namespace and match_labels(
                svc.spec.selector, pod.metadata.labels
            ):
                self.enqueue(svc)

    def sync(self, key: str):
        svc = self.services.get(key)
        if svc is None:
            return
        if not svc.spec.selector:
            # selector-less service: endpoints are managed manually
            # (ref: endpoints_controller.go skips services w/o selector)
            return
        ready_pods = [
            p
            for p in self.pods.list()
            if p.metadata.namespace == svc.metadata.namespace
            and not p.metadata.deletion_timestamp
            and match_labels(svc.spec.selector, p.metadata.labels)
            and p.status.phase == t.POD_RUNNING
            and any(
                c.type == "Ready" and c.status == "True" for c in p.status.conditions
            )
        ]
        subset = t.EndpointSubset(
            addresses=[
                t.EndpointAddress(ip=p.status.pod_ip or p.status.host_ip, node_name=p.spec.node_name)
                for p in sorted(ready_pods, key=lambda p: p.metadata.name)
            ],
            ports=[
                t.EndpointPort(name=sp.name, port=sp.target_port or sp.port, protocol=sp.protocol)
                for sp in svc.spec.ports
            ],
        )
        eps = t.Endpoints(subsets=[subset] if subset.addresses else [])
        eps.metadata.name = svc.metadata.name
        eps.metadata.namespace = svc.metadata.namespace
        try:
            existing = self.cs.endpoints.get(svc.metadata.name, svc.metadata.namespace)
            eps.metadata.resource_version = existing.metadata.resource_version
            eps.metadata.uid = existing.metadata.uid
            eps.metadata.creation_timestamp = existing.metadata.creation_timestamp
            self.cs.endpoints.update(eps)
        except NotFound:
            try:
                self.cs.endpoints.create(eps, svc.metadata.namespace)
            except AlreadyExists:
                pass
