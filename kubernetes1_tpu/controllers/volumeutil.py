"""Shared claim/consumer helpers for the PV binder and the dynamic
provisioner (both gate WaitForFirstConsumer on the same question: has a
pod consuming this claim been scheduled?)."""

from __future__ import annotations

from typing import Iterator

from ..api import types as t


def pod_claim_keys(pod: t.Pod) -> Iterator[str]:
    """'<ns>/<claim-name>' for every PVC the pod consumes."""
    ns = pod.metadata.namespace or "default"
    for v in pod.spec.volumes:
        src = v.persistent_volume_claim
        if src is not None and src.claim_name:
            yield f"{ns}/{src.claim_name}"


def has_scheduled_consumer(pods_informer, pvc: t.PersistentVolumeClaim) -> bool:
    """True when some pod consuming the claim has landed on a node."""
    want = f"{pvc.metadata.namespace or 'default'}/{pvc.metadata.name}"
    for pod in pods_informer.list():
        if pod.spec.node_name and want in pod_claim_keys(pod):
            return True
    return False
