"""CSR signer/approver (ref: pkg/controller/certificates/{certificate_controller,
approver, signer}.go): the kubelet TLS bootstrap seam. A node submits a
CertificateSigningRequest; auto-approval covers node client certs
(`system:node:*` usernames, mirroring the reference's sarApprover policy);
the signer then issues the credential into status.certificate.

Issued "certificates" are HMAC-bound attestations over (username, request)
rather than x509 — the trust chain (approve → sign → verify at authn) is the
same shape without an ASN.1 stack."""

from __future__ import annotations

import base64
import hashlib
import hmac
import json

from ..machinery import ApiError, NotFound, now_iso
from .base import Controller


def issue_certificate(ca_key: str, username: str, request: str, groups=None) -> str:
    """Self-describing credential: KTPU-CERT.b64(payload).b64(hmac).
    Carrying the subject in the payload lets the apiserver's cert
    authenticator recover identity from the bearer credential alone (the
    x509 CN/O convention, minus the ASN.1)."""
    payload = json.dumps(
        {"user": username, "groups": sorted(groups or []), "req": request},
        sort_keys=True, separators=(",", ":"),
    ).encode()
    mac = hmac.new(ca_key.encode(), payload, hashlib.sha256).digest()
    b64 = lambda b: base64.urlsafe_b64encode(b).rstrip(b"=").decode()  # noqa: E731
    return f"KTPU-CERT.{b64(payload)}.{b64(mac)}"


def parse_certificate(ca_key: str, cert: str):
    """Verify signature and return the payload dict, or None."""
    if not cert.startswith("KTPU-CERT."):
        return None
    try:
        _, p64, m64 = cert.split(".", 2)
        pad = lambda s: s + "=" * (-len(s) % 4)  # noqa: E731
        payload = base64.urlsafe_b64decode(pad(p64))
        mac = base64.urlsafe_b64decode(pad(m64))
    except (ValueError, TypeError):
        return None
    want = hmac.new(ca_key.encode(), payload, hashlib.sha256).digest()
    if not hmac.compare_digest(mac, want):
        return None
    try:
        return json.loads(payload)
    except json.JSONDecodeError:
        return None


def verify_certificate(ca_key: str, username: str, request: str, cert: str) -> bool:
    info = parse_certificate(ca_key, cert)
    return bool(info and info.get("user") == username and info.get("req") == request)


class CertificateController(Controller):
    name = "certificate-controller"

    def __init__(self, clientset, factory, ca_key: str = "ktpu-ca-key",
                 ca_cert_pem: str = "", workers: int = 1):
        super().__init__(clientset, factory, workers)
        self.ca_key = ca_key
        # x509 mode: ca_key is a PEM private key and ca_cert_pem its cert —
        # PEM CSRs get real certificates (ref certificates/signer); the HMAC
        # attestation path stays for CA-less in-process clusters
        self.ca_cert_pem = ca_cert_pem
        self.x509 = bool(ca_cert_pem) and "-----BEGIN" in (ca_key or "")

    def _sign(self, csr) -> str:
        from ..utils import pki

        if self.x509 and pki.is_pem_csr(csr.spec.request):
            # the approver already vetted spec.username/groups; the SIGNER
            # must also pin the CSR's x509 subject to that vetted identity,
            # or a node could smuggle an admin CN past the approver
            cn, orgs = pki.csr_identity(csr.spec.request)
            if cn != csr.spec.username or not set(orgs) <= set(csr.spec.groups):
                raise ValueError(
                    f"CSR subject CN={cn!r} O={orgs!r} does not match "
                    f"spec.username={csr.spec.username!r}/groups")
            # honor the requested usages (nodes ask for both: the kubelet
            # dials the apiserver AND serves :10250 from one CSR round-trip)
            usages = csr.spec.usages or ["client auth"]
            return pki.sign_csr(self.ca_cert_pem, self.ca_key,
                                csr.spec.request,
                                client="client auth" in usages,
                                server="server auth" in usages)
        return issue_certificate(self.ca_key, csr.spec.username,
                                 csr.spec.request, groups=csr.spec.groups)

    def setup(self):
        self.csrs = self.factory.informer("certificatesigningrequests")
        self.csrs.add_handler(
            on_add=self.enqueue, on_update=lambda _o, n: self.enqueue(n)
        )

    @staticmethod
    def _condition(csr, ctype: str) -> bool:
        return any(c.type == ctype for c in csr.status.conditions)

    @staticmethod
    def _creator_may_request(csr) -> bool:
        """The authenticated creator (IdentityStamp annotation) may request
        spec.username if it IS that identity (renewal), holds a bootstrap
        identity, or is a cluster admin (ref: the sarApprover's
        selfnodeclient/nodeclient posture)."""
        from ..apiserver.admission import (
            CREATED_BY_ANNOTATION,
            CREATED_BY_GROUPS_ANNOTATION,
        )

        creator = csr.metadata.annotations.get(CREATED_BY_ANNOTATION, "")
        groups = set(
            csr.metadata.annotations.get(CREATED_BY_GROUPS_ANNOTATION, "").split(",")
        )
        if not creator:
            # no identity recorded (AlwaysAllow mode) — keep legacy behavior
            return True
        return (
            creator == csr.spec.username
            or creator.startswith("system:bootstrap:")
            or "system:bootstrappers" in groups
            or "system:masters" in groups
        )

    # CSR garbage collection (ref pkg/controller/certificates/cleaner):
    # bootstrap mints a fresh random-named CSR per (re-)join, so without a
    # TTL the store grows one object per join forever
    SIGNED_TTL_S = 3600.0       # issued certs: the node already has it
    PENDING_TTL_S = 24 * 3600.0  # never-approved/denied leftovers

    def _gc(self, csr) -> bool:
        """Delete expired CSRs; returns True when the object is gone.
        Re-enqueues itself for the remaining TTL otherwise."""
        import time as _time

        from ..machinery.meta import parse_iso

        try:
            age = _time.time() - parse_iso(csr.metadata.creation_timestamp)  # ktpulint: ignore[KTPU005] vs API timestamp
        except (ValueError, TypeError):
            return False
        ttl = (self.SIGNED_TTL_S
               if csr.status.certificate or self._condition(csr, "Denied")
               else self.PENDING_TTL_S)
        if age >= ttl:
            try:
                self.cs.certificatesigningrequests.delete(csr.metadata.name, "")
            except ApiError:
                pass
            return True
        self.enqueue_after(csr.metadata.name, ttl - age + 1.0)
        return False

    def sync(self, key: str):
        cached = self.csrs.get(key)
        if cached is None:
            return
        if self._condition(cached, "Denied"):
            self._gc(cached)
            return
        from ..api import types as t

        # Work on a fresh server copy — mutating the informer-cached object
        # would make later syncs see state the server never accepted.
        try:
            csr = self.cs.certificatesigningrequests.get(cached.metadata.name, "")
        except NotFound:
            return
        if self._gc(csr):
            return
        changed = False
        if not self._condition(csr, "Approved"):
            # Auto-approve node client certs only; anything else waits for a
            # human `ktpu certificate approve`. Two spoofing vectors guarded:
            # groups are part of the signed identity (smuggling system:masters
            # would be one-step privilege escalation), and spec.username is
            # client-controlled — the authenticated creator recorded by the
            # IdentityStamp admission plugin must be the node itself renewing,
            # or a bootstrapper, to stop any CSR-creator minting other nodes'
            # identities.
            if (
                csr.spec.username.startswith("system:node:")
                and set(csr.spec.groups) <= {"system:nodes"}
                and self._creator_may_request(csr)
            ):
                csr.status.conditions.append(
                    t.CSRCondition(
                        type="Approved", reason="AutoApproved",
                        message="node client certificate",
                        last_update_time=now_iso(),
                    )
                )
                changed = True
            else:
                return
        if self._condition(csr, "Approved") and not csr.status.certificate:
            try:
                csr.status.certificate = self._sign(csr)
            except ValueError as e:
                csr.status.conditions.append(t.CSRCondition(
                    type="Denied", reason="SubjectMismatch", message=str(e),
                    last_update_time=now_iso()))
            changed = True
        if not changed:
            return
        try:
            self.cs.certificatesigningrequests.update_status(csr)
        except ApiError:
            self.enqueue_after(key, 0.5)  # conflicting write landed; retry
