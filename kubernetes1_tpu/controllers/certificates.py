"""CSR signer/approver (ref: pkg/controller/certificates/{certificate_controller,
approver, signer}.go): the kubelet TLS bootstrap seam. A node submits a
CertificateSigningRequest; auto-approval covers node client certs
(`system:node:*` usernames, mirroring the reference's sarApprover policy);
the signer then issues the credential into status.certificate.

Issued "certificates" are HMAC-bound attestations over (username, request)
rather than x509 — the trust chain (approve → sign → verify at authn) is the
same shape without an ASN.1 stack."""

from __future__ import annotations

import base64
import hashlib
import hmac

from ..machinery import ApiError, NotFound, now_iso
from .base import Controller


def issue_certificate(ca_key: str, username: str, request: str) -> str:
    mac = hmac.new(
        ca_key.encode(), f"{username}\n{request}".encode(), hashlib.sha256
    ).digest()
    return "KTPU-CERT." + base64.urlsafe_b64encode(mac).rstrip(b"=").decode()


def verify_certificate(ca_key: str, username: str, request: str, cert: str) -> bool:
    return hmac.compare_digest(issue_certificate(ca_key, username, request), cert)


class CertificateController(Controller):
    name = "certificate-controller"

    def __init__(self, clientset, factory, ca_key: str = "ktpu-ca-key", workers: int = 1):
        super().__init__(clientset, factory, workers)
        self.ca_key = ca_key

    def setup(self):
        self.csrs = self.factory.informer("certificatesigningrequests")
        self.csrs.add_handler(
            on_add=self.enqueue, on_update=lambda _o, n: self.enqueue(n)
        )

    @staticmethod
    def _condition(csr, ctype: str) -> bool:
        return any(c.type == ctype for c in csr.status.conditions)

    def sync(self, key: str):
        cached = self.csrs.get(key)
        if cached is None or self._condition(cached, "Denied"):
            return
        from ..api import types as t

        # Work on a fresh server copy — mutating the informer-cached object
        # would make later syncs see state the server never accepted.
        try:
            csr = self.cs.certificatesigningrequests.get(cached.metadata.name, "")
        except NotFound:
            return
        changed = False
        if not self._condition(csr, "Approved"):
            # Auto-approve node client certs only; anything else waits for a
            # human `ktpu certificate approve`.
            if csr.spec.username.startswith("system:node:"):
                csr.status.conditions.append(
                    t.CSRCondition(
                        type="Approved", reason="AutoApproved",
                        message="node client certificate",
                        last_update_time=now_iso(),
                    )
                )
                changed = True
            else:
                return
        if self._condition(csr, "Approved") and not csr.status.certificate:
            csr.status.certificate = issue_certificate(
                self.ca_key, csr.spec.username, csr.spec.request
            )
            changed = True
        if not changed:
            return
        try:
            self.cs.certificatesigningrequests.update_status(csr)
        except ApiError:
            self.enqueue_after(key, 0.5)  # conflicting write landed; retry
