"""ReplicaSet controller (ref: pkg/controller/replicaset/replica_set.go):
level-triggered replica reconciliation with owner-reference adoption."""

from __future__ import annotations

from typing import List

from ..api import types as t
from ..machinery import ApiError, NotFound
from ..machinery.labels import label_selector_matches
from ..machinery.scheme import from_dict, to_dict
from .base import Controller, delete_pods_batch, write_status_if_changed


def owned_by(pod: t.Pod, kind: str, uid: str) -> bool:
    return any(
        ref.kind == kind and ref.uid == uid and ref.controller
        for ref in pod.metadata.owner_references
    )


class ReplicaSetController(Controller):
    name = "replicaset-controller"

    def setup(self):
        self.rsets = self.factory.informer("replicasets")
        self.pods = self.factory.informer("pods")
        self.rsets.add_handler(
            on_add=self.enqueue,
            on_update=lambda _o, n: self.enqueue(n),
            on_delete=self.enqueue,
        )
        self.pods.add_handler(
            on_add=self._pod_event,
            on_update=lambda _o, n: self._pod_event(n),
            on_delete=self._pod_event,
        )

    def _pod_event(self, pod: t.Pod):
        for ref in pod.metadata.owner_references:
            if ref.kind == "ReplicaSet" and ref.controller:
                self.queue.add(f"{pod.metadata.namespace}/{ref.name}")

    def _select_pods(self, rs: t.ReplicaSet) -> List[t.Pod]:
        return [
            p
            for p in self.pods.list()
            if p.metadata.namespace == rs.metadata.namespace
            and not p.metadata.deletion_timestamp
            and label_selector_matches(rs.spec.selector, p.metadata.labels)
            and (
                owned_by(p, "ReplicaSet", rs.metadata.uid)
                or not p.metadata.owner_references  # adoptable orphan
            )
        ]

    def sync(self, key: str):
        rs = self.rsets.get(key)
        if rs is None:
            return
        pods = self._select_pods(rs)
        alive = [p for p in pods if p.status.phase not in (t.POD_FAILED, t.POD_SUCCEEDED)]
        want = rs.spec.replicas if rs.spec.replicas is not None else 1
        diff = want - len(alive)
        if diff > 0:
            for _ in range(min(diff, 50)):  # burst cap like the reference
                pod = t.Pod()
                pod.metadata.namespace = rs.metadata.namespace
                pod.metadata.generate_name = f"{rs.metadata.name}-"
                pod.metadata.labels = dict(rs.spec.template.metadata.labels)
                pod.metadata.annotations = dict(rs.spec.template.metadata.annotations)
                pod.metadata.owner_references = [
                    t.OwnerReference(
                        api_version=rs.API_VERSION, kind="ReplicaSet",
                        name=rs.metadata.name, uid=rs.metadata.uid, controller=True,
                    )
                ]
                pod.spec = from_dict(t.PodSpec, to_dict(rs.spec.template.spec))
                try:
                    self.cs.pods.create(pod)
                except ApiError:
                    break
        elif diff < 0:
            # prefer deleting unscheduled, then newest; the whole
            # scale-down ships as ONE pods/delete:batch group commit
            # (outcomes ignored — level-triggered, the next sync retries)
            doomed = sorted(
                alive,
                key=lambda p: (bool(p.spec.node_name), p.metadata.creation_timestamp),
            )[: -diff]
            delete_pods_batch(self.cs, doomed, reason="replicaset_scale_down")
        self._update_status(rs, alive)

    def _update_status(self, rs: t.ReplicaSet, alive: List[t.Pod]):
        try:
            fresh = self.cs.replicasets.get(rs.metadata.name, rs.metadata.namespace)
        except NotFound:
            return
        ready = [
            p
            for p in alive
            if any(c.type == "Ready" and c.status == "True" for c in p.status.conditions)
        ]
        def apply(st):
            st.replicas = len(alive)
            st.ready_replicas = len(ready)
            st.available_replicas = len(ready)
            st.fully_labeled_replicas = len(alive)
            st.observed_generation = fresh.metadata.generation

        try:
            write_status_if_changed(self.cs.replicasets, fresh, apply)
        except ApiError:
            pass
