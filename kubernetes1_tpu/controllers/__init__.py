from .manager import ControllerManager
from .job import JobController
from .replicaset import ReplicaSetController
from .deployment import DeploymentController
from .daemonset import DaemonSetController
from .nodelifecycle import NodeLifecycleController
from .namespace import NamespaceController, GarbageCollector
from .endpoints import EndpointsController
from .statefulset import StatefulSetController
from .cronjob import CronJobController
from .resourcequota import ResourceQuotaController
from .serviceaccount import ServiceAccountController
from .podautoscaler import HorizontalPodAutoscalerController
from .disruption import DisruptionController
from .podgc import PodGCController
from .ttl import TTLAfterFinishedController
from .certificates import CertificateController
from .volumebinder import PersistentVolumeBinder
