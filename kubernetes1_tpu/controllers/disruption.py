"""Disruption controller (ref: pkg/controller/disruption/disruption.go):
maintains PodDisruptionBudget status so voluntary evictions (`ktpu drain`,
the eviction path) know how many pods they may remove. For a TPU cluster a
PDB over a multi-host slice gang keeps maintenance from silently breaking a
training job's world membership."""

from __future__ import annotations

from ..api import types as t
from ..machinery import ApiError, NotFound
from ..machinery.labels import label_selector_matches
from .base import Controller


def _is_healthy(pod: t.Pod) -> bool:
    return (
        not pod.metadata.deletion_timestamp
        and pod.status.phase == t.POD_RUNNING
        and any(c.type == "Ready" and c.status == "True" for c in pod.status.conditions)
    )


class DisruptionController(Controller):
    name = "disruption-controller"

    def setup(self):
        self.pdbs = self.factory.informer("poddisruptionbudgets")
        self.pods = self.factory.informer("pods")
        self.pdbs.add_handler(
            on_add=self.enqueue, on_update=lambda _o, n: self.enqueue(n)
        )
        self.pods.add_handler(
            on_add=self._pod_event,
            on_update=lambda _o, n: self._pod_event(n),
            on_delete=self._pod_event,
        )

    def _pod_event(self, pod: t.Pod):
        for pdb in self.pdbs.list():
            if pdb.metadata.namespace == pod.metadata.namespace and (
                pdb.spec.selector is not None
                and label_selector_matches(pdb.spec.selector, pod.metadata.labels)
            ):
                self.enqueue(pdb)

    def sync(self, key: str):
        pdb = self.pdbs.get(key)
        if pdb is None or pdb.spec.selector is None:
            return
        matching = [
            p for p in self.pods.list()
            if p.metadata.namespace == pdb.metadata.namespace
            and label_selector_matches(pdb.spec.selector, p.metadata.labels)
        ]
        expected = len([p for p in matching if not p.metadata.deletion_timestamp])
        healthy = len([p for p in matching if _is_healthy(p)])
        if pdb.spec.min_available is not None:
            desired_healthy = pdb.spec.min_available
        elif pdb.spec.max_unavailable is not None:
            desired_healthy = max(0, expected - pdb.spec.max_unavailable)
        else:
            desired_healthy = expected
        allowed = max(0, healthy - desired_healthy)
        st = pdb.status
        if (
            st.current_healthy == healthy
            and st.desired_healthy == desired_healthy
            and st.expected_pods == expected
            and st.disruptions_allowed == allowed
        ):
            return
        try:
            fresh = self.cs.poddisruptionbudgets.get(
                pdb.metadata.name, pdb.metadata.namespace
            )
            fresh.status.current_healthy = healthy
            fresh.status.desired_healthy = desired_healthy
            fresh.status.expected_pods = expected
            fresh.status.disruptions_allowed = allowed
            fresh.status.observed_generation = fresh.metadata.generation
            self.cs.poddisruptionbudgets.update_status(fresh)
        except (NotFound, ApiError):
            pass  # requeued on the next pod event
