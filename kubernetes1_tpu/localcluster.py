"""LocalCluster: a full one-machine cluster in one process.

Ref: hack/local-up-cluster.sh (boots apiserver+kcm+scheduler+kubelet from
source) and pkg/kubemark (hollow nodes).  Used by `ktpu cluster-up`, the
e2e tests, and bench.py: an HTTP apiserver over the MVCC store, the
device-aware scheduler, the controller manager, and N kubelets — hollow
(FakeRuntime) for scale, or one real ProcessRuntime node that actually
execs container commands as host processes with the TPU env injected.
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass, field
from typing import List, Optional

from .apiserver import Master
from .client import Clientset
from .controllers import ControllerManager
from .deviceplugin.api import PluginServer, plugin_socket_path
from .deviceplugin.tpu_plugin import TPUDevicePlugin, _fake_devices, discover_tpu_devices
from .kubelet import FakeRuntime, Kubelet, ProcessRuntime
from .proxy import Proxier
from .scheduler import Scheduler
from .utils.slo import StartupSLITracker


@dataclass
class NodeHandle:
    kubelet: Kubelet
    plugin: Optional[PluginServer]
    clientset: Clientset


class LocalCluster:
    """start() brings everything up; stop() tears it down in order."""

    def __init__(
        self,
        nodes: int = 1,
        tpus_per_node: int = 4,
        tpu_type: str = "v5e",
        hollow: bool = True,
        real_tpu: bool = False,
        port: int = 0,
        root_dir: str = "",
        heartbeat_interval: float = 2.0,
        sync_interval: float = 0.25,
    ):
        self.n_nodes = nodes
        self.tpus_per_node = tpus_per_node
        self.tpu_type = tpu_type
        self.hollow = hollow
        self.real_tpu = real_tpu
        self.port = port
        self.root_dir = root_dir or tempfile.mkdtemp(prefix="ktpu-cluster-")
        self.heartbeat_interval = heartbeat_interval
        self.sync_interval = sync_interval

        self.master: Optional[Master] = None
        self.cs: Optional[Clientset] = None
        self.scheduler: Optional[Scheduler] = None
        self.kcm: Optional[ControllerManager] = None
        self.proxier: Optional[Proxier] = None
        self.sli: Optional[StartupSLITracker] = None
        self.nodes: List[NodeHandle] = []

    @property
    def url(self) -> str:
        return self.master.url

    def start(self) -> "LocalCluster":
        self.master = Master(port=self.port).start()
        self.cs = Clientset(self.master.url)
        # ephemeral /metrics + /debug/traces endpoint: the observability
        # surface is part of the cluster, not an opt-in extra
        self.scheduler = Scheduler(Clientset(self.master.url), metrics_port=0)
        self.scheduler.start()
        self.kcm = ControllerManager(Clientset(self.master.url))
        self.kcm.start()
        self._proxier_cs = Clientset(self.master.url)
        self.proxier = Proxier(self._proxier_cs).start()
        # pod-startup SLIs (utils/slo): per-phase histograms on /metrics
        self._sli_cs = Clientset(self.master.url)
        self.sli = StartupSLITracker(self._sli_cs, metrics_port=0).start()
        for i in range(self.n_nodes):
            self._add_node(i)
        return self

    def _add_node(self, i: int):
        name = f"node-{i}"
        plugin_dir = os.path.join(self.root_dir, name, "device-plugins")
        plugin = None
        if self.real_tpu and i == 0:
            devices = discover_tpu_devices()
        else:
            devices = _fake_devices(f"{self.tpu_type}:{self.tpus_per_node}:s{i}:0")
        if devices:
            impl = TPUDevicePlugin(devices=devices)
            plugin = PluginServer(
                impl, plugin_socket_path(plugin_dir, "google.com/tpu"))
            plugin.start()
        if self.hollow and not (self.real_tpu and i == 0):
            runtime = FakeRuntime()
        else:
            runtime = ProcessRuntime(root_dir=os.path.join(self.root_dir, name, "run"))
        kcs = Clientset(self.master.url)
        kubelet = Kubelet(
            kcs,
            node_name=name,
            runtime=runtime,
            plugin_dir=plugin_dir,
            heartbeat_interval=self.heartbeat_interval,
            sync_interval=self.sync_interval,
            pleg_interval=self.sync_interval,
        )
        kubelet.start()
        self.nodes.append(NodeHandle(kubelet=kubelet, plugin=plugin, clientset=kcs))

    def wait_ready(self, timeout: float = 60.0):
        from .utils.waitutil import must_poll_until

        def all_ready():
            nodes, _ = self.cs.nodes.list()
            ready = [
                n for n in nodes
                if any(c.type == "Ready" and c.status == "True"
                       for c in n.status.conditions)
            ]
            return len(ready) >= self.n_nodes

        must_poll_until(all_ready, timeout=timeout, desc="all nodes Ready")
        return self

    def stop(self):
        for h in self.nodes:
            h.kubelet.stop()
            if h.plugin:
                h.plugin.stop()
            h.clientset.close()
        if self.sli:
            self.sli.stop()
            self._sli_cs.close()
        if self.proxier:
            self.proxier.stop()
            self._proxier_cs.close()
        if self.kcm:
            self.kcm.stop()
        if self.scheduler:
            self.scheduler.stop()
        if self.cs:
            self.cs.close()
        if self.master:
            self.master.stop()
