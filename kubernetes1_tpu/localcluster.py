"""LocalCluster: a full one-machine cluster in one process.

Ref: hack/local-up-cluster.sh (boots apiserver+kcm+scheduler+kubelet from
source) and pkg/kubemark (hollow nodes).  Used by `ktpu cluster-up`, the
e2e tests, and bench.py: an HTTP apiserver over the MVCC store, the
device-aware scheduler, the controller manager, and N kubelets — hollow
(FakeRuntime) for scale, or one real ProcessRuntime node that actually
execs container commands as host processes with the TPU env injected.

Horizontal shape (PRs 9/10): ``store_shards=N`` partitions /registry/
across N in-process shard stores (stride revisions, composite rvs);
``apiservers=M`` runs M Masters over ONE shared store object (each with
its own cacher/registry — the stateless-apiserver shape without socket
plumbing); ``sched_shards=K`` runs K scheduler instances with static
shard ownership.  Exactly one Master renders the shared store's metrics
and the process-global client metrics, so a fleet merge over the
cluster's endpoints never double-counts.

Observability (this PR): every component endpoint is registered with an
``ObsCollector`` (``cluster.obs``) that scrapes them on an interval and
serves the fleet-level /metrics, /debug/traces, /debug/topology and
/debug/flightrecorder — the first layer that sees the sharded control
plane as one system.  ``obs=False`` opts out (micro-benchmarks that
cannot afford the scrape threads).
"""

from __future__ import annotations

import os
import tempfile
from dataclasses import dataclass
from typing import List, Optional

from .apiserver import Master
from .client import Clientset
from .controllers import ControllerManager
from .deviceplugin.api import PluginServer, plugin_socket_path
from .deviceplugin.tpu_plugin import TPUDevicePlugin, _fake_devices, discover_tpu_devices
from .kubelet import FakeRuntime, Kubelet, ProcessRuntime
from .obs import ObsCollector
from .proxy import Proxier
from .scheduler import Scheduler
from .utils.slo import StartupSLITracker


@dataclass
class NodeHandle:
    kubelet: Kubelet
    plugin: Optional[PluginServer]
    clientset: Clientset


def rotated(urls: List[str], k: int) -> str:
    """Comma server-list starting at k%len: every client keeps the full
    failover set, load spreads across apiserver peers (sched_perf's
    idiom, shared here for the in-process multi-apiserver shape)."""
    i = k % len(urls)
    return ",".join(urls[i:] + urls[:i])


class LocalCluster:
    """start() brings everything up; stop() tears it down in order."""

    def __init__(
        self,
        nodes: int = 1,
        tpus_per_node: int = 4,
        tpu_type: str = "v5e",
        hollow: bool = True,
        real_tpu: bool = False,
        port: int = 0,
        root_dir: str = "",
        heartbeat_interval: float = 2.0,
        sync_interval: float = 0.25,
        store_shards: int = 1,
        apiservers: int = 1,
        sched_shards: int = 1,
        obs: bool = True,
        obs_interval: float = 1.0,
        endpoints_coalesce_window: float = 0.0,
        monitor_grace: float = 40.0,
        eviction_timeout: float = 300.0,
    ):
        self.n_nodes = nodes
        self.tpus_per_node = tpus_per_node
        self.tpu_type = tpu_type
        self.hollow = hollow
        self.real_tpu = real_tpu
        self.port = port
        self.root_dir = root_dir or tempfile.mkdtemp(prefix="ktpu-cluster-")
        self.heartbeat_interval = heartbeat_interval
        self.sync_interval = sync_interval
        self.store_shards = max(1, store_shards)
        self.apiservers = max(1, apiservers)
        self.sched_shards = max(1, sched_shards)
        self.obs_enabled = obs
        self.obs_interval = obs_interval
        self.endpoints_coalesce_window = endpoints_coalesce_window
        # node-lifecycle clocks: chaos/mixer runs shrink these so a
        # killed node's eviction + gang re-place fits a seconds-scale run
        self.monitor_grace = monitor_grace
        self.eviction_timeout = eviction_timeout

        self.master: Optional[Master] = None
        self.masters: List[Master] = []
        self._shared_store = None
        self.cs: Optional[Clientset] = None
        self.scheduler: Optional[Scheduler] = None
        self.schedulers: List[Scheduler] = []
        self.kcm: Optional[ControllerManager] = None
        self.proxier: Optional[Proxier] = None
        self.sli: Optional[StartupSLITracker] = None
        self.obs: Optional[ObsCollector] = None
        self.nodes: List[NodeHandle] = []

    @property
    def url(self) -> str:
        return self.master.url

    @property
    def urls(self) -> List[str]:
        return [m.url for m in self.masters]

    def start(self) -> "LocalCluster":
        if self.apiservers > 1:
            # M stateless Masters over ONE shared in-process store: each
            # layers its own cacher/registry; only master 0 renders the
            # store block and the process-global client metrics (see
            # Master render gates) so fleet merges stay truthful
            from .machinery.scheme import global_scheme
            from .storage import Store
            from .storage.shardmap import build_sharded_store

            scheme = global_scheme.copy()
            if self.store_shards > 1:
                self._shared_store = build_sharded_store(
                    scheme.copy, self.store_shards)
            else:
                self._shared_store = Store(scheme.copy())
            for i in range(self.apiservers):
                self.masters.append(Master(
                    port=self.port if i == 0 else 0,
                    store=self._shared_store,
                    render_client_metrics=(i == 0),
                    render_store_metrics=(i == 0),
                ).start())
            self.master = self.masters[0]
        else:
            self.master = Master(port=self.port,
                                 store_shards=self.store_shards).start()
            self.masters = [self.master]
        urls = self.urls
        self.cs = Clientset(rotated(urls, 0))
        # ephemeral /metrics + /debug/traces endpoint per scheduler: the
        # observability surface is part of the cluster, not an opt-in
        # extra.  sched_shards>1 = static in-process shard ownership
        # (sched_perf's shape): instance k owns shard k.
        for k in range(self.sched_shards):
            kwargs = {}
            if self.sched_shards > 1:
                kwargs = {"shards": self.sched_shards, "owned_shards": {k}}
            self.schedulers.append(Scheduler(
                Clientset(rotated(urls, k)), metrics_port=0,
                identity=f"sched-{k}", **kwargs))
            self.schedulers[-1].start()
        self.scheduler = self.schedulers[0]
        self.kcm = ControllerManager(
            Clientset(rotated(urls, 1)),
            endpoints_coalesce_window=self.endpoints_coalesce_window,
            monitor_grace=self.monitor_grace,
            eviction_timeout=self.eviction_timeout)
        self.kcm.start()
        self._proxier_cs = Clientset(rotated(urls, 2))
        self.proxier = Proxier(self._proxier_cs).start()
        # pod-startup SLIs (utils/slo): per-phase histograms on /metrics
        self._sli_cs = Clientset(rotated(urls, 3))
        self.sli = StartupSLITracker(self._sli_cs, metrics_port=0).start()
        for i in range(self.n_nodes):
            self._add_node(i)
        if self.obs_enabled:
            self._start_obs()
        return self

    def _start_obs(self):
        # Registration audit (breach timelines are built from REGISTERED
        # endpoints): every component with an HTTP surface is listed
        # here — apiservers, schedulers, the SLI tracker, kubelets.  The
        # kcm and proxier expose no endpoint of their own; their flight-
        # recorder events live in the process-global rings every listed
        # endpoint serves, so their timelines still reach breach dumps.
        # Anything booted BESIDE the cluster (workload servers, the
        # scorecard) must register itself on cluster.obs the same way.
        self.obs = ObsCollector(interval=self.obs_interval)
        for i, m in enumerate(self.masters):
            self.obs.register("apiserver", m.url, instance=f"apiserver-{i}")
        for k, s in enumerate(self.schedulers):
            if s.metrics_server is not None:
                self.obs.register("scheduler", s.metrics_server.url,
                                  instance=f"sched-{k}",
                                  shard=k if self.sched_shards > 1 else None)
        if self.sli is not None and self.sli.metrics_server is not None:
            self.obs.register("sli", self.sli.metrics_server.url,
                              instance="sli-0")
        for h in self.nodes:
            srv = getattr(h.kubelet, "server", None)
            if srv is not None:
                self.obs.register("kubelet", srv.url,
                                  instance=h.kubelet.node_name)
        self.obs.start()

    def _add_node(self, i: int):
        name = f"node-{i}"
        plugin_dir = os.path.join(self.root_dir, name, "device-plugins")
        plugin = None
        if self.real_tpu and i == 0:
            devices = discover_tpu_devices()
        else:
            devices = _fake_devices(f"{self.tpu_type}:{self.tpus_per_node}:s{i}:0")
        if devices:
            impl = TPUDevicePlugin(devices=devices)
            plugin = PluginServer(
                impl, plugin_socket_path(plugin_dir, "google.com/tpu"))
            plugin.start()
        if self.hollow and not (self.real_tpu and i == 0):
            runtime = FakeRuntime()
        else:
            runtime = ProcessRuntime(root_dir=os.path.join(self.root_dir, name, "run"))
        kcs = Clientset(rotated(self.urls, i))
        kubelet = Kubelet(
            kcs,
            node_name=name,
            runtime=runtime,
            plugin_dir=plugin_dir,
            heartbeat_interval=self.heartbeat_interval,
            sync_interval=self.sync_interval,
            pleg_interval=self.sync_interval,
        )
        kubelet.start()
        self.nodes.append(NodeHandle(kubelet=kubelet, plugin=plugin, clientset=kcs))

    def wait_ready(self, timeout: float = 60.0):
        from .utils.waitutil import must_poll_until

        def all_ready():
            nodes, _ = self.cs.nodes.list()
            ready = [
                n for n in nodes
                if any(c.type == "Ready" and c.status == "True"
                       for c in n.status.conditions)
            ]
            return len(ready) >= self.n_nodes

        must_poll_until(all_ready, timeout=timeout, desc="all nodes Ready")
        return self

    def stop(self):
        if self.obs:
            self.obs.stop()
        for h in self.nodes:
            h.kubelet.stop()
            if h.plugin:
                h.plugin.stop()
            h.clientset.close()
        if self.sli:
            self.sli.stop()
            self._sli_cs.close()
        if self.proxier:
            self.proxier.stop()
            self._proxier_cs.close()
        if self.kcm:
            self.kcm.stop()
        for s in self.schedulers:
            s.stop()
        if self.cs:
            self.cs.close()
        for m in self.masters:
            m.stop()
        if self._shared_store is not None:
            # shared across Masters (none of them owns it): close once,
            # after every apiserver over it has stopped
            self._shared_store.close()
