from .server import ClusterDNS, encode_query, parse_response  # noqa: F401
