"""Cluster DNS: `<svc>.<ns>.svc.cluster.local` A records from informers.

Ref: cluster/addons/dns/kube-dns.yaml.base + the kubelet's --cluster-dns
wiring (pods' resolv.conf points at the cluster resolver).  The reference
ships kube-dns/CoreDNS as a cluster addon; here the resolver is NODE-LOCAL
(the NodeLocal DNSCache shape): each kubelet hosts one, fed by the same
service/endpoints informers the proxy uses, and wires pods to it via a
bind-mounted resolv.conf + a KTPU_DNS_SERVER env var.  This closes the
env-injection gap VERDICT r3 named: `*_SERVICE_HOST` env is
snapshot-at-start, DNS answers live — a service created AFTER a pod
started resolves on the next query (a JAX gang resolving its coordinator
by stable name needs exactly this).

The wire protocol is hand-rolled RFC 1035 (headers, QNAME labels, A
answers with compression pointers) — a DNS library would be a dependency
for ~120 lines.  Non-cluster names forward to the host's upstream
resolver so pods keep resolving the outside world.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import List, Optional, Tuple

from ..utils import faultline
from ..utils.logutil import RateLimitedReporter

DEFAULT_DNS_IP = "127.0.51.1"   # loopback alias, systemd-resolved style
CLUSTER_DOMAIN = "cluster.local"

_FLAG_RESPONSE = 0x8180         # QR | RD | RA
_RCODE_NXDOMAIN = 3
_RCODE_SERVFAIL = 2


# ------------------------------------------------------------- wire format

def _encode_name(name: str) -> bytes:
    out = b""
    for label in name.rstrip(".").split("."):
        raw = label.encode()
        out += bytes([len(raw)]) + raw
    return out + b"\x00"


def _decode_name(data: bytes, off: int) -> Tuple[str, int]:
    """Returns (name, next_offset); follows compression pointers."""
    labels = []
    jumped = False
    end = off
    hops = 0
    while True:
        if off >= len(data):
            raise ValueError("truncated name")
        length = data[off]
        if length & 0xC0 == 0xC0:  # pointer
            if off + 1 >= len(data):
                raise ValueError("truncated pointer")
            ptr = struct.unpack("!H", data[off:off + 2])[0] & 0x3FFF
            if not jumped:
                end = off + 2
            off = ptr
            jumped = True
            hops += 1
            if hops > 16:
                raise ValueError("pointer loop")
            continue
        if length == 0:
            if not jumped:
                end = off + 1
            return ".".join(labels), end
        off += 1
        labels.append(data[off:off + length].decode(errors="replace"))
        off += length


def encode_query(name: str, qtype: int = 1, qid: int = 0x1234) -> bytes:
    """Client-side helper (tests + in-framework lookups)."""
    header = struct.pack("!HHHHHH", qid, 0x0100, 1, 0, 0, 0)
    return header + _encode_name(name) + struct.pack("!HH", qtype, 1)


def parse_response(data: bytes) -> Tuple[int, List[str]]:
    """(rcode, [A record IPs]) from a response packet."""
    (qid, flags, qd, an, ns, ar) = struct.unpack("!HHHHHH", data[:12])
    rcode = flags & 0xF
    off = 12
    for _ in range(qd):
        _, off = _decode_name(data, off)
        off += 4
    ips = []
    for _ in range(an):
        _, off = _decode_name(data, off)
        rtype, rclass, ttl, rdlen = struct.unpack("!HHIH", data[off:off + 10])
        off += 10
        if rtype == 1 and rdlen == 4:
            ips.append(socket.inet_ntoa(data[off:off + 4]))
        off += rdlen
    return rcode, ips


def _build_response(qid: int, question: bytes, rcode: int,
                    ips: List[str]) -> bytes:
    flags = _FLAG_RESPONSE | (rcode & 0xF)
    header = struct.pack("!HHHHHH", qid, flags, 1, len(ips), 0, 0)
    answers = b""
    for ip in ips:
        answers += (b"\xc0\x0c"                # pointer to QNAME at offset 12
                    + struct.pack("!HHIH", 1, 1, 5, 4)
                    + socket.inet_aton(ip))
    return header + question + answers


# ------------------------------------------------------------------ server

class ClusterDNS:
    """Node-local cluster resolver over the service/endpoints informers."""

    def __init__(self, clientset, bind_ip: str = DEFAULT_DNS_IP,
                 port: int = 53, cluster_domain: str = CLUSTER_DOMAIN,
                 upstream: Optional[str] = None):
        from ..client import SharedInformer

        self.cluster_domain = cluster_domain
        self._suffix = tuple(cluster_domain.split("."))
        self.services = SharedInformer(clientset.services)
        self.endpoints = SharedInformer(clientset.endpoints)
        self._upstream = upstream if upstream is not None \
            else self._host_upstream(bind_ip)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((bind_ip, port))  # raises: caller decides fallback
        self.ip, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # at most 16 in-flight upstream forwards (each may block up to the
        # 2s upstream timeout); beyond that, _answer SERVFAILs immediately
        self._forward_slots = threading.Semaphore(16)
        self._drop_reporter = RateLimitedReporter("dns")

    @staticmethod
    def _host_upstream(self_ip: str) -> str:
        """First host nameserver that isn't us (resolv.conf chain-out)."""
        try:
            with open("/etc/resolv.conf") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2 and parts[0] == "nameserver" \
                            and parts[1] != self_ip:
                        return parts[1]
        except OSError:
            pass
        return ""

    def start(self) -> "ClusterDNS":
        self.services.start()
        self.endpoints.start()
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="cluster-dns")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self.services.stop()
        self.endpoints.stop()

    def resolv_conf(self, namespace: str) -> str:
        """The pod's resolv.conf (ref kubelet's --cluster-dns +
        --cluster-domain wiring): search path makes bare `redis-master`
        resolve within the pod's own namespace first."""
        d = self.cluster_domain
        return (f"nameserver {self.ip}\n"
                f"search {namespace}.svc.{d} svc.{d} {d}\n"
                f"options ndots:5\n")

    # ------------------------------------------------------------ resolution

    def resolve(self, name: str) -> Optional[List[str]]:
        """IPs for a cluster name, or None when the name is not ours.
        Accepted shapes: svc.ns | svc.ns.svc | svc.ns.svc.<domain>.
        Only the suffixed forms are AUTHORITATIVE (NXDOMAIN on miss); a
        bare two-label name that matches no service is None — it could be
        a real domain (example.com) and must forward upstream, exactly
        like kube-dns owning only cluster.local."""
        labels = tuple(name.rstrip(".").lower().split("."))
        authoritative = False
        if labels[-len(self._suffix):] == self._suffix:
            labels = labels[:-len(self._suffix)]
            authoritative = True
        if len(labels) == 3 and labels[2] == "svc":
            labels = labels[:2]
            authoritative = True
        if len(labels) != 2:
            # inside our zone with a shape we don't serve -> NXDOMAIN;
            # forwarding would leak every search-path expansion of every
            # external lookup (example.com.default.svc.cluster.local)
            # to the upstream resolver
            return [] if authoritative else None
        svc_name, ns = labels
        svc = self.services.get(f"{ns}/{svc_name}")
        if svc is None:
            return [] if authoritative else None
        if svc.spec.cluster_ip == "None":
            # headless: the endpoints ARE the answer (gang members find
            # each other directly)
            ep = self.endpoints.get(f"{ns}/{svc_name}")
            if ep is None:
                return []
            return [a.ip for s in ep.subsets for a in s.addresses if a.ip]
        return [svc.spec.cluster_ip] if svc.spec.cluster_ip else []

    # --------------------------------------------------------------- serving

    def _serve(self):
        while not self._stop.is_set():
            try:
                data, peer = self._sock.recvfrom(4096)
            except OSError:
                return
            try:
                resp = self._answer(data, peer)
            except Exception as e:  # noqa: BLE001 — a bad packet must not kill DNS
                # rate-limited: a spoofed-garbage flood must not turn the
                # single receive loop into a stderr-writing loop
                self._drop_reporter.report(f"malformed query from {peer}: {e}")
                continue
            if resp is not None:
                try:
                    self._sock.sendto(resp, peer)
                except OSError:
                    pass

    def _answer(self, data: bytes, peer) -> Optional[bytes]:
        if len(data) < 12:
            return None
        qid, flags, qd = struct.unpack("!HHH", data[:6])
        if qd < 1:
            return None
        name, off = _decode_name(data, 12)
        qtype, _qclass = struct.unpack("!HH", data[off:off + 4])
        question = data[12:off + 4]
        ips = self.resolve(name)
        if ips is None:
            # upstream forwards run OFF the serve thread: one slow external
            # lookup must not head-of-line-block every pod's cluster query.
            # Concurrency is BOUNDED (semaphore): an untrusted pod spamming
            # external lookups must not exhaust threads inside the kubelet
            # process hosting this resolver — saturation answers SERVFAIL
            # so the client can back off and retry.
            if not self._forward_slots.acquire(blocking=False):
                return _build_response(qid, question, _RCODE_SERVFAIL, [])
            try:
                threading.Thread(
                    target=self._forward_and_send,
                    args=(data, qid, question, peer), daemon=True).start()
            except RuntimeError:
                # can't spawn (process out of threads — the very pressure
                # this bound defends against): surrender the slot or 16
                # such failures would wedge forwarding permanently
                self._forward_slots.release()
                return _build_response(qid, question, _RCODE_SERVFAIL, [])
            return None
        if not ips:
            return _build_response(qid, question, _RCODE_NXDOMAIN, [])
        if qtype not in (1, 255):  # A / ANY only; AAAA etc: name exists,
            return _build_response(qid, question, 0, [])  # no records
        return _build_response(qid, question, 0, ips)

    def _forward_and_send(self, query: bytes, qid: int, question: bytes,
                          peer):
        try:
            self._sock.sendto(self._forward(query, qid, question), peer)
        except OSError:
            pass
        finally:
            self._forward_slots.release()

    def _forward(self, query: bytes, qid: int, question: bytes) -> bytes:
        if not self._upstream:
            return _build_response(qid, question, _RCODE_SERVFAIL, [])
        try:
            # dns.upstream: a dead/slow resolver must degrade to SERVFAIL
            # (FaultInjected is an OSError — the handler below absorbs it)
            faultline.check("dns.upstream")
            fwd = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            fwd.settimeout(2.0)
            fwd.sendto(query, (self._upstream, 53))
            resp, _ = fwd.recvfrom(4096)
            fwd.close()
            return resp
        except OSError:
            return _build_response(qid, question, _RCODE_SERVFAIL, [])
