"""API machinery: typed object model, scheme/serialization, selectors, watch.

TPU-native re-design of the reference's staging/src/k8s.io/apimachinery/:
runtime.Object/Scheme become a dataclass-based object model with automatic
camelCase JSON round-tripping; watch.Interface becomes an iterator of
WatchEvent; label selectors keep the same matching semantics.
"""

from .meta import ObjectMeta, OwnerReference, KObject, ListMeta, now_iso, new_uid
from .scheme import Scheme, encode, decode_into, to_dict, from_dict, global_scheme
from .errors import (
    ApiError,
    NotFound,
    AlreadyExists,
    Conflict,
    Invalid,
    TooOldResourceVersion,
    TooManyRequests,
    BadRequest,
    Forbidden,
    Unauthorized,
)
from .labels import match_labels, parse_selector, selector_matches, format_selector
from .watch import WatchEvent, ADDED, MODIFIED, DELETED, BOOKMARK, ERROR
