"""Watch primitives — the watch.Interface equivalent.

Ref: staging/src/k8s.io/apimachinery/pkg/watch/watch.go.  A watch is an
iterator of WatchEvent; event types match the reference's wire protocol so
the REST watch stream is line-delimited JSON {"type": ..., "object": ...}.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
BOOKMARK = "BOOKMARK"
ERROR = "ERROR"


@dataclass
class WatchEvent:
    type: str
    object: Any  # decoded KObject, or a Status dict for ERROR
