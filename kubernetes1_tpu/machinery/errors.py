"""Structured API errors, mirroring the reference's apimachinery Status errors.

Ref: staging/src/k8s.io/apimachinery/pkg/api/errors/errors.go — every error
carries an HTTP code and a machine-readable reason so clients (reflectors,
controllers) can react: Conflict -> retry CAS, Gone -> relist, NotFound ->
treat as deleted.
"""

from __future__ import annotations

__all__ = [
    "ApiError",
    "NotFound",
    "AlreadyExists",
    "Conflict",
    "Invalid",
    "BadRequest",
    "Forbidden",
    "TooOldResourceVersion",
]


class ApiError(Exception):
    code = 500
    reason = "InternalError"

    def __init__(self, message: str = ""):
        super().__init__(message or self.reason)
        self.message = message or self.reason

    def to_status(self):
        return {
            "kind": "Status",
            "apiVersion": "v1",
            "status": "Failure",
            "code": self.code,
            "reason": self.reason,
            "message": self.message,
        }

    @staticmethod
    def from_status(status: dict) -> "ApiError":
        reason = status.get("reason", "")
        cls = _BY_REASON.get(reason, ApiError)
        err = cls(status.get("message", ""))
        err.code = status.get("code", cls.code)
        return err


class NotFound(ApiError):
    code = 404
    reason = "NotFound"


class AlreadyExists(ApiError):
    code = 409
    reason = "AlreadyExists"


class Conflict(ApiError):
    """resourceVersion mismatch on a CAS write; caller should re-get + retry."""

    code = 409
    reason = "Conflict"


class Invalid(ApiError):
    code = 422
    reason = "Invalid"


class BadRequest(ApiError):
    code = 400
    reason = "BadRequest"


class Unauthorized(ApiError):
    code = 401
    reason = "Unauthorized"


class Forbidden(ApiError):
    code = 403
    reason = "Forbidden"


class TooOldResourceVersion(ApiError):
    """Watch/list from a compacted revision; client must relist (HTTP 410)."""

    code = 410
    reason = "Expired"


class TooManyRequests(ApiError):
    """Disruption not currently allowed (eviction vs PDB); retriable later
    (ref: eviction.go returns 429 when the budget is exhausted)."""

    code = 429
    reason = "TooManyRequests"


_BY_REASON = {
    c.reason: c
    for c in (NotFound, AlreadyExists, Conflict, Invalid, BadRequest, Forbidden,
              Unauthorized, TooOldResourceVersion, TooManyRequests)
}
