"""Scheme: kind registry + dataclass <-> JSON-dict round-tripping.

The reference's runtime.Scheme (staging/src/k8s.io/apimachinery/pkg/runtime/
scheme.go) does type registration, conversion, defaulting and serialization
through generated code.  Here the object model is Python dataclasses and the
(de)serializer is derived from type hints at import time, so there is no
generated code: snake_case attrs round-trip to the camelCase wire form the
reference uses, unknown wire fields are ignored (forward compatibility), and
values equal to the field default are omitted (the `omitempty` convention).
"""

from __future__ import annotations

import collections
import copy
import dataclasses
import functools
import json
import threading
import typing
from typing import Any, Dict, Optional, Tuple, Type

# Fields whose wire name is not the mechanical snake->camel conversion.
_SPECIAL_WIRE_NAMES = {
    "continue_token": "continue",
    "api_version": "apiVersion",
    "downward_api": "downwardAPI",
}


def _camel(name: str) -> str:
    if name in _SPECIAL_WIRE_NAMES:
        return _SPECIAL_WIRE_NAMES[name]
    parts = name.split("_")
    return parts[0] + "".join(p.capitalize() for p in parts[1:])


@functools.lru_cache(maxsize=None)
def _field_info(cls):
    """Resolved (name, wire_name, type, default) per dataclass field."""
    hints = typing.get_type_hints(cls)
    info = []
    for f in dataclasses.fields(cls):
        if f.default is not dataclasses.MISSING:
            default = f.default
        elif f.default_factory is not dataclasses.MISSING:  # type: ignore
            default = f.default_factory()  # type: ignore
        else:
            default = dataclasses.MISSING
        info.append((f.name, _camel(f.name), hints[f.name], default))
    return info


def _unwrap_optional(tp):
    origin = typing.get_origin(tp)
    if origin is typing.Union or str(origin) == "types.UnionType":
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def to_dict(obj: Any) -> Any:
    """Encode a dataclass (or primitive/list/dict) to plain JSON-able data."""
    # a frozen mutsan proxy (utils/mutsan) encodes as its target — encoding
    # only reads; the attribute protocol keeps machinery free of a utils
    # dependency, and is a no-op getattr for ordinary objects
    obj = getattr(obj, "_mutsan_target_", obj)
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for name, wire, _tp, default in _field_info(type(obj)):
            v = getattr(obj, name)
            if v is None:
                continue
            if default is not dataclasses.MISSING and v == default:
                continue
            out[wire] = to_dict(v)
        return out
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


# Decoding is THE framework hot path (every watch event / LIST item crosses
# it, and a 1000-node bench decodes millions of objects), so the per-type
# decode plan is compiled ONCE into a closure instead of re-deriving
# typing.get_origin/get_args/field info on every call.  _DECODERS only ever
# holds FINISHED decoders (lock-free fast path for readers); compilation
# runs under an RLock, with self-referential dataclasses resolved through a
# private in-progress map only the building thread can see.
_DECODERS: Dict[Any, Any] = {}
_DECODERS_BUILDING: Dict[Any, Any] = {}
_DECODERS_LOCK = threading.RLock()  # ktpulint: ignore[KTPU007] hot decode-path leaf lock, module-scope (machinery must not depend on utils)


def _decoder(tp):
    dec = _DECODERS.get(tp)
    if dec is not None:
        return dec
    with _DECODERS_LOCK:
        dec = _DECODERS.get(tp)
        if dec is not None:
            return dec
        thunk = _DECODERS_BUILDING.get(tp)
        if thunk is not None:
            return thunk  # recursive self-reference during this build
        cell = []
        _DECODERS_BUILDING[tp] = lambda data: cell[0](data)
        try:
            real = _build_decoder(tp)  # recurses into _decoder (RLock)
            cell.append(real)
            _DECODERS[tp] = real
        finally:
            del _DECODERS_BUILDING[tp]
        return real


def _build_decoder(tp):
    tp = _unwrap_optional(tp)
    origin = typing.get_origin(tp)
    if origin in (list, tuple):
        (item_tp,) = typing.get_args(tp) or (Any,)
        item_dec = _decoder(item_tp)

        def dec_list(data):
            if data is None:
                return None
            return [item_dec(v) for v in data]
        return dec_list
    if origin is dict:
        args = typing.get_args(tp)
        val_dec = _decoder(args[1] if len(args) == 2 else Any)

        def dec_dict(data):
            if data is None:
                return None
            return {k: val_dec(v) for k, v in data.items()}
        return dec_dict
    if dataclasses.is_dataclass(tp):
        fields = tuple((name, wire, _decoder(f_tp))
                       for name, wire, f_tp, _d in _field_info(tp))

        def dec_dc(data):
            if data is None:
                return None
            if not isinstance(data, dict):
                raise TypeError(f"cannot decode {data!r} into {tp.__name__}")
            kwargs = {}
            for name, wire, dec in fields:
                if wire in data:
                    kwargs[name] = dec(data[wire])
            return tp(**kwargs)
        return dec_dc
    if tp in (int, float, str, bool):
        def dec_prim(data):
            return tp(data) if data is not None else data
        return dec_prim
    # Any, TypeVars, unions with >1 concrete arm: pass through unchanged
    return lambda data: data


def from_dict(cls: Type, data: Any) -> Any:
    """Decode plain data into `cls` using its type hints."""
    return _decoder(cls)(data)


class Unstructured:
    """Schema-less API object (ref: apimachinery unstructured.Unstructured) —
    the representation for custom resources and for clients decoding kinds
    they have no compiled type for. All non-meta fields live in `content`."""

    KIND = ""
    API_VERSION = "v1"

    def __init__(self, kind: str = "", api_version: str = "v1", metadata=None,
                 content: Optional[Dict[str, Any]] = None):
        from .meta import ObjectMeta

        self.kind = kind
        self.api_version = api_version
        self.metadata = metadata if metadata is not None else ObjectMeta()
        self.content = content or {}

    # registry strategies poke .status on objects that have one
    @property
    def status(self):
        return self.content.get("status", {})

    @status.setter
    def status(self, v):
        self.content["status"] = v

    @property
    def spec(self):
        return self.content.get("spec", {})

    @spec.setter
    def spec(self, v):
        self.content["spec"] = v

    def key(self) -> str:
        if self.metadata.namespace:
            return f"{self.metadata.namespace}/{self.metadata.name}"
        return self.metadata.name

    def clone(self) -> "Unstructured":
        """Deep copy (the KObject.clone analog for dynamic kinds): the
        clone-before-mutate rule applies to CRD objects too."""
        import copy

        return copy.deepcopy(self)


class SerializationCache:
    """Once-per-revision serializer memo (the watch-cache economics of the
    reference's storage/cacher.go: one encode serves every watcher and
    every list/get response touching the same object revision).

    Entries are keyed by (uid, resourceVersion, requested api version).
    Both identifiers are server-stamped and immutable for a committed
    object state, so an entry can never go stale — it only ages out of
    the LRU window.  The reuse window is short (the fan-out of the commit
    that produced the revision, plus the lists and gets racing it), so a
    bounded LRU holds the entire hot set."""

    def __init__(self, capacity: int = 8192):
        self.capacity = capacity
        self._data: "collections.OrderedDict[tuple, bytes]" = \
            collections.OrderedDict()
        # hot leaf lock: one acquire per cached encode on the read path
        self._lock = threading.Lock()  # ktpulint: ignore[KTPU007] hot leaf serializer lock; machinery must not depend on utils
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple) -> Optional[bytes]:
        with self._lock:
            raw = self._data.get(key)
            if raw is None:
                self.misses += 1
                return None
            self._data.move_to_end(key)
            self.hits += 1
            return raw

    def put(self, key: tuple, raw: bytes):
        with self._lock:
            self._data[key] = raw
            self._data.move_to_end(key)
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)

    def clear(self):
        """Conversion/CRD (de)registration changes what an encode means;
        drop everything rather than reason about which keys survive."""
        with self._lock:
            self._data.clear()

    def stats(self) -> Tuple[int, int]:
        with self._lock:
            return self.hits, self.misses

    def hit_ratio(self) -> float:
        hits, misses = self.stats()
        total = hits + misses
        return hits / total if total else 0.0


class Scheme:
    """Kind registry: maps (kind) <-> dataclass and resource plural names.

    Ref: runtime.Scheme + the RESTMapper.  Resources are lowercase plurals
    ("pods"), kinds are CamelCase ("Pod").  Dynamic kinds (CRDs) round-trip
    as Unstructured.
    """

    def __init__(self):
        self.by_kind: Dict[str, Type] = {}
        self.by_resource: Dict[str, Type] = {}
        self.resource_of: Dict[str, str] = {}  # kind -> plural
        self.namespaced: Dict[str, bool] = {}  # plural -> bool
        self.dynamic_kinds: Dict[str, str] = {}  # kind -> apiVersion
        self.dynamic_resources: Dict[str, str] = {}  # plural -> kind
        # (kind, apiVersion) -> (from_internal, to_internal) dict converters
        self.conversions: Dict[tuple, tuple] = {}
        # once-per-revision canonical JSON bytes (see SerializationCache)
        self.serialization_cache = SerializationCache()

    def register(self, cls: Type, plural: Optional[str] = None, namespaced: bool = True):
        kind = cls.KIND or cls.__name__
        plural = plural or (kind.lower() + "s")
        self.by_kind[kind] = cls
        self.by_resource[plural] = cls
        self.resource_of[kind] = plural
        self.namespaced[plural] = namespaced
        return cls

    def copy(self) -> "Scheme":
        """Independent registry sharing the same type classes — an apiserver
        registers CRD kinds on its own copy so dynamic registrations never
        leak across Master instances in one process."""
        s = Scheme()
        s.by_kind = dict(self.by_kind)
        s.by_resource = dict(self.by_resource)
        s.resource_of = dict(self.resource_of)
        s.namespaced = dict(self.namespaced)
        s.dynamic_kinds = dict(self.dynamic_kinds)
        s.dynamic_resources = dict(self.dynamic_resources)
        s.conversions = dict(self.conversions)
        # fresh cache: two copies may register DIFFERENT conversions for
        # the same version string, so cached bytes must not cross schemes
        s.serialization_cache = SerializationCache()
        return s

    def register_dynamic(self, kind: str, plural: str, api_version: str,
                         namespaced: bool = True):
        """Register a CRD-backed kind served as Unstructured."""
        self.dynamic_kinds[kind] = api_version
        self.dynamic_resources[plural] = kind
        self.by_kind[kind] = Unstructured
        self.by_resource[plural] = Unstructured
        self.resource_of[kind] = plural
        self.namespaced[plural] = namespaced
        self.serialization_cache.clear()

    def deregister_dynamic(self, kind: str):
        plural = self.resource_of.pop(kind, "")
        self.dynamic_kinds.pop(kind, None)
        self.dynamic_resources.pop(plural, None)
        self.by_kind.pop(kind, None)
        self.by_resource.pop(plural, None)
        self.namespaced.pop(plural, None)
        self.serialization_cache.clear()

    def register_conversion(self, kind: str, api_version: str,
                            from_internal, to_internal):
        """Serve `kind` additionally at `api_version` (ref: runtime.Scheme
        conversion funcs; the dataclass wire form is the hub/internal
        version).  `from_internal(dict) -> dict` produces the versioned
        wire form; `to_internal(dict) -> dict` the reverse.  Both operate
        on plain JSON dicts, mirroring the reference's generated
        Convert_v1beta1_X_To_internal_X functions."""
        self.conversions[(kind, api_version)] = (from_internal, to_internal)
        self.serialization_cache.clear()

    def served_versions(self, kind: str) -> list:
        cls = self.by_kind.get(kind)
        out = [cls.API_VERSION] if cls is not None else []
        out += [v for (k, v) in self.conversions if k == kind]
        return out

    def encode(self, obj: Any, version: str = "") -> Dict[str, Any]:
        obj = getattr(obj, "_mutsan_target_", obj)  # thaw frozen proxies
        if isinstance(obj, Unstructured):
            # deep copy for the same reason decode() deep-copies: the
            # encoded dict is what the store COMMITS, and sharing nested
            # dicts with the caller's live object would let a later
            # mutation of that object rewrite committed history
            d = copy.deepcopy(obj.content)
            d["metadata"] = to_dict(obj.metadata)
            d["kind"] = obj.kind
            d["apiVersion"] = obj.api_version
            return d
        d = to_dict(obj)
        d["kind"] = type(obj).KIND or type(obj).__name__
        d["apiVersion"] = type(obj).API_VERSION
        return self.convert_dict(d, version) if version else d

    def convert_dict(self, d: Dict[str, Any], version: str) -> Dict[str, Any]:
        """Convert an internal-form wire dict to `version` when a conversion
        is registered (used for both single objects and watch frames)."""
        kind = d.get("kind", "")
        if not version or not kind or version == d.get("apiVersion"):
            return d
        conv = self.conversions.get((kind, version))
        if conv is None:
            return d
        out = conv[0](d)
        out["kind"], out["apiVersion"] = kind, version
        return out

    def encode_json(self, obj: Any) -> str:
        return json.dumps(self.encode(obj), separators=(",", ":"))

    # ---------------------------------------------- once-per-revision bytes
    #
    # The apiserver's whole read path (single GETs, list items, watch
    # frames) funnels through these two helpers so N watchers and M list
    # responses touching the same committed object state share ONE
    # serialization — the economics the reference gets from its watch
    # cache (storage/cacher.go serves pre-serialized event payloads).
    # The codec axis (machinery/codec.py) lets the store's binary wire
    # ride the SAME cache: the key carries the codec id, so a revision's
    # JSON bytes and its pybin1 bytes are independent entries and neither
    # can be served for the other.

    def encode_bytes(self, d: Dict[str, Any], version: str = "",
                     codec: str = "json") -> bytes:
        """Canonical codec bytes for an ALREADY-ENCODED wire dict (the
        form the store commits and watch events carry), memoized per
        (uid, resourceVersion, version, codec).  Uncommitted objects (no
        uid/rv — Status payloads, ERROR frames) bypass the cache."""
        meta = d.get("metadata") or {}
        uid, rv = meta.get("uid"), meta.get("resourceVersion")
        key = (uid, rv, version, codec) if uid and rv else None
        if key is not None:
            raw = self.serialization_cache.get(key)
            if raw is not None:
                return raw
        out = self.convert_dict(d, version) if version else d
        if codec == "json":
            raw = json.dumps(out, separators=(",", ":")).encode()
        else:
            from .codec import get_codec

            raw = get_codec(codec).encode(out)
        if key is not None:
            self.serialization_cache.put(key, raw)
        return raw

    def decode_bytes(self, raw: bytes, codec: str = "json") -> Dict[str, Any]:
        """Codec bytes -> the encoded wire dict (encode_bytes' inverse;
        the caller decides whether to Scheme.decode the dict further)."""
        if codec == "json":
            return json.loads(raw)
        from .codec import get_codec

        return get_codec(codec).decode(raw)

    def encode_obj_bytes(self, obj: Any, version: str = "",
                         codec: str = "json") -> bytes:
        """Canonical codec bytes for a DECODED object, sharing the same
        (uid, resourceVersion, version, codec) cache as encode_bytes — a
        write response populates the entry the watch fan-out then hits."""
        meta = getattr(obj, "metadata", None)
        uid = getattr(meta, "uid", "") if meta is not None else ""
        rv = getattr(meta, "resource_version", "") if meta is not None else ""
        key = (uid, rv, version, codec) if uid and rv else None
        if key is not None:
            raw = self.serialization_cache.get(key)
            if raw is not None:
                return raw
        encoded = self.encode(obj, version)
        if codec == "json":
            raw = json.dumps(encoded, separators=(",", ":")).encode()
        else:
            from .codec import get_codec

            raw = get_codec(codec).encode(encoded)
        if key is not None:
            self.serialization_cache.put(key, raw)
        return raw

    def watch_frame_bytes(self, typ: str, d: Dict[str, Any],
                          version: str = "") -> bytes:
        """One line-delimited watch frame; the object payload comes from
        the shared serialization cache."""
        return (b'{"type":"' + typ.encode() + b'","object":'
                + self.encode_bytes(d, version) + b"}\n")

    def converted_api_version(self, d: Dict[str, Any], version: str) -> str:
        """The apiVersion encode_bytes(d, version) will emit — what the
        List envelope must carry so envelope and items agree."""
        if version and (d.get("kind", ""), version) in self.conversions:
            return version
        return d.get("apiVersion", "")

    def decode(self, data: Dict[str, Any]) -> Any:
        from .meta import ObjectMeta

        kind = data.get("kind", "")
        ver = data.get("apiVersion", "")
        conv = self.conversions.get((kind, ver))
        if conv is not None:
            data = dict(conv[1](data))
            data["kind"] = kind  # converter output: internal wire form
        cls = self.by_kind.get(kind)
        if cls is None or cls is Unstructured:
            # unknown or dynamic kind -> Unstructured passthrough (the
            # client-go dynamic-client behavior).  content must be a DEEP
            # copy: a shallow one aliases the nested spec/status dicts of
            # the source — for an in-process store.get that source is the
            # COMMITTED store entry (shared with the history ring, the
            # watch cache and the serialization cache keyed on its
            # resourceVersion), so an in-place mutation of the decoded
            # object would corrupt stored state at an unchanged revision
            # (typed kinds never alias: their decoder builds fresh
            # containers at every level)
            content = {
                k: copy.deepcopy(v) for k, v in data.items()
                if k not in ("kind", "apiVersion", "metadata")
            }
            return Unstructured(
                kind=kind,
                api_version=data.get("apiVersion", "v1"),
                metadata=from_dict(ObjectMeta, data.get("metadata") or {}),
                content=content,
            )
        return from_dict(cls, data)

    def decode_json(self, raw: str) -> Any:
        return self.decode(json.loads(raw))

    def deepcopy(self, obj: Any) -> Any:
        obj = getattr(obj, "_mutsan_target_", obj)  # thaw frozen proxies
        if isinstance(obj, Unstructured):
            # one deepcopy, not the encode->decode round trip: both of
            # those now defensively deep-copy content, so chaining them
            # would pay the dominant cost twice
            return copy.deepcopy(obj)
        return from_dict(type(obj), to_dict(obj))


global_scheme = Scheme()


def encode(obj: Any) -> Dict[str, Any]:
    return global_scheme.encode(obj)


def decode_into(cls: Type, data: Dict[str, Any]) -> Any:
    return from_dict(cls, data)
