"""Scheme: kind registry + dataclass <-> JSON-dict round-tripping.

The reference's runtime.Scheme (staging/src/k8s.io/apimachinery/pkg/runtime/
scheme.go) does type registration, conversion, defaulting and serialization
through generated code.  Here the object model is Python dataclasses and the
(de)serializer is derived from type hints at import time, so there is no
generated code: snake_case attrs round-trip to the camelCase wire form the
reference uses, unknown wire fields are ignored (forward compatibility), and
values equal to the field default are omitted (the `omitempty` convention).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import typing
from typing import Any, Dict, Optional, Type

# Fields whose wire name is not the mechanical snake->camel conversion.
_SPECIAL_WIRE_NAMES = {
    "continue_token": "continue",
    "api_version": "apiVersion",
    "downward_api": "downwardAPI",
}


def _camel(name: str) -> str:
    if name in _SPECIAL_WIRE_NAMES:
        return _SPECIAL_WIRE_NAMES[name]
    parts = name.split("_")
    return parts[0] + "".join(p.capitalize() for p in parts[1:])


@functools.lru_cache(maxsize=None)
def _field_info(cls):
    """Resolved (name, wire_name, type, default) per dataclass field."""
    hints = typing.get_type_hints(cls)
    info = []
    for f in dataclasses.fields(cls):
        if f.default is not dataclasses.MISSING:
            default = f.default
        elif f.default_factory is not dataclasses.MISSING:  # type: ignore
            default = f.default_factory()  # type: ignore
        else:
            default = dataclasses.MISSING
        info.append((f.name, _camel(f.name), hints[f.name], default))
    return info


def _unwrap_optional(tp):
    origin = typing.get_origin(tp)
    if origin is typing.Union or str(origin) == "types.UnionType":
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def to_dict(obj: Any) -> Any:
    """Encode a dataclass (or primitive/list/dict) to plain JSON-able data."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for name, wire, _tp, default in _field_info(type(obj)):
            v = getattr(obj, name)
            if v is None:
                continue
            if default is not dataclasses.MISSING and v == default:
                continue
            out[wire] = to_dict(v)
        return out
    if isinstance(obj, dict):
        return {k: to_dict(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v) for v in obj]
    return obj


def from_dict(cls: Type, data: Any) -> Any:
    """Decode plain data into `cls` using its type hints."""
    cls = _unwrap_optional(cls)
    if data is None:
        return None
    origin = typing.get_origin(cls)
    if origin in (list, tuple):
        (item_tp,) = typing.get_args(cls) or (Any,)
        return [from_dict(item_tp, v) for v in data]
    if origin is dict:
        args = typing.get_args(cls)
        val_tp = args[1] if len(args) == 2 else Any
        return {k: from_dict(val_tp, v) for k, v in data.items()}
    if dataclasses.is_dataclass(cls):
        kwargs = {}
        if not isinstance(data, dict):
            raise TypeError(f"cannot decode {data!r} into {cls.__name__}")
        for name, wire, tp, default in _field_info(cls):
            if wire in data:
                kwargs[name] = from_dict(tp, data[wire])
        return cls(**kwargs)
    if cls is Any or isinstance(cls, typing.TypeVar):
        return data
    if cls in (int, float, str, bool):
        return cls(data) if data is not None else data
    return data


class Unstructured:
    """Schema-less API object (ref: apimachinery unstructured.Unstructured) —
    the representation for custom resources and for clients decoding kinds
    they have no compiled type for. All non-meta fields live in `content`."""

    KIND = ""
    API_VERSION = "v1"

    def __init__(self, kind: str = "", api_version: str = "v1", metadata=None,
                 content: Optional[Dict[str, Any]] = None):
        from .meta import ObjectMeta

        self.kind = kind
        self.api_version = api_version
        self.metadata = metadata if metadata is not None else ObjectMeta()
        self.content = content or {}

    # registry strategies poke .status on objects that have one
    @property
    def status(self):
        return self.content.get("status", {})

    @status.setter
    def status(self, v):
        self.content["status"] = v

    @property
    def spec(self):
        return self.content.get("spec", {})

    @spec.setter
    def spec(self, v):
        self.content["spec"] = v

    def key(self) -> str:
        if self.metadata.namespace:
            return f"{self.metadata.namespace}/{self.metadata.name}"
        return self.metadata.name


class Scheme:
    """Kind registry: maps (kind) <-> dataclass and resource plural names.

    Ref: runtime.Scheme + the RESTMapper.  Resources are lowercase plurals
    ("pods"), kinds are CamelCase ("Pod").  Dynamic kinds (CRDs) round-trip
    as Unstructured.
    """

    def __init__(self):
        self.by_kind: Dict[str, Type] = {}
        self.by_resource: Dict[str, Type] = {}
        self.resource_of: Dict[str, str] = {}  # kind -> plural
        self.namespaced: Dict[str, bool] = {}  # plural -> bool
        self.dynamic_kinds: Dict[str, str] = {}  # kind -> apiVersion
        self.dynamic_resources: Dict[str, str] = {}  # plural -> kind
        # (kind, apiVersion) -> (from_internal, to_internal) dict converters
        self.conversions: Dict[tuple, tuple] = {}

    def register(self, cls: Type, plural: Optional[str] = None, namespaced: bool = True):
        kind = cls.KIND or cls.__name__
        plural = plural or (kind.lower() + "s")
        self.by_kind[kind] = cls
        self.by_resource[plural] = cls
        self.resource_of[kind] = plural
        self.namespaced[plural] = namespaced
        return cls

    def copy(self) -> "Scheme":
        """Independent registry sharing the same type classes — an apiserver
        registers CRD kinds on its own copy so dynamic registrations never
        leak across Master instances in one process."""
        s = Scheme()
        s.by_kind = dict(self.by_kind)
        s.by_resource = dict(self.by_resource)
        s.resource_of = dict(self.resource_of)
        s.namespaced = dict(self.namespaced)
        s.dynamic_kinds = dict(self.dynamic_kinds)
        s.dynamic_resources = dict(self.dynamic_resources)
        s.conversions = dict(self.conversions)
        return s

    def register_dynamic(self, kind: str, plural: str, api_version: str,
                         namespaced: bool = True):
        """Register a CRD-backed kind served as Unstructured."""
        self.dynamic_kinds[kind] = api_version
        self.dynamic_resources[plural] = kind
        self.by_kind[kind] = Unstructured
        self.by_resource[plural] = Unstructured
        self.resource_of[kind] = plural
        self.namespaced[plural] = namespaced

    def deregister_dynamic(self, kind: str):
        plural = self.resource_of.pop(kind, "")
        self.dynamic_kinds.pop(kind, None)
        self.dynamic_resources.pop(plural, None)
        self.by_kind.pop(kind, None)
        self.by_resource.pop(plural, None)
        self.namespaced.pop(plural, None)

    def register_conversion(self, kind: str, api_version: str,
                            from_internal, to_internal):
        """Serve `kind` additionally at `api_version` (ref: runtime.Scheme
        conversion funcs; the dataclass wire form is the hub/internal
        version).  `from_internal(dict) -> dict` produces the versioned
        wire form; `to_internal(dict) -> dict` the reverse.  Both operate
        on plain JSON dicts, mirroring the reference's generated
        Convert_v1beta1_X_To_internal_X functions."""
        self.conversions[(kind, api_version)] = (from_internal, to_internal)

    def served_versions(self, kind: str) -> list:
        cls = self.by_kind.get(kind)
        out = [cls.API_VERSION] if cls is not None else []
        out += [v for (k, v) in self.conversions if k == kind]
        return out

    def encode(self, obj: Any, version: str = "") -> Dict[str, Any]:
        if isinstance(obj, Unstructured):
            d = dict(obj.content)
            d["metadata"] = to_dict(obj.metadata)
            d["kind"] = obj.kind
            d["apiVersion"] = obj.api_version
            return d
        d = to_dict(obj)
        d["kind"] = type(obj).KIND or type(obj).__name__
        d["apiVersion"] = type(obj).API_VERSION
        return self.convert_dict(d, version) if version else d

    def convert_dict(self, d: Dict[str, Any], version: str) -> Dict[str, Any]:
        """Convert an internal-form wire dict to `version` when a conversion
        is registered (used for both single objects and watch frames)."""
        kind = d.get("kind", "")
        if not version or not kind or version == d.get("apiVersion"):
            return d
        conv = self.conversions.get((kind, version))
        if conv is None:
            return d
        out = conv[0](d)
        out["kind"], out["apiVersion"] = kind, version
        return out

    def encode_json(self, obj: Any) -> str:
        return json.dumps(self.encode(obj), separators=(",", ":"))

    def decode(self, data: Dict[str, Any]) -> Any:
        from .meta import ObjectMeta

        kind = data.get("kind", "")
        ver = data.get("apiVersion", "")
        conv = self.conversions.get((kind, ver))
        if conv is not None:
            data = dict(conv[1](data))
            data["kind"] = kind  # converter output: internal wire form
        cls = self.by_kind.get(kind)
        if cls is None or cls is Unstructured:
            # unknown or dynamic kind -> Unstructured passthrough (the
            # client-go dynamic-client behavior)
            content = {
                k: v for k, v in data.items()
                if k not in ("kind", "apiVersion", "metadata")
            }
            return Unstructured(
                kind=kind,
                api_version=data.get("apiVersion", "v1"),
                metadata=from_dict(ObjectMeta, data.get("metadata") or {}),
                content=content,
            )
        return from_dict(cls, data)

    def decode_json(self, raw: str) -> Any:
        return self.decode(json.loads(raw))

    def deepcopy(self, obj: Any) -> Any:
        if isinstance(obj, Unstructured):
            return self.decode(self.encode(obj))
        return from_dict(type(obj), to_dict(obj))


global_scheme = Scheme()


def encode(obj: Any) -> Dict[str, Any]:
    return global_scheme.encode(obj)


def decode_into(cls: Type, data: Dict[str, Any]) -> Any:
    return from_dict(cls, data)
