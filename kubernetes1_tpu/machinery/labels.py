"""Label selectors with the reference's matching semantics.

Ref: staging/src/k8s.io/apimachinery/pkg/labels/selector.go — equality
(`k=v`, `k!=v`), set-based (`k in (a,b)`, `k notin (a,b)`, `k`, `!k`)
requirements ANDed together, plus the structured LabelSelector form
(matchLabels + matchExpressions) used by controllers.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional


def match_labels(selector: Optional[Dict[str, str]], labels: Dict[str, str]) -> bool:
    """matchLabels: every k=v must be present."""
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())


_REQ_RE = re.compile(
    r"\s*(?P<bang>!)?\s*(?P<key>[A-Za-z0-9_./-]+)\s*"
    r"(?:(?P<op>=|==|!=|\s+in\s+|\s+notin\s+)\s*(?P<val>\([^)]*\)|[A-Za-z0-9_.-]*))?\s*$"
)


def parse_selector(s: str) -> List[tuple]:
    """Parse a selector string into requirements [(key, op, values)]."""
    if not s or not s.strip():
        return []
    reqs = []
    for part in _split_top(s):
        m = _REQ_RE.match(part)
        if not m:
            raise ValueError(f"invalid selector: {part!r}")
        key, op, val = m.group("key"), m.group("op"), m.group("val")
        if m.group("bang"):
            reqs.append((key, "!", []))
        elif op is None:
            reqs.append((key, "exists", []))
        else:
            op = op.strip()
            if op in ("=", "=="):
                reqs.append((key, "=", [val]))
            elif op == "!=":
                reqs.append((key, "!=", [val]))
            else:  # in / notin
                vals = [v.strip() for v in val.strip("()").split(",") if v.strip()]
                reqs.append((key, op, vals))
    return reqs


def _split_top(s: str) -> List[str]:
    """Split on commas not inside parentheses."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return [p for p in (p.strip() for p in parts) if p]


def selector_matches(reqs: List[tuple], labels: Dict[str, str]) -> bool:
    for key, op, values in reqs:
        if op == "=":
            if labels.get(key) != values[0]:
                return False
        elif op == "!=":
            if labels.get(key) == values[0]:
                return False
        elif op == "exists":
            if key not in labels:
                return False
        elif op == "!":
            if key in labels:
                return False
        elif op == "in":
            if labels.get(key) not in values:
                return False
        elif op == "notin":
            if key in labels and labels[key] in values:
                return False
        else:
            raise ValueError(f"unknown op {op!r}")
    return True


def format_selector(match: Dict[str, str]) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(match.items()))


def label_selector_matches(selector, labels: Dict[str, str]) -> bool:
    """Structured LabelSelector (matchLabels + matchExpressions) matching.

    `selector` is an api.types.LabelSelector or None (matches nothing if None,
    matching the reference's controller semantics where a nil selector selects
    nothing to avoid mass-adoption accidents).
    """
    if selector is None:
        return False
    if selector.match_labels and not match_labels(selector.match_labels, labels):
        return False
    for expr in selector.match_expressions or []:
        op = expr.operator
        key, values = expr.key, expr.values or []
        if op == "In":
            if labels.get(key) not in values:
                return False
        elif op == "NotIn":
            if key in labels and labels[key] in values:
                return False
        elif op == "Exists":
            if key not in labels:
                return False
        elif op == "DoesNotExist":
            if key in labels:
                return False
        else:
            raise ValueError(f"unknown operator {op}")
    return True
