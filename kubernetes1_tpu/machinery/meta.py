"""Object metadata — the equivalent of the reference's meta/v1 types.

Ref: staging/src/k8s.io/apimachinery/pkg/apis/meta/v1/types.go (ObjectMeta,
ListMeta, OwnerReference).  Every persisted object carries ObjectMeta; the
store stamps uid/resourceVersion/creationTimestamp on create and bumps
resourceVersion on every write (ref: etcd3/store.go GuaranteedUpdate).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional


def now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def to_iso(epoch: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(epoch))


def now_iso_micro() -> str:
    """MicroTime (ref: meta/v1 MicroTime) — leases need sub-second
    resolution or short lease durations fall below timestamp granularity."""
    now = time.time()  # ktpulint: ignore[KTPU005] renders a wall-clock MicroTime
    frac = int((now % 1) * 1_000_000)
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(now)) + f".{frac:06d}Z"


def parse_iso(ts: str) -> float:
    """Parse either second- or microsecond-resolution UTC timestamps."""
    import calendar

    if "." in ts:
        base, frac = ts.rstrip("Z").split(".", 1)
        return calendar.timegm(time.strptime(base, "%Y-%m-%dT%H:%M:%S")) + float(
            "0." + frac
        )
    return calendar.timegm(time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))


def new_uid() -> str:
    return str(uuid.uuid4())


@dataclass
class OwnerReference:
    api_version: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: Optional[bool] = None
    block_owner_deletion: Optional[bool] = None


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = ""
    resource_version: str = ""
    generation: int = 0
    creation_timestamp: str = ""
    deletion_timestamp: Optional[str] = None
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    owner_references: List[OwnerReference] = field(default_factory=list)
    finalizers: List[str] = field(default_factory=list)
    # generateName: server appends a random suffix on create when name == "".
    generate_name: str = ""


@dataclass
class ListMeta:
    resource_version: str = ""
    continue_token: str = ""


@dataclass
class KObject:
    """Base for all API objects (the runtime.Object equivalent).

    Subclasses set class attrs KIND / API_VERSION and are registered with the
    Scheme.  `metadata` is present on every object.
    """

    metadata: ObjectMeta = field(default_factory=ObjectMeta)

    KIND = ""
    API_VERSION = "v1"

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace

    def key(self) -> str:
        if self.metadata.namespace:
            return f"{self.metadata.namespace}/{self.metadata.name}"
        return self.metadata.name

    def clone(self) -> "KObject":
        """Sanctioned deep copy for the clone-before-mutate rule: objects
        handed out by an informer or any other shared cache are immutable
        snapshots (enforced under KTPU_MUTSAN, see utils/mutsan.py); call
        clone() and mutate the copy.  Works on frozen proxies too — the
        result is always a fresh, mutable object graph."""
        import copy

        return copy.deepcopy(self)
