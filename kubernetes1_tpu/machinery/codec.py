"""Wire codecs: pluggable (encode, decode) pairs behind stable string ids.

The control plane's hot wire (store<->apiserver) historically spoke
newline-JSON only.  A codec abstracts "JSON-able data <-> bytes" so the
framing layer (storage/wire.py) can negotiate a cheaper encoding per
connection while JSON stays the default and the universal fallback —
compatibility is carried by the NEGOTIATION, not by every codec being
self-describing.

Codecs only ever see plain JSON-able data (dicts/lists/str/int/float/
bool/None): the Scheme has already flattened typed objects to their wire
dict form before a codec touches them, and decode hands the same plain
data back.  That restriction is what makes the binary codec safe.

``pybin1`` is the stdlib binary fast path: pickle protocol 5 of plain
data.  Encoding arbitrary pickles would be a remote-code-execution
primitive, so decode goes through a restricted Unpickler whose
find_class ALWAYS raises — plain-data pickles never reference a global,
and anything that does is rejected before it can import a single name.
The link this rides is already same-user (unix socket chmod 0600) or
mTLS (client_ca_file), same trust posture as etcd's peer protocol.
"""

from __future__ import annotations

import io
import json
import pickle
from typing import Any, Dict

JSON = "json"
PYBIN1 = "pybin1"


class CodecError(ValueError):
    """A payload that cannot be decoded under the negotiated codec."""


class _RestrictedUnpickler(pickle.Unpickler):
    """Plain-data pickles reference no globals; any that try are hostile
    or corrupt — refuse before resolution, never after."""

    def find_class(self, module, name):  # noqa: D102 - pickle API
        raise pickle.UnpicklingError(
            f"pybin1 payload requested global {module}.{name}; "
            f"only plain data may cross the wire")


class JsonCodec:
    """The default/fallback codec: canonical compact JSON."""

    id = JSON

    @staticmethod
    def encode(obj: Any) -> bytes:
        return json.dumps(obj, separators=(",", ":"), default=str).encode()

    @staticmethod
    def decode(raw: bytes) -> Any:
        try:
            return json.loads(raw)
        except ValueError as e:
            raise CodecError(f"corrupt json payload: {e}") from e


class PyBin1Codec:
    """Binary fast path: pickle protocol 5 of plain JSON-able data with a
    globals-free restricted decode (see module docstring)."""

    id = PYBIN1

    @staticmethod
    def encode(obj: Any) -> bytes:
        return pickle.dumps(obj, protocol=5)

    @staticmethod
    def decode(raw: bytes) -> Any:
        try:
            return _RestrictedUnpickler(io.BytesIO(raw)).load()
        except (pickle.UnpicklingError, EOFError, AttributeError,
                IndexError, ValueError) as e:
            raise CodecError(f"corrupt pybin1 payload: {e}") from e


_CODECS: Dict[str, Any] = {JSON: JsonCodec, PYBIN1: PyBin1Codec}


def get_codec(codec_id: str):
    """Codec class for a stable id; raises on unknown ids so a typo'd
    --wire-codec fails at startup, not as a silent JSON fallback."""
    try:
        return _CODECS[codec_id]
    except KeyError:
        raise ValueError(
            f"unknown codec {codec_id!r} (known: {sorted(_CODECS)})") from None


def known_codecs():
    return sorted(_CODECS)
