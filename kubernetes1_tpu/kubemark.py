"""Kubemark: hollow nodes at scale (ref: pkg/kubemark/hollow_kubelet.go:43-100).

A hollow node is the REAL kubelet loop — sync workers, PLEG, heartbeats,
status manager, device manager — over a FakeRuntime and a fake TPU
device plugin, so control-plane scale tests exercise the true node agent
code paths (watch fan-out, heartbeat write pressure, bind handling)
without containers or chips.  One worker process hosts K hollow nodes;
the scale harness (scripts/kubemark_bench.py) spawns W workers against
one real apiserver process and measures the apiserver's CPU/RSS budget,
the way the reference's density tests enforce per-size resource budgets
(test/e2e/scalability/density.go:129-162).

    python -m kubernetes1_tpu.kubemark --server http://... \
        --count 50 --index-base 0 --tpus-per-node 4
"""

from __future__ import annotations

import argparse
import os
import signal
import tempfile
import threading

from .client import Clientset
from .deviceplugin.api import PluginServer, plugin_socket_path
from .deviceplugin.tpu_plugin import TPUDevicePlugin, _fake_devices
from .kubelet import FakeRuntime, Kubelet


class HollowNode:
    """One hollow kubelet + its fake TPU plugin (hollow_kubelet.go:43)."""

    def __init__(self, server: str, name: str, root_dir: str,
                 tpus_per_node: int = 4, tpu_type: str = "v5e",
                 slice_id: str = "", host_index: int = 0,
                 heartbeat_interval: float = 10.0,
                 sync_interval: float = 1.0):
        plugin_dir = os.path.join(root_dir, name, "device-plugins")
        devices = _fake_devices(
            f"{tpu_type}:{tpus_per_node}:{slice_id or name}:{host_index}")
        self.plugin = PluginServer(
            TPUDevicePlugin(devices=devices),
            plugin_socket_path(plugin_dir, "google.com/tpu"))
        self.plugin.start()
        self.cs = Clientset(server)
        self.kubelet = Kubelet(
            self.cs,
            node_name=name,
            runtime=FakeRuntime(),
            plugin_dir=plugin_dir,
            heartbeat_interval=heartbeat_interval,
            sync_interval=sync_interval,
            pleg_interval=sync_interval,
        )

    def start(self):
        self.kubelet.start()
        return self

    def stop(self):
        self.kubelet.stop()
        self.plugin.stop()
        self.cs.close()


def run_worker(server: str, count: int, index_base: int,
               tpus_per_node: int, tpu_type: str, root_dir: str,
               heartbeat_interval: float, sync_interval: float,
               hosts_per_slice: int = 8):
    nodes = []
    for i in range(count):
        idx = index_base + i
        nodes.append(HollowNode(
            server, f"hollow-{idx}", root_dir,
            tpus_per_node=tpus_per_node, tpu_type=tpu_type,
            slice_id=f"slice-{idx // hosts_per_slice}",
            host_index=idx % hosts_per_slice,
            heartbeat_interval=heartbeat_interval,
            sync_interval=sync_interval).start())
    return nodes


def main():
    ap = argparse.ArgumentParser(description="kubemark hollow-node worker")
    ap.add_argument("--server", required=True)
    ap.add_argument("--count", type=int, default=50)
    ap.add_argument("--index-base", type=int, default=0)
    ap.add_argument("--tpus-per-node", type=int, default=4)
    ap.add_argument("--tpu-type", default="v5e")
    ap.add_argument("--root-dir", default="")
    ap.add_argument("--heartbeat-interval", type=float, default=10.0)
    ap.add_argument("--sync-interval", type=float, default=1.0)
    args = ap.parse_args()
    root = args.root_dir or tempfile.mkdtemp(prefix="kubemark-")
    nodes = run_worker(args.server, args.count, args.index_base,
                       args.tpus_per_node, args.tpu_type, root,
                       args.heartbeat_interval, args.sync_interval)
    print(f"kubemark worker: {len(nodes)} hollow nodes up "
          f"(hollow-{args.index_base}..hollow-{args.index_base + args.count - 1})",
          flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    for n in nodes:
        n.stop()


if __name__ == "__main__":
    main()
