"""kubernetes1_tpu — a TPU-native container-orchestration framework.

A from-scratch re-design of the capabilities of the reference system (an
NVIDIA fork of Kubernetes v1.9 that makes GPUs first-class schedulable
devices; see SURVEY.md) with Cloud TPU as the only accelerator:

- declarative API server over a consistent, watchable MVCC store
  (ref: staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go)
- level-triggered controllers (Job/ReplicaSet/Deployment/DaemonSet,
  node lifecycle, namespace GC; ref: pkg/controller/)
- device-aware scheduler allocating specific TPU chip IDs with attribute
  affinity and ICI-topology gang scheduling
  (ref: plugin/pkg/scheduler/core/extended_resources.go)
- per-node agent (kubelet) with a device-manager plugin layer
  (ref: pkg/kubelet/cm/devicemanager/)
- a libtpu device plugin advertising google.com/tpu with topology
  attributes and injecting /dev/accel* + TPU env into containers
- a JAX workload layer (models/ops/parallel) providing the training
  workloads the framework schedules: MNIST, ResNet-50, Llama-class
  transformers with dp/tp/sp/pp shardings over a jax.sharding.Mesh.
"""

__version__ = "0.1.0"

TPU_RESOURCE = "google.com/tpu"
