"""Event-loop serving: the shared dispatcher (utils/eventloop) and the
golden parity contract — threaded and event-loop watch serving produce
byte-identical wire frames (PR 18's tentpole acceptance).

Parity method: TWO Masters over ONE shared store (identical objects,
revisions, creationTimestamps), one per serving mode, each watched over
a raw socket.  Frames must match byte-for-byte; only pure keep-alive
heartbeat chunks (``\\n``) may differ in count/placement — the threaded
loop's deadline is heartbeat-quantized while the dispatcher's deadline
timer fires on time ("heartbeat cadence within tolerance").
"""

import socket
import threading
import time

import pytest

from kubernetes1_tpu.apiserver import server as apiserver
from kubernetes1_tpu.apiserver.server import Master
from kubernetes1_tpu.client.clientset import Clientset
from kubernetes1_tpu.machinery import global_scheme
from kubernetes1_tpu.storage.store import Store
from kubernetes1_tpu.utils import eventloop

from .helpers import make_tpu_pod


# ------------------------------------------------------------- loop unit


class TestEventLoop:
    def test_call_soon_runs_on_loop_thread(self):
        loop = eventloop.EventLoop(name="t-soon").start()
        try:
            done = threading.Event()
            seen = {}

            def cb():
                seen["in_loop"] = loop.in_loop()
                done.set()

            loop.call_soon(cb)
            assert done.wait(2)
            assert seen["in_loop"] is True
        finally:
            loop.stop()

    def test_call_later_orders_and_cancels(self):
        loop = eventloop.EventLoop(name="t-later").start()
        try:
            order = []
            done = threading.Event()
            loop.call_later(0.05, lambda: (order.append("b"), done.set()))
            loop.call_later(0.01, lambda: order.append("a"))
            cancelled = loop.call_later(0.02, lambda: order.append("x"))
            cancelled.cancel()
            assert done.wait(2)
            assert order == ["a", "b"]
        finally:
            loop.stop()

    def test_timer_lag_lands_in_histogram(self):
        loop = eventloop.EventLoop(name="t-lag").start()
        try:
            before = eventloop.loop_lag_seconds.render()
            done = threading.Event()
            loop.call_later(0.01, done.set)
            assert done.wait(2)
            after = eventloop.loop_lag_seconds.render()
            assert "ktpu_eventloop_lag_seconds" in after
            assert after != before  # one more observation
        finally:
            loop.stop()

    def test_wait_readable(self):
        a, b = socket.socketpair()
        try:
            assert eventloop.wait_readable(a, 0.05) is False
            b.sendall(b"x")
            assert eventloop.wait_readable(a, 1.0) is True
        finally:
            a.close()
            b.close()

    def test_shared_loop_restarts_after_death(self):
        loop = eventloop.shared_loop()
        assert loop.is_alive()
        assert eventloop.shared_loop() is loop  # singleton while alive


# --------------------------------------------------------- wire helpers


def _raw_watch(master, path, timeout=8.0, rcvbuf=None):
    """Open a raw-socket watch; return (sock, header_bytes).  A tiny
    ``rcvbuf`` (set before connect so the window scales to it) makes a
    deliberately-unread socket back up after a few KB instead of after
    the kernel's default ~hundreds of KB."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    if rcvbuf is not None:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, rcvbuf)
    s.settimeout(timeout)
    s.connect((master.host, master.port))
    s.sendall(b"GET " + path.encode() + b" HTTP/1.1\r\nHost: t\r\n\r\n")
    buf = b""
    while b"\r\n\r\n" not in buf:
        d = s.recv(65536)
        assert d, "connection closed before headers"
        buf += d
    head, _, rest = buf.partition(b"\r\n\r\n")
    assert b"200" in head.split(b"\r\n")[0]
    assert b"Transfer-Encoding: chunked" in head
    return s, rest


def _read_until_terminal(s, leftover=b"", deadline_s=10.0):
    buf = leftover
    end = time.monotonic() + deadline_s
    while not buf.endswith(b"0\r\n\r\n") and time.monotonic() < end:
        s.settimeout(max(0.05, end - time.monotonic()))
        try:
            d = s.recv(65536)
        except socket.timeout:
            break
        if not d:
            break
        buf += d
    return buf


def _decode_chunks(body):
    """Chunked-transfer body -> list of chunk payloads (terminal chunk
    dropped; asserts the framing is well-formed)."""
    frames = []
    i = 0
    while i < len(body):
        j = body.index(b"\r\n", i)
        size = int(body[i:j], 16)
        if size == 0:
            break
        payload = body[j + 2:j + 2 + size]
        assert len(payload) == size, "torn chunk"
        assert body[j + 2 + size:j + 4 + size] == b"\r\n"
        frames.append(payload)
        i = j + 4 + size
    return frames


def _substantive(frames):
    """Drop pure keep-alive heartbeats (cadence may differ between
    serving modes); every other frame must match byte-for-byte."""
    return [f for f in frames if f != b"\n"]


# ------------------------------------------------------------ golden A/B


@pytest.fixture
def shared_pair():
    """Two watched Masters over ONE store (identical revisions, uids and
    timestamps), one per serving mode, plus a THIRD writer Master the
    creates go through.  The writer matters: the master that serves a
    write memoizes the response's serialization (canonical typed-object
    key order) under the object's (uid, resourceVersion), while a master
    with a cold cache serializes the committed dict as stored — so
    routing writes through either watched master would make the two
    streams differ in JSON key order for reasons that have nothing to do
    with the serving mode under test."""
    store = Store(global_scheme.copy())
    m_loop = Master(store=store, event_loop_serving=True).start()
    m_thr = Master(store=store, event_loop_serving=False).start()
    m_writer = Master(store=store, event_loop_serving=True).start()
    yield m_loop, m_thr, Clientset(m_writer.url)
    m_loop.stop()
    m_thr.stop()
    m_writer.stop()
    store.close()


class TestGoldenParity:
    def test_watch_frames_byte_identical(self, shared_pair):
        m_loop, m_thr, cs = shared_pair
        path = "/api/v1/namespaces/default/pods?watch=1&timeoutSeconds=2"
        s1, r1 = _raw_watch(m_loop, path)
        s2, r2 = _raw_watch(m_thr, path)
        for i in range(5):
            cs.pods.create(make_tpu_pod(f"gp-{i}", tpus=0))
        b1 = _read_until_terminal(s1, r1)
        b2 = _read_until_terminal(s2, r2)
        s1.close()
        s2.close()
        f1 = _substantive(_decode_chunks(b1))
        f2 = _substantive(_decode_chunks(b2))
        assert len(f1) == 5, f1
        assert f1 == f2  # byte-identical event frames
        assert b1.endswith(b"0\r\n\r\n") and b2.endswith(b"0\r\n\r\n")

    def test_progress_bookmarks_byte_identical(self, shared_pair, monkeypatch):
        # shrink the heartbeat so both modes emit progress bookmarks
        # inside the window; bookmark FRAMES must match byte-for-byte
        # even if their cadence/count differs slightly
        monkeypatch.setattr(apiserver, "WATCH_HEARTBEAT_SECONDS", 0.2)
        m_loop, m_thr, cs = shared_pair
        cs.pods.create(make_tpu_pod("bm-seed", tpus=0))
        path = ("/api/v1/namespaces/default/pods?watch=1&timeoutSeconds=1"
                "&progressBookmarks=1")
        s1, r1 = _raw_watch(m_loop, path)
        s2, r2 = _raw_watch(m_thr, path)
        b1 = _read_until_terminal(s1, r1)
        b2 = _read_until_terminal(s2, r2)
        s1.close()
        s2.close()
        bm1 = [f for f in _substantive(_decode_chunks(b1))
               if b'"BOOKMARK"' in f]
        bm2 = [f for f in _substantive(_decode_chunks(b2))
               if b'"BOOKMARK"' in f]
        assert bm1 and bm2
        # identical resume position -> identical bookmark bytes
        assert set(bm1) == set(bm2)

    def test_eviction_410_byte_identical(self, shared_pair):
        m_loop, m_thr, _cs = shared_pair
        path = "/api/v1/namespaces/default/pods?watch=1&timeoutSeconds=5"
        s1, r1 = _raw_watch(m_loop, path)
        s2, r2 = _raw_watch(m_thr, path)
        # deterministic eviction: evict every server-side watcher the way
        # the slow-consumer path would (queue overflow calls exactly this)
        deadline = time.monotonic() + 5
        evicted = 0
        while evicted < 2 and time.monotonic() < deadline:
            evicted = 0
            for m in (m_loop, m_thr):
                for w in list(m.cacher._watchers):
                    w._evict()
                    evicted += 1
            time.sleep(0.05)
        assert evicted >= 2, "watchers never registered"
        b1 = _read_until_terminal(s1, r1)
        b2 = _read_until_terminal(s2, r2)
        s1.close()
        s2.close()
        f1 = _substantive(_decode_chunks(b1))
        f2 = _substantive(_decode_chunks(b2))
        assert f1 == f2
        assert len(f1) == 1 and b'"type":"ERROR"' in f1[0]
        assert b"410" in f1[0] or b"Expired" in f1[0]
        assert b1.endswith(b"0\r\n\r\n") and b2.endswith(b"0\r\n\r\n")


# ------------------------------------------------------- dispatcher e2e


class TestDispatcherBehavior:
    def test_backpressure_evicts_slow_consumer(self):
        """A client that never reads backs bytes up into the kernel and
        the outbuf; the watcher's bounded queue fills; the existing
        slow-consumer eviction fires; the client then reads its queued
        frames, the 410, and the terminal chunk."""
        m = Master(event_loop_serving=True, watch_queue_limit=16).start()
        try:
            # accepted sockets inherit the listener's SO_SNDBUF (and a
            # pre-set buffer opts out of TCP auto-tuning, which would
            # otherwise grow the kernel's send buffer to megabytes and
            # absorb the whole flood without ever blocking a send)
            m._httpd.socket.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDBUF, 8192)
            cs = Clientset(m.url)
            s, rest = _raw_watch(
                m, "/api/v1/namespaces/default/pods?watch=1", rcvbuf=4096)
            # never recv while flooding: the dispatcher drains the
            # watcher queue into the outbuf only while the outbuf is
            # empty, so eviction needs the socket to actually block —
            # the tiny client rcvbuf plus fat payloads fill the kernel's
            # send buffer within a handful of frames
            bulk = "x" * 8192
            for i in range(120):
                p = make_tpu_pod(f"bp-{i}", tpus=0)
                p.metadata.annotations["bulk"] = bulk
                cs.pods.create(p)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                evs = (m.cacher.watch_evictions
                       + getattr(m.store, "watch_evictions", 0))
                if evs:
                    break
                time.sleep(0.05)
            assert evs >= 1, "slow consumer never evicted"
            body = _read_until_terminal(s, rest, deadline_s=15.0)
            s.close()
            frames = _substantive(_decode_chunks(body))
            assert any(b'"type":"ERROR"' in f for f in frames[-1:]), \
                "stream must end with the 410 ERROR frame"
            assert body.endswith(b"0\r\n\r\n")
        finally:
            m.stop()

    def test_client_hangup_tears_down_connection(self):
        m = Master(event_loop_serving=True).start()
        try:
            base = eventloop.connection_count()
            s, _ = _raw_watch(m, "/api/v1/namespaces/default/pods?watch=1")
            deadline = time.monotonic() + 5
            while eventloop.connection_count() <= base \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert eventloop.connection_count() > base
            s.close()  # zero-byte read on the dispatcher side
            deadline = time.monotonic() + 5
            while eventloop.connection_count() > base \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert eventloop.connection_count() <= base
        finally:
            m.stop()

    def test_master_stop_ends_streams_with_terminal_chunk(self):
        m = Master(event_loop_serving=True).start()
        s, rest = _raw_watch(m, "/api/v1/namespaces/default/pods?watch=1")
        m.stop()
        body = _read_until_terminal(s, rest, deadline_s=5.0)
        s.close()
        assert body.endswith(b"0\r\n\r\n")

    def test_metrics_export_eventloop_gauges(self):
        m = Master(event_loop_serving=True).start()
        try:
            import urllib.request

            with urllib.request.urlopen(m.url + "/metrics", timeout=5) as r:
                text = r.read().decode()
            assert "ktpu_apiserver_threads " in text
            assert "ktpu_eventloop_connections " in text
            assert "ktpu_eventloop_lag_seconds" in text
        finally:
            m.stop()
