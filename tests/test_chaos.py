"""Chaos tier (ref: test/e2e/chaosmonkey/chaosmonkey.go + the upgrade
suite's disruption model): random component SIGKILL mid-workload, with
respawn, asserting the cluster CONVERGES — the Job completes, the
Deployment reaches spec, no acknowledged write is lost.

The kill set is every restartable control-plane component (apiservers,
KCM, scheduler, kubelets) plus ONE primary-store kill (the warm standby
promotes; the promoted store is then the cluster's L0 and is not killed
again — the two-member replication design's contract, storage/standby.py).
"""

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.utils.waitutil import must_poll_until

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(cmd, log):
    with open(log, "ab") as lf:
        return subprocess.Popen(
            cmd, stdout=lf, stderr=subprocess.STDOUT,
            start_new_session=True,
            env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu"),
            cwd=REPO)


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ChaosCluster:
    """Process cluster whose components can be killed and respawned by
    name — the chaosmonkey's substrate."""

    def __init__(self, d: str):
        self.d = d
        self.procs: dict = {}
        self.cmds: dict = {}
        psock = os.path.join(d, "p.sock")
        ssock = os.path.join(d, "s.sock")
        self.psock, self.ssock = psock, ssock
        pa, pb = _free_port(), _free_port()
        self.servers = f"http://127.0.0.1:{pa},http://127.0.0.1:{pb}"
        py = sys.executable
        stores = f"{psock},{ssock}"
        self.cmds = {
            "store-primary": [py, "-m", "kubernetes1_tpu.storage",
                              "--socket", psock,
                              "--wal", os.path.join(d, "p.wal")],
            "store-standby": [py, "-m", "kubernetes1_tpu.storage",
                              "--socket", ssock,
                              "--wal", os.path.join(d, "s.wal"),
                              "--standby-of", psock,
                              "--failover-grace", "0.5"],
            "api-a": [py, "-m", "kubernetes1_tpu.apiserver",
                      "--port", str(pa), "--store-address", stores],
            "api-b": [py, "-m", "kubernetes1_tpu.apiserver",
                      "--port", str(pb), "--store-address", stores],
            "kcm": [py, "-m", "kubernetes1_tpu.controllers",
                    "--server", self.servers],
            "sched": [py, "-m", "kubernetes1_tpu.scheduler",
                      "--server", self.servers, "--metrics-port", "-1"],
            "kubelet-0": [py, "-m", "kubernetes1_tpu.kubelet",
                          "--server", self.servers,
                          "--node-name", "chaos-0", "--runtime", "fake",
                          "--root-dir", os.path.join(d, "kl0")],
            "kubelet-1": [py, "-m", "kubernetes1_tpu.kubelet",
                          "--server", self.servers,
                          "--node-name", "chaos-1", "--runtime", "fake",
                          "--root-dir", os.path.join(d, "kl1")],
        }

    def spawn(self, name: str):
        self.procs[name] = _spawn(
            self.cmds[name], os.path.join(self.d, f"{name}.log"))

    def kill(self, name: str):
        p = self.procs.get(name)
        if p is None:
            return
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            p.wait(timeout=10)
        except Exception:  # noqa: BLE001
            pass

    def reap_all(self):
        for name in list(self.procs):
            self.kill(name)


def boot_cluster(tmp_path, request):
    """Shared bring-up for the chaos and rolling-upgrade suites: the full
    replicated process cluster, reaper registered before any spawn."""
    c = ChaosCluster(str(tmp_path))
    request.addfinalizer(c.reap_all)  # registered BEFORE any spawn
    c.spawn("store-primary")
    must_poll_until(lambda: os.path.exists(c.psock), timeout=20.0,
                    desc="primary store up")
    for name in ("store-standby", "api-a", "api-b"):
        c.spawn(name)
    cs = Clientset(c.servers)
    request.addfinalizer(cs.close)

    def healthy():
        try:
            cs.api.request("GET", "/healthz")
            return True
        except Exception:  # noqa: BLE001
            return False

    must_poll_until(healthy, timeout=60.0, desc="apiserver healthy")
    for name in ("kcm", "sched", "kubelet-0", "kubelet-1"):
        c.spawn(name)
    must_poll_until(
        lambda: sum(1 for n in cs.nodes.list()[0]
                    for cond in n.status.conditions
                    if cond.type == "Ready" and cond.status == "True") >= 2,
        timeout=60.0, desc="both nodes Ready")
    return c, cs


@pytest.fixture()
def chaos(tmp_path, request):
    return boot_cluster(tmp_path, request)


KILLABLE = ["api-a", "api-b", "kcm", "sched", "kubelet-0", "kubelet-1"]


class TestChaosMonkey:
    def test_random_component_kills_converge(self, chaos):
        c, cs = chaos
        rng = random.Random(1729)  # deterministic chaos: replayable CI

        # --- workloads under test
        dep = t.Deployment()
        dep.metadata.name = "steady-web"
        dep.spec.replicas = 3
        dep.spec.selector = t.LabelSelector(match_labels={"app": "web"})
        tmpl = t.PodTemplateSpec()
        tmpl.metadata.labels = {"app": "web"}
        tmpl.spec.containers = [t.Container(
            name="c", image="img", command=["sleep", "3600"])]
        dep.spec.template = tmpl
        cs.deployments.create(dep, "default")

        job = t.Job()
        job.metadata.name = "chaos-job"
        job.spec.completions = 6
        job.spec.parallelism = 2
        jt = t.PodTemplateSpec()
        jt.spec.restart_policy = "Never"
        jt.spec.containers = [t.Container(
            name="w", image="img", command=["sleep", "2"])]
        job.spec.template = jt
        cs.jobs.create(job, "default")

        # --- steady writer: every acknowledged write must survive
        acked = []
        stop_writer = threading.Event()

        def writer():
            from kubernetes1_tpu.machinery import AlreadyExists

            i = 0
            while not stop_writer.is_set():
                cm = t.ConfigMap(data={"i": str(i)})
                cm.metadata.name = f"chaos-w-{i}"
                try:
                    cs.configmaps.create(cm, "default")
                except AlreadyExists:
                    # a kill landed between commit and response on a prior
                    # attempt: the write IS durable — count it and move on
                    acked.append(cm.metadata.name)
                    i += 1
                except Exception:  # noqa: BLE001 — mid-kill blips: retry
                    pass
                else:
                    acked.append(cm.metadata.name)
                    i += 1
                time.sleep(0.1)

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()

        # --- the monkey: 8 kill/respawn cycles + one primary-store kill
        kills = []
        store_killed = False
        for cycle in range(8):
            name = rng.choice(KILLABLE)
            c.kill(name)
            kills.append(name)
            time.sleep(1.0)
            c.spawn(name)
            time.sleep(1.5)
            if cycle == 3 and not store_killed:
                c.kill("store-primary")  # standby promotes; not respawned
                kills.append("store-primary")
                store_killed = True
                time.sleep(2.0)
        stop_writer.set()
        wt.join(timeout=5)

        # --- convergence: the Job completes...
        must_poll_until(
            lambda: _succeeded(cs, "chaos-job") >= 6,
            timeout=240.0,
            desc=f"job completes despite kills {kills}")
        # ...the Deployment is back at spec with all pods running...
        must_poll_until(
            lambda: _running_web_pods(cs) >= 3,
            timeout=240.0, desc="deployment converges to 3 running")
        # ...and every acknowledged write survived the chaos (incl. the
        # store failover)
        live = {cm.metadata.name
                for cm in cs.configmaps.list(namespace="default")[0]}
        lost = [n for n in acked if n not in live]
        assert not lost, f"acknowledged writes lost: {lost} (kills={kills})"
        assert len(acked) > 10, "writer barely ran; chaos window too short"


@pytest.mark.slow
class TestSeededFaultSchedules:
    """Wire-level partial-failure tier (scripts/chaos.py's engine): seeded
    fault schedules — drop/delay/sever/truncate at every faultline site —
    against the replicated in-process topology, plus the mid-run primary
    kill.  The standing invariants must hold under fire for EVERY seed:
    zero acknowledged writes lost, strict per-stream revision order at the
    store/replica/cacher fan-outs, informers converge, recovery bounded.

    `slow` tier: each seed is ~6s of injection plus convergence; tier-1
    keeps the short no-kill smoke in tests/test_faultline.py instead.
    """

    @pytest.mark.thread_leak_ok  # full in-process topology per seed
    @pytest.mark.parametrize("seed", [1, 7, 42, 1729, 9000])
    def test_schedule_with_primary_kill(self, seed, tmp_path):
        from scripts.chaos import run_schedule

        v = run_schedule(seed, duration=6.0, kill_primary=True,
                         tmpdir=str(tmp_path))
        assert v["ok"], v
        assert v["lost"] == [], f"acknowledged writes lost: {v['lost']}"
        assert v["revision_order_ok"]
        assert v["informer_converged"]
        assert v["standby_promoted"]
        assert v["recovery_s"] < 30.0, v  # bounded recovery after faults
        # the schedule must actually have exercised the wired sites
        assert v["injected"], "no faults fired"

    @pytest.mark.thread_leak_ok
    def test_heavy_replication_sever_schedule(self, tmp_path):
        # concentrate mid-frame severs on the replication link (the
        # torn-frame + resync-cursor path) with the primary kill landing
        # mid-flap — the schedule that found the unprotected-ack hole
        from scripts.chaos import run_schedule

        v = run_schedule(4242, duration=6.0, kill_primary=True,
                         spec="repl.link=sever@0.25|drop@0.1;"
                              "wal.write=truncate@0.05",
                         tmpdir=str(tmp_path))
        assert v["ok"], v
        assert v["standby_resyncs"] >= 1


@pytest.mark.slow
class TestNodeFailureSchedules:
    """Node & slice failure domain (scripts/chaos.py run_node_schedule):
    seeded data-plane fault schedules — drops/delays at the kubelet's
    apiserver client and the device-plugin socket — against a gang-running
    3-node topology, with one seeded failure injected mid-run per mode.
    The verdicts encode the failure-domain invariants: zero device
    double-allocations at every sample, zero acked writes lost, the gang
    re-running within the recovery bound, a non-empty
    ktpu_gang_recovery_seconds distribution on /metrics, and (node-kill)
    NotReady marked exactly once with evictions counted exactly once per
    pod.  kubelet-restart is the no-checkpoint reconstruction proof: the
    fresh kubelet must rebuild device assignments from bound pod specs
    with zero recreates, zero evictions, zero spurious pod failures."""

    @pytest.mark.thread_leak_ok  # full in-process topology per seed
    @pytest.mark.parametrize("seed", [1, 7, 42, 1729, 9000])
    def test_node_kill_schedule(self, seed, tmp_path):
        from scripts.chaos import run_node_schedule

        v = run_node_schedule(seed, mode="node-kill", duration=5.0,
                              tmpdir=str(tmp_path))
        assert v["ok"], v
        assert v["double_allocations"] == []
        assert v["lost"] == []
        assert v["not_ready_marks"] == 1
        assert v["gang_recovery"]["recoveries"] >= 1
        assert v["mttr_exported"]

    @pytest.mark.thread_leak_ok
    @pytest.mark.parametrize("seed", [1, 7, 42, 1729, 9000])
    def test_chip_death_schedule(self, seed, tmp_path):
        from scripts.chaos import run_node_schedule

        v = run_node_schedule(seed, mode="chip-death", duration=5.0,
                              tmpdir=str(tmp_path))
        assert v["ok"], v
        assert v["double_allocations"] == []
        assert v["lost"] == []
        assert v["gang_recovery"]["recoveries"] >= 1
        # the deterministic kill targeted a chip the gang actually held
        # (recovered() already proved the replacement avoids every dead chip)
        assert v.get("killed_chip"), v
        assert v["mttr_exported"]

    @pytest.mark.thread_leak_ok
    @pytest.mark.parametrize("seed", [7, 1729])
    def test_kubelet_restart_schedule(self, seed, tmp_path):
        from scripts.chaos import run_node_schedule

        v = run_node_schedule(seed, mode="kubelet-restart", duration=5.0,
                              tmpdir=str(tmp_path))
        assert v["ok"], v
        assert v["reconstructed"], v
        assert v["evictions"] == 0
        assert v["gang_recovery"]["recoveries"] == 0
        assert v["double_allocations"] == []


def _succeeded(cs, name):
    try:
        return cs.jobs.get(name, "default").status.succeeded or 0
    except Exception:  # noqa: BLE001
        return 0


def _running_web_pods(cs):
    try:
        pods, _ = cs.pods.list(namespace="default",
                               label_selector="app=web")
        return sum(1 for p in pods
                   if p.status.phase == t.POD_RUNNING
                   and not p.metadata.deletion_timestamp)
    except Exception:  # noqa: BLE001
        return 0


@pytest.mark.slow
class TestStoreShardSchedules:
    """Sharded-store failure domain (scripts/chaos.py
    run_store_shard_schedule): N store shards, each a durable
    primary+standby pair with its own WAL and stride revisions, one
    Master over the shard set on store.shard.* faultline sites, and ONE
    shard primary killed mid-storm.  The standing invariants must hold
    per shard: zero acked writes lost across the shard failover, strict
    PER-SHARD revision order (primary fan-out, standby, and per-shard
    within the merged cacher stream), informer lossless convergence over
    the merged multi-shard watch, bounded recovery, zero unprotected
    acks."""

    @pytest.mark.thread_leak_ok  # full sharded topology per seed
    @pytest.mark.parametrize("seed", [7, 1729])
    def test_shard_primary_kill_schedule(self, seed, tmp_path):
        from scripts.chaos import run_store_shard_schedule

        v = run_store_shard_schedule(seed, duration=5.0,
                                     tmpdir=str(tmp_path))
        assert v["ok"], v
        assert v["lost"] == [], f"acknowledged writes lost: {v['lost']}"
        assert v["revision_order_ok"]
        assert v["informer_converged"]
        assert v["standby_promoted"]
        assert v["unprotected_acks"] == 0
        assert v["recovery_s"] < 30.0, v
        # the schedule exercised the shard link's own fault sites
        assert v["injected"].get("store.shard.rpc") or \
            v["injected"].get("store.shard.watch"), v["injected"]
