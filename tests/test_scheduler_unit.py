"""Scheduler unit tests against directly-constructed NodeInfos
(the reference's core/extended_resources_test.go + generic_scheduler_test.go
pattern: no apiserver, pure placement logic)."""

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.scheduler.cache import NodeInfo, SchedulerCache
from kubernetes1_tpu.scheduler.devices import allocate_for_pod, device_matches, pick_devices
from kubernetes1_tpu.scheduler.predicates import run_predicates
from kubernetes1_tpu.scheduler.priorities import prioritize, slice_packing

from tests.helpers import make_node, make_tpu_devices, make_tpu_pod


def ni(node):
    return NodeInfo(node)


class TestDeviceMatching:
    def test_affinity_in(self):
        dev = make_tpu_devices(1, tpu_type="v5p")[0]
        aff = t.ResourceAffinity(
            required=[t.ResourceSelectorRequirement(key=t.ATTR_TPU_TYPE, operator="In", values=["v5p"])]
        )
        assert device_matches(dev, aff)
        aff.required[0].values = ["v5e"]
        assert not device_matches(dev, aff)

    def test_affinity_gt_exists(self):
        dev = t.ExtendedResourceDevice(id="d0", attributes={"google.com/tpu/memory-gb": "16"})
        gt = t.ResourceAffinity(
            required=[t.ResourceSelectorRequirement(key="google.com/tpu/memory-gb", operator="Gt", values=["8"])]
        )
        assert device_matches(dev, gt)
        gt.required[0].values = ["16"]
        assert not device_matches(dev, gt)
        ex = t.ResourceAffinity(
            required=[t.ResourceSelectorRequirement(key=t.ATTR_TPU_SLICE, operator="Exists")]
        )
        assert not device_matches(dev, ex)

    def test_unhealthy_not_allocatable(self):
        node = make_node("n1", tpus=4)
        node.status.extended_resources["google.com/tpu"][0].health = t.DEVICE_UNHEALTHY
        info = ni(node)
        pod = make_tpu_pod("p", tpus=4)
        assignments, reason = allocate_for_pod(pod, info)
        assert assignments is None
        assert "insufficient" in reason
        pod3 = make_tpu_pod("p3", tpus=3)
        assignments, _ = allocate_for_pod(pod3, info)
        assert assignments is not None

    def test_slice_best_fit(self):
        # 2 free in slice-a, 4 free in slice-b: a 2-chip ask takes slice-a
        devices = make_tpu_devices(2, slice_id="slice-a") + make_tpu_devices(
            4, slice_id="slice-b"
        )
        ids = pick_devices(devices, 2)
        assert all("slice-a" in i for i in ids)
        # 3-chip ask doesn't fit slice-a; takes slice-b without spanning
        ids = pick_devices(devices, 3)
        assert all("slice-b" in i for i in ids)
        # 5-chip ask must span
        ids = pick_devices(devices, 5)
        assert len(ids) == 5

    def test_disjoint_multi_request(self):
        node = make_node("n1", tpus=4)
        pod = make_tpu_pod("p", tpus=2)
        per2 = t.PodExtendedResource(name="second", resource="google.com/tpu", quantity=2)
        pod.spec.extended_resources.append(per2)
        assignments, _ = allocate_for_pod(pod, ni(node))
        all_ids = assignments[pod.spec.extended_resources[0].name] + assignments["second"]
        assert len(set(all_ids)) == 4


class TestPredicates:
    def test_fits_resources(self):
        node = make_node("n1", cpu="1")
        info = ni(node)
        small = make_tpu_pod("s", tpus=0, cpu="500m")
        ok, _ = run_predicates(small, info)
        assert ok
        info.add_pod(small)
        big = make_tpu_pod("b", tpus=0, cpu="600m")
        ok, reasons = run_predicates(big, info)
        assert not ok and "insufficient cpu" in reasons[0]

    def test_node_selector_and_ready(self):
        node = make_node("n1", labels={"pool": "tpu"})
        pod = make_tpu_pod("p", tpus=0)
        pod.spec.node_selector = {"pool": "tpu"}
        assert run_predicates(pod, ni(node))[0]
        pod.spec.node_selector = {"pool": "gpu"}
        assert not run_predicates(pod, ni(node))[0]
        notready = make_node("n2", ready=False)
        pod.spec.node_selector = {}
        ok, reasons = run_predicates(pod, ni(notready))
        assert not ok and "not ready" in reasons[0]

    def test_taints_tolerations(self):
        node = make_node("n1")
        node.spec.taints = [t.Taint(key="tpu-maint", value="true", effect="NoSchedule")]
        pod = make_tpu_pod("p", tpus=0)
        assert not run_predicates(pod, ni(node))[0]
        pod.spec.tolerations = [t.Toleration(key="tpu-maint", operator="Exists")]
        assert run_predicates(pod, ni(node))[0]

    def test_host_ports(self):
        node = make_node("n1")
        info = ni(node)
        p1 = make_tpu_pod("p1", tpus=0)
        p1.spec.containers[0].ports = [t.ContainerPort(container_port=80, host_port=8080)]
        info.add_pod(p1)
        p2 = make_tpu_pod("p2", tpus=0)
        p2.spec.containers[0].ports = [t.ContainerPort(container_port=80, host_port=8080)]
        ok, reasons = run_predicates(p2, info)
        assert not ok and "host port" in reasons[0]


class TestPriorities:
    def test_least_requested_prefers_idle(self):
        idle, busy = ni(make_node("idle")), ni(make_node("busy"))
        filler = make_tpu_pod("f", tpus=0, cpu="6")
        busy.add_pod(filler)
        pod = make_tpu_pod("p", tpus=0)
        scores = prioritize(pod, [idle, busy])
        assert scores["idle"] > scores["busy"]

    def test_slice_packing_prefers_tight_fit(self):
        # node-a has exactly 4 free chips in one slice; node-b has 8
        a = ni(make_node("a", tpus=4, slice_id="sa"))
        b = ni(make_node("b", tpus=8, slice_id="sb"))
        pod = make_tpu_pod("p", tpus=4)
        assert slice_packing(pod, a) > slice_packing(pod, b)


class TestCacheAccounting:
    def test_assume_confirm_lifecycle(self):
        cache = SchedulerCache()
        cache.update_node(make_node("n1", tpus=4))
        pod = make_tpu_pod("p", tpus=2)
        pod.spec.extended_resources[0].assigned = ["slice-0-h0-tpu0", "slice-0-h0-tpu1"]
        pod.spec.node_name = "n1"
        cache.assume_pod(pod, "n1")
        assert len(cache.get_node("n1").available_devices("google.com/tpu")) == 2
        # confirm via add_pod (watch event) keeps the deduction exactly once
        cache.add_pod(pod)
        assert len(cache.get_node("n1").available_devices("google.com/tpu")) == 2
        cache.remove_pod(pod)
        assert len(cache.get_node("n1").available_devices("google.com/tpu")) == 4

    def test_forget_releases(self):
        cache = SchedulerCache()
        cache.update_node(make_node("n1", tpus=4))
        pod = make_tpu_pod("p", tpus=4)
        pod.spec.extended_resources[0].assigned = [
            f"slice-0-h0-tpu{i}" for i in range(4)
        ]
        cache.assume_pod(pod, "n1")
        assert len(cache.get_node("n1").available_devices("google.com/tpu")) == 0
        cache.forget_pod(pod)
        assert len(cache.get_node("n1").available_devices("google.com/tpu")) == 4

    def test_delete_of_unbound_version_releases_assumed_chips(self):
        """REGRESSION (gang-recovery chip-death wedge): a DELETED event
        racing an in-flight bind carries the UNBOUND pod version (no
        assigned chips) while the cache holds the scheduler's assumed
        version (chips refcounted).  remove_pod must release what was
        ACCOUNTED — the stored object — or the chips leak with no holder
        and no expiry path (forget_pod finds _pod_node already popped;
        cleanup_expired_assumes finds nothing), wedging every later
        placement on that slice."""
        cache = SchedulerCache()
        cache.update_node(make_node("n1", tpus=4))
        assumed = make_tpu_pod("p", tpus=2)
        assumed.spec.extended_resources[0].assigned = [
            "slice-0-h0-tpu0", "slice-0-h0-tpu1"]
        assumed.spec.node_name = "n1"
        cache.assume_pod(assumed, "n1")
        assert len(cache.get_node("n1").available_devices("google.com/tpu")) == 2
        # the watch's DELETED object: same key, NEVER bound
        deleted_version = make_tpu_pod("p", tpus=2)
        cache.remove_pod(deleted_version)
        assert len(cache.get_node("n1").available_devices("google.com/tpu")) == 4
        # the late forget (bind answered NotFound) stays a clean no-op
        cache.forget_pod(assumed)
        assert len(cache.get_node("n1").available_devices("google.com/tpu")) == 4

    def test_expired_assume_cleanup(self):
        cache = SchedulerCache()
        cache.ASSUME_EXPIRY_SECONDS = 0.0
        cache.update_node(make_node("n1", tpus=2))
        pod = make_tpu_pod("p", tpus=2)
        pod.spec.extended_resources[0].assigned = ["slice-0-h0-tpu0", "slice-0-h0-tpu1"]
        cache.assume_pod(pod, "n1")
        cache.cleanup_expired_assumes()
        assert len(cache.get_node("n1").available_devices("google.com/tpu")) == 2


class TestNewPriorities:
    """ImageLocality, NodeAffinity (preferred), NodePreferAvoidPods
    (ref: priorities/{image_locality,node_affinity,node_prefer_avoid_pods}.go)."""

    def _ni(self, name="n1", labels=None, images=None, annotations=None):
        from kubernetes1_tpu.scheduler.cache import NodeInfo

        node = t.Node()
        node.metadata.name = name
        node.metadata.labels = labels or {}
        node.metadata.annotations = annotations or {}
        node.status.capacity = {"cpu": "4", "memory": "8Gi", "pods": "10"}
        node.status.allocatable = dict(node.status.capacity)
        node.status.images = images or []
        ni = NodeInfo()
        ni.set_node(node)
        return ni

    def _pod(self, name="p", images=("img-a",), owner_uid=""):
        pod = t.Pod()
        pod.metadata.name = name
        if owner_uid:
            pod.metadata.owner_references = [
                t.OwnerReference(kind="ReplicaSet", name="rs", uid=owner_uid)]
        pod.spec.containers = [
            t.Container(name=f"c{i}", image=img, command=["x"])
            for i, img in enumerate(images)]
        return pod

    def test_image_locality_prefers_cached_images(self):
        from kubernetes1_tpu.scheduler.priorities import image_locality

        pod = self._pod(images=("img-a", "img-b"))
        assert image_locality(pod, self._ni(images=["img-a", "img-b"])) == 10.0
        assert image_locality(pod, self._ni(images=["img-a"])) == 5.0
        assert image_locality(pod, self._ni(images=[])) == 0.0

    def test_node_affinity_preferred_weights(self):
        from kubernetes1_tpu.scheduler.priorities import node_affinity

        pod = self._pod()
        pod.spec.affinity = t.Affinity(node_affinity_preferred=[
            t.PreferredSchedulingTerm(
                weight=3,
                preference=t.NodeAffinityTerm(match_expressions=[
                    t.NodeSelectorRequirement(key="zone", operator="In",
                                              values=["a"])])),
            t.PreferredSchedulingTerm(
                weight=1,
                preference=t.NodeAffinityTerm(match_expressions=[
                    t.NodeSelectorRequirement(key="disk", operator="In",
                                              values=["ssd"])])),
        ])
        both = self._ni(labels={"zone": "a", "disk": "ssd"})
        heavy = self._ni(labels={"zone": "a"})
        light = self._ni(labels={"disk": "ssd"})
        assert node_affinity(pod, both) == 10.0
        assert node_affinity(pod, heavy) == 7.5   # 3 of 4 weight
        assert node_affinity(pod, light) == 2.5   # 1 of 4 weight

    def test_prefer_avoid_pods_zeroes_marked_node(self):
        import json as _json

        from kubernetes1_tpu.scheduler.priorities import (
            PREFER_AVOID_PODS_ANNOTATION,
            node_prefer_avoid_pods,
        )

        pod = self._pod(owner_uid="rs-uid-1")
        ann = {PREFER_AVOID_PODS_ANNOTATION: _json.dumps({
            "preferAvoidPods": [{"podSignature": {"podController": {
                "uid": "rs-uid-1"}}}]})}
        assert node_prefer_avoid_pods(pod, self._ni(annotations=ann)) == 0.0
        assert node_prefer_avoid_pods(pod, self._ni()) == 10.0
        other = self._pod(owner_uid="other-rs")
        assert node_prefer_avoid_pods(other, self._ni(annotations=ann)) == 10.0

    def test_prefer_avoid_pods_malformed_annotation_is_inert(self):
        from kubernetes1_tpu.scheduler.priorities import (
            PREFER_AVOID_PODS_ANNOTATION,
            node_prefer_avoid_pods,
        )

        pod = self._pod(owner_uid="rs-uid-1")
        for bad in ("[]", '{"preferAvoidPods": ["x"]}', "not-json",
                    '{"preferAvoidPods": [{"podSignature": null}]}'):
            ni = self._ni(annotations={PREFER_AVOID_PODS_ANNOTATION: bad})
            assert node_prefer_avoid_pods(pod, ni) == 10.0, bad


class TestPrioritizeFusionParity:
    """prioritize() is a fused hot-path rewrite of prioritize_reference()
    — the scores must be IDENTICAL across pod/node shapes that exercise
    every skip branch (taints, affinity terms, owners, images, device
    requests, avoid-pods annotations)."""

    def _cases(self):
        import random

        from kubernetes1_tpu.scheduler.priorities import (
            PREFER_AVOID_PODS_ANNOTATION,
        )

        rng = random.Random(7)
        nodes = []
        for i in range(12):
            node = make_node(f"pp-{i}", cpu=str(rng.choice([4, 8, 64])),
                             memory=rng.choice(["8Gi", "64Gi", "256Gi"]),
                             tpus=rng.choice([0, 4, 8]),
                             slice_id=f"s{i % 3}", host_index=i % 4)
            if i % 3 == 0:
                node.spec.taints = [t.Taint(key="dedicated", value="tpu",
                                            effect="PreferNoSchedule")]
            if i % 4 == 0:
                node.metadata.annotations = {
                    PREFER_AVOID_PODS_ANNOTATION:
                    '{"preferAvoidPods": [{"podSignature": '
                    '{"podController": {"uid": "u-avoid"}}}]}'}
            node.status.images = ["img-a"] if i % 2 else []
            info = ni(node)
            # some load so least-requested/balanced differ per node
            filler = make_tpu_pod(f"fill-{i}", tpus=0)
            filler.spec.containers[0].resources.requests = {
                "cpu": f"{rng.choice([1, 2])}", "memory": "1Gi"}
            info.add_pod(filler)
            nodes.append(info)

        pods = []
        plain = make_tpu_pod("plain", tpus=0)
        pods.append(plain)
        chippy = make_tpu_pod("chippy", tpus=4)
        pods.append(chippy)
        owned = make_tpu_pod("owned", tpus=0)
        owned.metadata.owner_references = [t.OwnerReference(
            api_version="v1", kind="ReplicaSet", name="rs", uid="u-avoid")]
        owned.spec.containers[0].image = "img-a"
        pods.append(owned)
        tolerant = make_tpu_pod("tolerant", tpus=0)
        tolerant.spec.tolerations = [t.Toleration(
            key="dedicated", operator="Equal", value="tpu",
            effect="PreferNoSchedule")]
        pods.append(tolerant)
        prefery = make_tpu_pod("prefery", tpus=0)
        prefery.spec.affinity = t.Affinity(node_affinity_preferred=[
            t.PreferredSchedulingTerm(
                weight=3, preference=t.NodeAffinityTerm(match_expressions=[
                    t.NodeSelectorRequirement(
                        key="ktpu.io/tpu-slice", operator="Exists")]))])
        pods.append(prefery)
        return pods, nodes

    def test_scores_identical(self):
        from kubernetes1_tpu.scheduler.priorities import (
            prioritize,
            prioritize_reference,
        )

        pods, nodes = self._cases()
        for pod in pods:
            want = prioritize_reference(pod, nodes)
            got = prioritize(pod, nodes)
            assert got.keys() == want.keys()
            for name in want:
                assert abs(got[name] - want[name]) < 1e-9, \
                    (pod.metadata.name, name, got[name], want[name])
