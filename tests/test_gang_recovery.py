"""Node & slice failure domain — tier-1 coverage.

The chaos tier (tests/test_chaos.py TestNodeFailureSchedules, slow) proves
the failure domain under seeded fault schedules; this file is the fast
deterministic core:

- gang failure policy: one member dies -> the WHOLE gang is torn down and
  recreated as a new attempt (attempt label, capped backoff, attempt cap =
  backoff_limit, ktpu_gang_recovery_seconds MTTR);
- device-health propagation: a plugin-reported unhealthy chip fails the
  RUNNING pod holding it (the admit-time check only protects future pods),
  while endpoint/socket death never kills workloads;
- kubelet restart reconstruction: the no-checkpoint design — a fresh
  kubelet instance rebuilds device assignments from bound pod specs, with
  the 0.5s plugin-scan grace keeping healthy workloads alive meanwhile;
- node-lifecycle exactly-once accounting through the shared retry policy.
"""

import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset, InformerFactory
from kubernetes1_tpu.controllers import (
    ControllerManager,
    JobController,
    NodeLifecycleController,
)
from kubernetes1_tpu.controllers import job as job_ctrl
from kubernetes1_tpu.deviceplugin.api import (
    PluginClient,
    PluginServer,
    plugin_socket_path,
)
from kubernetes1_tpu.deviceplugin.tpu_plugin import TPUDevicePlugin, _fake_devices
from kubernetes1_tpu.kubelet import Kubelet
from kubernetes1_tpu.kubelet.devicemanager import DeviceManager
from kubernetes1_tpu.machinery import NotFound
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils import faultline
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.helpers import make_node, make_tpu_pod
from tests.test_controllers import job_with, start_hollow_node


def gang_pods(cs, job_name, live=True):
    pods, _ = cs.pods.list(namespace="default",
                           label_selector=f"{t.JOB_NAME_LABEL}={job_name}")
    if live:
        pods = [p for p in pods
                if p.status.phase not in (t.POD_SUCCEEDED, t.POD_FAILED)
                and not p.metadata.deletion_timestamp]
    return pods


def wait_gang_running(cs, job_name, n=2, timeout=60.0):
    def ok():
        pods = gang_pods(cs, job_name)
        return (len(pods) == n
                and all(p.status.phase == t.POD_RUNNING for p in pods))

    must_poll_until(ok, timeout=timeout, desc=f"gang {job_name} running")
    return gang_pods(cs, job_name)


@pytest.fixture()
def cluster(tmp_path):
    master = Master().start()
    cs = Clientset(master.url)
    sched = Scheduler(cs, gang_wait_seconds=5.0)
    sched.start()
    cm = ControllerManager(cs, monitor_grace=2.0, eviction_timeout=2.0)
    jc = next(c for c in cm.controllers if isinstance(c, JobController))
    jc.gang_backoff_base = 0.1  # fast attempts for test turnaround
    jc.gang_backoff_cap = 0.5
    cm.start()
    nodes = [
        start_hollow_node(cs, f"gr-{i}", str(tmp_path), tpus=4,
                          slice_id=f"grs{i}", host_index=i)
        for i in range(2)
    ]
    env = {"master": master, "cs": cs, "sched": sched, "cm": cm,
           "nodes": nodes}
    yield env
    for kubelet, plugin, _ in nodes:
        kubelet.stop()
        plugin.stop()
    cm.stop()
    sched.stop()
    cs.close()
    master.stop()


class TestGangFailurePolicy:
    def test_member_death_recreates_whole_gang(self, cluster):
        """One member evicted -> EVERY member is replaced as attempt 1 (new
        uids, new gang id), the job's attempt annotation advances, and the
        recovery lands in ktpu_gang_recovery_seconds."""
        cs = cluster["cs"]
        before = job_ctrl.gang_recovery_snapshot()
        cs.jobs.create(job_with("g1", completions=2, parallelism=2,
                                indexed=True, tpus=2, gang=True,
                                exit_after=600))
        pods = wait_gang_running(cs, "g1")
        uids0 = {p.metadata.name: p.metadata.uid for p in pods}
        for p in pods:
            assert (p.metadata.labels or {}).get(t.GANG_ATTEMPT_LABEL) == "0"
            assert p.spec.scheduling_gang.endswith("-a0")
        cs.pods.delete("g1-1", grace_seconds=0)  # a node eviction's end state

        def recreated():
            cur = gang_pods(cs, "g1")
            return (len(cur) == 2
                    and all(p.status.phase == t.POD_RUNNING for p in cur)
                    and all((p.metadata.labels or {})
                            .get(t.GANG_ATTEMPT_LABEL) == "1" for p in cur)
                    and all(p.metadata.uid != uids0[p.metadata.name]
                            for p in cur))

        must_poll_until(recreated, timeout=60.0,
                        desc="whole gang recreated as attempt 1")
        job = cs.jobs.get("g1")
        assert (job.metadata.annotations or {}).get(t.GANG_ATTEMPT_LABEL) == "1"
        for p in gang_pods(cs, "g1"):
            assert p.spec.scheduling_gang.endswith("-a1")
        after = job_ctrl.gang_recovery_snapshot()
        assert after["attempts"] == before["attempts"] + 1
        assert after["recoveries"] == before["recoveries"] + 1
        cs.jobs.delete("g1")

    def test_attempt_exhaustion_marks_job_failed(self, cluster):
        """backoff_limit caps ATTEMPTS for gangs: with 0 retries left, a
        member death fails the job (GangBackoffLimitExceeded) and the
        surviving members are torn down — a broken slice holds no chips."""
        cs = cluster["cs"]
        job = job_with("g2", completions=2, parallelism=2, indexed=True,
                       tpus=2, gang=True, exit_after=600)
        job.spec.backoff_limit = 0
        cs.jobs.create(job)
        wait_gang_running(cs, "g2")
        cs.pods.delete("g2-0", grace_seconds=0)

        def failed():
            j = cs.jobs.get("g2")
            return any(c.type == "Failed" and c.status == "True"
                       and c.reason == "GangBackoffLimitExceeded"
                       for c in j.status.conditions)

        must_poll_until(failed, timeout=45.0, desc="gang job marked Failed")
        must_poll_until(lambda: gang_pods(cs, "g2") == [], timeout=45.0,
                        desc="surviving members torn down")
        cs.jobs.delete("g2")

    def test_chip_death_fails_running_pod_and_recovers_excluding_chip(
            self, cluster):
        """The running-pod half of the device-health contract, end to end:
        a plugin-reported unhealthy chip FAILS the pod that holds it (not
        just future admits), the gang policy recreates the gang, and the
        scheduler re-places it on chips that are still healthy."""
        cs, nodes = cluster["cs"], cluster["nodes"]
        cs.jobs.create(job_with("g3", completions=2, parallelism=2,
                                indexed=True, tpus=2, gang=True,
                                exit_after=600))
        pods = wait_gang_running(cs, "g3")
        victim_chip = pods[0].spec.extended_resources[0].assigned[0]
        impl = next(i for _, _, i in nodes if victim_chip in i._by_id)
        impl.set_health(victim_chip, t.DEVICE_UNHEALTHY)

        def recovered():
            cur = gang_pods(cs, "g3")
            return (len(cur) == 2
                    and all(p.status.phase == t.POD_RUNNING for p in cur)
                    and all(int((p.metadata.labels or {})
                                .get(t.GANG_ATTEMPT_LABEL, "0")) >= 1
                            for p in cur)
                    and all(victim_chip not in per.assigned
                            for p in cur
                            for per in p.spec.extended_resources))

        # The historical ~1-in-5 file-context flake here was NOT timing:
        # a teardown racing an in-flight bind leaked the assumed chips'
        # refcounts in the scheduler cache (NodeInfo.remove_pod released
        # the DELETED event's unbound object instead of the stored
        # assumed one), wedging every later attempt on a slice with no
        # free-looking chips — fixed in scheduler/cache.py (regression
        # unit: test_scheduler_unit.py::test_delete_of_unbound_version_
        # releases_assumed_chips).  The budget is still generous for
        # loaded boxes; the predicate, not the budget, is the assertion.
        must_poll_until(recovered, timeout=120.0,
                        desc="gang re-placed off the dead chip")

        # the kubelet surfaced the reason, not a generic failure — the
        # Event write races the recovery poll above (it rides its own
        # client retry loop), so poll instead of asserting one snapshot
        def device_unhealthy_event():
            evs, _ = cs.events.list(namespace="default")
            return any(e.reason == "DeviceUnhealthy" for e in evs)

        must_poll_until(device_unhealthy_event, timeout=20.0,
                        desc="DeviceUnhealthy event recorded")
        cs.jobs.delete("g3")


class TestDeviceHealthPropagation:
    RES = "google.com/tpu"

    def _dm(self, tmp_path):
        dm = DeviceManager(str(tmp_path / "plugins"))
        events = []
        dm.on_device_unhealthy = lambda r, ids: events.append((r, sorted(ids)))
        return dm, events

    def test_transition_fires_once_and_rearms_on_recovery(self, tmp_path):
        dm, events = self._dm(tmp_path)
        dm.store_update(self.RES, [{"id": "c0", "health": t.DEVICE_HEALTHY}])
        assert events == []
        dm.store_update(self.RES, [{"id": "c0", "health": t.DEVICE_UNHEALTHY}])
        assert events == [(self.RES, ["c0"])]
        # repeat frames must not re-fire (the kubelet would spam status
        # PUTs and events against an already-failed pod)
        dm.store_update(self.RES, [{"id": "c0", "health": t.DEVICE_UNHEALTHY}])
        assert len(events) == 1
        dm.store_update(self.RES, [{"id": "c0", "health": t.DEVICE_HEALTHY}])
        dm.store_update(self.RES, [{"id": "c0", "health": t.DEVICE_UNHEALTHY}])
        assert len(events) == 2  # re-armed by the healthy frame

    def test_first_frame_unhealthy_fires(self, tmp_path):
        # kubelet restart: the chip died while the kubelet was down — the
        # FIRST ListAndWatch frame after restart must still fail the holder
        dm, events = self._dm(tmp_path)
        dm.store_update(self.RES, [{"id": "c0", "health": t.DEVICE_UNHEALTHY}])
        assert events == [(self.RES, ["c0"])]

    def test_endpoint_death_blocks_admits_but_spares_running_pods(
            self, tmp_path):
        """The two halves of the health contract, side by side: socket
        death (store_mark_unhealthy) must NOT fire the running-pod callback
        — a restarting plugin would kill its own healthy workloads — while
        the admit-time path still rejects terminally on the stale-marked
        inventory."""
        dm, events = self._dm(tmp_path)
        dm.store_update(self.RES, [{"id": "c0", "health": t.DEVICE_HEALTHY}])
        dm.store_mark_unhealthy(self.RES)
        assert events == []
        dm._endpoints[self.RES] = object()  # presence is all admit reads
        pod = make_tpu_pod("p0", tpus=1)
        pod.spec.extended_resources[0].assigned = ["c0"]
        res = dm.admit_pod(pod)
        assert not res.allowed and not res.retriable
        assert "unhealthy" in res.reason


class TestPluginScanGraceWindow:
    def test_bound_pod_delivered_before_scan_is_retriable_not_fatal(
            self, tmp_path):
        """The kubelet-restart seam, directly: bound pods arrive from the
        informer BEFORE the 0.5s plugin scan finds the socket.  Admission
        must answer RETRIABLE through the whole warmup (no plugin yet, then
        no inventory yet) — a terminal answer anywhere in that window would
        kill healthy workloads on every kubelet restart."""
        plugin_dir = str(tmp_path / "plugins")
        impl = TPUDevicePlugin(devices=_fake_devices("v5e:2:sg:0"))
        server = PluginServer(
            impl, plugin_socket_path(plugin_dir, "google.com/tpu"))
        server.start()
        dm = DeviceManager(plugin_dir, poll_interval=0.1)
        pod = make_tpu_pod("early", tpus=2)
        pod.spec.extended_resources[0].assigned = [d["id"] for d in impl.devices]
        try:
            res = dm.admit_pod(pod)  # scan has not even started
            assert not res.allowed and res.retriable
            dm.start()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                res = dm.admit_pod(pod)
                if res.allowed:
                    break
                assert res.retriable, res  # never terminal mid-warmup
                time.sleep(0.05)
            assert res.allowed, res
        finally:
            dm.stop()
            server.stop()


class TestDataPlaneFaultSites:
    def test_plugin_rpc_drop_is_connection_error(self, tmp_path):
        """An injected plugin.rpc fault surfaces as the ConnectionError the
        admit path classifies RETRIABLE — the chaos schedules ride this."""
        plugin_dir = str(tmp_path / "plugins")
        impl = TPUDevicePlugin(devices=_fake_devices("v5e:2:sf:0"))
        server = PluginServer(
            impl, plugin_socket_path(plugin_dir, "google.com/tpu"))
        server.start()
        client = PluginClient(plugin_socket_path(plugin_dir, "google.com/tpu"))
        try:
            assert client.call("GetPluginInfo")["device_count"] == 2
            faultline.activate(1, "plugin.rpc=error@1.0")
            with pytest.raises(ConnectionError):
                client.call("GetPluginInfo")
        finally:
            faultline.deactivate()
            client.close()
            server.stop()

    def test_device_health_site_flips_one_chip_per_injection(self):
        impl = TPUDevicePlugin(devices=_fake_devices("v5e:2:sh:0"))
        assert impl._inject_chip_death() is None  # identity when inactive
        try:
            faultline.activate(1, "device.health=error@1.0")
            first = impl._inject_chip_death()
            assert first is not None
            assert impl._by_id[first]["health"] == t.DEVICE_UNHEALTHY
            second = impl._inject_chip_death()
            assert second is not None and second != first
            assert impl._inject_chip_death() is None  # nothing healthy left
        finally:
            faultline.deactivate()


class TestKubeletRestartReconstruction:
    @pytest.mark.thread_leak_ok  # the killed kubelet's pool drains async
    def test_restart_mid_gang_rebuilds_from_pod_specs(self, tmp_path):
        """SIGKILL analog mid-gang: every bit of kubelet state is
        in-memory (no checkpoint file exists), so a fresh instance over the
        same runtime + plugin dir IS the restarted process.  It must
        rebuild device assignments from bound pod specs — no recreates, no
        spurious failures, no duplicated containers."""
        master = Master().start()
        cs = Clientset(master.url)
        sched = Scheduler(cs, gang_wait_seconds=5.0)
        sched.start()
        cm = ControllerManager(cs)  # default 40s grace: restart != death
        cm.start()
        kubelet, plugin, _impl = start_hollow_node(
            cs, "rk-0", str(tmp_path), tpus=4, slice_id="rk")
        fresh = None
        try:
            cs.jobs.create(job_with("rg", completions=2, parallelism=2,
                                    indexed=True, tpus=2, gang=True,
                                    exit_after=600))
            pods = wait_gang_running(cs, "rg")
            uids0 = {p.metadata.uid for p in pods}
            runtime = kubelet.runtime
            containers0 = {c.id for c in runtime.list_containers()}
            before = job_ctrl.gang_recovery_snapshot()
            kubelet.stop()
            fresh = Kubelet(cs, node_name="rk-0", runtime=runtime,
                            plugin_dir=kubelet.device_manager.plugin_dir,
                            heartbeat_interval=0.5, sync_interval=0.2,
                            pleg_interval=0.2)
            fresh.start()
            # across the reconstruction window (plugin rescan + informer
            # redelivery + several sync passes) the gang must stay exactly
            # as it was: same uids, Running, zero Failed phases
            deadline = time.monotonic() + 6.0
            while time.monotonic() < deadline:
                cur = gang_pods(cs, "rg", live=False)
                assert len(cur) == 2
                assert {p.metadata.uid for p in cur} == uids0, \
                    "gang recreated across a mere kubelet restart"
                assert all(p.status.phase == t.POD_RUNNING for p in cur), \
                    "spurious pod failure across kubelet restart"
                time.sleep(0.3)
            assert {c.id for c in runtime.list_containers()} == containers0, \
                "restarted kubelet duplicated containers instead of adopting"
            after = job_ctrl.gang_recovery_snapshot()
            assert after["recoveries"] == before["recoveries"]
            assert after["attempts"] == before["attempts"]
        finally:
            (fresh or kubelet).stop()
            plugin.stop()
            cm.stop()
            sched.stop()
            cs.close()
            master.stop()


class TestNodeLifecycleExactlyOnce:
    @pytest.mark.thread_leak_ok  # controller loop drains async
    def test_stale_node_marked_once_pods_evicted_once(self):
        """NotReady marked exactly once, the eviction counted exactly once
        per pod (the force-finalize pass is not a second eviction), and a
        clean run takes zero errors through the shared retry policy."""
        master = Master().start()
        cs = Clientset(master.url)
        factory = InformerFactory(cs)
        nlc = NodeLifecycleController(cs, factory, monitor_grace=0.6,
                                      eviction_timeout=0.3,
                                      monitor_interval=0.1)
        try:
            node = make_node("dead-0")  # Ready=True, no heartbeat => stale
            cs.nodes.create(node)
            pod = make_tpu_pod("victim", tpus=0)
            pod.spec.node_name = "dead-0"  # bound; its kubelet never existed
            cs.pods.create(pod)
            factory.start_all()
            factory.wait_for_sync()
            nlc.start()
            must_poll_until(lambda: int(nlc.evictions_total.value) >= 1,
                            timeout=10.0, desc="eviction fired")

            def gone():
                try:
                    cs.pods.get("victim")
                    return False
                except NotFound:
                    return True

            must_poll_until(gone, timeout=10.0, desc="pod force-finalized")
            time.sleep(0.5)  # several more monitor passes over the corpse
            assert int(nlc.evictions_total.value) == 1
            assert int(nlc.not_ready_total.value) == 1
            assert int(nlc.errors_total.value) == 0
            fresh = cs.nodes.get("dead-0", "")
            cond = next(c for c in fresh.status.conditions
                        if c.type == t.NODE_READY)
            assert cond.status == "Unknown"
            evs, _ = cs.events.list(namespace="default")
            assert sum(1 for e in evs if e.reason == "NodeEviction") >= 1
        finally:
            nlc.stop()
            factory.stop_all()
            cs.close()
            master.stop()
