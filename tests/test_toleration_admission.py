"""Toleration admission family (ref: plugin/pkg/admission/
extendedresourcetoleration/admission.go:31, defaulttolerationseconds,
podnodeselector, alwayspullimages)."""

import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.machinery import Forbidden
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.helpers import make_tpu_pod
from tests.test_controllers import start_hollow_node


@pytest.fixture()
def cluster(tmp_path):
    master = Master(admission_plugins=["AlwaysPullImages"]).start()
    cs = Clientset(master.url)
    sched = Scheduler(cs)
    sched.start()
    # a tainted TPU pool node + an untainted CPU node
    tpu_kl, tpu_pl, _ = start_hollow_node(cs, "tpu-pool-0", str(tmp_path), tpus=4)
    cpu_kl, cpu_pl, _ = start_hollow_node(cs, "cpu-0", str(tmp_path), tpus=0)

    def taint_applied():
        node = cs.nodes.get("tpu-pool-0", "")
        node.spec.taints = [t.Taint(key="google.com/tpu", effect="NoSchedule")]
        try:
            cs.nodes.update(node)
            return True
        except Exception:  # noqa: BLE001  (heartbeat conflict; retry)
            return False

    must_poll_until(taint_applied, timeout=10.0, desc="taint the TPU pool")
    env = {"master": master, "cs": cs}
    yield env
    tpu_kl.stop()
    tpu_pl.stop()
    cpu_kl.stop()
    cpu_pl.stop()
    sched.stop()
    cs.close()
    master.stop()


class TestExtendedResourceToleration:
    def test_tpu_pod_lands_on_tainted_pool_without_user_tolerations(self, cluster):
        """The VERDICT r3 'done' bar: a tainted TPU pool accepts TPU pods
        with no user-written tolerations; CPU pods stay off it."""
        cs = cluster["cs"]
        tpu_pod = make_tpu_pod("trainer", tpus=2)
        tpu_pod.spec.containers[0].command = ["serve"]
        assert not tpu_pod.spec.tolerations  # user wrote none
        created = cs.pods.create(tpu_pod)
        # admission injected the matching toleration
        assert any(tol.key == "google.com/tpu" and tol.operator == "Exists"
                   for tol in created.spec.tolerations)
        must_poll_until(
            lambda: cs.pods.get("trainer", "default").spec.node_name == "tpu-pool-0",
            timeout=15.0, desc="TPU pod on the tainted pool",
        )
        # a CPU pod never tolerates the pool taint
        cpu_pod = make_tpu_pod("web", tpus=0)
        cpu_pod.spec.containers[0].command = ["serve"]
        created = cs.pods.create(cpu_pod)
        assert not any(tol.key == "google.com/tpu"
                       for tol in created.spec.tolerations)
        must_poll_until(
            lambda: cs.pods.get("web", "default").spec.node_name == "cpu-0",
            timeout=15.0, desc="CPU pod on the CPU node",
        )


class TestDefaultTolerationSeconds:
    def test_not_ready_tolerations_injected(self, cluster):
        cs = cluster["cs"]
        pod = make_tpu_pod("anypod", tpus=0)
        pod.spec.containers[0].command = ["serve"]
        created = cs.pods.create(pod)
        by_key = {tol.key: tol for tol in created.spec.tolerations}
        for key in ("node.kubernetes.io/not-ready",
                    "node.kubernetes.io/unreachable"):
            assert key in by_key
            assert by_key[key].toleration_seconds == 300
            assert by_key[key].effect == "NoExecute"

    def test_user_toleration_not_overridden(self, cluster):
        cs = cluster["cs"]
        pod = make_tpu_pod("custom", tpus=0)
        pod.spec.containers[0].command = ["serve"]
        pod.spec.tolerations = [t.Toleration(
            key="node.kubernetes.io/not-ready", operator="Exists",
            effect="NoExecute", toleration_seconds=5)]
        created = cs.pods.create(pod)
        mine = [tol for tol in created.spec.tolerations
                if tol.key == "node.kubernetes.io/not-ready"]
        assert len(mine) == 1 and mine[0].toleration_seconds == 5


class TestPodNodeSelector:
    def test_namespace_selector_merged_and_conflicts_rejected(self, cluster):
        cs = cluster["cs"]
        ns = t.Namespace()
        ns.metadata.name = "tpu-tenant"
        ns.metadata.annotations = {
            "scheduler.ktpu.io/node-selector": "pool=v5e,team=ml"}
        cs.namespaces.create(ns, "")
        pod = make_tpu_pod("tenant-pod", tpus=0, ns="tpu-tenant")
        pod.spec.containers[0].command = ["serve"]
        created = cs.pods.create(pod, "tpu-tenant")
        assert created.spec.node_selector["pool"] == "v5e"
        assert created.spec.node_selector["team"] == "ml"
        # conflicting pod-level selector is rejected, not silently merged
        bad = make_tpu_pod("rogue", tpus=0, ns="tpu-tenant")
        bad.spec.containers[0].command = ["serve"]
        bad.spec.node_selector = {"pool": "v5p"}
        with pytest.raises(Forbidden, match="conflicts with the namespace"):
            cs.pods.create(bad, "tpu-tenant")


class TestAlwaysPullImages:
    def test_pull_policy_forced(self, cluster):
        cs = cluster["cs"]
        pod = make_tpu_pod("pully", tpus=0)
        pod.spec.containers[0].command = ["serve"]
        pod.spec.containers[0].image_pull_policy = "Never"
        created = cs.pods.create(pod)
        assert created.spec.containers[0].image_pull_policy == "Always"
