"""Integration tests: real in-process apiserver + real store, no nodes.

Mirrors the reference's test/integration pattern (framework.RunAMaster,
master_utils.go:193): every test gets an embedded master over the MVCC
store and talks to it through the real HTTP client stack.
"""

import threading
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset, LeaderElector, SharedInformer
from kubernetes1_tpu.machinery import Conflict, Invalid, NotFound
from kubernetes1_tpu.utils.waitutil import must_poll_until
from tests.test_machinery import make_pod


@pytest.fixture(scope="module")
def master():
    m = Master().start()
    yield m
    m.stop()


@pytest.fixture()
def cs(master):
    c = Clientset(master.url)
    yield c
    c.close()


class TestRest:
    def test_create_get_list_delete(self, cs):
        pod = make_pod("rest-a")
        created = cs.pods.create(pod)
        assert created.metadata.uid
        got = cs.pods.get("rest-a")
        assert got.spec.containers[0].image == "busybox"
        items, rv = cs.pods.list(namespace="default")
        assert any(p.metadata.name == "rest-a" for p in items)
        assert int(rv) > 0
        cs.pods.delete("rest-a", grace_seconds=0)
        with pytest.raises(NotFound):
            cs.pods.get("rest-a")

    def test_generate_name(self, cs):
        pod = make_pod()
        pod.metadata.name = ""
        pod.metadata.generate_name = "gen-"
        created = cs.pods.create(pod)
        assert created.metadata.name.startswith("gen-")
        assert len(created.metadata.name) > len("gen-")
        cs.pods.delete(created.metadata.name, grace_seconds=0)

    def test_validation_rejected(self, cs):
        pod = t.Pod()
        pod.metadata.name = "noname"
        with pytest.raises(Invalid):
            cs.pods.create(pod)

    def test_conflict_on_stale_update(self, cs):
        created = cs.pods.create(make_pod("rest-conflict"))
        fresh = cs.pods.get("rest-conflict")
        fresh.metadata.labels["x"] = "1"
        cs.pods.update(fresh)
        created.metadata.labels["y"] = "2"
        with pytest.raises(Conflict):
            cs.pods.update(created)
        cs.pods.delete("rest-conflict", grace_seconds=0)

    def test_merge_patch(self, cs):
        cs.pods.create(make_pod("rest-patch"))
        out = cs.pods.patch(
            "rest-patch", {"metadata": {"labels": {"patched": "yes"}}}
        )
        assert out.metadata.labels["patched"] == "yes"
        assert out.metadata.labels["app"] == "test"  # merge, not replace
        cs.pods.delete("rest-patch", grace_seconds=0)

    def test_status_subresource(self, cs):
        cs.pods.create(make_pod("rest-status"))
        pod = cs.pods.get("rest-status")
        pod.status.phase = t.POD_RUNNING
        pod.spec.node_name = ""  # spec changes via status endpoint must not land
        updated = cs.pods.update_status(pod)
        assert updated.status.phase == t.POD_RUNNING
        cs.pods.delete("rest-status", grace_seconds=0)

    def test_field_selector(self, cs):
        a = make_pod("fs-a")
        a.spec.node_name = "node-1"
        cs.pods.create(a)
        cs.pods.create(make_pod("fs-b"))
        bound, _ = cs.pods.list(
            namespace="default", field_selector="spec.nodeName=node-1"
        )
        assert [p.metadata.name for p in bound] == ["fs-a"]
        unbound, _ = cs.pods.list(
            namespace="default", field_selector="spec.nodeName="
        )
        assert any(p.metadata.name == "fs-b" for p in unbound)
        assert all(p.metadata.name != "fs-a" for p in unbound)
        cs.pods.delete("fs-a", grace_seconds=0)
        cs.pods.delete("fs-b", grace_seconds=0)


class TestResourceV2Admission:
    def test_tpu_limit_rewritten_to_pod_level(self, cs):
        """The fork's signature behavior (resourcev2/admission.go:62-92),
        TPU-flavored: container google.com/tpu limits become pod-level
        extended resources."""
        pod = make_pod("adm-tpu", tpus=4)
        created = cs.pods.create(pod)
        assert "google.com/tpu" not in created.spec.containers[0].resources.limits
        assert len(created.spec.extended_resources) == 1
        per = created.spec.extended_resources[0]
        assert per.resource == "google.com/tpu"
        assert per.quantity == 4
        assert created.spec.containers[0].extended_resource_requests == [per.name]
        cs.pods.delete("adm-tpu", grace_seconds=0)

    def test_nvidia_resource_rejected_with_pointer(self, cs):
        pod = make_pod("adm-gpu")
        pod.spec.containers[0].resources.limits["nvidia.com/gpu"] = 1
        with pytest.raises(Invalid, match="google.com/tpu"):
            cs.pods.create(pod)


class TestBindingSubresource:
    def test_bind_applies_node_and_devices(self, cs):
        pod = make_pod("bind-a", tpus=2)
        created = cs.pods.create(pod)
        per_name = created.spec.extended_resources[0].name
        binding = t.Binding(
            target_node="node-1",
            extended_resource_assignments={per_name: ["tpu-0", "tpu-1"]},
        )
        binding.metadata.name = "bind-a"
        status = cs.bind("default", "bind-a", binding)
        assert status.get("status") == "Success"  # upstream returns Status
        bound = cs.pods.get("bind-a", "default")
        assert bound.spec.node_name == "node-1"
        assert bound.spec.extended_resources[0].assigned == ["tpu-0", "tpu-1"]
        # double-bind to another node must conflict
        b2 = t.Binding(target_node="node-2")
        with pytest.raises(Conflict):
            cs.bind("default", "bind-a", b2)
        cs.pods.delete("bind-a", grace_seconds=0)

    def test_bind_quantity_mismatch(self, cs):
        created = cs.pods.create(make_pod("bind-q", tpus=2))
        per_name = created.spec.extended_resources[0].name
        binding = t.Binding(
            target_node="node-1",
            extended_resource_assignments={per_name: ["tpu-0"]},
        )
        with pytest.raises(Invalid):
            cs.bind("default", "bind-q", binding)
        cs.pods.delete("bind-q", grace_seconds=0)


class TestGracefulDelete:
    def test_scheduled_pod_marked_then_removed(self, cs):
        pod = make_pod("gd-a")
        cs.pods.create(pod)
        fresh = cs.pods.get("gd-a")
        fresh.spec.node_name = ""  # not bound: immediate delete
        out = cs.pods.delete("gd-a")
        with pytest.raises(NotFound):
            cs.pods.get("gd-a")

        pod = make_pod("gd-b", tpus=0)
        created = cs.pods.create(pod)
        cs.bind("default", "gd-b", t.Binding(target_node="n1"))
        out = cs.pods.delete("gd-b")
        assert out.metadata.deletion_timestamp  # graceful: marked, not gone
        got = cs.pods.get("gd-b")
        assert got.metadata.deletion_timestamp
        cs.pods.delete("gd-b", grace_seconds=0)
        with pytest.raises(NotFound):
            cs.pods.get("gd-b")


class TestWatchStream:
    def test_watch_sees_create_update_delete(self, cs):
        stream = cs.pods.watch(namespace="default")
        events = []
        th = threading.Thread(
            target=lambda: [events.append(e) for e in stream], daemon=True
        )
        th.start()
        time.sleep(0.2)
        cs.pods.create(make_pod("w-a"))
        pod = cs.pods.get("w-a")
        pod.metadata.labels["w"] = "1"
        cs.pods.update(pod)
        cs.pods.delete("w-a", grace_seconds=0)
        must_poll_until(lambda: len(events) >= 3, desc="3 watch events")
        stream.close()
        types = [e[0] for e in events[:3]]
        assert types == ["ADDED", "MODIFIED", "DELETED"]

    def test_watch_resume_from_rv(self, cs):
        cs.pods.create(make_pod("w-r1"))
        _, rv = cs.pods.list(namespace="default")
        cs.pods.create(make_pod("w-r2"))
        stream = cs.pods.watch(namespace="default", resource_version=rv)
        it = iter(stream)
        ev_type, obj = next(it)
        assert ev_type == "ADDED"
        assert obj["metadata"]["name"] == "w-r2"
        stream.close()
        cs.pods.delete("w-r1", grace_seconds=0)
        cs.pods.delete("w-r2", grace_seconds=0)


class TestInformer:
    def test_informer_sync_and_events(self, cs, master):
        cs.pods.create(make_pod("inf-pre"))
        inf = SharedInformer(cs.pods, namespace="default")
        adds, updates, deletes = [], [], []
        inf.add_handler(
            on_add=lambda o: adds.append(o.metadata.name),
            on_update=lambda o, n: updates.append(n.metadata.name),
            on_delete=lambda o: deletes.append(o.metadata.name),
        )
        inf.start()
        assert inf.wait_for_sync()
        must_poll_until(lambda: "inf-pre" in adds, desc="initial add")
        cs.pods.create(make_pod("inf-live"))
        must_poll_until(lambda: "inf-live" in adds, desc="live add")
        pod = cs.pods.get("inf-live")
        pod.metadata.labels["u"] = "1"
        cs.pods.update(pod)
        must_poll_until(lambda: "inf-live" in updates, desc="live update")
        cs.pods.delete("inf-live", grace_seconds=0)
        must_poll_until(lambda: "inf-live" in deletes, desc="live delete")
        assert inf.get("default/inf-pre") is not None
        inf.stop()
        cs.pods.delete("inf-pre", grace_seconds=0)


class TestLeaderElection:
    def test_single_leader_and_failover(self, master):
        cs1, cs2 = Clientset(master.url), Clientset(master.url)
        e1 = LeaderElector(cs1, "test-lock", "id-1", lease_duration=1.0, retry_period=0.1)
        e1.start()
        assert e1.wait_for_leadership(5)
        e2 = LeaderElector(cs2, "test-lock", "id-2", lease_duration=1.0, retry_period=0.1)
        e2.start()
        time.sleep(0.5)
        assert not e2.is_leader
        e1.stop()  # releases the lease
        assert e2.wait_for_leadership(5)
        e2.stop()
        cs1.close()
        cs2.close()
