"""5000-node read/write envelope — tier-1 wire-compat coverage.

Three contracts this PR's hot paths must keep as the tree grows:

1. SELECTOR INDEXES narrow, never change: an index-backed
   spec.nodeName LIST returns exactly the full-scan result — alone,
   combined with other selector requirements, under concurrent writes,
   and across the sharded merge.  (The ≥10x speed claim lives in the
   slow tier; tier-1 asserts equality, which timing noise can't flake.)
2. PAGINATION is wire-compatible and lossless: shards=1 with no limit=
   stays byte-identical to the unpaginated response (golden bytes);
   chunked LISTs union to the unpaginated result; a stale continue
   token 410s and the client restarts cleanly — an informer relisting
   in tiny chunks under churn still converges to the authoritative
   state (the first-chunk-rv watch-resume rule).
3. The BIND STREAM is an optimization, never a semantic: outcomes match
   the per-request path, any stream failure (seeded sever included)
   falls back cleanly with zero lost binds, and a server that refused
   the upgrade is never probed again.
"""

import json
import threading
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset, SharedInformer
from kubernetes1_tpu.client import bindstream as bindstream_mod
from kubernetes1_tpu.machinery import Conflict, TooOldResourceVersion
from kubernetes1_tpu.utils import faultline
from kubernetes1_tpu.utils.streams import UpgradeRefused

from tests.helpers import make_node, make_tpu_pod
from tests.test_machinery import make_pod


def _binding(pod_name, node, chips=None, ns="default"):
    b = t.Binding(target_node=node,
                  extended_resource_assignments=(
                      {f"{pod_name}-tpu": chips} if chips else {}))
    b.metadata.name = pod_name
    b.metadata.namespace = ns
    return b


def _names(pods):
    return sorted(p.metadata.name for p in pods)


class TestSelectorIndex:
    def test_indexed_equals_scan(self):
        """The kubelet-shaped LIST (spec.nodeName=) through the index
        equals the full scan — alone and combined with label + extra
        field requirements (the index narrows; every requirement still
        filters)."""
        master = Master().start()
        cs = Clientset(master.url)
        try:
            for i in range(3):
                cs.nodes.create(make_node(f"n{i}", tpus=8))
            for i in range(12):
                p = make_tpu_pod(f"p{i:02d}", tpus=1)
                p.metadata.labels = {"par": str(i % 2)}
                cs.pods.create(p)
            for i in range(8):  # bind 8 of 12 across 2 nodes
                cs.bind(
                    "default", f"p{i:02d}",
                    _binding(f"p{i:02d}", f"n{i % 2}", [f"n{i % 2}-c{i}"]))
            reg = master.registry
            for sel in ("spec.nodeName=n0", "spec.nodeName=n1",
                        "spec.nodeName=", "spec.nodeName=ghost"):
                idx, _ = reg.list_raw(master.cacher, "pods", "default",
                                      field_selector=sel)
                scan, _ = reg.list_raw(master.store, "pods", "default",
                                       field_selector=sel)
                assert idx == scan, sel
            # combined requirements: index narrows on the equality, the
            # label + inequality requirements still filter the subset
            hits0 = reg.list_index_hits
            idx, _ = reg.list_raw(
                master.cacher, "pods", "default",
                label_selector="par=0",
                field_selector="spec.nodeName=n0,status.phase!=Failed")
            scan, _ = reg.list_raw(
                master.store, "pods", "default",
                label_selector="par=0",
                field_selector="spec.nodeName=n0,status.phase!=Failed")
            assert idx == scan and idx
            assert reg.list_index_hits == hits0 + 1
            # the HTTP path agrees with the registry
            pods, _ = cs.pods.list(namespace="default",
                                   field_selector="spec.nodeName=n0")
            assert {p.spec.node_name for p in pods} == {"n0"}
            assert len(pods) == 4  # p00..p07 bound alternating n0/n1
        finally:
            cs.close()
            master.stop()

    def test_indexed_equals_scan_under_concurrent_writes(self):
        """Churn (create/bind/delete) while reading through the index:
        every indexed snapshot satisfies the selector, and once writers
        stop the indexed result is exactly the scan result."""
        master = Master().start()
        cs = Clientset(master.url)
        stop = threading.Event()
        errors = []

        def writer(wid):
            try:
                k = 0
                while not stop.is_set():
                    name = f"w{wid}-{k}"
                    cs.pods.create(make_pod(name))
                    cs.bind("default", name, _binding(name, f"n{k % 3}"))
                    if k % 3 == 0:
                        cs.pods.delete(name)
                    k += 1
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        try:
            for i in range(3):
                cs.nodes.create(make_node(f"n{i}", tpus=8))
            threads = [threading.Thread(target=writer, args=(w,),
                                        daemon=True) for w in range(3)]
            for th in threads:
                th.start()
            reg = master.registry
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline:
                entries, _ = reg.list_entries(
                    master.cacher, "pods", "default",
                    field_selector="spec.nodeName=n1")
                for _k, _r, d in entries:
                    assert (d.get("spec") or {}).get("nodeName") == "n1"
            stop.set()
            for th in threads:
                th.join(timeout=10)
            assert not errors, errors
            for sel in ("spec.nodeName=n0", "spec.nodeName=n1",
                        "spec.nodeName=n2", "spec.nodeName="):
                idx, _ = reg.list_raw(master.cacher, "pods", "default",
                                      field_selector=sel)
                scan, _ = reg.list_raw(master.store, "pods", "default",
                                       field_selector=sel)
                assert idx == scan, sel
        finally:
            stop.set()
            cs.close()
            master.stop()

    def test_indexed_sharded_merge(self):
        """Per-shard indexes merge to the same result the sharded scan
        gives, with a composite rv."""
        master = Master(store_shards=2).start()
        cs = Clientset(master.url)
        try:
            for i in range(2):
                cs.nodes.create(make_node(f"n{i}", tpus=8))
            for i in range(10):
                cs.pods.create(make_tpu_pod(f"s{i:02d}", tpus=1))
                cs.bind("default", f"s{i:02d}",
                        _binding(f"s{i:02d}", f"n{i % 2}",
                                 [f"n{i % 2}-c{i}"]))
            reg = master.registry
            idx, rv_idx = reg.list_raw(master.cacher, "pods", "default",
                                       field_selector="spec.nodeName=n1")
            scan, _ = reg.list_raw(master.store, "pods", "default",
                                   field_selector="spec.nodeName=n1")
            assert idx == scan and len(idx) == 5
            assert "." in str(rv_idx)  # composite: one part per shard
        finally:
            cs.close()
            master.stop()

    @pytest.mark.slow
    def test_index_microbench_10x(self):
        """The acceptance number: at ≥30k pods the indexed spec.nodeName
        LIST is ≥10x faster than the full-scan path, identical results.
        (Measured ~2500x on the dev box; 10x leaves room for load.)"""
        from kubernetes1_tpu.apiserver.registry import Registry
        from kubernetes1_tpu.machinery.scheme import global_scheme
        from kubernetes1_tpu.storage import Cacher, Store

        scheme = global_scheme.copy()
        store = Store(scheme)
        reg = Registry(store, scheme)
        nodes, pods = 600, 30000
        ops = []
        for i in range(pods):
            p = t.Pod()
            p.metadata.name = f"p{i:05d}"
            p.metadata.namespace = "default"
            p.spec.containers = [t.Container(name="c", image="x")]
            p.spec.node_name = f"node-{i % nodes}"
            ops.append({"op": "create",
                        "key": f"/registry/pods/default/p{i:05d}",
                        "obj": scheme.encode(p)})
        for i in range(0, pods, 500):
            store.commit_batch(ops[i:i + 500])
        cacher = Cacher(store, scheme).start()
        try:
            sel = "spec.nodeName=node-7"
            idx, _ = reg.list_entries(cacher, "pods", "default",
                                      field_selector=sel)
            # scan forced through the cacher (inequality can't use the
            # index): the exact pre-index cost model on the same data
            scan_sel = "spec.nodeName!=__nobody__"

            def timed(fn, n):
                best = None
                for _ in range(n):
                    t0 = time.perf_counter()
                    fn()
                    dt = time.perf_counter() - t0
                    best = dt if best is None else min(best, dt)
                return best

            t_idx = timed(lambda: reg.list_entries(
                cacher, "pods", "default", field_selector=sel), 10)
            t_scan = timed(lambda: reg.list_entries(
                cacher, "pods", "default", field_selector=scan_sel), 3)
            assert len(idx) == pods // nodes
            assert t_scan / t_idx >= 10, \
                f"indexed {t_idx*1e3:.2f}ms vs scan {t_scan*1e3:.2f}ms"
        finally:
            cacher.stop()
            store.close()


class TestPaginatedList:
    def test_golden_bytes_no_limit(self):
        """shards=1 + no limit= must stay BYTE-identical to the
        historical response: head built from the literal format, items
        spliced from the per-revision serialization cache."""
        import http.client

        master = Master().start()
        cs = Clientset(master.url)
        try:
            for i in range(7):
                cs.pods.create(make_pod(f"g{i}"))
            conn = http.client.HTTPConnection(master.host, master.port)
            conn.request("GET", "/api/v1/namespaces/default/pods")
            body = conn.getresponse().read()
            conn.close()
            entries, rev = master.cacher.list_raw("/registry/pods/default/")
            assert isinstance(rev, int)  # plain rv — no composite leak
            head = ('{"kind":"PodList","apiVersion":"v1",'
                    '"metadata":{"resourceVersion":"%s"},"items":['
                    % rev).encode()
            expected = head + b",".join(
                master.scheme.encode_bytes(d, "v1")
                for _k, _r, d in entries) + b"]}"
            assert body == expected
            # and no continue key anywhere near the plain wire
            assert b'"continue"' not in body
        finally:
            cs.close()
            master.stop()

    def test_pages_union_to_unpaginated(self):
        master = Master().start()
        cs = Clientset(master.url)
        try:
            for i in range(11):
                cs.pods.create(make_pod(f"u{i:02d}"))
            whole, rv_whole = cs.pods.list(namespace="default")
            paged, rv_paged = cs.pods.list(namespace="default", limit=4)
            assert _names(paged) == _names(whole)
            # the paginated rv is the FIRST chunk's — presenting it to a
            # watch replays anything later chunks raced, so it must be a
            # real revision the server can serve
            w = cs.pods.watch(namespace="default",
                              resource_version=rv_paged)
            w.close()
            # chunk walk: 11 items at limit 4 = 3 pages, 2 continues
            rounds0 = master.registry.list_continue_rounds
            page, rv1, cont = cs.pods.list_page(namespace="default",
                                                limit=4)
            seen = list(page)
            while cont:
                page, _rv, cont = cs.pods.list_page(
                    namespace="default", limit=4, continue_token=cont)
                seen.extend(page)
            assert _names(seen) == _names(whole)
            assert master.registry.list_continue_rounds == rounds0 + 2
            # selector + pagination compose (index-narrowed chunk walk)
            sel_whole, _ = cs.pods.list(namespace="default",
                                        field_selector="spec.nodeName=")
            sel_paged, _ = cs.pods.list(namespace="default",
                                        field_selector="spec.nodeName=",
                                        limit=3)
            assert _names(sel_paged) == _names(sel_whole)
        finally:
            cs.close()
            master.stop()

    def test_limit_must_be_non_negative(self):
        """A negative limit is a client bug: 400, not a truncated page
        with a bogus continue token (or a 500 on an empty collection)."""
        from kubernetes1_tpu.machinery import ApiError

        master = Master().start()
        cs = Clientset(master.url)
        try:
            with pytest.raises(ApiError) as ei:
                cs.api.request("GET", "/api/v1/namespaces/default/pods",
                               params={"limit": "-1"})
            assert ei.value.code == 400
        finally:
            cs.close()
            master.stop()

    def test_stale_continue_token_410_clean_restart(self):
        """A token whose anchor revision fell below the watch-cache
        floor answers 410; the paginating client restarts and still
        returns the complete, current collection."""
        master = Master().start()
        cs = Clientset(master.url)
        try:
            for i in range(9):
                cs.pods.create(make_pod(f"s{i:02d}"))
            _page, _rv, cont = cs.pods.list_page(namespace="default",
                                                 limit=3)
            assert cont
            # age the anchor out of the cache window: shrink the history
            # ring and churn past it
            master.cacher._history_limit = 8
            for i in range(9, 29):
                cs.pods.create(make_pod(f"s{i:02d}"))
            with pytest.raises(TooOldResourceVersion):
                cs.pods.list_page(namespace="default", limit=3,
                                  continue_token=cont)
            # the auto-paginating list() restarts and converges: every
            # pod, exactly once
            items, _rv = cs.pods.list(namespace="default", limit=3)
            assert _names(items) == sorted(f"s{i:02d}" for i in range(29))
        finally:
            cs.close()
            master.stop()

    def test_informer_chunked_relist_lossless_under_churn(self):
        """An informer relisting in tiny chunks while the collection
        churns converges to the authoritative state: the watch resumes
        from the FIRST chunk's rv, so deletes/updates that raced later
        chunks replay instead of ghosting."""
        master = Master().start()
        cs = Clientset(master.url)
        inf = None
        stop = threading.Event()

        def churner():
            k = 0
            while not stop.is_set():
                name = f"c{k % 17:02d}"
                try:
                    if k % 3 == 2:
                        cs.pods.delete(name)
                    else:
                        cs.pods.create(make_pod(name))
                except Exception:  # noqa: BLE001 — create/delete races itself
                    pass
                k += 1

        try:
            for i in range(8):
                cs.pods.create(make_pod(f"c{i:02d}"))
            th = threading.Thread(target=churner, daemon=True)
            th.start()
            inf = SharedInformer(cs.pods, namespace="default",
                                 relist_limit=3).start()
            assert inf.wait_for_sync(10)
            time.sleep(1.0)  # churn across several chunked relists
            stop.set()
            th.join(timeout=10)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                want = {p.metadata.name
                        for p in cs.pods.list(namespace="default")[0]}
                got = {p.metadata.name for p in inf.list()}
                if want == got:
                    break
                time.sleep(0.1)
            assert want == got
        finally:
            stop.set()
            if inf is not None:
                inf.stop()
            cs.close()
            master.stop()


class TestBindStream:
    def _cluster(self, **cs_kw):
        master = Master().start()
        cs = Clientset(master.url, **cs_kw)
        cs.nodes.create(make_node("bn0", tpus=32))
        cs.nodes.create(make_node("bn1", tpus=32))
        return master, cs

    def _make_pods(self, cs, lo, hi):
        for i in range(lo, hi):
            cs.pods.create(make_tpu_pod(f"bs{i}", tpus=1))

    def _bindings(self, lo, hi, node="bn0"):
        return [_binding(f"bs{i}", node, [f"{node}-c{i}"])
                for i in range(lo, hi)]

    def test_outcomes_match_http_path(self):
        """Stream outcomes are the HTTP outcomes: successes bind, a
        real conflict (already bound elsewhere) surfaces per item, the
        stream stays up for the next round."""
        master, cs = self._cluster(bind_stream=True)
        try:
            self._make_pods(cs, 0, 4)
            f0 = bindstream_mod.bindstream_frames_total.value
            outcomes = cs.bind_batch("default", self._bindings(0, 4))
            assert outcomes == [None] * 4
            assert bindstream_mod.bindstream_frames_total.value == f0 + 1
            pod = cs.pods.get("bs0")
            assert pod.spec.node_name == "bn0"
            assert pod.spec.extended_resources[0].assigned == ["bn0-c0"]
            # second round on the SAME stream: rebinding bs0 to another
            # node is a per-item Conflict, neighbors still succeed
            self._make_pods(cs, 4, 6)
            mixed = ([_binding("bs0", "bn1", ["bn1-c0"])]
                     + self._bindings(4, 6))
            outcomes = cs.bind_batch("default", mixed)
            assert isinstance(outcomes[0], Conflict)
            assert outcomes[1:] == [None, None]
            assert bindstream_mod.bindstream_frames_total.value == f0 + 2
        finally:
            cs.close()
            master.stop()

    def test_fault_fallback_and_recovery(self):
        """Seeded sever on client.bindstream: the batch falls back to
        the per-request HTTP path (zero lost binds, fallback counted);
        after the redial floor the stream comes back."""
        master, cs = self._cluster(bind_stream=True)
        try:
            self._make_pods(cs, 0, 6)
            assert cs.bind_batch("default", self._bindings(0, 2)) \
                == [None, None]
            falls0 = bindstream_mod.bindstream_fallbacks_total.value
            faultline.activate(99, "client.bindstream=sever@1.0")
            try:
                outcomes = cs.bind_batch("default", self._bindings(2, 4))
            finally:
                faultline.deactivate()
            assert outcomes == [None, None]  # fell back, still bound
            assert bindstream_mod.bindstream_fallbacks_total.value \
                == falls0 + 1
            assert cs.pods.get("bs2").spec.node_name == "bn0"
            time.sleep(bindstream_mod.REDIAL_FLOOR_SECONDS + 0.1)
            f0 = bindstream_mod.bindstream_frames_total.value
            assert cs.bind_batch("default", self._bindings(4, 6)) \
                == [None, None]
            assert bindstream_mod.bindstream_frames_total.value == f0 + 1
        finally:
            cs.close()
            master.stop()

    def test_unsupported_server_sticky_fallback(self):
        """A server that answers the upgrade with a real status (an
        older apiserver's 404) is never probed again: the first batch
        falls back and later batches go straight to HTTP."""
        master, cs = self._cluster(bind_stream=True)
        try:
            calls = []

            def refusing_upgrade(path, proto, timeout=30.0):
                calls.append(path)
                raise UpgradeRefused("upgrade refused: HTTP/1.1 404", 404)

            cs._bind_stream.api = type(
                "_Api", (), {"upgrade": staticmethod(refusing_upgrade)})()
            self._make_pods(cs, 0, 4)
            assert cs.bind_batch("default", self._bindings(0, 2)) \
                == [None, None]
            assert cs._bind_stream.unsupported
            assert len(calls) == 1
            assert cs.bind_batch("default", self._bindings(2, 4)) \
                == [None, None]
            assert len(calls) == 1  # sticky: no second probe
        finally:
            cs.close()
            master.stop()

    def test_cross_namespace_binding_forbidden(self):
        """A bulk bind authorized against one namespace must not commit
        an item naming another (the authz check never looked there) —
        enforced identically on the stream round and the HTTP batch."""
        from kubernetes1_tpu.machinery import Forbidden

        master, cs = self._cluster(bind_stream=True)
        try:
            self._make_pods(cs, 0, 2)
            evil = _binding("bs0", "bn0", ["bn0-c0"], ns="other-ns")
            # stream path: the round errors, the fallback HTTP path gets
            # the same Forbidden — either way the caller sees the denial
            with pytest.raises(Forbidden):
                cs.bind_batch("default", [evil])
            # plain HTTP path (no stream) agrees
            cs2 = Clientset(master.url)
            try:
                with pytest.raises(Forbidden):
                    cs2.bind_batch("default", [evil])
            finally:
                cs2.close()
            assert not cs.pods.get("bs0").spec.node_name  # nothing landed
        finally:
            cs.close()
            master.stop()

    def test_stream_request_is_one_frame(self):
        """The wire shape: a json-codec round splices the caller's item
        bytes into ONE length-prefixed frame whose payload parses back
        to the envelope (no HTTP, no chunking, no re-walk drift)."""
        captured = []

        class _F:
            def send_payloads(self, payloads):
                captured.extend(payloads)
                raise ConnectionError("capture only")

        bs = bindstream_mod.BindStream.__new__(bindstream_mod.BindStream)
        bs.codec_id = "json"
        bs._local = threading.local()
        bs._local.framer = _F()
        bs._socks = []
        import kubernetes1_tpu.utils.locksan as locksan

        bs._socks_lock = locksan.make_lock("test.bindstream")
        items = [{"kind": "Binding", "apiVersion": "v1",
                  "metadata": {"name": f"x{i}"}} for i in range(3)]
        with pytest.raises(ConnectionError):
            bs.bind_batch("default", items)
        assert len(captured) == 1
        env = json.loads(captured[0])
        assert env == {"namespace": "default", "items": items}
