"""Eviction subresource + PDB enforcement + PDB-aware preemption (ref:
pkg/registry/core/pod/storage/eviction.go:57, kubectl drain,
scheduler.go:209-250 preemption, and the disruption e2e suite).

The VERDICT r3 'done' bar: a high-priority gang evicts a low-priority gang
while a PDB-protected service survives; drain goes through eviction; the
nominated node is reserved for the preemptor."""

import io
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.cli import CLI
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.controllers import ControllerManager
from kubernetes1_tpu.machinery import NotFound, TooManyRequests
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.helpers import make_tpu_pod
from tests.test_controllers import start_hollow_node


@pytest.fixture()
def cluster(tmp_path):
    """2 TPU hosts (4 chips each, one slice) + controllers."""
    master = Master().start()
    cs = Clientset(master.url)
    sched = Scheduler(cs, gang_wait_seconds=5.0)
    sched.start()
    cm = ControllerManager(cs, monitor_grace=5.0, eviction_timeout=5.0)
    cm.start()
    nodes = [
        start_hollow_node(cs, f"tpu-{i}", str(tmp_path), tpus=4,
                          slice_id="s0", host_index=i)
        for i in range(2)
    ]
    env = {"master": master, "cs": cs, "sched": sched}
    yield env
    for kubelet, plugin, _ in nodes:
        kubelet.stop()
        plugin.stop()
    cm.stop()
    sched.stop()
    cs.close()
    master.stop()


def _pdb(name, selector_labels, min_available):
    pdb = t.PodDisruptionBudget()
    pdb.metadata.name = name
    pdb.spec.selector = t.LabelSelector(match_labels=selector_labels)
    pdb.spec.min_available = min_available
    return pdb


def _wait_running(cs, selector, n, timeout=30.0):
    def check():
        pods, _ = cs.pods.list(label_selector=selector)
        return len([p for p in pods if p.status.phase == t.POD_RUNNING
                    and not p.metadata.deletion_timestamp]) == n
    must_poll_until(check, timeout=timeout, desc=f"{n} running for {selector}")


class TestEvictionSubresource:
    def test_eviction_respects_pdb_with_429(self, cluster):
        cs = cluster["cs"]
        for i in range(3):
            p = make_tpu_pod(f"web-{i}", tpus=0)
            p.metadata.labels = {"app": "web"}
            p.spec.containers[0].command = ["serve"]
            cs.pods.create(p)
        _wait_running(cs, "app=web", 3)
        cs.poddisruptionbudgets.create(_pdb("web-pdb", {"app": "web"}, 2))
        must_poll_until(
            lambda: cs.poddisruptionbudgets.get("web-pdb", "default")
            .status.disruptions_allowed == 1,
            timeout=15.0, desc="PDB status settles",
        )
        # first eviction consumes the budget
        cs.evict("default", "web-0")
        # the second is rejected 429 until the replacement becomes healthy
        with pytest.raises(TooManyRequests, match="disruption budget"):
            cs.evict("default", "web-1")
        # pods without any PDB evict freely
        lone = make_tpu_pod("lone", tpus=0)
        lone.spec.containers[0].command = ["serve"]
        cs.pods.create(lone)
        _wait_running(cs, "", 3 + 1 - 1, timeout=30.0)  # web-1, web-2, lone (+web-0 gone)
        cs.evict("default", "lone")

    def test_drain_retries_pdb_blocked_evictions(self, cluster):
        cs, master = cluster["cs"], cluster["master"]
        for i in range(2):
            p = make_tpu_pod(f"svc-{i}", tpus=0)
            p.metadata.labels = {"app": "svc"}
            p.spec.containers[0].command = ["serve"]
            # pin one pod per node for a deterministic drain
            p.spec.node_name = f"tpu-{i}"
            cs.pods.create(p)
        _wait_running(cs, "app=svc", 2)
        cs.poddisruptionbudgets.create(_pdb("svc-pdb", {"app": "svc"}, 2))
        must_poll_until(
            lambda: cs.poddisruptionbudgets.get("svc-pdb", "default")
            .status.expected_pods == 2,
            timeout=15.0, desc="PDB status",
        )
        out = io.StringIO()
        cli = CLI(master.url, "default", out=out)
        # minAvailable=2 of 2 -> no disruptions allowed -> drain must fail
        # loudly rather than deleting around the budget
        with pytest.raises(SystemExit):
            cli.drain(type("A", (), {"node": "tpu-0", "force": False, "timeout": 3})())
        cli.cs.close()
        text = out.getvalue()
        assert "NOT evicted" in text and "disruption budget" in text
        assert "drain INCOMPLETE" in text
        assert cs.pods.get("svc-0", "default") is not None


class TestPreemption:
    def test_preemption_respects_pdb(self, cluster):
        """A high-priority pod must NOT preempt victims whose PDB has no
        budget — even when that leaves it pending."""
        cs = cluster["cs"]
        # fill both nodes' chips with protected low-priority pods
        for i in range(2):
            p = make_tpu_pod(f"prot-{i}", tpus=4, priority=-10)
            p.metadata.labels = {"app": "prot"}
            p.spec.containers[0].command = ["serve"]
            cs.pods.create(p)
        _wait_running(cs, "app=prot", 2)
        cs.poddisruptionbudgets.create(_pdb("prot-pdb", {"app": "prot"}, 2))
        must_poll_until(
            lambda: cs.poddisruptionbudgets.get("prot-pdb", "default")
            .status.expected_pods == 2,
            timeout=15.0, desc="PDB status",
        )
        high = make_tpu_pod("vip", tpus=4, priority=100)
        high.spec.containers[0].command = ["serve"]
        cs.pods.create(high)
        time.sleep(4.0)
        pods, _ = cs.pods.list(label_selector="app=prot")
        assert len([p for p in pods if not p.metadata.deletion_timestamp]) == 2, \
            "PDB-protected victims were preempted"
        assert not cs.pods.get("vip", "default").spec.node_name

    def test_preemptor_lands_on_nominated_node(self, cluster):
        cs = cluster["cs"]
        victims = []
        for i in range(2):
            p = make_tpu_pod(f"low-{i}", tpus=4, priority=-10)
            p.metadata.labels = {"app": "low"}
            p.spec.containers[0].command = ["serve"]
            cs.pods.create(p)
            victims.append(p)
        _wait_running(cs, "app=low", 2)
        high = make_tpu_pod("boss", tpus=4, priority=100)
        high.spec.containers[0].command = ["serve"]
        cs.pods.create(high)

        def bound():
            p = cs.pods.get("boss", "default")
            return bool(p.spec.node_name)

        must_poll_until(bound, timeout=30.0, desc="preemptor binds")
        boss = cs.pods.get("boss", "default")
        # it bound to real freed chips
        assert len(boss.spec.extended_resources[0].assigned) == 4
        # exactly one victim fell (fewest-victims search), via eviction
        pods, _ = cs.pods.list(label_selector="app=low")
        alive = [p for p in pods if not p.metadata.deletion_timestamp]
        assert len(alive) == 1


class TestGangPreemption:
    def test_high_priority_gang_evicts_low_priority_gang_pdb_service_survives(
        self, cluster
    ):
        cs = cluster["cs"]
        # PDB-protected service pod on one node (cpu only, no chips)
        svc = make_tpu_pod("frontend", tpus=0)
        svc.metadata.labels = {"app": "frontend"}
        svc.spec.containers[0].command = ["serve"]
        cs.pods.create(svc)
        _wait_running(cs, "app=frontend", 1)
        cs.poddisruptionbudgets.create(_pdb("fe-pdb", {"app": "frontend"}, 1))
        must_poll_until(
            lambda: cs.poddisruptionbudgets.get("fe-pdb", "default")
            .status.expected_pods == 1,
            timeout=15.0, desc="PDB status",
        )
        # low-priority gang occupies all 8 chips
        for i in range(2):
            p = make_tpu_pod(f"lowgang-{i}", tpus=4, priority=-100,
                             gang="low", gang_size=2)
            p.metadata.labels = {"app": "lowgang"}
            p.spec.containers[0].command = ["serve"]
            cs.pods.create(p)
        _wait_running(cs, "app=lowgang", 2)
        # high-priority gang needs those same 8 chips
        for i in range(2):
            p = make_tpu_pod(f"higang-{i}", tpus=4, priority=100,
                             gang="hi", gang_size=2)
            p.metadata.labels = {"app": "higang"}
            p.spec.containers[0].command = ["serve"]
            cs.pods.create(p)
        _wait_running(cs, "app=higang", 2, timeout=60.0)
        # the low gang fell as a unit
        pods, _ = cs.pods.list(label_selector="app=lowgang")
        assert not [p for p in pods if not p.metadata.deletion_timestamp]
        # the PDB-protected frontend never flinched
        fe = cs.pods.get("frontend", "default")
        assert fe.status.phase == t.POD_RUNNING
        assert not fe.metadata.deletion_timestamp
