"""Streaming exec/attach/port-forward through the apiserver (ref:
pkg/kubelet/server/remotecommand, client-go/tools/remotecommand,
registry/core/pod/rest/subresources.go — SPDY there, the ktpu-stream
channel protocol here).

Security posture under test (ADVICE r2 medium): the kubelet token lives in
a kube-system Secret, not a Node annotation; every workload-facing kubelet
endpoint requires it; clients only ever talk to the apiserver, which
authorizes per-verb on the pods/exec style subresources."""

import io
import json
import socket
import sys
import threading
import time
import urllib.request

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.cli import CLI
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.kubelet import Kubelet, ProcessRuntime
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils import streams
from kubernetes1_tpu.utils.waitutil import must_poll_until


@pytest.fixture()
def env(tmp_path):
    master = Master().start()
    cs = Clientset(master.url)
    sched = Scheduler(cs)
    sched.start()
    runtime = ProcessRuntime(root_dir=str(tmp_path / "ktpu"))
    kubelet = Kubelet(
        cs, node_name="stream-node", runtime=runtime,
        plugin_dir=str(tmp_path / "plugins"),
        heartbeat_interval=0.5, sync_interval=0.3, pleg_interval=0.3,
    )
    kubelet.start()
    e = {"master": master, "cs": cs, "kubelet": kubelet, "tmp": tmp_path}
    yield e
    kubelet.stop()
    runtime.kill_all()  # containers must not outlive the fixture
    sched.stop()
    cs.close()
    master.stop()


def run_pod(cs, name, code, restart="Never"):
    pod = t.Pod()
    pod.metadata.name = name
    pod.spec.restart_policy = restart
    pod.spec.containers = [
        t.Container(name="main", image="python",
                    command=[sys.executable, "-u", "-c", code])
    ]
    cs.pods.create(pod)
    must_poll_until(
        lambda: cs.pods.get(name, "default").status.phase == t.POD_RUNNING,
        timeout=20.0, desc=f"{name} running",
    )
    return cs.pods.get(name, "default")


def cli_for(master, out=None):
    return CLI(master.url, "default", out=out or io.StringIO())


class TestExec:
    def test_exec_streams_output_and_exit_code(self, env):
        run_pod(env["cs"], "worker", "import time; time.sleep(60)")
        out = io.StringIO()
        cli = cli_for(env["master"], out)
        cli.exec_(type("A", (), {
            "pod": "worker", "container": "",
            "command": [sys.executable, "-c", "print('from-exec')"],
        })())
        cli.cs.close()
        assert "from-exec" in out.getvalue()

    def test_exec_nonzero_exit_code_raises(self, env):
        run_pod(env["cs"], "worker2", "import time; time.sleep(60)")
        cli = cli_for(env["master"])
        with pytest.raises(SystemExit) as exc:
            cli.exec_(type("A", (), {
                "pod": "worker2", "container": "",
                "command": [sys.executable, "-c", "raise SystemExit(7)"],
            })())
        cli.cs.close()
        assert exc.value.code == 7

    def test_exec_interactive_stdin(self, env):
        """-i: stdin frames reach the exec'd process (cat echoes them)."""
        run_pod(env["cs"], "worker3", "import time; time.sleep(60)")
        out = io.StringIO()
        cli = cli_for(env["master"], out)
        stdin_stream = io.BytesIO(b"hello-stdin\n")
        cli.exec_(type("A", (), {
            "pod": "worker3", "container": "", "stdin": True,
            "stdin_stream": stdin_stream,
            "command": [sys.executable, "-c",
                        "import sys; sys.stdout.write(sys.stdin.readline())"],
        })())
        cli.cs.close()
        assert "hello-stdin" in out.getvalue()

    def test_exec_tty_allocates_terminal(self, env):
        run_pod(env["cs"], "worker4", "import time; time.sleep(60)")
        out = io.StringIO()
        cli = cli_for(env["master"], out)
        cli.exec_(type("A", (), {
            "pod": "worker4", "container": "", "tty": True,
            "command": [sys.executable, "-c",
                        "import sys; print('tty?', sys.stdout.isatty())"],
        })())
        cli.cs.close()
        assert "tty? True" in out.getvalue()

    def test_exec_sees_container_env(self, env):
        """The exec'd process runs with the container's env (device
        injection included) — the reference's CRI Exec contract."""
        cs = env["cs"]
        pod = t.Pod()
        pod.metadata.name = "envpod"
        pod.spec.restart_policy = "Never"
        pod.spec.containers = [
            t.Container(name="main", image="python",
                        command=[sys.executable, "-c", "import time; time.sleep(60)"],
                        env=[t.EnvVar(name="MARKER", value="xyz42")])
        ]
        cs.pods.create(pod)
        must_poll_until(
            lambda: cs.pods.get("envpod", "default").status.phase == t.POD_RUNNING,
            timeout=20.0, desc="envpod running",
        )
        out = io.StringIO()
        cli = cli_for(env["master"], out)
        cli.exec_(type("A", (), {
            "pod": "envpod", "container": "",
            "command": [sys.executable, "-c",
                        "import os; print(os.environ['MARKER'])"],
        })())
        cli.cs.close()
        assert "xyz42" in out.getvalue()


class TestLogsAndAttach:
    def test_logs_proxy_through_apiserver(self, env):
        cs = env["cs"]
        run_pod(cs, "logger",
                "print('log-line-1'); print('log-line-2');"
                "import time; time.sleep(60)")
        cli = cli_for(env["master"])

        def logs_text():
            out = io.StringIO()
            cli.out = out
            cli.logs(type("A", (), {"pod": "logger", "container": "", "tail": 0})())
            return out.getvalue()

        # the workload interpreter takes a beat to start; poll
        must_poll_until(lambda: "log-line-1" in logs_text(), timeout=15.0,
                        desc="log content via apiserver")
        cli.cs.close()

    def test_attach_follows_live_output(self, env):
        from urllib.parse import urlparse

        cs = env["cs"]
        run_pod(cs, "chatty",
                "import time\nfor i in range(100):\n print('tick', i, flush=True)\n time.sleep(0.2)")
        base = urlparse(env["master"].url)
        sock = streams.upgrade_request(
            base.hostname, base.port,
            "/api/v1/namespaces/default/pods/chatty/attach", {})
        got = b""
        deadline = time.time() + 10
        while time.time() < deadline and b"tick" not in got:
            frame = streams.read_frame(sock)
            if frame is None:
                break
            ch, payload = frame
            if ch == streams.STDOUT:
                got += payload
        sock.close()
        assert b"tick" in got


class TestPortForward:
    def test_port_forward_relays_tcp(self, env):
        cs = env["cs"]
        # in-pod HTTP server on a fixed port
        run_pod(cs, "server-pod",
                "import http.server\n"
                "http.server.HTTPServer(('127.0.0.1', 18761), "
                "http.server.SimpleHTTPRequestHandler).serve_forever()")
        # wait for the in-pod server to actually listen (interpreter startup
        # takes a beat)
        def pod_server_up():
            try:
                socket.create_connection(("127.0.0.1", 18761), timeout=0.5).close()
                return True
            except OSError:
                return False

        must_poll_until(pod_server_up, timeout=20.0, desc="in-pod http server")
        out = io.StringIO()
        cli = cli_for(env["master"], out)
        th = threading.Thread(
            target=cli.port_forward,
            args=(type("A", (), {"pod": "server-pod", "ports": "0:18761",
                                 "connections": 1})(),),
            daemon=True,
        )
        th.start()
        must_poll_until(lambda: hasattr(cli, "_pf_listener"), timeout=5.0,
                        desc="listener up")
        port = cli._pf_listener.getsockname()[1]
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/", timeout=10) as r:
            assert r.status == 200
        th.join(timeout=5)
        cli._pf_listener.close()
        cli.cs.close()


class TestSecurity:
    def test_kubelet_endpoints_require_token(self, env):
        """Direct kubelet access without the token is denied — the only
        open doors are healthz and metrics (ADVICE r2)."""
        kl = env["kubelet"]
        base = kl.server.url
        for path in ("/pods", "/stats/summary", "/containerLogs/default/x/y"):
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(base + path, timeout=5)
            assert e.value.code == 401
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            assert r.status == 200

    def test_token_not_in_node_annotations(self, env):
        node = env["cs"].nodes.get("stream-node", "")
        assert "kubelet.ktpu.io/exec-token" not in node.metadata.annotations
        sec = env["cs"].secrets.get("kubelet-token-stream-node", "kube-system")
        assert sec.data["token"] == env["kubelet"].server_token

    def test_rbac_denies_exec_without_subresource_grant(self, tmp_path):
        """A role granting get/list on pods does NOT grant pods/exec —
        upstream subresource semantics."""
        from urllib.parse import urlparse

        master = Master(
            authorization_mode="Node,RBAC",
            static_tokens={
                "admin-tok": ("system:admin", ["system:masters"]),
                "alice-tok": ("alice", []),
            },
        ).start()
        try:
            admin = Clientset(master.url, token="admin-tok")
            runtime = ProcessRuntime(root_dir=str(tmp_path / "kt"))
            # register a node + pod so exec has a target
            kubelet = Kubelet(admin, node_name="n1", runtime=runtime,
                              plugin_dir=str(tmp_path / "p"),
                              heartbeat_interval=0.5, sync_interval=0.3,
                              pleg_interval=0.3)
            kubelet.start()
            sched = Scheduler(admin)
            sched.start()
            pod = t.Pod()
            pod.metadata.name = "target"
            pod.spec.restart_policy = "Never"
            pod.spec.containers = [
                t.Container(name="m", image="python",
                            command=[sys.executable, "-c",
                                     "import time; time.sleep(60)"])]
            admin.pods.create(pod)
            must_poll_until(
                lambda: admin.pods.get("target", "default").status.phase
                == t.POD_RUNNING, timeout=20.0, desc="target running")

            # a user with pods read access but no pods/exec
            role = t.Role()
            role.metadata.name = "viewer"
            role.metadata.namespace = "default"
            role.rules = [t.PolicyRule(verbs=["get", "list"], resources=["pods"])]
            admin.roles.create(role, "default")
            rb = t.RoleBinding()
            rb.metadata.name = "viewer-b"
            rb.metadata.namespace = "default"
            rb.subjects = [t.Subject(kind="User", name="alice")]
            rb.role_ref = t.RoleRef(kind="Role", name="viewer")
            admin.rolebindings.create(rb, "default")
            alice_token = "alice-tok"
            base = urlparse(master.url)
            with pytest.raises(ConnectionError, match="403|Forbidden"):
                streams.upgrade_request(
                    base.hostname, base.port,
                    "/api/v1/namespaces/default/pods/target/exec"
                    "?command=id",
                    {"Authorization": f"Bearer {alice_token}"})
            # granting the subresource opens it
            role.rules.append(t.PolicyRule(verbs=["get"], resources=["pods/exec"]))
            admin.roles.update(role)
            sock = streams.upgrade_request(
                base.hostname, base.port,
                "/api/v1/namespaces/default/pods/target/exec"
                f"?command={sys.executable}&command=-c&command=print(1)",
                {"Authorization": f"Bearer {alice_token}"})
            frames = []
            while True:
                f = streams.read_frame(sock)
                if f is None:
                    break
                frames.append(f)
                if f[0] == streams.ERROR:
                    break
            sock.close()
            status = json.loads(
                next(p for c, p in frames if c == streams.ERROR))
            assert status["exitCode"] == 0
        finally:
            # in finally, or an assertion failure above leaks the kubelet,
            # scheduler, and the pod's sleep process (and the leak police
            # would bury the real failure under its own)
            kubelet.stop()
            runtime.kill_all()  # containers must not outlive the test
            sched.stop()
            admin.close()
            master.stop()


class TestCp:
    def test_cp_both_directions(self, env, tmp_path):
        """`ktpu cp` rides the exec stream (ref kubectl cp over SPDY exec):
        local -> pod writes through `cat > path`, pod -> local reads
        `cat path` — binary-safe both ways."""
        run_pod(env["cs"], "cp-pod", "import time; time.sleep(60)")
        payload = bytes(range(256)) * 64  # binary: every byte value
        src = tmp_path / "in.bin"
        src.write_bytes(payload)
        cli = cli_for(env["master"])
        try:
            remote = str(tmp_path / "remote.bin")  # host-process runtime:
            # the pod's fs IS the host fs, so any absolute path works
            cli.cp(type("A", (), {
                "src": str(src), "dst": f"cp-pod:{remote}",
                "container": "",
            })())
            back = tmp_path / "back.bin"
            cli.cp(type("A", (), {
                "src": f"cp-pod:{remote}", "dst": str(back),
                "container": "",
            })())
            assert back.read_bytes() == payload
        finally:
            cli.cs.close()
