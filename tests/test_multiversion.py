"""Multi-version API serving + conversion (ref: runtime.Scheme conversion;
the reference serves Deployment at extensions/v1beta1 AND apps/* with
generated Convert_* funcs; SURVEY L1 'Scheme (convert/default/serialize)')."""

import json
import urllib.request

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.machinery.scheme import global_scheme


@pytest.fixture
def env():
    master = Master().start()
    cs = Clientset(master.url)
    yield master, cs
    cs.close()
    master.stop()


def _req(url, method="GET", body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


V1BETA1_DEPLOY = {
    "kind": "Deployment", "apiVersion": "extensions/v1beta1",
    "metadata": {"name": "legacy", "namespace": "default"},
    "spec": {
        # no selector: v1beta1 defaults it from template labels
        "replicas": 2,
        "rollbackTo": {"revision": 3},  # deprecated field: accepted, dropped
        "template": {
            "metadata": {"labels": {"app": "legacy"}},
            "spec": {"containers": [{"name": "c", "image": "img",
                                     "command": ["sleep", "60"]}]},
        },
    },
}


class TestServedVersions:
    def test_scheme_lists_versions(self):
        assert set(global_scheme.served_versions("Deployment")) == {
            "apps/v1", "extensions/v1beta1"}

    def test_create_via_v1beta1_reads_back_converted(self, env):
        master, cs = env
        out = _req(f"{master.url}/apis/extensions/v1beta1/namespaces/default"
                   f"/deployments", "POST", V1BETA1_DEPLOY)
        # response comes back in the REQUESTED version
        assert out["apiVersion"] == "extensions/v1beta1"
        # internally it is the hub version with the selector defaulted
        internal = cs.deployments.get("legacy")
        assert internal.API_VERSION == "apps/v1"
        assert internal.spec.selector.match_labels == {"app": "legacy"}
        assert internal.spec.replicas == 2

    def test_hub_read_at_both_versions(self, env):
        master, cs = env
        _req(f"{master.url}/apis/extensions/v1beta1/namespaces/default"
             f"/deployments", "POST", V1BETA1_DEPLOY)
        hub = _req(f"{master.url}/apis/apps/v1/namespaces/default"
                   f"/deployments/legacy")
        assert hub["apiVersion"] == "apps/v1"
        assert hub["spec"]["selector"]["matchLabels"] == {"app": "legacy"}
        legacy = _req(f"{master.url}/apis/extensions/v1beta1/namespaces"
                      f"/default/deployments/legacy")
        assert legacy["apiVersion"] == "extensions/v1beta1"
        # round-trip elides the defaulted selector on the way out
        assert "selector" not in legacy["spec"]

    def test_cronjob_v1beta1_alias(self, env):
        master, cs = env
        body = {
            "kind": "CronJob", "apiVersion": "batch/v1beta1",
            "metadata": {"name": "nightly", "namespace": "default"},
            "spec": {"schedule": "0 3 * * *", "suspend": True,
                     "jobTemplate": {"spec": {"template": {"spec": {
                         "containers": [{"name": "c", "image": "i",
                                         "command": ["true"]}]}}}}},
        }
        out = _req(f"{master.url}/apis/batch/v1beta1/namespaces/default"
                   f"/cronjobs", "POST", body)
        assert out["apiVersion"] == "batch/v1beta1"
        assert cs.cronjobs.get("nightly").spec.schedule == "0 3 * * *"

    def test_explicit_selector_preserved(self, env):
        master, _ = env
        body = json.loads(json.dumps(V1BETA1_DEPLOY))
        body["metadata"]["name"] = "explicit"
        body["spec"]["selector"] = {"matchLabels": {"app": "legacy",
                                                    "tier": "x"}}
        body["spec"]["template"]["metadata"]["labels"] = {
            "app": "legacy", "tier": "x"}
        _req(f"{master.url}/apis/extensions/v1beta1/namespaces/default"
             f"/deployments", "POST", body)
        hub = _req(f"{master.url}/apis/apps/v1/namespaces/default"
                   f"/deployments/explicit")
        assert hub["spec"]["selector"]["matchLabels"] == {
            "app": "legacy", "tier": "x"}


class TestConversionEdgeCases:
    def test_match_expressions_selector_round_trips(self, env):
        """A matchExpressions selector must never be replaced or elided by
        v1beta1 selector defaulting."""
        master, cs = env
        body = json.loads(json.dumps(V1BETA1_DEPLOY))
        body["metadata"]["name"] = "expr"
        body["spec"]["selector"] = {
            "matchExpressions": [{"key": "app", "operator": "In",
                                  "values": ["legacy"]}]}
        _req(f"{master.url}/apis/extensions/v1beta1/namespaces/default"
             f"/deployments", "POST", body)
        hub = _req(f"{master.url}/apis/apps/v1/namespaces/default"
                   f"/deployments/expr")
        assert hub["spec"]["selector"].get("matchExpressions")
        assert "matchLabels" not in hub["spec"]["selector"]
        legacy = _req(f"{master.url}/apis/extensions/v1beta1/namespaces"
                      f"/default/deployments/expr")
        assert legacy["spec"]["selector"].get("matchExpressions")

    def test_watch_frames_in_requested_version(self, env):
        import threading
        import urllib.request as _ur

        master, _ = env
        frames = []

        def watcher():
            req = _ur.Request(
                f"{master.url}/apis/extensions/v1beta1/namespaces/default"
                f"/deployments?watch=1&timeoutSeconds=5")
            with _ur.urlopen(req) as r:
                for line in r:
                    line = line.strip()
                    if line:
                        frames.append(json.loads(line))
                        return

        th = threading.Thread(target=watcher, daemon=True)
        th.start()
        import time as _t

        _t.sleep(0.3)
        _req(f"{master.url}/apis/extensions/v1beta1/namespaces/default"
             f"/deployments", "POST", V1BETA1_DEPLOY)
        th.join(timeout=10)
        assert frames and frames[0]["object"]["apiVersion"] == \
            "extensions/v1beta1"
