"""Watch-cache semantics: fresh reads, exact resume, 410 floor, slow-watcher
eviction at both the cache and store layers, and informer 410-recovery."""

import threading
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.machinery import (
    ADDED,
    DELETED,
    MODIFIED,
    TooOldResourceVersion,
)
from kubernetes1_tpu.machinery.scheme import global_scheme
from kubernetes1_tpu.storage import Store
from kubernetes1_tpu.storage.cacher import Cacher, key_for_dict

from tests.test_machinery import make_pod


@pytest.fixture
def store():
    s = Store(global_scheme)
    yield s
    s.close()


# both feed modes must expose identical semantics: synchronous commit-hook
# feeding (in-process store, the Master default) and the watch-fed pump
# (remote stores)
@pytest.fixture(params=["sync", "pump"])
def feed_mode(request):
    return request.param


def make_cacher(store, feed_mode="sync", **kw):
    return Cacher(store, global_scheme,
                  force_watch_feed=(feed_mode == "pump"), **kw).start()


def key(pod):
    return f"/registry/pods/{pod.metadata.namespace}/{pod.metadata.name}"


class TestCacherReads:
    def test_list_serves_preexisting_state(self, store, feed_mode):
        for i in range(3):
            store.create(key(make_pod(f"p{i}")), make_pod(f"p{i}"))
        c = make_cacher(store, feed_mode)
        try:
            entries, rev = c.list_raw("/registry/pods/default/")
            assert [e[2]["metadata"]["name"] for e in entries] == \
                ["p0", "p1", "p2"]
            assert rev == store.current_revision()
        finally:
            c.stop()

    def test_read_your_write_freshness(self, store, feed_mode):
        c = make_cacher(store, feed_mode)
        try:
            # every write must be visible to the immediately-following
            # read, even though the cache is fed asynchronously
            for i in range(20):
                pod = make_pod(f"rw{i}")
                store.create(key(pod), pod)
                raw = c.get_raw(key(pod))
                assert raw is not None and \
                    raw["metadata"]["name"] == f"rw{i}"
        finally:
            c.stop()

    def test_get_raw_missing_is_none(self, store):
        c = make_cacher(store)
        try:
            assert c.get_raw("/registry/pods/default/nope") is None
        finally:
            c.stop()

    def test_delete_removes_from_cache(self, store, feed_mode):
        c = make_cacher(store, feed_mode)
        try:
            pod = make_pod("gone")
            store.create(key(pod), pod)
            store.delete(key(pod))
            assert c.get_raw(key(pod)) is None
            entries, _ = c.list_raw("/registry/pods/default/")
            assert entries == []
        finally:
            c.stop()


class TestCacherWatch:
    def test_resume_from_revision_returns_exactly_missed_events(self, store, feed_mode):
        c = make_cacher(store, feed_mode)
        try:
            store.create(key(make_pod("a")), make_pod("a"))
            _, rev = c.list_raw("/registry/pods/")
            store.create(key(make_pod("b")), make_pod("b"))
            fresh = store.get(key(make_pod("b")))
            fresh.spec.node_name = "n1"
            store.update_cas(key(make_pod("b")), fresh)
            store.delete(key(make_pod("a")))
            w = c.watch("/registry/pods/", since_rev=rev)
            evs = [w.next_timeout(2) for _ in range(3)]
            assert [(e.type, e.object["metadata"]["name"]) for e in evs] == \
                [(ADDED, "b"), (MODIFIED, "b"), (DELETED, "a")]
            # exactly the missed events: nothing more queued
            assert w.next_timeout(0.2) is None
            # revision order is strict
            revs = [int(e.object["metadata"]["resourceVersion"])
                    for e in evs]
            assert revs == sorted(revs) and revs[0] > rev
            w.stop()
        finally:
            c.stop()

    def test_resume_below_floor_is_410_and_relist_recovers(self, store, feed_mode):
        c = make_cacher(store, feed_mode, history_limit=4)
        try:
            for i in range(10):
                store.create(key(make_pod(f"p{i}")), make_pod(f"p{i}"))
            c.wait_fresh()
            with pytest.raises(TooOldResourceVersion):
                c.watch("/registry/pods/", since_rev=1)
            # the relist + re-watch path recovers cleanly
            entries, rev = c.list_raw("/registry/pods/default/")
            assert len(entries) == 10
            w = c.watch("/registry/pods/", since_rev=rev)
            store.create(key(make_pod("p10")), make_pod("p10"))
            ev = w.next_timeout(2)
            assert ev.type == ADDED
            assert ev.object["metadata"]["name"] == "p10"
            w.stop()
        finally:
            c.stop()

    def test_slow_watcher_evicted_with_410(self, store, feed_mode):
        c = make_cacher(store, feed_mode)
        try:
            w = c.watch("/registry/pods/", queue_limit=3)
            # sustained traffic, not a fixed burst: a pump-mode feed may
            # coalesce many commits into ONE delivery batch (the
            # documented queue-bound overshoot), and an over-limit
            # watcher is only evicted when the NEXT push finds it still
            # undrained — so publish until that push lands
            n = 0
            deadline = time.monotonic() + 10
            while not w.evicted and time.monotonic() < deadline:
                store.create(key(make_pod(f"s{n}")), make_pod(f"s{n}"))
                n += 1
                time.sleep(0.01)
            assert w.evicted
            assert c.watch_evictions == 1
            # queued events (a prefix of the stream, in order) still
            # drain, then the stream ends
            got = []
            while True:
                ev = w.next_timeout(1)
                if ev is None:
                    break
                got.append(ev.object["metadata"]["name"])
            assert got == [f"s{i}" for i in range(len(got))]
            # the evicting push was dropped, so the slow consumer kept a
            # strict prefix of the stream, never the whole thing
            assert len(got) < n
            # the cacher itself keeps serving; new watchers are unaffected
            entries, rev = c.list_raw("/registry/pods/default/")
            assert len(entries) == n
        finally:
            c.stop()

    def test_feed_death_reseeds_and_evicts_open_watchers(self, store):
        c = make_cacher(store, "pump")
        try:
            c.wait_fresh()
            w = c.watch("/registry/pods/")
            # kill the internal feed: the pump must reseed and 410 the
            # open watcher (it may have a gap it can't prove it doesn't)
            c._feed.stop()
            deadline = time.monotonic() + 5
            while not w.evicted and time.monotonic() < deadline:
                time.sleep(0.01)
            assert w.evicted
            assert w.next_timeout(1) is None  # stream ended
            # post-reseed the cache still answers fresh reads
            pod = make_pod("after-reseed")
            store.create(key(pod), pod)
            assert c.get_raw(key(pod)) is not None
            assert c.reseeds >= 1
        finally:
            c.stop()


class TestKeyForDict:
    def test_namespaced_cluster_scoped_and_unknown(self):
        pod = global_scheme.encode(make_pod("k1"))
        assert key_for_dict(global_scheme, pod) == \
            "/registry/pods/default/k1"
        node = t.Node()
        node.metadata.name = "n1"
        assert key_for_dict(global_scheme, global_scheme.encode(node)) == \
            "/registry/nodes/n1"
        assert key_for_dict(global_scheme, {"kind": "NoSuchKind",
                                            "metadata": {"name": "x"}}) is None
        assert key_for_dict(global_scheme, {"kind": "Pod",
                                            "metadata": {}}) is None


class TestStoreWatcherBounds:
    def test_store_watcher_evicted_on_overflow(self, store):
        w = store.watch("/registry/pods/", queue_limit=2)
        for i in range(6):
            store.create(key(make_pod(f"b{i}")), make_pod(f"b{i}"))
        assert w.evicted
        assert store.watch_evictions == 1
        got = []
        while True:
            ev = w.next_timeout(1)
            if ev is None:
                break
            got.append(ev.object["metadata"]["name"])
        assert got == ["b0", "b1"]
        # the evicted watcher is pruned from fan-out; new ones still work
        w2 = store.watch("/registry/pods/")
        store.create(key(make_pod("b9")), make_pod("b9"))
        ev = w2.next_timeout(1)
        assert ev.object["metadata"]["name"] == "b9"
        w2.stop()

    def test_replica_feed_evicted_on_overflow(self, store):
        feed = store.replication_feed(queue_limit=3)
        for i in range(8):
            store.create(key(make_pod(f"r{i}")), make_pod(f"r{i}"))
        assert feed.evicted
        assert store.replica_evictions == 1
        # queued records drain in order, then the feed ends (standby
        # reconnects and resyncs)
        got = []
        while True:
            rec = feed.next_timeout(1)
            if rec is None:
                break
            got.append(rec[3]["metadata"]["name"])
        assert got == ["r0", "r1", "r2"]

    def test_resume_replay_is_ordered_with_concurrent_commits(self, store):
        """Replay now happens outside the store lock with live events
        buffered; revision order must survive the interleave."""
        for i in range(50):
            store.create(key(make_pod(f"o{i}")), make_pod(f"o{i}"))
        stop = threading.Event()

        def writer():
            i = 50
            while not stop.is_set():
                store.create(key(make_pod(f"o{i}")), make_pod(f"o{i}"))
                i += 1

        th = threading.Thread(target=writer, daemon=True)
        th.start()
        try:
            for _ in range(10):
                w = store.watch("/registry/pods/", since_rev=5)
                revs = []
                for _ in range(60):
                    ev = w.next_timeout(1)
                    if ev is None:
                        break
                    revs.append(int(ev.object["metadata"]["resourceVersion"]))
                w.stop()
                assert revs == sorted(revs), "events out of revision order"
                assert revs and revs[0] == 6
        finally:
            stop.set()
            th.join(timeout=5)


class TestBatchedCommitOrdering:
    def test_concurrent_batched_and_singleton_commits_ordered(
            self, store, feed_mode):
        """Group commit must not reorder or drop events: with N writers
        landing commits via commit_batch INTERLEAVED with singleton
        creates, watchers (store + cacher), the replica feed, and the
        cacher's own history must each observe strict revision order and
        the complete event set."""
        c = make_cacher(store, feed_mode)
        cw = c.watch("/registry/pods/")
        sw = store.watch("/registry/pods/", queue_limit=0)
        feed = store.replication_feed()
        n_writers, per_writer = 4, 5  # batch writers: 5 batches of 3
        total = n_writers * per_writer * 3 + n_writers * per_writer
        barrier = threading.Barrier(2 * n_writers)

        def batch_writer(k):
            barrier.wait()
            for i in range(per_writer):
                ops = []
                for j in range(3):
                    name = f"bw{k}-{i}-{j}"
                    pod = make_pod(name)
                    pod.metadata.uid = f"uid-{name}"
                    ops.append({"op": "create", "key": key(pod),
                                "obj": global_scheme.encode(pod)})
                out = store.commit_batch(ops)
                assert all("obj" in r for r in out), out

        def single_writer(k):
            barrier.wait()
            for i in range(per_writer):
                pod = make_pod(f"sw{k}-{i}")
                store.create(key(pod), pod)

        threads = [threading.Thread(target=batch_writer, args=(k,))
                   for k in range(n_writers)]
        threads += [threading.Thread(target=single_writer, args=(k,))
                    for k in range(n_writers)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=30)
        assert not any(th.is_alive() for th in threads)

        def drain_events(w):
            revs, names = [], set()
            while len(revs) < total:
                ev = w.next_timeout(5)
                if ev is None:
                    break
                revs.append(int(ev.object["metadata"]["resourceVersion"]))
                names.add(ev.object["metadata"]["name"])
            return revs, names

        want = {f"bw{k}-{i}-{j}" for k in range(n_writers)
                for i in range(per_writer) for j in range(3)}
        want |= {f"sw{k}-{i}" for k in range(n_writers)
                 for i in range(per_writer)}
        try:
            for label, w in (("store", sw), ("cacher", cw)):
                revs, names = drain_events(w)
                assert len(revs) == total, (label, len(revs))
                assert revs == sorted(revs) and len(set(revs)) == total, label
                assert names == want, label
            # replica feed sees the same commit records, in order
            rrevs = []
            while len(rrevs) < total:
                rec = feed.next_timeout(5)
                if rec is None:
                    break
                rrevs.append(rec[0])
            assert rrevs == sorted(rrevs) and len(rrevs) == total
            # the cacher's own view converged: every key present, history
            # strictly ordered
            entries, _rev = c.list_raw("/registry/pods/default/")
            assert {e[2]["metadata"]["name"] for e in entries} == want
            with c._cond:
                hrevs = [r for r, _t, _k, _o in c._history]
            assert hrevs == sorted(hrevs)
        finally:
            cw.stop()
            sw.stop()
            feed.stop(store)
            c.stop()


class TestInformerUnderWatchTruncation:
    @pytest.mark.slow  # up-to-40s probabilistic schedule: the exit waits
    # for BOTH recovery paths to fire, which on a loaded box can take the
    # whole budget — long fault schedules stay out of tier-1 (the
    # faultline smoke covers injected-disconnect convergence there)
    @pytest.mark.thread_leak_ok  # Master's HTTP worker threads
    def test_relist_and_reconnect_converge_losslessly(self):
        """Injected watch-stream truncation (utils/faultline on the
        client.watch site), with the cacher's history window shrunk so a
        re-dial can land below the 410 floor: the informer must take BOTH
        recovery paths — reconnect-from-last-rv after a mid-stream cut,
        and a full relist after a 410 — and the cache must still end
        byte-equal to the authoritative list (no event lost, none
        double-applied)."""
        from kubernetes1_tpu.apiserver import Master
        from kubernetes1_tpu.client import Clientset, SharedInformer
        from kubernetes1_tpu.utils import faultline

        master = Master().start()
        cs = Clientset(master.url)
        inf = SharedInformer(cs.pods, namespace="default")
        try:
            inf.start()
            assert inf.wait_for_sync(10.0)
            # a 2-revision watch window: any reconnect that lags a couple
            # of commits is below the floor -> 410 -> relist
            with master.cacher._cond:
                master.cacher._history_limit = 2
            faultline.activate(5, "client.watch=drop@0.25")
            created = []
            try:
                deadline = time.monotonic() + 40.0
                i = 0
                # create until both recovery paths have demonstrably run
                while time.monotonic() < deadline:
                    name = f"cut-{i}"
                    cs.pods.create(make_pod(name))
                    created.append(name)
                    i += 1
                    time.sleep(0.01)
                    if inf.reconnects >= 1 and inf.relists >= 2 \
                            and i >= 30:
                        break
            finally:
                faultline.deactivate()
            assert inf.reconnects >= 1, (inf.reconnects, inf.relists)
            assert inf.relists >= 2, (inf.reconnects, inf.relists)
            # lossless convergence: informer cache == authoritative list
            want = set(created)
            deadline = time.monotonic() + 30.0
            have: set = set()
            while time.monotonic() < deadline:
                have = {p.metadata.name for p in inf.list()}
                if have == want:
                    break
                time.sleep(0.1)
            assert have == want, (
                f"missing={sorted(want - have)[:5]} "
                f"extra={sorted(have - want)[:5]}")
        finally:
            inf.stop()
            cs.close()
            master.stop()


class TestDeepHistoryFallback:
    def test_resume_below_cache_window_falls_back_to_store_history(self):
        """A resume below the cache's window but inside the store's deeper
        history ring must replay from the store (no 410, no relist storm —
        e.g. informers reconnecting after the cache window rolled)."""
        from kubernetes1_tpu.apiserver import Master
        from kubernetes1_tpu.client import Clientset

        master = Master().start()
        cs = Clientset(master.url)
        try:
            cs.pods.create(make_pod("base"))
            _, rv0 = cs.pods.list(namespace="default")
            # shrink the CACHE window only; the store ring stays deep
            with master.cacher._cond:
                master.cacher._history_limit = 2
            names = [f"deep-{i}" for i in range(8)]
            for n in names:
                cs.pods.create(make_pod(n))
            # cache floor has rolled past rv0 by now
            assert master.cacher._compacted_rev > int(rv0)
            got = []
            with cs.pods.watch(namespace="default",
                               resource_version=rv0) as stream:
                for ev_type, obj in stream:
                    assert ev_type != "ERROR", obj
                    got.append(obj["metadata"]["name"])
                    if len(got) == len(names):
                        break
            assert got == names
        finally:
            cs.close()
            master.stop()
