"""Component /metrics endpoints (ref: plugin/pkg/scheduler/metrics/,
pkg/kubelet/metrics/ incl. the fork's DevicePluginAllocationLatency):
scheduler latency must be observable from OUTSIDE the process (VERDICT r2
weak #1/#3)."""

import urllib.request

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.helpers import make_tpu_pod
from tests.test_controllers import start_hollow_node


@pytest.fixture()
def cluster(tmp_path):
    master = Master().start()
    cs = Clientset(master.url)
    sched = Scheduler(cs, metrics_port=0)  # ephemeral /metrics endpoint
    sched.start()
    kl, pl, _ = start_hollow_node(cs, "m0", str(tmp_path), tpus=4)
    yield {"master": master, "cs": cs, "sched": sched, "kubelet": kl}
    kl.stop()
    pl.stop()
    sched.stop()
    cs.close()
    master.stop()


def scrape(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.read().decode()


class TestSchedulerMetrics:
    def test_metrics_endpoint_serves_attempt_latency(self, cluster):
        cs, sched = cluster["cs"], cluster["sched"]
        for i in range(3):
            p = make_tpu_pod(f"mp-{i}", tpus=1)
            p.spec.containers[0].command = ["serve"]
            cs.pods.create(p)
        must_poll_until(
            lambda: all(cs.pods.get(f"mp-{i}", "default").spec.node_name
                        for i in range(3)),
            timeout=15.0, desc="pods scheduled",
        )
        text = scrape(sched.metrics_server.url + "/metrics")
        assert "scheduler_schedule_attempts_total" in text
        assert 'scheduler_scheduling_algorithm_seconds{quantile="0.99"}' in text
        assert "scheduler_e2e_scheduling_seconds" in text
        assert "scheduler_binding_seconds" in text
        assert "scheduler_pending_pods" in text
        # the counters reflect the work that just happened
        attempts = [line for line in text.splitlines()
                    if line.startswith("scheduler_schedule_attempts_total ")]
        assert attempts and float(attempts[0].split()[-1]) >= 3
        assert scrape(sched.metrics_server.url + "/healthz")

    def test_sched_perf_scrapes_multiproc_metrics(self):
        """The perf harness parses the endpoint's text (no more null
        attempt counters in multiproc mode)."""
        from scripts.sched_perf import scrape_metrics

        # parse-level check against a live endpoint
        import threading

        from kubernetes1_tpu.utils.metrics import MetricsServer, Registry

        reg = Registry()
        reg.counter("scheduler_schedule_attempts_total").inc(7)
        reg.histogram("scheduler_scheduling_algorithm_seconds").observe(0.005)
        srv = MetricsServer(reg, port=0).start()
        try:
            mx = scrape_metrics(srv.url)
            assert mx["scheduler_schedule_attempts_total"] == 7
            assert mx['scheduler_scheduling_algorithm_seconds{quantile="0.5"}'] == pytest.approx(0.005)
        finally:
            srv.stop()


class TestKubeletMetrics:
    def test_allocation_latency_exported(self, cluster):
        cs, kl = cluster["cs"], cluster["kubelet"]
        p = make_tpu_pod("alloc-pod", tpus=2)
        p.spec.containers[0].command = ["serve"]
        cs.pods.create(p)
        must_poll_until(
            lambda: cs.pods.get("alloc-pod", "default").status.phase == t.POD_RUNNING,
            timeout=15.0, desc="tpu pod running",
        )
        text = scrape(kl.server.url + "/metrics")
        assert "device_plugin_allocation_seconds" in text \
            or "allocation" in text  # fork-signature metric scrapeable
        assert "kubelet_running_pods" in text
        assert "kubelet_running_containers" in text
