"""Runtime dispatcher-blocking sanitizer (utils/loopsan): the dynamic
twin of the KTPU016 static pass.  The load-bearing contracts:

- armed + blocking primitive ON the dispatcher -> BlockingOnDispatcherError
  carrying the callback's REGISTRATION site (where the fix goes), not just
  the blocking frame;
- the sanctioned patterns stay legal: zero-timeout I/O, shared_pool
  offload, off-dispatcher threads;
- inactive mode is identity: primitives restored, zeroed stats (so the
  cluster_life ``loopsan`` scorecard block renders zeros, not missing
  keys);
- measured stalls (lock waits, timer lag) are telemetry, never raises.
"""

import inspect
import queue
import threading
import time
from concurrent.futures import Future

import pytest

from kubernetes1_tpu.utils import eventloop, loopsan


@pytest.fixture
def armed():
    """Ensure loopsan is armed for the test and restore the prior state
    (conftest arms it via KTPU_LOOPSAN=1, but A/B runs may not)."""
    was = loopsan.active()
    loopsan.activate()
    yield
    if not was:
        loopsan.deactivate()


@pytest.fixture
def dispatcher_self(armed):
    """Mark the test's own thread as the dispatcher: primitive guards
    check the ident set, so violations can be asserted synchronously
    without standing up a loop."""
    loopsan.mark_dispatcher()
    yield
    loopsan.unmark_dispatcher()


def _wait_until(pred, timeout=3.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return pred()


# ---------------------------------------------------------------- raising


def test_sleep_on_dispatcher_raises_and_records(dispatcher_self):
    before = loopsan.stats()["violations"]
    with pytest.raises(loopsan.BlockingOnDispatcherError) as ei:
        time.sleep(0.05)
    assert "time.sleep" in ei.value.primitive
    s = loopsan.stats()
    assert s["violations"] == before + 1
    assert loopsan.violations()[-1]["primitive"] == ei.value.primitive


def test_zero_sleep_and_off_dispatcher_sleep_legal(dispatcher_self):
    time.sleep(0)  # scheduler hint, cannot stall the loop
    loopsan.unmark_dispatcher()
    try:
        time.sleep(0.001)  # not the dispatcher: no opinion
    finally:
        loopsan.mark_dispatcher()  # fixture's unmark stays balanced


def test_queue_get_and_future_result_guards(dispatcher_self):
    q = queue.Queue()
    with pytest.raises(loopsan.BlockingOnDispatcherError):
        q.get()
    with pytest.raises(queue.Empty):
        q.get(block=True, timeout=0)  # zero-timeout poll is legal
    fut = Future()
    with pytest.raises(loopsan.BlockingOnDispatcherError):
        fut.result()
    fut.set_result(7)
    assert fut.result() == 7  # done future returns without waiting


# ------------------------------------------------------------ attribution


def test_injected_blocking_callback_names_registration_site(armed):
    """THE regression the ISSUE seeds: a time.sleep smuggled into a
    call_soon callback must fail loudly and name the line that REGISTERED
    the callback — the blocking frame alone points at the symptom, the
    registration site points at the owner."""
    loop = eventloop.EventLoop(name="loopsan-test").start()
    try:
        before = loopsan.stats()["violations"]
        ran = threading.Event()

        def smuggled():
            try:
                time.sleep(0.05)
            finally:
                ran.set()

        reg_line = inspect.currentframe().f_lineno + 1
        loop.call_soon(smuggled)
        assert ran.wait(3.0)
        assert _wait_until(
            lambda: loopsan.stats()["violations"] > before)
        v = loopsan.violations()[-1]
        assert v["registration_site"] == f"test_loopsan.py:{reg_line}"
        assert v["callback"] == "call_soon:smuggled"
        assert "time.sleep" in v["primitive"]
        # the raise is swallowed by the loop's _guard: the dispatcher
        # survives and still runs later callbacks
        again = threading.Event()
        loop.call_soon(again.set)
        assert again.wait(3.0)
    finally:
        loop.stop()


def test_pool_offload_is_legal(armed):
    """The sanctioned shape: the dispatcher callback only SUBMITS; the
    blocking body runs on a pool slot loopsan has no opinion about."""
    loop = eventloop.EventLoop(name="loopsan-pool-test").start()
    pool = eventloop.WorkerPool(size=1, name="loopsan-pool")
    try:
        before = loopsan.stats()["violations"]
        done = threading.Event()

        def blocking_body():
            time.sleep(0.02)
            done.set()

        loop.call_soon(lambda: pool.submit(blocking_body))
        assert done.wait(3.0)
        assert loopsan.stats()["violations"] == before
    finally:
        loop.stop()
        pool._q.put(None)  # retire the worker so no thread outlives the test


# -------------------------------------------------------- stall telemetry


def test_lock_wait_is_measured_not_raised(dispatcher_self):
    s0 = loopsan.stats()
    loopsan.note_lock_wait("TestLock._mu", 0.5)  # past the 0.25s threshold
    s1 = loopsan.stats()
    assert s1["stalls"] == s0["stalls"] + 1
    assert s1["max_stall_s"] >= 0.5
    assert s1["violations"] == s0["violations"]  # measured, never raised


# ------------------------------------------------------------ identity off


def test_inactive_mode_is_identity():
    was = loopsan.active()
    loopsan.deactivate()
    try:
        assert not loopsan.active()
        orig_sleep = time.sleep
        loopsan.mark_dispatcher()
        try:
            time.sleep(0.001)  # no raise: the primitive is the original
        finally:
            loopsan.unmark_dispatcher()
        assert loopsan.stats() == {
            "violations": 0, "max_stall_s": 0.0, "stalls": 0}
        assert loopsan.violations() == []
        loopsan.activate()
        assert time.sleep is not orig_sleep  # arming patches...
        loopsan.deactivate()
        assert time.sleep is orig_sleep  # ...and disarming restores
    finally:
        if was and not loopsan.active():
            loopsan.activate()
