"""StatefulSet + CronJob controller tests (ref: test/integration +
pkg/controller/{statefulset,cronjob} unit suites)."""

import datetime
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset, InformerFactory
from kubernetes1_tpu.controllers import ControllerManager
from kubernetes1_tpu.controllers.cronjob import CronJobController
from kubernetes1_tpu.controllers.statefulset import POD_NAME_LABEL, REVISION_LABEL
from kubernetes1_tpu.machinery import Invalid
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils.cron import next_fire, parse_cron, unmet_times
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.helpers import mutate_with_retry
from tests.test_controllers import start_hollow_node


UTC = datetime.timezone.utc


class TestCronParser:
    def test_every_minute(self):
        nxt = next_fire("* * * * *", datetime.datetime(2026, 7, 29, 12, 0, 30, tzinfo=UTC))
        assert nxt == datetime.datetime(2026, 7, 29, 12, 1, tzinfo=UTC)

    def test_steps_and_ranges(self):
        fields = parse_cron("*/15 9-17 * * 1-5")
        assert fields[0] == {0, 15, 30, 45}
        assert fields[1] == set(range(9, 18))
        assert fields[4] == {1, 2, 3, 4, 5}

    def test_specific_time(self):
        nxt = next_fire("30 3 * * *", datetime.datetime(2026, 7, 29, 4, 0, tzinfo=UTC))
        assert nxt == datetime.datetime(2026, 7, 30, 3, 30, tzinfo=UTC)

    def test_dow_sunday_as_7(self):
        fields = parse_cron("0 0 * * 7")
        assert fields[4] == {0}

    def test_dow_ranges_with_7(self):
        assert parse_cron("0 0 * * 5-7")[4] == {5, 6, 0}
        assert parse_cron("0 0 * * 0-7")[4] == {0, 1, 2, 3, 4, 5, 6}

    def test_never_firing_schedule_rejected(self):
        with pytest.raises(ValueError):
            parse_cron("0 0 31 2 *")
        with pytest.raises(ValueError):
            parse_cron("0 0 30 2 *")
        parse_cron("0 0 29 2 *")  # leap years: valid

    def test_bad_schedules(self):
        for bad in ("* * * *", "61 * * * *", "* * * * mon", "a b c d e"):
            with pytest.raises(ValueError):
                parse_cron(bad)

    def test_unmet_times(self):
        earliest = datetime.datetime(2026, 7, 29, 12, 0, tzinfo=UTC)
        now = datetime.datetime(2026, 7, 29, 12, 5, 30, tzinfo=UTC)
        times, truncated = unmet_times("* * * * *", earliest, now)
        assert len(times) == 5 and not truncated
        assert times[-1] == datetime.datetime(2026, 7, 29, 12, 5, tzinfo=UTC)

    def test_unmet_truncation(self):
        earliest = datetime.datetime(2026, 7, 1, tzinfo=UTC)
        now = datetime.datetime(2026, 7, 29, tzinfo=UTC)
        _, truncated = unmet_times("* * * * *", earliest, now)
        assert truncated


@pytest.fixture()
def cluster(tmp_path):
    master = Master().start()
    cs = Clientset(master.url)
    sched = Scheduler(cs)
    sched.start()
    cm = ControllerManager(cs, monitor_grace=5.0, eviction_timeout=5.0)
    cm.start()
    nodes = [
        start_hollow_node(cs, f"ss-host-{i}", str(tmp_path), tpus=4, host_index=i)
        for i in range(2)
    ]
    env = {"master": master, "cs": cs}
    yield env
    for kubelet, plugin, _ in nodes:
        kubelet.stop()
        plugin.stop()
    cm.stop()
    sched.stop()
    cs.close()
    master.stop()


def sset(name, replicas=2, image="v1", policy="OrderedReady"):
    ss = t.StatefulSet()
    ss.metadata.name = name
    ss.spec.replicas = replicas
    ss.spec.pod_management_policy = policy
    ss.spec.service_name = name
    ss.spec.selector = t.LabelSelector(match_labels={"app": name})
    ss.spec.template.metadata.labels = {"app": name}
    ss.spec.template.spec.containers = [
        t.Container(name="c", image=image, command=["serve"])
    ]
    return ss


class TestStatefulSet:
    def test_ordered_creation_and_identity(self, cluster):
        cs = cluster["cs"]
        cs.statefulsets.create(sset("db", replicas=3))

        def names():
            pods, _ = cs.pods.list(namespace="default", label_selector="app=db")
            return sorted(
                p.metadata.name for p in pods if not p.metadata.deletion_timestamp
            )

        must_poll_until(lambda: names() == ["db-0", "db-1", "db-2"],
                        timeout=20.0, desc="3 ordinal pods")
        pods, _ = cs.pods.list(namespace="default", label_selector="app=db")
        for p in pods:
            assert p.metadata.labels[POD_NAME_LABEL] == p.metadata.name
        must_poll_until(
            lambda: cs.statefulsets.get("db").status.ready_replicas == 3,
            timeout=20.0, desc="status ready",
        )

    def test_scale_down_removes_highest_ordinal(self, cluster):
        cs = cluster["cs"]
        cs.statefulsets.create(sset("cache", replicas=3, policy="Parallel"))

        def names():
            pods, _ = cs.pods.list(namespace="default", label_selector="app=cache")
            return sorted(
                p.metadata.name for p in pods if not p.metadata.deletion_timestamp
            )

        must_poll_until(lambda: names() == ["cache-0", "cache-1", "cache-2"],
                        timeout=20.0, desc="3 pods")
        mutate_with_retry(cs.statefulsets, "cache", lambda ss: setattr(ss.spec, "replicas", 1))
        must_poll_until(lambda: names() == ["cache-0"], timeout=20.0,
                        desc="scaled to ordinal 0")

    def test_rolling_update_recreates_at_new_revision(self, cluster):
        cs = cluster["cs"]
        cs.statefulsets.create(sset("web", replicas=2))
        must_poll_until(
            lambda: cs.statefulsets.get("web").status.ready_replicas == 2,
            timeout=20.0, desc="2 ready",
        )
        old_rev = cs.statefulsets.get("web").status.current_revision
        def set_v2(ss):
            ss.spec.template.spec.containers[0].image = "v2"

        mutate_with_retry(cs.statefulsets, "web", set_v2)

        def updated():
            s = cs.statefulsets.get("web").status
            return s.current_revision != old_rev and s.ready_replicas == 2

        must_poll_until(updated, timeout=30.0, desc="rolled to new revision")
        pods, _ = cs.pods.list(namespace="default", label_selector="app=web")
        live = [p for p in pods if not p.metadata.deletion_timestamp]
        assert all(p.spec.containers[0].image == "v2" for p in live)
        assert sorted(p.metadata.name for p in live) == ["web-0", "web-1"]

    def test_validation(self, cluster):
        cs = cluster["cs"]
        ss = sset("bad")
        ss.spec.pod_management_policy = "Chaotic"
        with pytest.raises(Invalid):
            cs.statefulsets.create(ss)


class TestCronJob:
    def make(self, name, schedule="* * * * *", policy="Allow"):
        cj = t.CronJob()
        cj.metadata.name = name
        cj.spec.schedule = schedule
        cj.spec.concurrency_policy = policy
        cj.spec.job_template.spec.template.spec.containers = [
            t.Container(name="c", image="task", command=["sleep", "0.1"])
        ]
        cj.spec.job_template.spec.completions = 1
        return cj

    def test_schedule_validation(self, cluster):
        cs = cluster["cs"]
        cj = self.make("bad", schedule="nope")
        with pytest.raises(Invalid):
            cs.cronjobs.create(cj)

    def test_fires_on_schedule_with_fake_clock(self, tmp_path):
        master = Master().start()
        cs = Clientset(master.url)
        try:
            fake_now = [time.time()]
            factory = InformerFactory(cs)
            ctl = CronJobController(cs, factory, clock=lambda: fake_now[0])
            ctl.setup()
            factory.start_all()
            factory.wait_for_sync()

            cs.cronjobs.create(self.make("tick"))
            key = "default/tick"
            # first sync: nothing unmet yet (created just now)
            ctl.sync(key)
            jobs, _ = cs.jobs.list(namespace="default")
            assert len(jobs) == 0

            fake_now[0] += 61  # cross a minute boundary
            ctl.sync(key)
            must_poll_until(
                lambda: len(cs.jobs.list(namespace="default")[0]) == 1,
                timeout=5.0, desc="job created",
            )
            cj = cs.cronjobs.get("tick")
            assert cj.status.last_schedule_time
            assert len(cj.status.active) == 1

            # same minute again: name collision → no duplicate
            ctl.sync(key)
            assert len(cs.jobs.list(namespace="default")[0]) == 1

            # long outage: backlog is skipped, not replayed as a storm
            cs.cronjobs.create(self.make("stale"))
            fake_now[0] += 3 * 86400

            def stale_advanced():
                # re-drive sync until the informer has observed the object
                # (manual sync can race the watch event delivery)
                ctl.sync("default/stale")
                return cs.cronjobs.get("stale").status.last_schedule_time != ""

            must_poll_until(
                stale_advanced, timeout=5.0,
                desc="lastScheduleTime advanced past backlog",
            )
            stale_jobs = [
                j for j in cs.jobs.list(namespace="default")[0]
                if j.metadata.name.startswith("stale-")
            ]
            assert stale_jobs == []

            # Forbid policy blocks while active
            fresh = mutate_with_retry(
                cs.cronjobs, "tick",
                lambda cj: setattr(cj.spec, "concurrency_policy", "Forbid"),
            )
            factory.wait_for_sync()
            fake_now[0] += 60
            must_poll_until(
                lambda: (ctl.cronjobs.get(key) or fresh).spec.concurrency_policy
                == "Forbid",
                timeout=5.0, desc="informer saw Forbid",
            )
            ctl.sync(key)
            assert len(cs.jobs.list(namespace="default")[0]) == 1
        finally:
            cs.close()
            master.stop()
