"""TLS end to end: PKI, HTTPS apiserver, x509 authn, CSR x509 signing.

Ref: cmd/kubeadm/app/phases/certs/certs.go:37 (CreatePKIAssets),
staging/src/k8s.io/apiserver/pkg/server/serve.go (secure serving),
staging authenticator/request/x509 (CN=user, O=groups mapping),
pkg/controller/certificates/signer (CSR → signed cert).
"""

import http.client

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver.server import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.machinery import ApiError
from kubernetes1_tpu.utils import pki


@pytest.fixture(scope="module")
def cluster_pki(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("pki"))
    ca_cert, ca_key = pki.create_ca()
    pki.write_pki(d, "ca", ca_cert, ca_key)
    srv_cert, srv_key = pki.issue_cert(
        ca_cert, ca_key, cn="kube-apiserver", server=True,
        dns_sans=["localhost"], ip_sans=["127.0.0.1"])
    pki.write_pki(d, "apiserver", srv_cert, srv_key)
    adm_cert, adm_key = pki.issue_cert(
        ca_cert, ca_key, cn="ktpu-admin", orgs=["system:masters"], client=True)
    pki.write_pki(d, "admin", adm_cert, adm_key)
    return {"dir": d, "ca_cert": ca_cert, "ca_key": ca_key}


@pytest.fixture(scope="module")
def tls_master(cluster_pki):
    d = cluster_pki["dir"]
    m = Master(tls_cert_file=f"{d}/apiserver.crt",
               tls_key_file=f"{d}/apiserver.key",
               client_ca_file=f"{d}/ca.crt",
               authorization_mode="Node,RBAC").start()
    yield m
    m.stop()


class TestPKI:
    def test_ca_and_leaf_roundtrip(self):
        ca_cert, ca_key = pki.create_ca("test-ca")
        cert, _key = pki.issue_cert(ca_cert, ca_key, cn="u", orgs=["g1", "g2"],
                                    client=True)
        assert pki.cert_identity(cert) == ("u", ["g1", "g2"])

    def test_csr_identity_and_sign(self):
        ca_cert, ca_key = pki.create_ca()
        csr, _key = pki.create_csr("system:node:n1", ["system:nodes"],
                                   dns_sans=["n1"], ip_sans=["127.0.0.1"])
        assert pki.is_pem_csr(csr)
        assert pki.csr_identity(csr) == ("system:node:n1", ["system:nodes"])
        cert = pki.sign_csr(ca_cert, ca_key, csr, client=True, server=True)
        assert pki.cert_identity(cert) == ("system:node:n1", ["system:nodes"])

    def test_ca_hash_pins(self):
        a, _ = pki.create_ca("a")
        b, _ = pki.create_ca("b")
        assert pki.ca_cert_hash(a).startswith("sha256:")
        assert pki.ca_cert_hash(a) != pki.ca_cert_hash(b)


class TestTLSMaster:
    def test_https_with_ca_verification(self, tls_master, cluster_pki):
        d = cluster_pki["dir"]
        assert tls_master.url.startswith("https://")
        cs = Clientset(tls_master.url, ca_file=f"{d}/ca.crt",
                       cert_file=f"{d}/admin.crt", key_file=f"{d}/admin.key")
        assert cs.api.request("GET", "/healthz") == {"status": "ok"}
        cs.close()

    def test_x509_identity_is_cn_and_o(self, tls_master, cluster_pki):
        # the admin cert (O=system:masters) passes RBAC with no token at all
        d = cluster_pki["dir"]
        cs = Clientset(tls_master.url, ca_file=f"{d}/ca.crt",
                       cert_file=f"{d}/admin.crt", key_file=f"{d}/admin.key")
        ns = t.Namespace()
        ns.metadata.name = "x509-test"
        assert cs.namespaces.create(ns, "").metadata.name == "x509-test"
        cs.close()

    def test_no_credential_is_anonymous(self, tls_master, cluster_pki):
        d = cluster_pki["dir"]
        cs = Clientset(tls_master.url, ca_file=f"{d}/ca.crt")
        with pytest.raises(ApiError):
            cs.pods.list()
        cs.close()

    def test_plaintext_rejected(self, tls_master):
        with pytest.raises((OSError, http.client.HTTPException)):
            c = http.client.HTTPConnection(tls_master.host, tls_master.port,
                                           timeout=5)
            c.request("GET", "/healthz")
            c.getresponse()

    def test_wrong_ca_client_rejected(self, tls_master, tmp_path):
        evil_cert, evil_key = pki.create_ca("evil")
        pki.write_pki(str(tmp_path), "evil", evil_cert, evil_key)
        cs = Clientset(tls_master.url, ca_file=f"{tmp_path}/evil.crt")
        with pytest.raises(OSError):
            cs.api.request("GET", "/healthz")
        cs.close()

    def test_cert_from_untrusted_ca_gets_no_identity(self, tls_master,
                                                     tmp_path, cluster_pki):
        # handshake with a cert signed by a DIFFERENT CA must fail outright
        evil_ca, evil_key = pki.create_ca("evil")
        cert, key = pki.issue_cert(evil_ca, evil_key, cn="ktpu-admin",
                                   orgs=["system:masters"], client=True)
        pki.write_pki(str(tmp_path), "fake-admin", cert, key)
        d = cluster_pki["dir"]
        cs = Clientset(tls_master.url, ca_file=f"{d}/ca.crt",
                       cert_file=f"{tmp_path}/fake-admin.crt",
                       key_file=f"{tmp_path}/fake-admin.key")
        with pytest.raises(OSError):
            cs.namespaces.list()
        cs.close()


class TestX509Signer:
    def test_signer_issues_real_cert_for_pem_csr(self, cluster_pki):
        from kubernetes1_tpu.controllers.certificates import (
            CertificateController,
        )

        ctrl = CertificateController.__new__(CertificateController)
        ctrl.ca_key = cluster_pki["ca_key"]
        ctrl.ca_cert_pem = cluster_pki["ca_cert"]
        ctrl.x509 = True
        csr_pem, _key = pki.create_csr("system:node:n2", ["system:nodes"])
        csr = t.CertificateSigningRequest()
        csr.spec.request = csr_pem
        csr.spec.username = "system:node:n2"
        csr.spec.groups = ["system:nodes"]
        csr.spec.usages = ["client auth", "server auth"]
        cert = ctrl._sign(csr)
        assert pki.cert_identity(cert) == ("system:node:n2", ["system:nodes"])

    def test_signer_rejects_subject_smuggling(self, cluster_pki):
        # CSR x509 subject asks for admin while spec.username is a node:
        # the signer must refuse (approval checked spec.username only)
        from kubernetes1_tpu.controllers.certificates import (
            CertificateController,
        )

        ctrl = CertificateController.__new__(CertificateController)
        ctrl.ca_key = cluster_pki["ca_key"]
        ctrl.ca_cert_pem = cluster_pki["ca_cert"]
        ctrl.x509 = True
        csr_pem, _key = pki.create_csr("ktpu-admin", ["system:masters"])
        csr = t.CertificateSigningRequest()
        csr.spec.request = csr_pem
        csr.spec.username = "system:node:n3"
        csr.spec.groups = ["system:nodes"]
        with pytest.raises(ValueError):
            ctrl._sign(csr)
