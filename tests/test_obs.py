"""Fleet observability plane (kubernetes1_tpu/obs/ + utils/flightrec).

Covers the PR's acceptance surface:
- the bucket-wise histogram merge golden (merged p99 correct where the
  old quantile-max rule is wrong by orders of magnitude);
- the ObsCollector over a sharded LocalCluster (store_shards=2,
  apiservers=2, sched shards=2): per-shard informer lag on the fleet
  /metrics, merged store-shard commits equal to the per-shard sum,
  fleet counters equal to the sum of per-instance scrapes, one-trace-id
  union across components;
- the watch-lag SLI under a paused-then-resumed watch (resume from a
  pre-pause revision replays events whose commit stamps are the pause
  old — the informer's mid-stream-reconnect shape);
- flight-recorder ring bounds, the kind enum, and dump-on-failed-
  chaos-verdict;
- a dead scrape target never wedges the collector's serving path.
"""

import time
import urllib.request

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset, SharedInformer
from kubernetes1_tpu.client import informer as informer_mod
from kubernetes1_tpu.client.rest import ApiClient
from kubernetes1_tpu.localcluster import LocalCluster
from kubernetes1_tpu.obs import ObsCollector, aggregate
from kubernetes1_tpu.utils import flightrec
from kubernetes1_tpu.utils.metrics import Counter, Histogram, MetricsServer, Registry
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.helpers import make_tpu_pod


def fetch(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


# ------------------------------------------------------- aggregate golden


class TestBucketWiseMerge:
    def test_merged_p99_exact_where_max_rule_is_wrong(self):
        """The golden: a skewed split (one instance holds ALL the slow
        samples, but they are <1% of the fleet).  Bucket-wise merge
        lands the fleet p99 in the fast bucket; quantile-max reports
        the slow instance's p99 as the fleet's — off by ~1000x."""
        a = Histogram("ktpu_g_seconds")
        b = Histogram("ktpu_g_seconds")
        for _ in range(9950):
            a.observe(0.009)
        for _ in range(50):
            b.observe(9.0)
        pa = aggregate.parse_metrics_text(a.render())
        pb = aggregate.parse_metrics_text(b.render())
        merged = aggregate.merge_parsed([pa, pb])
        p99 = list(aggregate.select(
            merged, "ktpu_g_seconds", quantile="0.99").values())[0]
        # pooled truth: rank 9900 of 10000 lands among the 0.009s —
        # the right answer is in the (0.005, 0.01] bucket
        pooled = sorted([0.009] * 9950 + [9.0] * 50)
        truth = pooled[int(0.99 * len(pooled))]
        assert truth == 0.009
        assert 0.005 <= p99 <= 0.025, p99  # correct bucket (interpolated)
        # the old rule: max of per-instance reservoir p99s
        max_rule = max(a.quantile(0.99), b.quantile(0.99))
        assert max_rule >= 9.0  # wrong by ~1000x
        # counts and sums merged cumulatively
        assert list(aggregate.select(
            merged, "ktpu_g_seconds_count").values())[0] == 10000

    def test_counters_sum_gauges_max_and_flat_dict_compat(self):
        t1 = "# TYPE ktpu_x_total counter\nktpu_x_total 3\n" \
             "# TYPE ktpu_depth gauge\nktpu_depth 7\n"
        t2 = "# TYPE ktpu_x_total counter\nktpu_x_total 4\n" \
             "# TYPE ktpu_depth gauge\nktpu_depth 5\n"
        merged = aggregate.merge_parsed(
            [aggregate.parse_metrics_text(x) for x in (t1, t2)])
        assert merged.samples["ktpu_x_total"] == 7
        assert merged.samples["ktpu_depth"] == 7  # gauge: max
        # flat-dict compat (the sched_perf scrape shape): same rules
        flat = aggregate.merge_metrics([
            {"ktpu_x_total": 3.0, "ktpu_depth": 7.0},
            {"ktpu_x_total": 4.0, "ktpu_depth": 5.0}])
        assert flat == {"ktpu_x_total": 7.0, "ktpu_depth": 7.0}

    def test_mismatched_bucket_boundaries_raise(self):
        """Summing cumulative _bucket lines is only sound when every
        input bucketed the SAME way; a silent merge across different
        `le` sets invents a distribution neither instance measured.
        The merge must refuse, loudly."""
        t1 = ('ktpu_m_seconds_bucket{le="0.1"} 3\n'
              'ktpu_m_seconds_bucket{le="+Inf"} 3\n'
              "ktpu_m_seconds_count 3\nktpu_m_seconds_sum 0.2\n")
        t2 = ('ktpu_m_seconds_bucket{le="0.25"} 5\n'
              'ktpu_m_seconds_bucket{le="+Inf"} 5\n'
              "ktpu_m_seconds_count 5\nktpu_m_seconds_sum 0.9\n")
        with pytest.raises(ValueError, match="mismatched histogram"):
            aggregate.merge_parsed(
                [aggregate.parse_metrics_text(x) for x in (t1, t2)])
        # flat-dict leg enforces the same contract
        with pytest.raises(ValueError, match="mismatched histogram"):
            aggregate.merge_metrics([
                {'ktpu_m_seconds_bucket{le="0.1"}': 3.0},
                {'ktpu_m_seconds_bucket{le="0.25"}': 5.0}])

    def test_empty_histogram_merge_is_identity(self):
        """An instance that has observed NOTHING renders zero-filled
        buckets (no quantile lines); merging it in must not move the
        populated instance's buckets, count, sum, or quantiles."""
        a = Histogram("ktpu_e_seconds")
        b = Histogram("ktpu_e_seconds")  # never observed
        for _ in range(100):
            a.observe(0.02)
        pa = aggregate.parse_metrics_text(a.render())
        pb = aggregate.parse_metrics_text(b.render())
        alone = aggregate.merge_parsed([pa])
        merged = aggregate.merge_parsed([pa, pb])
        assert merged.samples == alone.samples
        p99_alone = list(aggregate.select(
            alone, "ktpu_e_seconds", quantile="0.99").values())[0]
        p99_merged = list(aggregate.select(
            merged, "ktpu_e_seconds", quantile="0.99").values())[0]
        assert p99_merged == p99_alone

    def test_quantile_max_fallback_for_reservoir_only_metrics(self):
        """No _bucket lines rendered -> the documented fallback: max."""
        t1 = 'ktpu_r_seconds{quantile="0.99"} 0.5\n'
        t2 = 'ktpu_r_seconds{quantile="0.99"} 2.0\n'
        merged = aggregate.merge_parsed(
            [aggregate.parse_metrics_text(x) for x in (t1, t2)])
        assert merged.samples['ktpu_r_seconds{quantile="0.99"}'] == 2.0

    def test_render_roundtrip(self):
        text = ("# TYPE ktpu_y_total counter\n"
                "ktpu_y_total 5\n"
                '# TYPE ktpu_z gauge\nktpu_z{shard="0"} 1\n')
        parsed = aggregate.parse_metrics_text(text)
        again = aggregate.parse_metrics_text(
            aggregate.render_metrics(parsed))
        assert again.samples == parsed.samples
        assert again.types == parsed.types

    def test_render_groups_interleaved_families_contiguously(self):
        """Merging two scrapes whose label sets differ interleaves a
        family's series in insertion order; the render must still emit
        ONE contiguous block per family (the exposition grouping rule a
        real Prometheus enforces) and keep non-finite values parseable."""
        t1 = ('# TYPE ktpu_r_total counter\n'
              'ktpu_r_total{reason="a"} 1\n'
              "# TYPE ktpu_other gauge\nktpu_other 2\n")
        t2 = ('# TYPE ktpu_r_total counter\n'
              'ktpu_r_total{reason="b"} 3\n'
              '# TYPE ktpu_q gauge\nktpu_q{quantile="0.5"} +Inf\n')
        merged = aggregate.merge_parsed(
            [aggregate.parse_metrics_text(x) for x in (t1, t2)])
        out = aggregate.render_metrics(merged)
        fams = [ln.split()[2] for ln in out.splitlines()
                if ln.startswith("# TYPE")]
        assert len(fams) == len(set(fams))  # one header per family
        r_lines = [i for i, ln in enumerate(out.splitlines())
                   if ln.startswith("ktpu_r_total")]
        assert r_lines == list(range(r_lines[0], r_lines[0] + 2))
        assert 'ktpu_q{quantile="0.5"} +Inf' in out


# ----------------------------------------------- collector over a fleet


@pytest.fixture(scope="class")
def sharded_cluster():
    c = LocalCluster(nodes=2, store_shards=2, apiservers=2, sched_shards=2,
                     obs_interval=0.25).start()
    try:
        c.wait_ready(60)
        yield c
    finally:
        c.stop()


class TestCollectorOverShardedCluster:
    def _bind_pods(self, c, n=3, prefix="obsp"):
        for i in range(n):
            p = make_tpu_pod(f"{prefix}-{i}", tpus=1)
            p.spec.containers[0].command = ["serve"]
            c.cs.pods.create(p)
        must_poll_until(
            lambda: all(c.cs.pods.get(f"{prefix}-{i}", "default")
                        .spec.node_name for i in range(n)),
            timeout=30.0, desc="pods bound")

    def test_fleet_metrics_lag_per_shard_and_shard_commit_sum(
            self, sharded_cluster):
        c = sharded_cluster
        self._bind_pods(c)
        time.sleep(0.8)  # >= 2 scrape intervals: snapshots fresh

        # per-shard informer lag on the fleet endpoint
        parsed = aggregate.parse_metrics_text(fetch(c.obs.url + "/metrics"))
        for shard in ("0", "1"):
            lag = aggregate.select(parsed, "ktpu_informer_lag_seconds",
                                   shard=shard, quantile="0.99")
            assert lag, f"no lag series for shard {shard}"
            assert all(0 <= v < 30 for v in lag.values()), lag

        # merged ktpu_store_shard_commits == the per-shard sum: bracket
        # the scrape between two direct reads of the shard stores (the
        # counters keep moving with heartbeats)
        shards = c._shared_store.shard_stores
        before = [s.commit_count for s in shards]
        for tgt in c.obs.targets():
            if tgt.instance == "apiserver-0":
                assert c.obs.scrape_once(tgt)
        parsed = aggregate.parse_metrics_text(fetch(c.obs.url + "/metrics"))
        after = [s.commit_count for s in shards]
        total_fleet = 0.0
        for i in range(len(shards)):
            series = aggregate.select(
                parsed, "ktpu_store_shard_commits_total", shard=str(i))
            assert len(series) == 1, series
            val = list(series.values())[0]
            assert before[i] <= val <= after[i], (i, before[i], val, after[i])
            total_fleet += val
        assert sum(before) <= total_fleet <= sum(after)

    def test_fleet_counters_equal_sum_of_per_instance_scrapes(
            self, sharded_cluster):
        c = sharded_cluster
        # merge the SNAPSHOTS the fleet view is built from: the sum rule
        # must hold exactly over real multi-instance scrapes
        snaps = [tgt.parsed for tgt in c.obs.targets()
                 if tgt.parsed is not None]
        assert len(snaps) >= 5  # 2 apiservers + 2 scheds + sli (+nodes)
        merged = aggregate.merge_parsed(snaps)
        name = "scheduler_schedule_attempts_total"
        per_instance = [s.samples[name] for s in snaps if name in s.samples]
        assert len(per_instance) == 2  # one per scheduler shard
        assert merged.samples[name] == sum(per_instance)

    def test_one_trace_id_union_across_components(self, sharded_cluster):
        c = sharded_cluster
        self._bind_pods(c, n=1, prefix="obstr")
        pod = c.cs.pods.get("obstr-0", "default")
        trace_id = pod.metadata.annotations.get(t.TRACE_ID_ANNOTATION)
        assert trace_id

        def union_components():
            spans = c.obs.traces(trace_id)["spans"]
            return {s["component"] for s in spans}

        must_poll_until(lambda: len(union_components()) >= 2,
                        timeout=15.0, desc="trace union >= 2 components")
        comps = union_components()
        assert "apiserver" in comps
        assert comps & {"scheduler", "kubelet"}, comps

    def test_topology_lists_every_instance_with_shards(self, sharded_cluster):
        c = sharded_cluster
        topo = c.obs.topology()
        instances = {i["instance"]: i for i in topo["instances"]}
        assert {"apiserver-0", "apiserver-1", "sched-0",
                "sched-1"} <= set(instances)
        assert instances["sched-1"]["shard"] == 1
        assert all(i["up"] for i in topo["instances"])


# ------------------------------------------------------- watch-lag SLI


class TestWatchLagSLI:
    def test_paused_then_resumed_watch_reports_the_pause(self):
        """Resume a lagStamps watch from a PRE-pause revision: the
        replayed events' commit stamps are the pause old, and the lag
        bookmark proves it — the exact shape of an informer resuming
        after a stall.  Fresh events then stamp near-zero lag."""
        master = Master().start()
        cs = Clientset(master.url)
        try:
            _, rv0 = cs.configmaps.list(namespace="default")
            for i in range(3):
                cm = t.ConfigMap()
                cm.metadata.name = f"lag-{i}"
                cm.data = {"k": str(i)}
                cs.configmaps.create(cm, "default")
            pause = 1.0
            time.sleep(pause)
            api = ApiClient(master.url)
            stamps = []
            with api.watch("/api/v1/namespaces/default/configmaps",
                           {"resourceVersion": str(rv0),
                            "lagStamps": "1"}) as stream:
                got = 0
                for etype, obj in stream:
                    if etype == "BOOKMARK":
                        ann = ((obj.get("metadata") or {})
                               .get("annotations") or {})
                        stamp = ann.get(t.COMMITTED_AT_ANNOTATION)
                        if stamp:
                            now = time.monotonic()
                            for tok in stamp.split():
                                shard, _, ts = tok.partition(":")
                                stamps.append((shard, now - float(ts)))
                        if got >= 3:
                            break
                        continue
                    got += 1
            api.close()
            assert stamps, "no lag stamps on the resumed stream"
            # the replayed batch was committed before the pause
            assert max(lag for _sh, lag in stamps) >= pause * 0.9
            assert all(sh == "0" for sh, _lag in stamps)  # unsharded
        finally:
            cs.close()
            master.stop()

    def test_live_informer_exports_sane_lag(self):
        master = Master().start()
        cs = Clientset(master.url)
        inf = SharedInformer(cs.configmaps, namespace="default")
        try:
            inf.start()
            assert inf.wait_for_sync(10)
            child = informer_mod.informer_lag_seconds.labels(shard="0")
            before = child.count
            cm = t.ConfigMap()
            cm.metadata.name = "lag-live"
            cm.data = {"k": "v"}
            cs.configmaps.create(cm, "default")
            must_poll_until(lambda: child.count > before,
                            timeout=10.0, desc="lag observation")
            # fresh event on an idle in-process cluster: small, >= 0
            assert 0 <= child.quantile(0.99) < 5.0
            # migrated counters keep their per-instance int views
            assert isinstance(inf.relists, int) and inf.relists >= 1
            assert inf.reconnects == 0
        finally:
            inf.stop()
            cs.close()
            master.stop()

    def test_plain_watch_without_opt_in_has_no_bookmarks(self):
        """Streams that didn't ask stay byte-compatible: no BOOKMARK
        frames on an unsharded watch without lagStamps."""
        master = Master().start()
        cs = Clientset(master.url)
        try:
            _, rv0 = cs.configmaps.list(namespace="default")
            cm = t.ConfigMap()
            cm.metadata.name = "plain-0"
            cs.configmaps.create(cm, "default")
            api = ApiClient(master.url)
            types = []
            with api.watch("/api/v1/namespaces/default/configmaps",
                           {"resourceVersion": str(rv0)}) as stream:
                for etype, _obj in stream:
                    types.append(etype)
                    break
            api.close()
            assert types == ["ADDED"]
        finally:
            cs.close()
            master.stop()


# ------------------------------------------------------ flight recorder


class TestFlightRecorder:
    def setup_method(self):
        flightrec.reset()

    def teardown_method(self):
        flightrec.reset()

    def test_ring_is_bounded_and_keeps_the_tail(self):
        for i in range(flightrec.RING_CAPACITY + 100):
            flightrec.note("apiserver", flightrec.SHED_429, seq=i)
        events = flightrec.dump("apiserver")["components"]["apiserver"]
        assert len(events) == flightrec.RING_CAPACITY
        assert events[-1]["seq"] == flightrec.RING_CAPACITY + 99
        assert events[0]["seq"] == 100  # oldest aged out

    def test_kinds_are_a_closed_enum(self):
        with pytest.raises(ValueError):
            flightrec.note("apiserver", "made_up_kind")
        assert flightrec.SHED_429 in flightrec.KINDS

    def test_failed_chaos_verdict_ships_timelines(self, monkeypatch):
        from scripts.chaos import _finalize_verdict

        flightrec.note("informer", flightrec.INFORMER_RELIST, resource="p")
        flightrec.note("store", flightrec.WAL_REPAIR, op="torn_tail")
        flightrec.note("store-standby", flightrec.STANDBY_PROMOTION, rev=9)
        red = _finalize_verdict({"seed": 1, "ok": False})
        assert set(red["flightrecorder"]) == {
            "informer", "store", "store-standby"}
        # a green verdict ships no black box...
        green = _finalize_verdict({"seed": 1, "ok": True})
        assert "flightrecorder" not in green
        # ...unless the forced-fail hook flips it red (the acceptance
        # path: a forced failing verdict writes >=3 components)
        monkeypatch.setenv("KTPU_CHAOS_FORCE_FAIL", "1")
        forced = _finalize_verdict({"seed": 1, "ok": True})
        assert forced["forced_fail"] and not forced["ok"]
        assert len(forced["flightrecorder"]) >= 3

    def test_collector_union_dedups_same_process_rings(self):
        """Two targets in ONE process serve identical rings: the fleet
        union keeps one copy of each event (and would CONCATENATE
        distinct processes' events, never drop a ring)."""
        flightrec.note("scheduler", flightrec.LEASE_SHED, shard=0)
        flightrec.note("scheduler", flightrec.LEASE_STEAL, shard=1)
        a = MetricsServer(Registry(), port=0).start()
        b = MetricsServer(Registry(), port=0).start()
        obs = ObsCollector(interval=5.0)
        try:
            obs.register("x", a.url, instance="x-0")
            obs.register("x", b.url, instance="x-1")
            obs.start()
            events = obs.flightrecorder()["components"]["scheduler"]
            assert [e["kind"] for e in events] == [
                flightrec.LEASE_SHED, flightrec.LEASE_STEAL]  # deduped, ordered
        finally:
            obs.stop()
            a.stop()
            b.stop()

    def test_metrics_server_serves_the_dump(self):
        flightrec.note("scheduler", flightrec.LEASE_STEAL, shard=1)
        srv = MetricsServer(Registry(), port=0).start()
        try:
            import json

            data = json.loads(fetch(srv.url + "/debug/flightrecorder"))
            assert data["components"]["scheduler"][0]["kind"] == \
                flightrec.LEASE_STEAL
        finally:
            srv.stop()


# -------------------------------------------- collector failure domain


class TestCollectorRobustness:
    def test_dead_target_marked_down_never_wedges_serving(self):
        reg = Registry()
        reg.counter("ktpu_live_total").inc(5)
        srv = MetricsServer(reg, port=0).start()
        obs = ObsCollector(interval=0.2, fetch_timeout=0.5)
        try:
            obs.register("live", srv.url, instance="live-0")
            obs.register("ghost", "http://127.0.0.1:1", instance="ghost-0")
            obs.start()
            must_poll_until(lambda: obs.scrapes_total >= 2
                            and obs.scrape_errors_total >= 1,
                            timeout=10.0, desc="scrapes + errors")
            t0 = time.monotonic()
            parsed = aggregate.parse_metrics_text(
                fetch(obs.url + "/metrics", timeout=2.0))
            assert time.monotonic() - t0 < 2.0  # serving never blocks
            up = aggregate.select(parsed, "ktpu_obs_scrape_up")
            assert up['ktpu_obs_scrape_up{instance="live-0"}'] == 1
            assert up['ktpu_obs_scrape_up{instance="ghost-0"}'] == 0
            assert parsed.samples["ktpu_live_total"] == 5
        finally:
            obs.stop()
            srv.stop()

    def test_reregister_moves_url_and_unregister_stops(self):
        obs = ObsCollector(interval=0.2)
        name = obs.register("c", "http://127.0.0.1:1")
        assert name == "c-0"
        assert obs.register("c", "http://127.0.0.1:2", instance="c-0") == "c-0"
        assert len(obs.targets()) == 1
        # a MOVED endpoint drops the old process's last-good snapshot —
        # the fleet view must not keep merging a dead process's counters
        tgt = obs.targets()[0]
        tgt.parsed = aggregate.parse_metrics_text("ktpu_x_total 1\n")
        tgt.up = True
        obs.register("c", "http://127.0.0.1:3", instance="c-0")
        assert tgt.parsed is None and not tgt.up
        obs.unregister("c-0")
        assert obs.targets() == []

    def test_generated_names_never_hijack_a_live_target(self):
        """Regression: count-based naming after an unregister collided
        with a live instance and silently rewrote its URL."""
        obs = ObsCollector(interval=0.2)
        obs.register("k", "http://127.0.0.1:1")    # k-0
        obs.register("k", "http://127.0.0.1:2")    # k-1
        obs.unregister("k-0")
        assert obs.register("k", "http://127.0.0.1:3") == "k-0"
        urls = {t.instance: t.url for t in obs.targets()}
        assert urls == {"k-1": "http://127.0.0.1:2",
                        "k-0": "http://127.0.0.1:3"}
