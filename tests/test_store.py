"""MVCC store tests: CAS semantics, watch resume, compaction, WAL replay."""

import threading

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.machinery import (
    ADDED,
    AlreadyExists,
    Conflict,
    DELETED,
    MODIFIED,
    NotFound,
    TooOldResourceVersion,
)
from kubernetes1_tpu.machinery.scheme import global_scheme
from kubernetes1_tpu.storage import Store

from tests.test_machinery import make_pod


@pytest.fixture
def store():
    s = Store(global_scheme)
    yield s
    s.close()


def key(pod):
    return f"/registry/pods/{pod.metadata.namespace}/{pod.metadata.name}"


class TestCRUD:
    def test_create_get(self, store):
        pod = make_pod()
        created = store.create(key(pod), pod)
        assert created.metadata.uid
        assert created.metadata.resource_version == "1"
        got = store.get(key(pod))
        assert got.metadata.name == "p1"

    def test_create_duplicate(self, store):
        pod = make_pod()
        store.create(key(pod), pod)
        with pytest.raises(AlreadyExists):
            store.create(key(pod), make_pod())

    def test_get_missing(self, store):
        with pytest.raises(NotFound):
            store.get("/registry/pods/default/nope")

    def test_list_prefix(self, store):
        for i in range(3):
            store.create(key(make_pod(f"p{i}")), make_pod(f"p{i}"))
        store.create(key(make_pod("x", ns="other")), make_pod("x", ns="other"))
        items, rev = store.list("/registry/pods/default/")
        assert [p.metadata.name for p in items] == ["p0", "p1", "p2"]
        allpods, _ = store.list("/registry/pods/")
        assert len(allpods) == 4
        assert rev >= 3

    def test_delete(self, store):
        pod = store.create(key(make_pod()), make_pod())
        store.delete(key(pod))
        with pytest.raises(NotFound):
            store.get(key(pod))


class TestCAS:
    def test_stale_rv_conflicts(self, store):
        pod = store.create(key(make_pod()), make_pod())
        fresh = store.get(key(pod))
        fresh.spec.node_name = "n1"
        store.update_cas(key(pod), fresh)
        # pod still has rv=1; this write must fail
        pod.spec.node_name = "n2"
        with pytest.raises(Conflict):
            store.update_cas(key(pod), pod)

    def test_guaranteed_update_retries(self, store):
        pod = store.create(key(make_pod()), make_pod())
        k = key(pod)
        calls = {"n": 0}

        def bump(p):
            if calls["n"] == 0:
                # sabotage: concurrent writer bumps the rv mid-update
                other = store.get(k)
                other.metadata.labels["racer"] = "1"
                store.update_cas(k, other)
            calls["n"] += 1
            p.metadata.labels["winner"] = "1"
            return p

        out = store.guaranteed_update(k, bump)
        assert calls["n"] == 2  # retried once after the injected conflict
        assert out.metadata.labels == {"app": "test", "racer": "1", "winner": "1"}

    def test_concurrent_guaranteed_updates_all_land(self, store):
        pod = store.create(key(make_pod()), make_pod())
        k = key(pod)

        def inc(i):
            def fn(p):
                p.metadata.annotations[f"w{i}"] = "1"
                return p
            store.guaranteed_update(k, fn)

        threads = [threading.Thread(target=inc, args=(i,)) for i in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        final = store.get(k)
        assert len(final.metadata.annotations) == 8


class TestWatch:
    def test_watch_live_events(self, store):
        w = store.watch("/registry/pods/")
        pod = store.create(key(make_pod()), make_pod())
        fresh = store.get(key(pod))
        fresh.spec.node_name = "n1"
        store.update_cas(key(pod), fresh)
        store.delete(key(pod))
        evs = [w.next_timeout(1) for _ in range(3)]
        assert [e.type for e in evs] == [ADDED, MODIFIED, DELETED]
        assert evs[1].object["spec"]["nodeName"] == "n1"
        w.stop()

    def test_watch_resume_from_revision(self, store):
        store.create(key(make_pod("a")), make_pod("a"))
        _, rev = store.list("/registry/pods/")
        store.create(key(make_pod("b")), make_pod("b"))
        w = store.watch("/registry/pods/", since_rev=rev)
        ev = w.next_timeout(1)
        assert ev.type == ADDED
        assert ev.object["metadata"]["name"] == "b"
        w.stop()

    def test_watch_prefix_filtering(self, store):
        w = store.watch("/registry/nodes/")
        store.create(key(make_pod()), make_pod())
        n = t.Node()
        n.metadata.name = "n1"
        store.create("/registry/nodes/n1", n)
        ev = w.next_timeout(1)
        assert ev.object["kind"] == "Node"
        w.stop()

    def test_compaction_forces_relist(self, store):
        for i in range(10):
            store.create(key(make_pod(f"p{i}")), make_pod(f"p{i}"))
        store.compact(keep_last=2)
        with pytest.raises(TooOldResourceVersion):
            store.watch("/registry/pods/", since_rev=1)
        # resuming above the floor still works
        w = store.watch("/registry/pods/", since_rev=9)
        ev = w.next_timeout(1)
        assert ev.object["metadata"]["name"] == "p9"
        w.stop()


class TestWAL:
    def test_replay(self, tmp_path):
        wal = str(tmp_path / "store.wal")
        s1 = Store(global_scheme, wal_path=wal)
        s1.create(key(make_pod("a")), make_pod("a"))
        s1.create(key(make_pod("b")), make_pod("b"))
        s1.delete(key(make_pod("a")))
        s1.close()

        s2 = Store(global_scheme, wal_path=wal)
        items, rev = s2.list("/registry/pods/")
        assert [p.metadata.name for p in items] == ["b"]
        assert rev == 3  # revision counter survives restart
        # new writes continue the sequence
        s2.create(key(make_pod("c")), make_pod("c"))
        assert s2.get(key(make_pod("c"))).metadata.resource_version == "4"
        s2.close()


class TestHistoryImmutability:
    def test_delete_does_not_restamp_history(self, store):
        """Regression: _commit must not mutate dicts already in history —
        a replayed ADDED event keeps its own revision, not the delete's."""
        store.create(key(make_pod("a")), make_pod("a"))
        _, rev_after_a = store.list("/registry/pods/")
        store.create(key(make_pod("b")), make_pod("b"))
        store.delete(key(make_pod("b")))
        w = store.watch("/registry/pods/", since_rev=rev_after_a)
        added = w.next_timeout(1)
        deleted = w.next_timeout(1)
        assert added.type == ADDED
        assert added.object["metadata"]["resourceVersion"] == str(rev_after_a + 1)
        assert deleted.type == DELETED
        assert deleted.object["metadata"]["resourceVersion"] == str(rev_after_a + 2)
        w.stop()
