"""Shared-object mutation sanitizer (utils/mutsan, KTPU_MUTSAN=1) tests:
freeze semantics, the clone() escape hatch across every registered API
type, informer snapshot semantics, and the stale-serialization hazard
the sanitizer exists to catch (a mutated shared object vs the bytes
already cached for its resourceVersion)."""

import copy
import dataclasses
import json

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.machinery.meta import KObject, ObjectMeta
from kubernetes1_tpu.machinery.scheme import (
    Unstructured,
    global_scheme,
    to_dict,
)
from kubernetes1_tpu.storage import Store
from kubernetes1_tpu.storage.cacher import Cacher
from kubernetes1_tpu.utils import mutsan
from kubernetes1_tpu.utils.mutsan import SharedObjectMutationError

from tests.test_machinery import make_pod

# tests/conftest.py turns the sanitizer on for the whole suite; these
# tests are about its semantics, so double-check rather than assume
pytestmark = pytest.mark.skipif(
    not mutsan.enabled(), reason="KTPU_MUTSAN disabled")


def frozen_pod(name="p1", origin="test-origin"):
    pod = make_pod(name)
    pod.metadata.uid = f"uid-{name}"
    pod.metadata.resource_version = "7"
    pod.metadata.annotations = {"a": "1"}
    pod.spec.extended_resources = [
        t.PodExtendedResource(name="tpu", resource="google.com/tpu",
                              quantity=2, assigned=["0", "1"])
    ]
    return pod, mutsan.freeze(pod, origin)


class TestFreezeSemantics:
    def test_attribute_assignment_raises_with_both_sites(self):
        _pod, froz = frozen_pod()
        with pytest.raises(SharedObjectMutationError) as ei:
            froz.status.phase = "Failed"
        assert "test-origin" in str(ei.value)  # acquisition site
        assert "clone()" in str(ei.value)      # the fix

    def test_nested_dict_and_list_mutations_raise(self):
        _pod, froz = frozen_pod()
        with pytest.raises(SharedObjectMutationError):
            froz.metadata.annotations["x"] = "y"
        with pytest.raises(SharedObjectMutationError):
            froz.metadata.labels.update({"x": "y"})
        with pytest.raises(SharedObjectMutationError):
            froz.spec.containers.append(t.Container(name="evil"))
        with pytest.raises(SharedObjectMutationError):
            froz.spec.containers[0].resources.limits.pop("cpu")
        with pytest.raises(SharedObjectMutationError):
            froz.spec.extended_resources[0].assigned.clear()
        with pytest.raises(SharedObjectMutationError):
            del froz.metadata.annotations["a"]

    def test_reads_recurse_and_match_the_raw_object(self):
        pod, froz = frozen_pod()
        assert froz.metadata.name == "p1"
        assert froz.spec.containers[0].resources.limits["cpu"] == "500m"
        assert [c.name for c in froz.spec.containers] == ["main"]
        assert froz.key() == pod.key()
        assert isinstance(froz, t.Pod)
        assert froz == pod
        assert froz.KIND == "Pod"  # class attrs forward per-instance

    def test_container_handouts_are_snapshots(self):
        pod, froz = frozen_pod()
        anns = froz.metadata.annotations
        pod.metadata.annotations["later"] = "write"  # raw write-side update
        assert "later" not in anns  # the earlier handout is a snapshot

    def test_memo_slots_write_through(self):
        pod, froz = frozen_pod()
        froz._ktpu_mcpu = 500  # the scheduler's request-size memo idiom
        assert pod._ktpu_mcpu == 500

    def test_encode_paths_thaw_transparently(self):
        pod, froz = frozen_pod()
        assert to_dict(froz) == to_dict(pod)
        assert global_scheme.encode(froz) == global_scheme.encode(pod)
        assert global_scheme.encode_obj_bytes(froz) == \
            global_scheme.encode_obj_bytes(pod)

    def test_clone_and_deepcopy_thaw(self):
        pod, froz = frozen_pod()
        for thawed in (froz.clone(), copy.deepcopy(froz),
                       global_scheme.deepcopy(froz)):
            thawed.status.phase = "Failed"
            thawed.metadata.annotations["x"] = "y"
            assert pod.status.phase != "Failed"
            assert "x" not in pod.metadata.annotations

    def test_frozen_dict_still_jsons(self):
        d = global_scheme.encode(make_pod())
        froz = mutsan.freeze(d, "test-origin")
        assert json.loads(json.dumps(froz)) == d
        with pytest.raises(SharedObjectMutationError):
            froz["spec"]["nodeName"] = "n1"
        with pytest.raises(SharedObjectMutationError):
            froz["metadata"].setdefault("labels", {})

    def test_unstructured_freezes_too(self):
        u = Unstructured(kind="Widget", api_version="example/v1",
                         content={"spec": {"size": 3}})
        froz = mutsan.freeze(u, "test-origin")
        assert froz.spec["size"] == 3
        with pytest.raises(SharedObjectMutationError):
            froz.spec["size"] = 4
        c = froz.clone()
        c.content["spec"]["size"] = 4
        assert u.content["spec"]["size"] == 3

    def test_disabled_is_identity(self, monkeypatch):
        monkeypatch.setenv("KTPU_MUTSAN", "0")
        pod = make_pod()
        assert mutsan.freeze(pod) is pod


class TestCloneRegistryRoundTrip:
    """clone() deep-copy independence for EVERY registered API type,
    driven off the scheme's registry so new kinds are covered the moment
    they register."""

    @staticmethod
    def _mutate_everything(obj, depth=0):
        """Recursively deface every reachable field of a clone."""
        if depth > 6 or not dataclasses.is_dataclass(obj):
            return
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if isinstance(v, str):
                setattr(obj, f.name, "mutated")
            elif isinstance(v, bool):
                setattr(obj, f.name, not v)
            elif isinstance(v, int):
                setattr(obj, f.name, 999)
            elif isinstance(v, dict):
                v["__mutated__"] = "x"
            elif isinstance(v, list):
                v.append("__mutated__")
            elif dataclasses.is_dataclass(v):
                TestCloneRegistryRoundTrip._mutate_everything(v, depth + 1)

    def test_every_registered_type_clones_independently(self):
        kinds = {kind: cls for kind, cls in global_scheme.by_kind.items()
                 if dataclasses.is_dataclass(cls)}
        assert len(kinds) > 20  # the registry is populated
        for kind, cls in sorted(kinds.items()):
            obj = cls()
            obj.metadata = ObjectMeta(
                name="orig", namespace="ns", uid="u1", resource_version="5",
                labels={"k": "v"}, annotations={"a": "1"})
            before = to_dict(obj)
            clone = obj.clone()
            assert clone is not obj
            self._mutate_everything(clone)
            assert to_dict(obj) == before, (
                f"{kind}: mutating a clone leaked into the original")

    def test_clone_covers_deep_pod_structure(self):
        pod = make_pod(tpus=4)
        pod.spec.extended_resources = [
            t.PodExtendedResource(name="tpu", resource="google.com/tpu",
                                  quantity=4, assigned=["0", "1", "2", "3"])
        ]
        before = to_dict(pod)
        c = pod.clone()
        c.spec.containers[0].resources.limits["cpu"] = "9"
        c.spec.extended_resources[0].assigned.append("4")
        c.metadata.labels["x"] = "y"
        assert to_dict(pod) == before


class _FakeResourceClient:
    """Just enough of ResourceClient for SharedInformer._relist."""

    resource = "pods"
    scheme = global_scheme

    def __init__(self, items):
        self.items = items

    def list(self, namespace="", label_selector="", field_selector="",
             limit=0):
        return list(self.items), "5"


class TestInformerSnapshotSemantics:
    def _informer(self, pods):
        from kubernetes1_tpu.client.informer import SharedInformer

        inf = SharedInformer(_FakeResourceClient(pods))
        inf._relist()
        return inf

    def test_handouts_are_frozen_and_list_is_fresh(self):
        pods = [make_pod("a"), make_pod("b")]
        inf = self._informer(pods)
        got = inf.list()
        assert {p.metadata.name for p in got} == {"a", "b"}
        assert got is not inf.list()  # fresh list object per call
        with pytest.raises(SharedObjectMutationError):
            got[0].status.phase = "Failed"
        with pytest.raises(SharedObjectMutationError):
            inf.get("default/a").metadata.annotations["x"] = "y"

    def test_handlers_see_frozen_objects(self):
        seen = []
        pods = [make_pod("a")]
        inf = self._informer(pods)
        inf.add_handler(on_add=lambda o: seen.append(o))
        inf._relist()  # resync dispatches adds/updates against the cache
        update_args = []
        inf.add_handler(on_update=lambda o, n: update_args.append((o, n)))
        inf._relist()
        for obj in seen + [o for pair in update_args for o in pair]:
            with pytest.raises(SharedObjectMutationError):
                obj.metadata.labels["x"] = "y"

    def test_clone_then_write_is_the_sanctioned_path(self):
        inf = self._informer([make_pod("a")])
        fresh = inf.get("default/a").clone()
        fresh.status.phase = "Failed"  # fine: private copy
        assert inf.get("default/a").status.phase != "Failed"


class TestStaleSerializationHazard:
    """The PR 3 read path caches serialized bytes per
    (uid, resourceVersion): an in-place mutation of a shared object
    CANNOT invalidate those bytes — live state and every cached response
    silently diverge at the same revision.  This is the hazard class the
    sanitizer turns into a loud error at the mutation site."""

    def test_mutating_a_shared_dict_would_go_stale(self):
        # demonstrate the hazard with the cache machinery itself, on a
        # private (unfrozen) dict standing in for an aliased cache entry
        d = global_scheme.encode(make_pod("stale"))
        d["metadata"]["uid"] = "u-stale"
        d["metadata"]["resourceVersion"] = "42"
        raw1 = global_scheme.encode_bytes(d)
        d["spec"]["nodeName"] = "mutated-in-place"  # the bug class
        raw2 = global_scheme.encode_bytes(d)
        # same (uid, rv) -> same cached bytes: the mutation is INVISIBLE
        # to every LIST/GET/watch consumer — live object and wire bytes
        # now disagree at revision 42
        assert raw2 == raw1
        assert b"mutated-in-place" not in raw2

    def test_cacher_handouts_refuse_the_mutation(self):
        store = Store(global_scheme)
        try:
            pod = make_pod("guarded")
            key = "/registry/pods/default/guarded"
            store.create(key, pod)
            cacher = Cacher(store, global_scheme).start()
            try:
                d = cacher.get_raw(key)
                raw_before = global_scheme.encode_bytes(d)
                with pytest.raises(SharedObjectMutationError):
                    d["spec"]["nodeName"] = "mutated-in-place"
                with pytest.raises(SharedObjectMutationError):
                    d["metadata"]["annotations"] = {"x": "y"}
                (entry,), _rev = cacher.list_raw("/registry/pods/default/")
                with pytest.raises(SharedObjectMutationError):
                    entry[2]["metadata"]["labels"]["x"] = "y"
                # the cached bytes for this revision stayed authoritative
                assert global_scheme.encode_bytes(
                    cacher.get_raw(key)) == raw_before
            finally:
                cacher.stop()
        finally:
            store.close()

    def test_unstructured_decode_no_longer_aliases_committed_state(self):
        """Regression for a real pre-existing bug the mutation-safety work
        surfaced: Scheme.decode built Unstructured.content as a SHALLOW
        copy, so a decoded CRD object's spec/status dicts WERE the
        committed store entry's dicts — and `guaranteed_update`'s
        documented mutate-in-place idiom then rewrote committed history,
        the watch cache, and the bytes cached for an UNCHANGED
        resourceVersion.  (encode had the same aliasing in the write
        direction.)  Both now deep-copy."""
        scheme = global_scheme.copy()
        scheme.register_dynamic("Widget", "widgets", "example/v1")
        store = Store(scheme)
        try:
            key = "/registry/widgets/default/w1"
            u = Unstructured(kind="Widget", api_version="example/v1",
                             content={"spec": {"replicas": 1}})
            u.metadata.name = "w1"
            u.metadata.namespace = "default"
            store.create(key, u)
            # write-direction isolation: the caller keeps mutating its own
            # object after create — committed state must not follow
            u.spec["replicas"] = 50
            cacher = Cacher(store, scheme).start()
            try:
                d = cacher.get_raw(key)
                rv = d["metadata"]["resourceVersion"]
                raw_before = scheme.encode_bytes(d)
                # read-direction isolation: mutate a decoded object the way
                # guaranteed_update's update_fn is invited to
                cur = store.get(key)
                cur.spec["replicas"] = 99
                again = store.get(key)
                assert again.spec["replicas"] == 1  # pristine at same rv
                assert again.metadata.resource_version == rv
                # and the cached bytes for that revision still match the
                # live committed state — no silent divergence
                d2 = cacher.get_raw(key)
                assert d2["spec"]["replicas"] == 1
                assert scheme.encode_bytes(d2) == raw_before
            finally:
                cacher.stop()
        finally:
            store.close()

    def test_sanctioned_path_produces_new_revision_and_new_bytes(self):
        store = Store(global_scheme)
        try:
            pod = make_pod("rewrite")
            key = "/registry/pods/default/rewrite"
            store.create(key, pod)
            cacher = Cacher(store, global_scheme).start()
            try:
                d = cacher.get_raw(key)
                fresh = copy.deepcopy(d)  # clone-before-mutate on a raw dict
                fresh["spec"]["nodeName"] = "node-9"
                obj = global_scheme.decode(fresh)  # carries the CAS rv
                store.update_cas(key, obj)
                d2 = cacher.get_raw(key)
                assert d2["spec"]["nodeName"] == "node-9"
                assert d2["metadata"]["resourceVersion"] != \
                    d["metadata"]["resourceVersion"]
                assert b"node-9" in global_scheme.encode_bytes(d2)
            finally:
                cacher.stop()
        finally:
            store.close()
