"""Store replication: WAL shipping to a warm standby + self-promotion +
client failover (VERDICT r4 Missing #1 — the store was the last SPOF).

Ref role: etcd quorum behind stateless apiservers
(staging/src/k8s.io/apiserver/pkg/storage/etcd3/store.go:152,263).  The
two-member analog here: semi-synchronous commit shipping (a write acks to
the client only after the standby acked it), standby promotes on
connection-refused, RemoteStore fails over on NotPrimary."""

import os
import signal
import subprocess
import sys
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.machinery.scheme import global_scheme
from kubernetes1_tpu.storage import Store
from kubernetes1_tpu.storage.remote import RemoteStore
from kubernetes1_tpu.storage.server import NotPrimary, StoreServer
from kubernetes1_tpu.storage.standby import StandbyServer
from kubernetes1_tpu.utils.waitutil import must_poll_until


def make_pod(name, ns="d"):
    pod = t.Pod()
    pod.metadata.name = name
    pod.metadata.namespace = ns
    return pod


@pytest.fixture()
def pair(tmp_path):
    """primary StoreServer + in-process StandbyServer replicating from it."""
    psock = str(tmp_path / "primary.sock")
    ssock = str(tmp_path / "standby.sock")
    store = Store(global_scheme.copy(), wal_path=str(tmp_path / "p.wal"))
    primary = StoreServer(store, psock).start()
    standby = StandbyServer(psock, ssock,
                            wal_path=str(tmp_path / "s.wal"),
                            failover_grace=0.5).start()
    yield {"primary": primary, "standby": standby, "store": store,
           "psock": psock, "ssock": ssock, "tmp": tmp_path}
    standby.stop()
    primary.stop()


class TestReplication:
    def test_writes_ship_to_standby(self, pair):
        rs = RemoteStore(global_scheme.copy(), pair["psock"])
        # writes are only ack-gated once the standby's replicate handshake
        # has registered — wait for attachment or the semi-sync assertion
        # below races the standby's startup
        must_poll_until(lambda: pair["primary"]._replica_acks,
                        timeout=10.0, desc="standby attached")
        for i in range(20):
            rs.create(f"/registry/pods/d/p{i}", make_pod(f"p{i}"))
        # semi-sync: by the time create() returned, the standby acked —
        # its local store must already hold every write
        st = pair["standby"].store
        assert st.current_revision() == pair["store"].current_revision()
        items, _ = st.list("/registry/pods/")
        assert len(items) == 20
        rs.close()

    def test_standby_refuses_clients_until_promoted(self, pair):
        direct = RemoteStore(global_scheme.copy(), pair["ssock"])
        with pytest.raises((NotPrimary, ConnectionError)):
            direct.create("/registry/pods/d/x", make_pod("x"))
        direct.close()

    def test_snapshot_catchup_for_late_standby(self, tmp_path):
        """A standby joining AFTER history compaction bootstraps from a
        snapshot, not the (gone) incremental history."""
        psock = str(tmp_path / "p.sock")
        store = Store(global_scheme.copy(), history_limit=10)
        primary = StoreServer(store, psock).start()
        rs = RemoteStore(global_scheme.copy(), psock)
        for i in range(50):  # compaction floor moves past rev 0
            rs.create(f"/registry/pods/d/p{i}", make_pod(f"p{i}"))
        standby = StandbyServer(psock, str(tmp_path / "s.sock"),
                                failover_grace=0.5).start()
        must_poll_until(
            lambda: standby.store.current_revision() ==
            store.current_revision(),
            timeout=10.0, desc="standby caught up via snapshot")
        items, _ = standby.store.list("/registry/pods/")
        assert len(items) == 50
        rs.close()
        standby.stop()
        primary.stop()

    def test_promotion_on_primary_death_and_client_failover(self, pair):
        both = f'{pair["psock"]},{pair["ssock"]}'
        rs = RemoteStore(global_scheme.copy(), both)
        created = [f"p{i}" for i in range(10)]
        for name in created:
            rs.create(f"/registry/pods/d/{name}", make_pod(name))
        # kill the primary the hard way (in-process: stop it so the socket
        # refuses), wait for standby self-promotion
        pair["primary"].stop()
        os.unlink(pair["psock"])  # a dead unix socket must refuse, not hang
        must_poll_until(lambda: pair["standby"].promoted.is_set(),
                        timeout=10.0, desc="standby promoted")
        # the same client keeps working via failover...
        rs.create("/registry/pods/d/after", make_pod("after"))
        # ...and NO acknowledged write was lost
        items, _ = rs.list("/registry/pods/")
        names = {o.metadata.name for o in items}
        assert names == set(created) | {"after"}
        rs.close()



# ---------------------------------------------------------------- process e2e

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(cmd, log):
    with open(log, "ab") as lf:
        return subprocess.Popen(
            cmd, stdout=lf, stderr=subprocess.STDOUT,
            start_new_session=True,
            env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu"),
            cwd=REPO)


def _free_port():
    import socket as s

    with s.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


@pytest.fixture()
def replicated_cluster(tmp_path, request):
    """primary store + standby store + apiserver(both) + KCM + scheduler +
    fake kubelet — all real processes; reaper registered before spawning
    (the r4 leak lesson)."""
    from kubernetes1_tpu.client import Clientset

    d = str(tmp_path)
    psock, ssock = os.path.join(d, "p.sock"), os.path.join(d, "s.sock")
    port = _free_port()
    url = f"http://127.0.0.1:{port}"
    py = sys.executable
    procs = {}
    clients = []

    def reap():
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001
                pass
        for p in procs.values():
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass

    request.addfinalizer(reap)
    procs["store-primary"] = _spawn(
        [py, "-m", "kubernetes1_tpu.storage", "--socket", psock,
         "--wal", os.path.join(d, "p.wal")],
        os.path.join(d, "store-primary.log"))
    must_poll_until(lambda: os.path.exists(psock), timeout=20.0,
                    desc="primary store socket")
    procs["store-standby"] = _spawn(
        [py, "-m", "kubernetes1_tpu.storage", "--socket", ssock,
         "--wal", os.path.join(d, "s.wal"),
         "--standby-of", psock, "--failover-grace", "0.5"],
        os.path.join(d, "store-standby.log"))
    procs["apiserver"] = _spawn(
        [py, "-m", "kubernetes1_tpu.apiserver", "--port", str(port),
         "--store-address", f"{psock},{ssock}"],
        os.path.join(d, "apiserver.log"))
    cs = Clientset(url)
    clients.append(cs)

    def healthy():
        try:
            cs.api.request("GET", "/healthz")
            return True
        except Exception:  # noqa: BLE001
            return False

    must_poll_until(healthy, timeout=60.0, desc="apiserver healthy")
    procs["kcm"] = _spawn(
        [py, "-m", "kubernetes1_tpu.controllers", "--server", url],
        os.path.join(d, "kcm.log"))
    procs["sched"] = _spawn(
        [py, "-m", "kubernetes1_tpu.scheduler", "--server", url,
         "--metrics-port", "-1"],
        os.path.join(d, "sched.log"))
    procs["kubelet"] = _spawn(
        [py, "-m", "kubernetes1_tpu.kubelet", "--server", url,
         "--node-name", "repl-node", "--runtime", "fake",
         "--root-dir", os.path.join(d, "kubelet")],
        os.path.join(d, "kubelet.log"))
    return {"cs": cs, "procs": procs, "dir": d}


class TestStoreFailoverE2E:
    def test_sigkill_primary_store_mid_job(self, replicated_cluster):
        """THE r4 bar (Missing #1): kill the store process mid-Job; the
        warm standby promotes, no acknowledged write is lost, the Job
        completes.  Before round 5 this killed the whole control plane."""
        env = replicated_cluster
        cs = env["cs"]
        must_poll_until(
            lambda: any(c.type == "Ready" and c.status == "True"
                        for n in cs.nodes.list()[0]
                        for c in n.status.conditions),
            timeout=60.0, desc="node Ready")
        job = t.Job()
        job.metadata.name = "repl-job"
        job.spec.completions = 4
        job.spec.parallelism = 2
        pod_t = t.PodTemplateSpec()
        pod_t.spec.restart_policy = "Never"
        pod_t.spec.containers = [t.Container(
            name="w", image="img", command=["sleep", "1"])]
        job.spec.template = pod_t
        cs.jobs.create(job, "default")
        must_poll_until(
            lambda: len(cs.pods.list(namespace="default")[0]) >= 1,
            timeout=30.0, desc="job pods created")
        # acknowledged just before the kill: must exist after failover
        marker = t.ConfigMap(data={"written": "before-kill"})
        marker.metadata.name = "pre-kill-marker"
        cs.configmaps.create(marker, "default")
        os.killpg(env["procs"]["store-primary"].pid, signal.SIGKILL)
        # standby promotes; apiserver's RemoteStore fails over; the Job
        # completes through the promoted store
        must_poll_until(
            lambda: _succeeded(cs) >= 4,
            timeout=240.0, desc="job completes through promoted standby")
        assert cs.configmaps.get(
            "pre-kill-marker", "default").data["written"] == "before-kill"
        with open(os.path.join(env["dir"], "store-standby.log")) as f:
            assert "PROMOTED" in f.read()


def _succeeded(cs):
    try:
        return cs.jobs.get("repl-job", "default").status.succeeded or 0
    except Exception:  # noqa: BLE001
        return 0


class TestLaggardStandby:
    def test_wedged_standby_dropped_writes_continue(self, tmp_path, request):
        """A SIGSTOPped standby (full buffers, no acks) must cost writes at
        most the ack timeout ONCE — the primary drops it and severs the
        socket rather than wedging the control plane."""
        from kubernetes1_tpu.storage.server import (
            REPLICATION_ACK_TIMEOUT_SECONDS,
        )

        d = str(tmp_path)
        psock, ssock = os.path.join(d, "p.sock"), os.path.join(d, "s.sock")
        store = Store(global_scheme.copy())
        primary = StoreServer(store, psock).start()
        request.addfinalizer(primary.stop)
        proc = _spawn(
            [sys.executable, "-m", "kubernetes1_tpu.storage",
             "--socket", ssock, "--standby-of", psock],
            os.path.join(d, "standby.log"))

        def reap():
            try:
                os.killpg(proc.pid, signal.SIGCONT)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            proc.wait(timeout=10)

        request.addfinalizer(reap)
        rs = RemoteStore(global_scheme.copy(), psock)
        request.addfinalizer(rs.close)
        must_poll_until(lambda: primary._replica_acks, timeout=20.0,
                        desc="standby attached")
        rs.create("/registry/pods/d/warm", make_pod("warm"))
        os.killpg(proc.pid, signal.SIGSTOP)  # wedge: reads nothing, acks nothing
        t0 = time.monotonic()
        rs.create("/registry/pods/d/during", make_pod("during"))
        first = time.monotonic() - t0
        t0 = time.monotonic()
        rs.create("/registry/pods/d/after", make_pod("after"))
        second = time.monotonic() - t0
        # first write paid the ack timeout; the laggard was then dropped
        assert first < REPLICATION_ACK_TIMEOUT_SECONDS + 3.0
        assert second < 1.0
        assert not primary._replica_acks  # standby really was dropped

    @pytest.mark.thread_leak_ok  # in-process standby worker threads
    def test_laggard_drop_keeps_expectation_with_healthy_standby(
            self, tmp_path, request):
        """Dropping a laggard must NOT disarm the replication expectation
        while another healthy standby remains attached: a later flap of
        the healthy link still has to gate write acks (regression — the
        global disarm silently reopened the unprotected reconnect window
        for the survivor)."""
        from kubernetes1_tpu.storage.server import (
            REPLICATION_ACK_TIMEOUT_SECONDS,
        )

        d = str(tmp_path)
        psock = os.path.join(d, "p.sock")
        store = Store(global_scheme.copy())
        primary = StoreServer(store, psock).start()
        request.addfinalizer(primary.stop)
        # the laggard: a subprocess standby this test can SIGSTOP
        proc = _spawn(
            [sys.executable, "-m", "kubernetes1_tpu.storage",
             "--socket", os.path.join(d, "s1.sock"), "--standby-of", psock],
            os.path.join(d, "standby1.log"))

        def reap():
            for sig in (signal.SIGCONT, signal.SIGKILL):
                try:
                    os.killpg(proc.pid, sig)
                except (ProcessLookupError, PermissionError):
                    pass
            proc.wait(timeout=10)

        request.addfinalizer(reap)
        # the healthy survivor: in-process, so stop() can flap its link
        healthy = StandbyServer(psock, os.path.join(d, "s2.sock"),
                                failover_grace=60.0).start()
        request.addfinalizer(healthy.stop)
        must_poll_until(lambda: len(primary._replica_acks) == 2,
                        timeout=20.0, desc="both standbys attached")
        rs = RemoteStore(global_scheme.copy(), psock)
        request.addfinalizer(rs.close)
        rs.create("/registry/pods/d/warm", make_pod("warm"))
        os.killpg(proc.pid, signal.SIGSTOP)  # standby 1 wedges
        # pays the ack timeout once; the laggard is dropped
        rs.create("/registry/pods/d/during", make_pod("during"))
        with primary._repl_cond:
            assert len(primary._replica_acks) == 1, \
                "healthy standby must survive the laggard drop"
            assert primary._expect_replicas, \
                "expectation must stay armed while a standby remains"
        # the survivor's link now drops: the next write must WAIT for a
        # reattach (timing out into a COUNTED unprotected ack), never
        # fast-ack silently into the flap window
        before = primary.unprotected_acks
        healthy.stop()
        must_poll_until(lambda: not primary._replica_acks, timeout=10.0,
                        desc="healthy standby detached")
        t0 = time.monotonic()
        rs.create("/registry/pods/d/after", make_pod("after"))
        waited = time.monotonic() - t0
        assert waited >= 1.0, \
            f"write fast-acked into the flap window after {waited:.2f}s"
        assert waited < REPLICATION_ACK_TIMEOUT_SECONDS + 3.0
        assert primary.unprotected_acks == before + 1


class TestBatchUnprotectedAckCount:
    @pytest.mark.thread_leak_ok  # in-process standby worker threads
    def test_timed_out_gate_counts_every_batch_member(
            self, tmp_path, request):
        """A group commit gates N ops on ONE replication wait: when that
        wait times out into an unprotected ack, all N successful ops ship
        unprotected — the exposure counter must grow by N, not by 1
        (regression: the transition batch undercounted by N-1)."""
        d = str(tmp_path)
        psock = os.path.join(d, "p.sock")
        store = Store(global_scheme.copy())
        primary = StoreServer(store, psock).start()
        request.addfinalizer(primary.stop)
        standby = StandbyServer(psock, os.path.join(d, "s.sock"),
                                failover_grace=60.0).start()
        must_poll_until(lambda: primary._replica_acks, timeout=20.0,
                        desc="standby attached")
        standby.stop()  # link drops; expectation stays armed
        must_poll_until(lambda: not primary._replica_acks, timeout=10.0,
                        desc="standby detached")
        with primary._repl_cond:
            assert primary._expect_replicas
        rs = RemoteStore(global_scheme.copy(), psock)
        request.addfinalizer(rs.close)
        before = primary.unprotected_acks
        scheme = global_scheme.copy()
        out = rs.commit_batch([
            {"op": "create", "key": f"/registry/pods/d/b{i}",
             "obj": scheme.encode(make_pod(f"b{i}"))}
            for i in range(3)])
        assert all("obj" in r for r in out)
        assert primary.unprotected_acks == before + 3, \
            f"expected +3 exposed acks, got +{primary.unprotected_acks - before}"
