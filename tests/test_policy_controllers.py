"""Policy-layer tests: quota/limits admission, service accounts + tokens,
HPA over the metrics pipeline, PDB status, pod GC, job TTL, CSR signing, and
PV/PVC binding — the reference's test/integration/{quota,serviceaccount,
evictions,garbagecollector} areas plus autoscaling."""

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.controllers import ControllerManager
from kubernetes1_tpu.controllers.certificates import verify_certificate
from kubernetes1_tpu.controllers.serviceaccount import verify_token
from kubernetes1_tpu.machinery import Forbidden, NotFound
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.helpers import mutate_with_retry
from tests.test_controllers import start_hollow_node


@pytest.fixture()
def cluster(tmp_path):
    master = Master().start()
    cs = Clientset(master.url)
    sched = Scheduler(cs)
    sched.start()
    cm = ControllerManager(cs, monitor_grace=2.0, eviction_timeout=2.0)
    cm.start()
    kubelet, plugin, impl = start_hollow_node(cs, "node-0", str(tmp_path), tpus=4)
    env = {"master": master, "cs": cs, "kubelet": kubelet}
    yield env
    kubelet.stop()
    plugin.stop()
    cm.stop()
    sched.stop()
    cs.close()
    master.stop()


def simple_pod(name, cpu_request="100m", labels=None, command=None):
    pod = t.Pod()
    pod.metadata.name = name
    pod.metadata.labels = labels or {}
    pod.spec.containers = [
        t.Container(
            name="c",
            image="busybox",
            command=command or ["serve"],
            resources=t.ResourceRequirements(requests={"cpu": cpu_request}),
        )
    ]
    return pod


class TestQuotaAndLimits:
    def test_quota_blocks_over_limit_and_tracks_usage(self, cluster):
        cs = cluster["cs"]
        quota = t.ResourceQuota()
        quota.metadata.name = "q"
        quota.spec.hard = {"pods": "2", "google.com/tpu": "2"}
        cs.resourcequotas.create(quota)

        cs.pods.create(simple_pod("p1"))
        cs.pods.create(simple_pod("p2"))
        with pytest.raises(Forbidden, match="exceeded quota"):
            cs.pods.create(simple_pod("p3"))

        must_poll_until(
            lambda: cs.resourcequotas.get("q").status.used.get("pods") == "2",
            timeout=10.0, desc="quota status.used",
        )

    def test_quota_enforces_tpu_chips(self, cluster):
        cs = cluster["cs"]
        quota = t.ResourceQuota()
        quota.metadata.name = "tpuq"
        quota.spec.hard = {"google.com/tpu": "2"}
        cs.resourcequotas.create(quota)

        pod = simple_pod("tpu-pod")
        pod.spec.containers[0].resources.limits = {"google.com/tpu": 4}
        with pytest.raises(Forbidden, match="exceeded quota"):
            cs.pods.create(pod)

    def test_limitranger_defaults_and_max(self, cluster):
        cs = cluster["cs"]
        lr = t.LimitRange()
        lr.metadata.name = "limits"
        lr.spec.limits = [
            t.LimitRangeItem(
                type="Container",
                default={"cpu": "500m"},
                default_request={"cpu": "250m"},
                max={"cpu": "1"},
            )
        ]
        cs.limitranges.create(lr)

        pod = t.Pod()
        pod.metadata.name = "defaulted"
        pod.spec.containers = [t.Container(name="c", image="busybox", command=["serve"])]
        created = cs.pods.create(pod)
        assert created.spec.containers[0].resources.limits["cpu"] == "500m"
        assert created.spec.containers[0].resources.requests["cpu"] == "250m"

        big = simple_pod("big")
        big.spec.containers[0].resources.limits = {"cpu": "4"}
        with pytest.raises(Forbidden, match="LimitRange max"):
            cs.pods.create(big)

    def test_patch_cannot_bypass_limitrange(self, cluster):
        """ADVICE r1: a merge patch must run the admission chain — raising
        container resources past the LimitRange max via PATCH was an
        admission bypass."""
        cs = cluster["cs"]
        lr = t.LimitRange()
        lr.metadata.name = "patch-limits"
        lr.spec.limits = [t.LimitRangeItem(type="Container", max={"cpu": "1"})]
        cs.limitranges.create(lr)

        pod = simple_pod("patch-victim")
        pod.spec.containers[0].resources.limits = {"cpu": "500m"}
        cs.pods.create(pod)
        with pytest.raises(Forbidden, match="LimitRange max"):
            cs.pods.patch(
                "patch-victim",
                {"spec": {"containers": [
                    {"name": "c", "image": "busybox", "command": ["serve"],
                     "resources": {"limits": {"cpu": "8"}}}
                ]}},
            )

    def test_patch_cannot_delete_resource_limits(self, cluster):
        """ADVICE r2: a merge patch of {"limits": {"cpu": null}} deletes the
        key under RFC 7386 — which would leave the container unbounded while
        LimitRanger's max check sees no value to judge. Removal of a
        previously-present limit/request is forbidden at the registry."""
        cs = cluster["cs"]
        lr = t.LimitRange()
        lr.metadata.name = "null-limits"
        lr.spec.limits = [t.LimitRangeItem(type="Container", max={"cpu": "1"})]
        cs.limitranges.create(lr)

        pod = simple_pod("null-victim")
        pod.spec.containers[0].resources.limits = {"cpu": "500m"}
        cs.pods.create(pod)
        with pytest.raises(Forbidden, match="may not be removed"):
            cs.pods.patch(
                "null-victim",
                {"spec": {"containers": [
                    {"name": "c", "image": "busybox", "command": ["serve"],
                     "resources": {"limits": {"cpu": None}}}
                ]}},
            )
        # requests are protected the same way, even with no LimitRange in play
        pod2 = simple_pod("null-victim-2")
        pod2.metadata.namespace = "default"
        pod2.spec.containers[0].resources.requests = {"memory": "1Gi"}
        cs.pods.create(pod2)
        with pytest.raises(Forbidden, match="may not be removed"):
            cs.pods.patch(
                "null-victim-2",
                {"spec": {"containers": [
                    {"name": "c", "image": "busybox", "command": ["serve"],
                     "resources": {"requests": {"memory": None}}}
                ]}},
            )

    def test_limitrange_created_later_does_not_brick_existing_pods(self, cluster):
        """A stricter LimitRange must only judge values a write changes —
        metadata-only patches on pre-existing pods stay possible."""
        cs = cluster["cs"]
        pod = simple_pod("grandfathered")
        pod.spec.containers[0].resources.limits = {"cpu": "8"}
        cs.pods.create(pod)

        lr = t.LimitRange()
        lr.metadata.name = "stricter"
        lr.spec.limits = [t.LimitRangeItem(type="Container", max={"cpu": "1"})]
        cs.limitranges.create(lr)

        patched = cs.pods.patch(
            "grandfathered", {"metadata": {"labels": {"touched": "yes"}}}
        )
        assert patched.metadata.labels["touched"] == "yes"
        # but raising the limit further is still rejected
        with pytest.raises(Forbidden, match="LimitRange max"):
            cs.pods.patch(
                "grandfathered",
                {"spec": {"containers": [
                    {"name": "c", "image": "busybox", "command": ["serve"],
                     "resources": {"limits": {"cpu": "16"}}}
                ]}},
            )


class TestServiceAccounts:
    def test_default_sa_created_with_signed_token(self, cluster):
        cs = cluster["cs"]
        must_poll_until(
            lambda: _sa_with_secret(cs, "default"), timeout=10.0,
            desc="default SA + token",
        )
        sa = cs.serviceaccounts.get("default", "default")
        secret = cs.secrets.get(sa.secrets[0].name, "default")
        claims = verify_token("ktpu-sa-key", secret.data["token"])
        assert claims["sub"] == "system:serviceaccount:default:default"

    def test_pod_gets_default_service_account(self, cluster):
        cs = cluster["cs"]
        created = cs.pods.create(simple_pod("sa-pod"))
        assert created.spec.service_account_name == "default"


def _sa_with_secret(cs, ns):
    try:
        return bool(cs.serviceaccounts.get("default", ns).secrets)
    except NotFound:
        return False


class TestAutoscaling:
    def test_hpa_scales_up_on_cpu(self, cluster):
        cs = cluster["cs"]
        kubelet = cluster["kubelet"]
        rs = t.ReplicaSet()
        rs.metadata.name = "workers"
        rs.spec.replicas = 1
        rs.spec.selector = t.LabelSelector(match_labels={"app": "w"})
        rs.spec.template.metadata.labels = {"app": "w"}
        rs.spec.template.spec.containers = [
            t.Container(
                name="c", image="busybox", command=["serve"],
                resources=t.ResourceRequirements(requests={"cpu": "100m"}),
            )
        ]
        cs.replicasets.create(rs)
        must_poll_until(
            lambda: _running_count(cs, "app=w") == 1, timeout=15.0, desc="1 replica up"
        )
        # drive observed usage to 4x the request → HPA must scale up
        kubelet.runtime.set_usage("c", cpu=0.4)

        hpa = t.HorizontalPodAutoscaler()
        hpa.metadata.name = "workers-hpa"
        hpa.spec.scale_target_ref = t.CrossVersionObjectReference(
            kind="ReplicaSet", name="workers"
        )
        hpa.spec.min_replicas = 1
        hpa.spec.max_replicas = 3
        hpa.spec.target_cpu_utilization_percentage = 100
        cs.horizontalpodautoscalers.create(hpa)

        must_poll_until(
            lambda: (cs.replicasets.get("workers").spec.replicas or 0) >= 3,
            timeout=30.0, desc="HPA scaled to max",
        )
        must_poll_until(
            lambda: cs.horizontalpodautoscalers.get("workers-hpa").status.desired_replicas >= 3,
            timeout=10.0, desc="HPA status",
        )


def _running_count(cs, selector):
    pods, _ = cs.pods.list(namespace="default", label_selector=selector)
    return len([p for p in pods if p.status.phase == t.POD_RUNNING])


class TestMetricsPipeline:
    def test_kubelet_publishes_node_and_pod_metrics(self, cluster):
        cs = cluster["cs"]
        cs.pods.create(simple_pod("metered", labels={"app": "m"}))
        must_poll_until(
            lambda: _running_count(cs, "app=m") == 1, timeout=15.0, desc="pod running"
        )

        def has_metrics():
            try:
                pm = cs.podmetrics.get("metered", "default")
                nm = cs.nodemetrics.get("node-0", "")
            except NotFound:
                return False
            return bool(pm.containers) and "cpu" in nm.usage

        must_poll_until(has_metrics, timeout=15.0, desc="metrics published")


class TestDisruption:
    def test_pdb_status_reflects_healthy_pods(self, cluster):
        cs = cluster["cs"]
        for i in range(3):
            cs.pods.create(simple_pod(f"web-{i}", labels={"app": "web"}))
        must_poll_until(
            lambda: _running_count(cs, "app=web") == 3, timeout=15.0, desc="3 running"
        )
        pdb = t.PodDisruptionBudget()
        pdb.metadata.name = "web-pdb"
        pdb.spec.selector = t.LabelSelector(match_labels={"app": "web"})
        pdb.spec.min_available = 2
        cs.poddisruptionbudgets.create(pdb)

        def settled():
            st = cs.poddisruptionbudgets.get("web-pdb").status
            return st.current_healthy == 3 and st.disruptions_allowed == 1
        must_poll_until(settled, timeout=15.0, desc="PDB status")


class TestGCAndTTL:
    def test_orphaned_pod_deleted_when_node_gone(self, cluster):
        cs = cluster["cs"]
        pod = simple_pod("orphan")
        pod.spec.node_name = "ghost-node"  # pre-bound to a node that never existed
        cs.pods.create(pod)
        must_poll_until(
            lambda: _gone(cs, "orphan"), timeout=30.0, desc="orphan GCed"
        )

    def test_finished_job_deleted_after_ttl(self, cluster):
        cs = cluster["cs"]
        job = t.Job()
        job.metadata.name = "quick"
        job.spec.completions = 1
        job.spec.ttl_seconds_after_finished = 1
        job.spec.template.spec.containers = [
            t.Container(name="c", image="busybox", command=["sleep", "0.1"])
        ]
        cs.jobs.create(job)
        must_poll_until(
            lambda: _job_gone(cs, "quick"), timeout=30.0, desc="job TTL-deleted"
        )


def _gone(cs, name):
    try:
        cs.pods.get(name, "default")
        return False
    except NotFound:
        return True


def _job_gone(cs, name):
    try:
        cs.jobs.get(name, "default")
        return False
    except NotFound:
        return True


class TestCertificates:
    def test_node_csr_auto_approved_and_signed(self, cluster):
        cs = cluster["cs"]
        csr = t.CertificateSigningRequest()
        csr.metadata.name = "node-1-client"
        csr.spec.request = "CSR-PAYLOAD"
        csr.spec.username = "system:node:node-1"
        cs.certificatesigningrequests.create(csr)

        must_poll_until(
            lambda: bool(cs.certificatesigningrequests.get("node-1-client", "").status.certificate),
            timeout=15.0, desc="CSR signed",
        )
        signed = cs.certificatesigningrequests.get("node-1-client", "")
        assert any(c.type == "Approved" for c in signed.status.conditions)
        assert verify_certificate(
            "ktpu-ca-key", "system:node:node-1", "CSR-PAYLOAD",
            signed.status.certificate,
        )

    def test_user_csr_waits_for_manual_approval(self, cluster):
        cs = cluster["cs"]
        csr = t.CertificateSigningRequest()
        csr.metadata.name = "alice"
        csr.spec.request = "REQ"
        csr.spec.username = "alice"
        cs.certificatesigningrequests.create(csr)
        import time
        time.sleep(1.0)
        assert not cs.certificatesigningrequests.get("alice", "").status.certificate


class TestVolumes:
    def test_pvc_binds_smallest_satisfying_pv(self, cluster):
        cs = cluster["cs"]
        for name, size in (("pv-big", "100Gi"), ("pv-small", "10Gi")):
            pv = t.PersistentVolume()
            pv.metadata.name = name
            pv.spec.capacity = {"storage": size}
            pv.spec.access_modes = ["ReadWriteOnce"]
            cs.persistentvolumes.create(pv)

        pvc = t.PersistentVolumeClaim()
        pvc.metadata.name = "ckpt"
        pvc.spec.access_modes = ["ReadWriteOnce"]
        pvc.spec.resources = t.ResourceRequirements(requests={"storage": "5Gi"})
        cs.persistentvolumeclaims.create(pvc)

        must_poll_until(
            lambda: cs.persistentvolumeclaims.get("ckpt").status.phase == "Bound",
            timeout=15.0, desc="claim bound",
        )
        bound = cs.persistentvolumeclaims.get("ckpt")
        assert bound.spec.volume_name == "pv-small"
        pv = cs.persistentvolumes.get("pv-small", "")
        assert pv.status.phase == "Bound"
        assert pv.spec.claim_ref.name == "ckpt"

    def test_pv_released_when_claim_deleted(self, cluster):
        cs = cluster["cs"]
        pv = t.PersistentVolume()
        pv.metadata.name = "pv-r"
        pv.spec.capacity = {"storage": "1Gi"}
        pv.spec.access_modes = ["ReadWriteOnce"]
        cs.persistentvolumes.create(pv)
        pvc = t.PersistentVolumeClaim()
        pvc.metadata.name = "tmp-claim"
        pvc.spec.access_modes = ["ReadWriteOnce"]
        pvc.spec.resources = t.ResourceRequirements(requests={"storage": "1Gi"})
        cs.persistentvolumeclaims.create(pvc)
        must_poll_until(
            lambda: cs.persistentvolumeclaims.get("tmp-claim").status.phase == "Bound",
            timeout=15.0, desc="bound",
        )
        cs.persistentvolumeclaims.delete("tmp-claim")
        must_poll_until(
            lambda: cs.persistentvolumes.get("pv-r", "").status.phase == "Released",
            timeout=15.0, desc="released",
        )
