"""Machinery kernel tests: serialization round-trip, selectors, errors."""

from kubernetes1_tpu import api
from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.machinery import (
    ApiError,
    Conflict,
    labels,
    scheme as scheme_mod,
)
from kubernetes1_tpu.machinery.scheme import from_dict, global_scheme, to_dict
from kubernetes1_tpu.utils.quantity import parse_milli, parse_quantity


def make_pod(name="p1", ns="default", tpus=0):
    pod = t.Pod()
    pod.metadata.name = name
    pod.metadata.namespace = ns
    pod.metadata.labels = {"app": "test"}
    c = t.Container(name="main", image="busybox", command=["sleep", "1"])
    c.resources.limits = {"cpu": "500m", "memory": "128Mi"}
    if tpus:
        c.resources.limits["google.com/tpu"] = tpus
    pod.spec.containers = [c]
    return pod


class TestScheme:
    def test_roundtrip_pod(self):
        pod = make_pod(tpus=4)
        pod.spec.extended_resources = [
            t.PodExtendedResource(
                name="tpu-0",
                resource="google.com/tpu",
                quantity=4,
                affinity=t.ResourceAffinity(
                    required=[
                        t.ResourceSelectorRequirement(
                            key=t.ATTR_TPU_TYPE, operator="In", values=["v5e"]
                        )
                    ]
                ),
            )
        ]
        d = global_scheme.encode(pod)
        assert d["kind"] == "Pod"
        assert d["apiVersion"] == "v1"
        assert d["spec"]["containers"][0]["resources"]["limits"]["cpu"] == "500m"
        pod2 = global_scheme.decode(d)
        assert pod2.metadata.name == "p1"
        assert pod2.spec.extended_resources[0].affinity.required[0].values == ["v5e"]
        assert global_scheme.encode(pod2) == d

    def test_camel_case_wire_names(self):
        pod = make_pod()
        pod.spec.node_name = "node-1"
        pod.spec.termination_grace_period_seconds = 5
        d = to_dict(pod)
        assert d["spec"]["nodeName"] == "node-1"
        assert d["spec"]["terminationGracePeriodSeconds"] == 5
        assert "node_name" not in d["spec"]

    def test_omitempty(self):
        pod = t.Pod()
        d = to_dict(pod)
        # defaults are omitted entirely
        assert d == {}

    def test_unknown_fields_ignored(self):
        d = global_scheme.encode(make_pod())
        d["spec"]["someFutureField"] = {"x": 1}
        pod = global_scheme.decode(d)
        assert pod.metadata.name == "p1"

    def test_deepcopy_isolation(self):
        pod = make_pod()
        cp = global_scheme.deepcopy(pod)
        cp.spec.containers[0].image = "other"
        assert pod.spec.containers[0].image == "busybox"

    def test_job_indexed(self):
        job = t.Job()
        job.metadata.name = "train"
        job.spec.completions = 8
        job.spec.completion_mode = "Indexed"
        job.spec.gang_scheduling = True
        d = global_scheme.encode(job)
        assert d["apiVersion"] == "batch/v1"
        assert d["spec"]["completionMode"] == "Indexed"
        job2 = global_scheme.decode(d)
        assert job2.spec.gang_scheduling is True


class TestSelectors:
    def test_match_labels(self):
        assert labels.match_labels({"a": "b"}, {"a": "b", "c": "d"})
        assert not labels.match_labels({"a": "x"}, {"a": "b"})
        assert labels.match_labels(None, {})

    def test_parse_and_match(self):
        reqs = labels.parse_selector("app=web,tier!=db,env in (prod,stage),!legacy")
        assert labels.selector_matches(reqs, {"app": "web", "env": "prod"})
        assert not labels.selector_matches(reqs, {"app": "web", "env": "dev"})
        assert not labels.selector_matches(
            reqs, {"app": "web", "env": "prod", "legacy": "1"}
        )

    def test_structured_selector(self):
        sel = t.LabelSelector(
            match_labels={"app": "web"},
            match_expressions=[
                t.LabelSelectorRequirement(key="tier", operator="NotIn", values=["db"])
            ],
        )
        assert labels.label_selector_matches(sel, {"app": "web", "tier": "fe"})
        assert not labels.label_selector_matches(sel, {"app": "web", "tier": "db"})
        assert not labels.label_selector_matches(None, {"app": "web"})


class TestQuantity:
    def test_parse(self):
        assert parse_quantity("500m") == 0.5
        assert parse_quantity("2") == 2
        assert parse_quantity("1Gi") == 2**30
        assert parse_quantity("1G") == 10**9
        assert parse_milli("250m") == 250
        assert parse_milli(2) == 2000


class TestErrors:
    def test_status_roundtrip(self):
        err = Conflict("rv mismatch")
        st = err.to_status()
        assert st["code"] == 409
        back = ApiError.from_status(st)
        assert isinstance(back, Conflict)
        assert back.message == "rv mismatch"


class TestLeaseExpiry:
    def test_expired_uses_utc(self):
        """Regression: renew_time is UTC; expiry math must use timegm."""
        import time as _time

        from kubernetes1_tpu.api import types as t
        from kubernetes1_tpu.client.leaderelection import LeaderElector

        elector = LeaderElector.__new__(LeaderElector)
        elector.lease_duration = 10.0
        fresh = t.Lease(
            renew_time=_time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime()),
            lease_duration_seconds=10,
        )
        assert not elector._expired(fresh)
        stale = t.Lease(
            renew_time=_time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", _time.gmtime(_time.time() - 60)
            ),
            lease_duration_seconds=10,
        )
        assert elector._expired(stale)


class TestEventRecorderAggregation:
    def test_burst_coalesces_to_count_not_duplicates(self):
        """A burst of identical events enqueued before the async sink sends
        the first must become ONE Event with count=N, not N duplicates
        (ADVICE r4: the _seen cache is populated only on the sink thread,
        so enqueue-side bursts used to miss it)."""
        from kubernetes1_tpu.api import types as t
        from kubernetes1_tpu.apiserver.server import Master
        from kubernetes1_tpu.client import Clientset, EventRecorder

        master = Master().start()
        cs = Clientset(master.url)
        try:
            pod = t.Pod()
            pod.metadata.name = "burst-pod"
            pod.metadata.namespace = "default"
            pod.spec.containers = [t.Container(name="c", image="img")]
            pod = cs.pods.create(pod)
            rec = EventRecorder(cs, "test-component")
            for _ in range(25):
                rec.event(pod, "Warning", "FailedMount", "volume not ready")
            rec.flush()
            evs = [e for e in cs.events.list()[0]
                   if e.reason == "FailedMount"]
            assert len(evs) == 1, [e.metadata.name for e in evs]
            assert evs[0].count == 25
        finally:
            cs.close()
            master.stop()
