"""Scheduler extenders (ref: plugin/pkg/scheduler/core/extender.go +
examples/scheduler-policy-config.json): out-of-process filter/prioritize/
bind callouts."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.scheduler.extender import (
    ExtenderError,
    HTTPExtender,
    extenders_from_policy,
)
from kubernetes1_tpu.utils.waitutil import must_poll_until


class _ExtenderServer:
    """Scriptable extender endpoint: handlers per verb."""

    def __init__(self, handlers):
        outer = self

        class _H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                verb = self.path.strip("/").split("/")[-1]
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n)) if n else {}
                outer.calls.append((verb, payload))
                fn = handlers.get(verb)
                if fn is None:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                body = json.dumps(fn(payload)).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.calls = []
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        self._httpd.daemon_threads = True
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}"
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def make_node(cs, name):
    node = t.Node()
    node.metadata.name = name
    node.status.capacity = {"cpu": "4", "memory": "8Gi", "pods": "10"}
    node.status.allocatable = dict(node.status.capacity)
    node.status.conditions = [t.NodeCondition(type="Ready", status="True")]
    cs.nodes.create(node)


def make_pod(name):
    pod = t.Pod()
    pod.metadata.name = name
    pod.spec.containers = [t.Container(name="c", image="i",
                                       command=["sleep", "60"])]
    return pod


class TestExtenderUnit:
    def test_policy_parsing(self):
        exts = extenders_from_policy({"extenders": [{
            "urlPrefix": "http://e/x", "filterVerb": "filter",
            "prioritizeVerb": "prioritize", "weight": 3,
            "ignorable": True}]})
        assert len(exts) == 1
        assert exts[0].weight == 3 and exts[0].ignorable

    def test_ignorable_down_extender_is_skipped(self):
        ext = HTTPExtender("http://127.0.0.1:9", filter_verb="filter",
                           prioritize_verb="prioritize", ignorable=True,
                           timeout=0.2)
        nodes, failed = ext.filter({}, ["a", "b"])
        assert nodes == ["a", "b"] and failed == {}
        assert ext.prioritize({}, ["a"]) == {}

    def test_non_ignorable_down_extender_raises(self):
        ext = HTTPExtender("http://127.0.0.1:9", filter_verb="filter",
                           timeout=0.2)
        with pytest.raises(ExtenderError):
            ext.filter({}, ["a"])


class TestExtenderScheduling:
    @pytest.fixture
    def env(self):
        master = Master().start()
        cs = Clientset(master.url)
        yield master, cs
        cs.close()
        master.stop()

    def _wait_bound(self, cs, name, timeout=15):
        deadline = time.time() + timeout
        while time.time() < deadline:
            p = cs.pods.get(name)
            if p.spec.node_name:
                return p
            time.sleep(0.1)
        raise AssertionError(f"pod {name} never bound")

    def test_filter_vetoes_nodes(self, env):
        _, cs = env
        srv = _ExtenderServer({
            "filter": lambda p: {
                "nodeNames": [n for n in p["nodeNames"] if n == "good"],
                "failedNodes": {n: "vetoed" for n in p["nodeNames"]
                                if n != "good"}},
        })
        sched = Scheduler(cs, extenders=[
            HTTPExtender(srv.url, filter_verb="filter")])
        sched.start()
        try:
            make_node(cs, "bad-1")
            make_node(cs, "bad-2")
            make_node(cs, "good")
            cs.pods.create(make_pod("veto-me"))
            p = self._wait_bound(cs, "veto-me")
            assert p.spec.node_name == "good"
            assert any(v == "filter" for v, _ in srv.calls)
        finally:
            sched.stop()
            srv.stop()

    def test_prioritize_steers_choice(self, env):
        _, cs = env
        srv = _ExtenderServer({
            "prioritize": lambda p: [
                {"host": n, "score": 10 if n == "preferred" else 0}
                for n in p["nodeNames"]],
        })
        sched = Scheduler(cs, extenders=[
            HTTPExtender(srv.url, prioritize_verb="prioritize",
                         weight=100)])
        sched.start()
        try:
            make_node(cs, "a-node")
            make_node(cs, "preferred")
            make_node(cs, "z-node")
            cs.pods.create(make_pod("steer-me"))
            p = self._wait_bound(cs, "steer-me")
            assert p.spec.node_name == "preferred"
        finally:
            sched.stop()
            srv.stop()

    def test_extender_bind_delegation(self, env):
        master, cs = env
        bound = {}

        def do_bind(p):
            # the extender itself POSTs the Binding (the reference's
            # extender-bind contract)
            bcs = Clientset(master.url)
            binding = t.Binding(target_node=p["node"])
            binding.metadata.name = p["podName"]
            binding.metadata.namespace = p["podNamespace"]
            bcs.bind(p["podNamespace"], p["podName"], binding)
            bcs.close()
            bound.update(p)
            return {}

        srv = _ExtenderServer({"bind": do_bind})
        sched = Scheduler(cs, extenders=[
            HTTPExtender(srv.url, bind_verb="bind")])
        sched.start()
        try:
            make_node(cs, "only-node")
            cs.pods.create(make_pod("ext-bound"))
            p = self._wait_bound(cs, "ext-bound")
            assert p.spec.node_name == "only-node"
            assert bound.get("podName") == "ext-bound"
        finally:
            sched.stop()
            srv.stop()

    def test_policy_json_via_scheduler(self, env):
        _, cs = env
        srv = _ExtenderServer({
            "filter": lambda p: {"nodeNames": p["nodeNames"],
                                 "failedNodes": {}},
        })
        sched = Scheduler(cs, policy={"extenders": [{
            "urlPrefix": srv.url, "filterVerb": "filter"}]})
        sched.start()
        try:
            make_node(cs, "n1")
            cs.pods.create(make_pod("via-policy"))
            self._wait_bound(cs, "via-policy")
            assert any(v == "filter" for v, _ in srv.calls)
        finally:
            sched.stop()
            srv.stop()
