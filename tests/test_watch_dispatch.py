"""Selector-indexed watch dispatch + progress bookmarks (the 5000-node
fan-out levers).

Invariants under test:
- indexed dispatch == scan dispatch, frame for frame: buckets only NARROW
  the candidate set and the serving layer re-checks every selector, so an
  indexed stream's event multiset equals a scan stream's client-side
  filter — including the update-that-moves-the-indexed-value (delivered
  to BOTH buckets) and DELETED-while-matching;
- idle watchers are FREE and STAY FRESH: a bucket watcher whose value
  never fires costs zero dispatch work, and progress bookmarks keep its
  resume rv above the compaction floor so a reconnect after a churned-out
  window performs ZERO full relists (the A/B control without bookmarks
  proves the 410 path this replaces);
- the work bound: at 1000 single-node watchers, per-event dispatch work
  is >= 10x below the per-watcher scan (slow tier);
- streams that didn't opt in stay byte-identical (golden).
"""

import json
import threading
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.apiserver import server as apiserver_server
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.client.informer import SharedInformer
from kubernetes1_tpu.client.rest import ApiClient
from kubernetes1_tpu.machinery import ADDED, DELETED, MODIFIED
from kubernetes1_tpu.machinery.scheme import global_scheme
from kubernetes1_tpu.storage import Store
from kubernetes1_tpu.storage.cacher import Cacher

from tests.test_machinery import make_pod


def key(pod):
    return f"/registry/pods/{pod.metadata.namespace}/{pod.metadata.name}"


def pod_on(name, node):
    p = make_pod(name)
    p.spec.node_name = node
    return p


@pytest.fixture
def store():
    s = Store(global_scheme)
    yield s
    s.close()


@pytest.fixture
def cacher(store):
    c = Cacher(store, global_scheme).start()
    yield c
    c.stop()


def drain(w, timeout=2.0):
    """Every event currently deliverable on a watcher (non-blocking-ish)."""
    out = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        batch = w.next_batch_timeout(0.05)
        if batch:
            out.extend(batch)
        elif out:
            break
    return out


class TestDispatchIndex:
    """Cacher-layer bucket routing."""

    def test_bucketed_watcher_gets_only_its_value(self, store, cacher):
        wa = cacher.watch("/registry/pods/",
                          index_hint=("spec.nodeName", "node-a"))
        wb = cacher.watch("/registry/pods/",
                          index_hint=("spec.nodeName", "node-b"))
        store.create(key(pod_on("pa", "node-a")), pod_on("pa", "node-a"))
        store.create(key(pod_on("pb", "node-b")), pod_on("pb", "node-b"))
        evs_a = drain(wa)
        evs_b = drain(wb)
        assert [(e.type, e.object["metadata"]["name"]) for e in evs_a] == \
            [(ADDED, "pa")]
        assert [(e.type, e.object["metadata"]["name"]) for e in evs_b] == \
            [(ADDED, "pb")]
        # routing went through the index, not the scan leg
        assert cacher.dispatch_indexed_hits == 2
        assert cacher.dispatch_scans == 0
        wa.stop()
        wb.stop()

    def test_update_moving_value_delivers_to_both_buckets(self, store,
                                                          cacher):
        """The both-buckets rule: a nodeName move is a transition BOTH
        sides must see (old side drops it at event_matches, exactly like
        a scan stream would — but the delivery must reach the bucket)."""
        created = store.create(key(pod_on("p1", "node-a")),
                               pod_on("p1", "node-a"))
        wa = cacher.watch("/registry/pods/",
                          index_hint=("spec.nodeName", "node-a"))
        wb = cacher.watch("/registry/pods/",
                          index_hint=("spec.nodeName", "node-b"))
        moved = pod_on("p1", "node-b")
        moved.metadata.resource_version = created.metadata.resource_version
        moved.metadata.uid = created.metadata.uid
        store.update_cas(key(moved), moved)
        evs_a = drain(wa)
        evs_b = drain(wb)
        # old bucket: sees the MODIFIED (object now names node-b — the
        # serving layer's event_matches would drop the frame, same as a
        # scan stream's filter; the cacher's job is only delivery)
        assert [(e.type, e.object["spec"]["nodeName"]) for e in evs_a] == \
            [(MODIFIED, "node-b")]
        # new bucket: sees the same MODIFIED (its filter passes it)
        assert [(e.type, e.object["spec"]["nodeName"]) for e in evs_b] == \
            [(MODIFIED, "node-b")]
        wa.stop()
        wb.stop()

    def test_deleted_while_matching_delivered(self, store, cacher):
        store.create(key(pod_on("p1", "node-a")), pod_on("p1", "node-a"))
        wa = cacher.watch("/registry/pods/",
                          index_hint=("spec.nodeName", "node-a"))
        store.delete(key(pod_on("p1", "node-a")))
        evs = drain(wa)
        assert [(e.type, e.object["metadata"]["name"]) for e in evs] == \
            [(DELETED, "p1")]
        wa.stop()

    def test_undeclared_field_hint_falls_back_to_scan(self, store, cacher):
        w = cacher.watch("/registry/pods/",
                         index_hint=("status.phase", "Running"))
        assert w.dispatch_hint is None  # not a declared index: scan leg
        store.create(key(make_pod("p1")), make_pod("p1"))
        assert [e.type for e in drain(w)] == [ADDED]
        assert cacher.dispatch_scans >= 1
        assert cacher.dispatch_indexed_hits == 0
        w.stop()

    def test_idle_bucket_watcher_costs_zero_dispatch_work(self, store,
                                                          cacher):
        w = cacher.watch("/registry/pods/",
                         index_hint=("spec.nodeName", "ghost"))
        for i in range(20):
            store.create(key(pod_on(f"p{i}", "node-a")),
                         pod_on(f"p{i}", "node-a"))
        # unbound-value buckets were never walked for this watcher: the
        # whole point — an idle watcher is invisible to the fan-out
        assert cacher.dispatch_indexed_hits == 0
        assert cacher.dispatch_scans == 0
        assert w.next_batch_timeout(0.05) is None
        w.stop()

    def test_stop_cleans_bucket_and_scan_registrations(self, store, cacher):
        wa = cacher.watch("/registry/pods/",
                          index_hint=("spec.nodeName", "node-a"))
        ws = cacher.watch("/registry/pods/")
        wa.stop()
        ws.stop()
        with cacher._cond:
            assert cacher._watchers == []
            assert cacher._scan_watchers == []
            assert cacher._watch_index == {}

    def test_progress_rv_safe_only_when_drained(self, store, cacher):
        store.create(key(pod_on("p0", "node-a")), pod_on("p0", "node-a"))
        w = cacher.watch("/registry/pods/",
                         index_hint=("spec.nodeName", "node-a"))
        assert w.progress_rv() == store.current_revision()
        store.create(key(pod_on("p1", "node-a")), pod_on("p1", "node-a"))
        # an undelivered event is queued: no safe progress answer
        assert w.progress_rv() is None
        drain(w)
        assert w.progress_rv() == store.current_revision()
        w.stop()


class TestIndexedScanEquivalence:
    """HTTP-layer golden: an indexed stream's frames == a scan stream's
    frames client-side-filtered, under concurrent writes that create,
    annotate, move, and delete pods across nodes."""

    def test_equivalence_under_concurrent_writes(self):
        master = Master().start()
        cs = Clientset(master.url)
        try:
            _, rv0 = cs.pods.list(namespace="default")
            fin = "equiv-fin"
            indexed, scanned = [], []
            fin_seen = [threading.Event(), threading.Event()]

            def collect(params, sink, ev):
                api = ApiClient(master.url)
                try:
                    with api.watch("/api/v1/namespaces/default/pods",
                                   params) as stream:
                        for etype, obj in stream:
                            if etype == "BOOKMARK":
                                continue
                            m = obj.get("metadata") or {}
                            sink.append(
                                (etype, m.get("name"),
                                 m.get("resourceVersion"),
                                 (obj.get("spec") or {}).get("nodeName")))
                            ann = m.get("annotations") or {}
                            if ann.get("fin") == fin:
                                ev.set()
                                return
                finally:
                    api.close()

            threads = [
                threading.Thread(target=collect, args=(
                    {"resourceVersion": str(rv0),
                     "fieldSelector": "spec.nodeName=n1"},
                    indexed, fin_seen[0]), daemon=True),
                threading.Thread(target=collect, args=(
                    {"resourceVersion": str(rv0)},
                    scanned, fin_seen[1]), daemon=True),
            ]
            for th in threads:
                th.start()

            def writer(widx):
                wcs = Clientset(master.url)
                try:
                    for i in range(8):
                        name = f"eq-{widx}-{i}"
                        cs_node = ("n1", "n2", "")[i % 3]
                        p = make_pod(name)
                        p.spec.node_name = cs_node
                        wcs.pods.create(p)
                        wcs.pods.patch(name, {"metadata": {"annotations": {
                            "w": str(i)}}})
                        if i % 3 == 2:
                            # the MOVE the API allows: "" -> n1 (the bind
                            # transition) — the default-value bucket to
                            # the n1 bucket, both must see it
                            wcs.pods.patch(
                                name, {"spec": {"nodeName": "n1"}})
                        if i % 4 == 1:
                            wcs.pods.delete(name, "default")
                finally:
                    wcs.close()

            writers = [threading.Thread(target=writer, args=(k,),
                                        daemon=True) for k in range(4)]
            for th in writers:
                th.start()
            for th in writers:
                th.join()
            marker = make_pod("eq-fin")
            marker.spec.node_name = "n1"
            marker.metadata.annotations = {"fin": fin}
            cs.pods.create(marker)
            for ev in fin_seen:
                assert ev.wait(10.0), "stream never saw the fin marker"
            want = sorted(e for e in scanned if e[3] == "n1")
            got = sorted(e for e in indexed if e[3] == "n1")
            assert got == want
            # the indexed stream is pure: nothing with another node's
            # value survives the server-side re-check
            assert all(e[3] == "n1" for e in indexed)
            assert master.cacher.dispatch_indexed_hits > 0
        finally:
            cs.close()
            master.stop()


class TestProgressBookmarks:
    """Idle-informer freshness across a compacted window."""

    WINDOW = 64

    def _churn_master(self, monkeypatch):
        monkeypatch.setattr(apiserver_server, "WATCH_HEARTBEAT_SECONDS",
                            0.2)
        return Master(cacher_history_limit=self.WINDOW,
                      store_history_limit=self.WINDOW).start()

    def _churn(self, cs, n):
        for i in range(n):
            cm = t.ConfigMap(data={"i": str(i)})
            cm.metadata.name = f"churn-{i}"
            cs.configmaps.create(cm, namespace="default")

    def _cut_and_wait_reconnect(self, inf, relists0, timeout=10.0):
        ws = inf._watch_stream
        assert ws is not None
        ws.close()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if inf.reconnects >= 1 or inf.relists > relists0:
                return
            time.sleep(0.05)
        raise AssertionError("informer never re-established its watch")

    def test_idle_informer_survives_compaction_with_bookmarks(
            self, monkeypatch):
        master = self._churn_master(monkeypatch)
        cs = Clientset(master.url)
        inf = SharedInformer(cs.pods, namespace="default",
                             field_selector="spec.nodeName=ghost").start()
        try:
            assert inf.wait_for_sync(10.0)
            relists0 = inf.relists
            self._churn(cs, self.WINDOW + 20)  # roll BOTH history rings
            time.sleep(1.0)  # >= several heartbeats: bookmark lands
            self._cut_and_wait_reconnect(inf, relists0)
            # THE claim: reconnect across the compacted window without a
            # single 410 full relist — the bookmark kept the rv fresh
            assert inf.relists == relists0
            assert inf.reconnects >= 1
            # and the resumed stream is live + lossless: a pod landing on
            # the ghost node arrives through the bucket path
            p = make_pod("ghost-pod")
            p.spec.node_name = "ghost"
            cs.pods.create(p)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and \
                    inf.get("default/ghost-pod") is None:
                time.sleep(0.05)
            assert inf.get("default/ghost-pod") is not None
            assert master.watch_bookmarks > 0
        finally:
            inf.stop()
            cs.close()
            master.stop()

    def test_without_bookmarks_compaction_forces_relist(self, monkeypatch):
        """A/B control: the exact same scenario minus the opt-in pays the
        410 full relist the bookmarks eliminate — proves the mechanism,
        not just the absence of a symptom."""
        master = self._churn_master(monkeypatch)
        cs = Clientset(master.url)
        inf = SharedInformer(cs.pods, namespace="default",
                             field_selector="spec.nodeName=ghost",
                             progress_bookmarks=False).start()
        try:
            assert inf.wait_for_sync(10.0)
            relists0 = inf.relists
            self._churn(cs, self.WINDOW + 20)
            time.sleep(1.0)
            self._cut_and_wait_reconnect(inf, relists0)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and inf.relists == relists0:
                time.sleep(0.05)
            assert inf.relists > relists0
        finally:
            inf.stop()
            cs.close()
            master.stop()

    def test_non_opt_in_stream_stays_byte_identical(self, monkeypatch):
        """Golden: a stream with NO opt-in params carries exactly the
        per-event frames (the scheme's cached bytes) and newline
        heartbeats — no BOOKMARK ever, byte-for-byte the PR 12 wire."""
        monkeypatch.setattr(apiserver_server, "WATCH_HEARTBEAT_SECONDS",
                            0.2)
        master = Master().start()
        cs = Clientset(master.url)
        import http.client
        from urllib.parse import urlparse

        u = urlparse(master.url)
        conn = http.client.HTTPConnection(u.hostname, u.port, timeout=10)
        try:
            _, rv0 = cs.pods.list(namespace="default")
            conn.request(
                "GET",
                f"/api/v1/namespaces/default/pods?watch=true"
                f"&resourceVersion={rv0}")
            resp = conn.getresponse()
            assert resp.status == 200
            for i in range(3):
                cs.pods.create(make_pod(f"g{i}"))
            time.sleep(0.8)  # several heartbeat periods
            # the server's own committed wire dicts: what _serve_watch
            # ships, frame for frame (cached bytes may order keys
            # differently than a fresh dumps, so compare canonically)
            entries, _rev = master.store.list_raw(
                "/registry/pods/default/")
            expected = [
                {"type": ADDED, "object": obj}
                for _k, _r, obj in sorted(entries, key=lambda e: e[1])]
            got_frames = []
            deadline = time.monotonic() + 5
            while len(got_frames) < 3 and time.monotonic() < deadline:
                line = resp.readline()
                if not line or line.strip() == b"":
                    continue  # heartbeat newline: the only non-event byte
                assert b"BOOKMARK" not in line
                got_frames.append(json.loads(line))
            assert got_frames == expected[:3]
        finally:
            try:
                conn.close()
            except OSError:
                pass
            cs.close()
            master.stop()


@pytest.mark.slow
class TestDispatchWorkBound:
    def test_1000_single_node_watchers_10x_under_scan(self, store, cacher):
        """The acceptance bound: per-event fan-out work at 1000
        single-node watchers is >= 10x below the per-watcher scan."""
        WATCHERS, EVENTS = 1000, 200
        ws = [cacher.watch("/registry/pods/",
                           index_hint=("spec.nodeName", f"node-{i}"))
              for i in range(WATCHERS)]
        try:
            base_hits = cacher.dispatch_indexed_hits
            base_scans = cacher.dispatch_scans
            for i in range(EVENTS):
                node = f"node-{i % WATCHERS}"
                store.create(key(pod_on(f"wp{i}", node)),
                             pod_on(f"wp{i}", node))
            work = (cacher.dispatch_indexed_hits - base_hits
                    + cacher.dispatch_scans - base_scans)
            scan_equivalent = WATCHERS * EVENTS
            assert work * 10 <= scan_equivalent, (
                f"dispatch work {work} not >=10x under the "
                f"{scan_equivalent} scan equivalent")
            # and delivery is still correct: each event reached exactly
            # its node's watcher
            assert cacher.dispatch_indexed_hits - base_hits == EVENTS
        finally:
            for w in ws:
                w.stop()


class TestResyncWiring:
    def test_resync_period_redelivers_locally(self):
        master = Master().start()
        cs = Clientset(master.url)
        inf = SharedInformer(cs.pods, namespace="default",
                             resync_period=0.1)
        updates = []
        inf.add_handler(on_update=lambda old, new: updates.append(
            (old.metadata.name, old is new)))
        inf.start()
        try:
            cs.pods.create(make_pod("rs-1"))
            assert inf.wait_for_sync(10.0)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and len(updates) < 3:
                time.sleep(0.05)
            assert len(updates) >= 3  # periodic backstop fired
            # resync convention: old IS new (a backstop tick, not a diff)
            assert all(same for _name, same in updates)
            # and it is LOCAL redelivery, not API polling: one initial
            # LIST is the informer's entire request budget
            assert inf.relists == 1
        finally:
            inf.stop()
            cs.close()
            master.stop()

    def test_factory_shortest_resync_wins(self):
        from kubernetes1_tpu.client.informer import InformerFactory

        master = Master().start()
        cs = Clientset(master.url)
        try:
            factory = InformerFactory(cs)
            a = factory.informer("pods", resync_period=10.0)
            b = factory.informer("pods", resync_period=2.0)
            assert a is b
            assert a.resync_period == 2.0
            c = factory.informer("pods")  # no ask: keeps the 2.0
            assert c.resync_period == 2.0
        finally:
            cs.close()
            master.stop()


class TestHarnessGuards:
    def test_hollow_watchers_require_multiproc(self):
        from scripts.sched_perf import run_sched_perf

        with pytest.raises(ValueError, match="hollow-watchers"):
            run_sched_perf(10, 20, multiproc=False, hollow_watchers=100)

    def test_negative_hollow_watchers_refused(self):
        from scripts.sched_perf import run_sched_perf

        with pytest.raises(ValueError, match="hollow-watchers"):
            run_sched_perf(10, 20, multiproc=True, hollow_watchers=-1)

    def test_dispatch_metrics_rendered(self):
        master = Master().start()
        cs = Clientset(master.url)
        try:
            cs.pods.create(make_pod("m1"))
            body = cs.api.request("GET", "/metrics", raw=True).decode()
            for name in ("ktpu_watch_dispatch_indexed_hits_total",
                         "ktpu_watch_dispatch_scans_total",
                         "ktpu_watch_bookmarks_total",
                         "ktpu_informer_relist_bytes_total"):
                assert name in body
        finally:
            cs.close()
            master.stop()
