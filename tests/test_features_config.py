"""Feature gates + ComponentConfig / dynamic kubelet config (ref:
pkg/features/kube_features.go:70-76, pkg/kubelet/kubeletconfig/
controller.go:81)."""

import json
import sys
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.kubelet import FakeRuntime, Kubelet
from kubernetes1_tpu.utils.features import DEFAULT_GATES, FeatureGates, gates
from kubernetes1_tpu.utils.waitutil import must_poll_until


class TestFeatureGates:
    def test_parse_and_defaults(self):
        fg = FeatureGates()
        assert fg.enabled("DevicePlugins") is True
        assert fg.enabled("TaintBasedEvictions") is False
        fg.apply("TaintBasedEvictions=true,DevicePlugins=false")
        assert fg.enabled("TaintBasedEvictions") is True
        assert fg.enabled("DevicePlugins") is False

    def test_unknown_gate_rejected(self):
        fg = FeatureGates()
        with pytest.raises(ValueError, match="unknown feature gate"):
            fg.apply("Typo=true")
        with pytest.raises(ValueError, match="want Name"):
            fg.apply("DevicePlugins=maybe")
        with pytest.raises(KeyError):
            fg.enabled("Nope")

    def test_all_binaries_accept_the_flag(self):
        """One shared --feature-gates map across every component binary
        (the reference's single kube_features.go switchboard)."""
        import subprocess

        for mod in ("kubernetes1_tpu.apiserver", "kubernetes1_tpu.scheduler",
                    "kubernetes1_tpu.controllers", "kubernetes1_tpu.kubelet"):
            r = subprocess.run(
                [sys.executable, "-m", mod, "--help"],
                capture_output=True, timeout=60,
                env={"PATH": "/usr/bin:/bin", "PYTHONPATH": "/root/repo"},
            )
            assert b"--feature-gates" in r.stdout, mod


@pytest.fixture()
def env(tmp_path):
    master = Master().start()
    cs = Clientset(master.url)
    kubelet = Kubelet(
        cs, node_name="cfg-node", runtime=FakeRuntime(),
        plugin_dir=str(tmp_path / "p"),
        heartbeat_interval=0.2, sync_interval=0.2, pleg_interval=0.2,
    )
    kubelet.TOKEN_RECHECK_BEATS = 2  # fast config polling for the test
    kubelet.start()
    yield {"master": master, "cs": cs, "kubelet": kubelet}
    kubelet.stop()
    cs.close()
    master.stop()


class TestDynamicKubeletConfig:
    def test_config_applies_and_invalid_keeps_last_known_good(self, env):
        cs, kl = env["cs"], env["kubelet"]
        cm = t.ConfigMap(data={"kubelet": json.dumps({
            "syncIntervalSeconds": 0.7,
            "maxPods": 42,
            "evictionThresholds": {"memory": 0.05},
        })})
        cm.metadata.name = "kubelet-config-cfg-node"
        cs.configmaps.create(cm, "kube-system")
        must_poll_until(lambda: kl.sync_interval == 0.7, timeout=15.0,
                        desc="dynamic config applied")
        assert kl.capacity["pods"] == "42"
        assert kl.eviction.thresholds["memory"] == 0.05
        must_poll_until(
            lambda: cs.nodes.get("cfg-node", "").status.capacity.get("pods") == "42",
            timeout=10.0, desc="capacity published",
        )
        # an invalid update must NOT disturb the applied settings
        fresh = cs.configmaps.get("kubelet-config-cfg-node", "kube-system")
        fresh.data = {"kubelet": json.dumps({"syncIntervalSeconds": -3})}
        cs.configmaps.update(fresh)
        time.sleep(1.5)
        assert kl.sync_interval == 0.7  # last-known-good retained
        # and a later valid write applies again
        fresh = cs.configmaps.get("kubelet-config-cfg-node", "kube-system")
        fresh.data = {"kubelet": json.dumps({"syncIntervalSeconds": 0.9})}
        cs.configmaps.update(fresh)
        must_poll_until(lambda: kl.sync_interval == 0.9, timeout=15.0,
                        desc="recovered config applied")

    def test_cluster_wide_config_as_fallback(self, env):
        cs, kl = env["cs"], env["kubelet"]
        cm = t.ConfigMap(data={"kubelet": json.dumps({"plegIntervalSeconds": 0.55})})
        cm.metadata.name = "kubelet-config"
        cs.configmaps.create(cm, "kube-system")
        must_poll_until(lambda: kl.pleg_interval == 0.55, timeout=15.0,
                        desc="cluster-wide config applied")


class TestTaintBasedEvictions:
    def test_gate_controls_not_ready_taint(self, tmp_path):
        from kubernetes1_tpu.controllers import ControllerManager

        assert gates.enabled("TaintBasedEvictions") is False
        master = Master().start()
        cs = Clientset(master.url)
        cm = ControllerManager(cs, monitor_grace=1.0, eviction_timeout=30.0)
        cm.start()
        kl = Kubelet(cs, node_name="taintee", runtime=FakeRuntime(),
                     plugin_dir=str(tmp_path / "p"),
                     heartbeat_interval=0.3, sync_interval=0.3,
                     pleg_interval=0.3)
        kl.start()
        try:
            must_poll_until(
                lambda: cs.nodes.get("taintee", "") is not None,
                timeout=10.0, desc="node registered")
            # two pods on the node: one with a short toleration, one
            # tolerating the outage indefinitely
            short = t.Pod()
            short.metadata.name = "short-fuse"
            short.spec.node_name = "taintee"
            short.spec.containers = [t.Container(name="c", image="x", command=["r"])]
            short.spec.tolerations = [t.Toleration(
                key="node.kubernetes.io/not-ready", operator="Exists",
                effect="NoExecute", toleration_seconds=1)]
            cs.pods.create(short)
            forever = t.Pod()
            forever.metadata.name = "rides-it-out"
            forever.spec.node_name = "taintee"
            forever.spec.containers = [t.Container(name="c", image="x", command=["r"])]
            forever.spec.tolerations = [t.Toleration(
                key="node.kubernetes.io/not-ready", operator="Exists",
                effect="NoExecute")]  # no seconds = unbounded
            cs.pods.create(forever)
            gates.apply("TaintBasedEvictions=true")
            kl.stop()  # heartbeats cease -> NotReady -> taint
            must_poll_until(
                lambda: any(
                    tt.key == "node.kubernetes.io/not-ready"
                    for tt in cs.nodes.get("taintee", "").spec.taints),
                timeout=20.0, desc="not-ready NoExecute taint applied",
            )
            taints = cs.nodes.get("taintee", "").spec.taints
            assert any(tt.effect == "NoExecute" for tt in taints)
            # tolerationSeconds=1 expires -> evicted; unbounded survives
            from kubernetes1_tpu.machinery import NotFound

            def short_gone():
                try:
                    p = cs.pods.get("short-fuse", "default")
                except NotFound:
                    return True
                return bool(p.metadata.deletion_timestamp)

            must_poll_until(short_gone, timeout=20.0,
                            desc="short toleration expires -> eviction")
            survivor = cs.pods.get("rides-it-out", "default")
            assert not survivor.metadata.deletion_timestamp
        finally:
            gates.apply("TaintBasedEvictions=false")
            cm.stop()
            cs.close()
            master.stop()
