"""BASELINE config e2e: the five example manifests (examples/*.yaml) apply
through the CLI against a hollow multi-host cluster and produce the
scheduling outcomes each config claims (ref: the reference validates its
headline configs through test/e2e/scheduling/nvidia-gpus.go + density)."""

import io
import os

import pytest
import yaml

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.cli import CLI
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.controllers import ControllerManager
from kubernetes1_tpu.deviceplugin.tpu_plugin import ANN_WORKER_ID
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.test_controllers import start_hollow_node

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


@pytest.fixture()
def big_cluster(tmp_path):
    """8 v5p hosts on one slice + 2 v5e hosts + 2 CPU-only nodes."""
    master = Master().start()
    cs = Clientset(master.url)
    sched = Scheduler(cs, gang_wait_seconds=10.0)
    sched.start()
    cm = ControllerManager(cs, monitor_grace=5.0, eviction_timeout=5.0)
    cm.start()
    nodes = []
    for i in range(8):
        nodes.append(start_hollow_node(
            cs, f"v5p-host-{i}", str(tmp_path), tpus=4,
            slice_id="v5p-slice", host_index=i, tpu_type="v5p",
        ))
    for i in range(2):
        nodes.append(start_hollow_node(
            cs, f"v5e-host-{i}", str(tmp_path), tpus=4,
            slice_id="v5e-slice", host_index=i,
        ))
    for i in range(2):
        nodes.append(start_hollow_node(cs, f"cpu-{i}", str(tmp_path), tpus=0))
    env = {"master": master, "cs": cs}
    yield env
    for kubelet, plugin, _ in nodes:
        kubelet.stop()
        plugin.stop()
    cm.stop()
    sched.stop()
    cs.close()
    master.stop()


def apply_example(master, name):
    out = io.StringIO()
    cli = CLI(master.url, "default", out=out)
    cli.apply(type("A", (), {"filename": os.path.join(EXAMPLES, name)})())
    cli.cs.close()
    return out.getvalue()


def running(cs, selector):
    pods, _ = cs.pods.list(namespace="default", label_selector=selector)
    return [p for p in pods if p.status.phase == t.POD_RUNNING
            and not p.metadata.deletion_timestamp]


class TestGuestbook:
    def test_cpu_only_deployment_and_service(self, big_cluster):
        master, cs = big_cluster["master"], big_cluster["cs"]
        apply_example(master, "guestbook.yaml")
        must_poll_until(
            lambda: len(running(cs, "app=guestbook")) == 3,
            timeout=30.0, desc="3 frontends running",
        )
        svc = cs.services.get("guestbook-frontend")
        assert svc.spec.cluster_ip.startswith("10.96.")
        must_poll_until(
            lambda: sum(
                len(s.addresses)
                for s in cs.endpoints.get("guestbook-frontend").subsets
            ) == 3,
            timeout=20.0, desc="endpoints",
        )
        # no TPU chips consumed by a CPU workload
        for p in running(cs, "app=guestbook"):
            assert not p.spec.extended_resources


class TestMNISTSingleChip:
    def test_single_chip_job(self, big_cluster):
        master, cs = big_cluster["master"], big_cluster["cs"]
        apply_example(master, "mnist-single-chip.yaml")
        must_poll_until(
            lambda: len(running(cs, "app=mnist")) == 1,
            timeout=30.0, desc="mnist pod running",
        )
        pod = running(cs, "app=mnist")[0]
        # ResourceV2 rewrite: raw limit gone, pod-level request present
        assert "google.com/tpu" not in pod.spec.containers[0].resources.limits
        assert len(pod.spec.extended_resources) == 1
        assert pod.spec.extended_resources[0].quantity == 1
        assert len(pod.spec.extended_resources[0].assigned) == 1


class TestResNetV5E4:
    def test_four_chips_one_host(self, big_cluster):
        master, cs = big_cluster["master"], big_cluster["cs"]
        apply_example(master, "resnet50-v5e4.yaml")
        must_poll_until(
            lambda: len(running(cs, "app=resnet50")) == 1,
            timeout=30.0, desc="resnet pod running",
        )
        pod = running(cs, "app=resnet50")[0]
        assigned = pod.spec.extended_resources[0].assigned
        assert len(assigned) == 4
        node = cs.nodes.get(pod.spec.node_name, "")
        node_ids = {d.id for d in node.status.extended_resources["google.com/tpu"]}
        assert set(assigned) <= node_ids  # all 4 chips on the bound host


class TestBertV5P32:
    def test_gang_on_one_v5p_slice_with_worker_identity(self, big_cluster):
        master, cs = big_cluster["master"], big_cluster["cs"]
        apply_example(master, "bert-large-v5p32.yaml")
        must_poll_until(
            lambda: len(running(cs, "app=bert-large")) == 8,
            timeout=60.0, desc="8 bert workers running",
        )
        pods = running(cs, "app=bert-large")
        slices, worker_ids, hosts = set(), set(), set()
        for p in pods:
            per = p.spec.extended_resources[0]
            assert per.quantity == 4 and len(per.assigned) == 4
            node = cs.nodes.get(p.spec.node_name, "")
            devs = {d.id: d for d in node.status.extended_resources["google.com/tpu"]}
            for chip in per.assigned:
                assert devs[chip].attributes[t.ATTR_TPU_TYPE] == "v5p"
                slices.add(devs[chip].attributes[t.ATTR_TPU_SLICE])
            worker_ids.add(p.metadata.annotations[ANN_WORKER_ID])
            hosts.add(p.spec.node_name)
        assert slices == {"v5p-slice"}  # affinity + gang slice co-location
        assert worker_ids == {str(i) for i in range(8)}
        assert len(hosts) == 8  # 4 chips per host -> one worker per host


class TestLlamaPreemptible:
    def test_elastic_low_priority_gang(self, big_cluster):
        master, cs = big_cluster["master"], big_cluster["cs"]
        with open(os.path.join(EXAMPLES, "llama3-8b-v5e256-preemptible.yaml")) as f:
            docs = [d for d in yaml.safe_load_all(f) if d]
        # scale the 64-worker config to the 2 v5e hosts of this fixture
        for doc in docs:
            if doc["kind"] == "Job":
                doc["spec"]["completions"] = 2
                doc["spec"]["parallelism"] = 2
        for doc in docs:
            obj = cs.scheme.decode(doc)
            cs.resource(cs.scheme.resource_of[doc["kind"]]).create(obj)
        must_poll_until(
            lambda: len(running(cs, "app=llama3-8b")) == 2,
            timeout=60.0, desc="2 llama workers running",
        )
        pods = running(cs, "app=llama3-8b")
        for p in pods:
            assert p.spec.priority == -100  # PriorityClass resolved
            assert p.spec.scheduling_gang  # gang stamped by the Job controller
            slice_ids = set()
            node = cs.nodes.get(p.spec.node_name, "")
            devs = {d.id: d for d in node.status.extended_resources["google.com/tpu"]}
            for chip in p.spec.extended_resources[0].assigned:
                slice_ids.add(devs[chip].attributes[t.ATTR_TPU_SLICE])
            assert slice_ids == {"v5e-slice"}  # affinity kept it off v5p
        # the checkpoint PVC bound and materialized: RUNNING proves the
        # kubelet mounted it (FailedMount blocks container start), and the
        # claim must be Bound to the example's PV
        pvc = cs.persistentvolumeclaims.get("llama3-ckpt", "default")
        assert pvc.status.phase == "Bound"
        assert pvc.spec.volume_name == "llama3-ckpt-pv"
        for p in pods:
            assert p.spec.volumes[0].persistent_volume_claim.claim_name == "llama3-ckpt"
