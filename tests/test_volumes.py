"""Volume + env-injection subsystem e2e (ref: pkg/kubelet/volumemanager/
volume_manager.go, kubelet_pods.go:591 makeEnvironmentVariables, and the
e2e volume tests under test/e2e/common/) — pods consuming emptyDir,
hostPath, ConfigMap, Secret, PVC, downward API, envFrom/valueFrom, and the
automounted ServiceAccount token, through the real sync loop with a real
(process) runtime and real bind mounts where the host supports them."""

import os
import sys
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.controllers import ControllerManager
from kubernetes1_tpu.kubelet import Kubelet, ProcessRuntime
from kubernetes1_tpu.kubelet.volumemanager import SA_TOKEN_MOUNT_PATH
from kubernetes1_tpu.machinery import Invalid
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils.waitutil import must_poll_until


@pytest.fixture()
def vol_env(tmp_path):
    """master + scheduler + controllers (PV binder, SA tokens) + kubelet
    with ProcessRuntime — the volume paths need real processes."""
    master = Master().start()
    cs = Clientset(master.url)
    sched = Scheduler(cs)
    sched.start()
    cm = ControllerManager(cs, monitor_grace=5.0, eviction_timeout=5.0,
                           pv_base_dir=str(tmp_path / "dynpv"))
    cm.start()
    runtime = ProcessRuntime(root_dir=str(tmp_path / "ktpu"))
    kubelet = Kubelet(
        cs,
        node_name="vol-node-0",
        runtime=runtime,
        plugin_dir=str(tmp_path / "plugins"),
        heartbeat_interval=0.5,
        sync_interval=0.3,
        pleg_interval=0.3,
    )
    kubelet.volume_manager.refresh_interval = 1.0  # fast configmap propagation
    kubelet.start()
    env = {
        "master": master, "cs": cs, "sched": sched, "cm": cm,
        "runtime": runtime, "kubelet": kubelet, "tmp": tmp_path,
    }
    yield env
    # env["kubelet"], not the local: the restart-safety test swaps in a
    # NEW kubelet — stopping the stale one would leave the live one
    # restarting containers right after kill_all reaps them
    env["kubelet"].stop()
    runtime.kill_all()  # containers must not outlive the fixture
    cm.stop()
    sched.stop()
    cs.close()
    master.stop()


def wait_phase(cs, name, phase, timeout=20.0, ns="default"):
    must_poll_until(
        lambda: cs.pods.get(name, ns).status.phase == phase,
        timeout=timeout, desc=f"pod {name} -> {phase}",
    )
    return cs.pods.get(name, ns)


def py_pod(name, code, restart="Never"):
    pod = t.Pod()
    pod.metadata.name = name
    pod.spec.restart_policy = restart
    pod.spec.containers = [
        t.Container(name="main", image="python", command=[sys.executable, "-c", code])
    ]
    return pod


class TestVolumeSources:
    def test_emptydir_and_hostpath(self, vol_env):
        """An emptyDir is pod-lifetime scratch; hostPath survives the pod."""
        cs, tmp = vol_env["cs"], vol_env["tmp"]
        hp = str(tmp / "host-data")
        code = (
            "import os;"
            "open(os.environ['KTPU_VOLUME_SCRATCH'] + '/f', 'w').write('s');"
            "open(os.environ['KTPU_VOLUME_HOSTVOL'] + '/kept', 'w').write('h')"
        )
        pod = py_pod("vol-ed", code)
        pod.spec.volumes = [
            t.Volume(name="scratch", empty_dir=t.EmptyDirVolumeSource()),
            t.Volume(name="hostvol", host_path=t.HostPathVolumeSource(path=hp)),
        ]
        pod.spec.containers[0].volume_mounts = [
            t.VolumeMount(name="scratch", mount_path="/scratch"),
            t.VolumeMount(name="hostvol", mount_path="/hostvol"),
        ]
        cs.pods.create(pod)
        bound = wait_phase(cs, "vol-ed", t.POD_SUCCEEDED)
        uid = bound.metadata.uid
        vm = vol_env["kubelet"].volume_manager
        scratch = os.path.join(vm.root, "pods", uid, "volumes", "emptydir", "scratch")
        assert open(os.path.join(scratch, "f")).read() == "s"
        assert open(os.path.join(hp, "kept")).read() == "h"
        # deletion reclaims the emptyDir but not the hostPath
        cs.pods.delete("vol-ed", "default")
        must_poll_until(lambda: not os.path.exists(scratch), timeout=15.0,
                        desc="emptyDir reclaimed")
        assert os.path.exists(os.path.join(hp, "kept"))

    def test_bind_mounts_give_container_path_view(self, vol_env):
        """With mount namespaces the pod sees its mounts at the declared
        mount_path (/data), not just via env — per-pod private views."""
        cs = vol_env["cs"]
        code = "open('/data/out.txt', 'w').write('via-bind-mount')"
        pod = py_pod("vol-bind", code)
        pod.spec.volumes = [t.Volume(name="data", empty_dir=t.EmptyDirVolumeSource())]
        pod.spec.containers[0].volume_mounts = [
            t.VolumeMount(name="data", mount_path="/data")
        ]
        cs.pods.create(pod)
        runtime = vol_env["runtime"]
        if not runtime._mount_ns:
            pytest.skip("host cannot create mount namespaces")
        bound = wait_phase(cs, "vol-bind", t.POD_SUCCEEDED)
        vm = vol_env["kubelet"].volume_manager
        host_side = os.path.join(vm.root, "pods", bound.metadata.uid,
                                 "volumes", "emptydir", "data", "out.txt")
        assert open(host_side).read() == "via-bind-mount"

    def test_configmap_and_secret_volumes(self, vol_env):
        cs = vol_env["cs"]
        cm = t.ConfigMap(data={"app.conf": "mode=train", "lr": "3e-4"})
        cm.metadata.name = "trainer-config"
        cs.configmaps.create(cm)
        sec = t.Secret(data={"api-key": "hunter2"})
        sec.metadata.name = "trainer-secret"
        cs.secrets.create(sec)

        code = (
            "import os;"
            "c=os.environ['KTPU_VOLUME_CFG'];s=os.environ['KTPU_VOLUME_SEC'];"
            "assert open(c+'/app.conf').read()=='mode=train', 'cm';"
            "assert open(s+'/api-key').read()=='hunter2', 'sec'"
        )
        pod = py_pod("vol-cms", code)
        pod.spec.volumes = [
            t.Volume(name="cfg", config_map=t.ConfigMapVolumeSource(name="trainer-config")),
            t.Volume(name="sec", secret=t.SecretVolumeSource(secret_name="trainer-secret")),
        ]
        pod.spec.containers[0].volume_mounts = [
            t.VolumeMount(name="cfg", mount_path="/etc/cfg", read_only=True),
            t.VolumeMount(name="sec", mount_path="/etc/sec", read_only=True),
        ]
        cs.pods.create(pod)
        bound = wait_phase(cs, "vol-cms", t.POD_SUCCEEDED)
        # secret files are written 0600 under a 0700 dir
        vm = vol_env["kubelet"].volume_manager
        sec_dir = os.path.join(vm.root, "pods", bound.metadata.uid, "volumes",
                               "secret", "sec")
        assert oct(os.stat(sec_dir).st_mode & 0o777) == "0o700"
        assert oct(os.stat(os.path.join(sec_dir, "api-key")).st_mode & 0o777) == "0o600"

    def test_configmap_update_propagates_to_mounted_volume(self, vol_env):
        """Mounted ConfigMap content refreshes while the pod runs (the
        reference's configmap-volume update propagation)."""
        cs = vol_env["cs"]
        cm = t.ConfigMap(data={"flag": "v1"})
        cm.metadata.name = "live-config"
        cs.configmaps.create(cm)
        # long-running pod so refresh happens while it is alive
        pod = py_pod("vol-refresh", "import time; time.sleep(30)", restart="Never")
        pod.spec.volumes = [
            t.Volume(name="cfg", config_map=t.ConfigMapVolumeSource(name="live-config"))
        ]
        pod.spec.containers[0].volume_mounts = [
            t.VolumeMount(name="cfg", mount_path="/etc/live")
        ]
        cs.pods.create(pod)
        bound = wait_phase(cs, "vol-refresh", t.POD_RUNNING)
        vm = vol_env["kubelet"].volume_manager
        path = os.path.join(vm.root, "pods", bound.metadata.uid, "volumes",
                            "configmap", "cfg", "flag")
        assert open(path).read() == "v1"
        fresh = cs.configmaps.get("live-config", "default")
        fresh.data["flag"] = "v2"
        cs.configmaps.update(fresh)
        must_poll_until(
            lambda: os.path.exists(path) and open(path).read() == "v2",
            timeout=15.0, desc="configmap refresh",
        )

    def test_pvc_checkpoint_flow(self, vol_env):
        """The VERDICT r2 'done' bar: a Job-style pod writes a checkpoint
        through a PVC-backed mount; the data lands in the bound PV."""
        cs, tmp = vol_env["cs"], vol_env["tmp"]
        pv_dir = str(tmp / "pv0")
        pv = t.PersistentVolume()
        pv.metadata.name = "pv0"
        pv.spec.capacity = {"storage": "1Gi"}
        pv.spec.access_modes = ["ReadWriteOnce"]
        pv.spec.host_path = t.HostPathVolumeSource(path=pv_dir)
        cs.persistentvolumes.create(pv, "")
        pvc = t.PersistentVolumeClaim()
        pvc.metadata.name = "ckpt-claim"
        pvc.spec.access_modes = ["ReadWriteOnce"]
        pvc.spec.resources = t.ResourceRequirements(requests={"storage": "1Gi"})
        cs.persistentvolumeclaims.create(pvc)

        code = (
            "import os; d=os.environ['KTPU_VOLUME_CKPT'];"
            "open(d + '/step-100.ckpt', 'w').write('weights')"
        )
        pod = py_pod("trainer", code)
        pod.spec.volumes = [
            t.Volume(name="ckpt",
                     persistent_volume_claim=t.PersistentVolumeClaimVolumeSource(
                         claim_name="ckpt-claim"))
        ]
        pod.spec.containers[0].volume_mounts = [
            t.VolumeMount(name="ckpt", mount_path="/ckpt")
        ]
        cs.pods.create(pod)
        wait_phase(cs, "trainer", t.POD_SUCCEEDED)
        assert open(os.path.join(pv_dir, "step-100.ckpt")).read() == "weights"

    def test_pod_waits_for_unbound_pvc(self, vol_env):
        """A pod whose PVC has no matching PV stays Pending with a
        FailedMount event; creating the PV unblocks it."""
        cs, tmp = vol_env["cs"], vol_env["tmp"]
        pvc = t.PersistentVolumeClaim()
        pvc.metadata.name = "late-claim"
        pvc.spec.access_modes = ["ReadWriteOnce"]
        pvc.spec.resources = t.ResourceRequirements(requests={"storage": "1Gi"})
        cs.persistentvolumeclaims.create(pvc)
        pod = py_pod("waiter", "print('ran')")
        pod.spec.volumes = [
            t.Volume(name="v",
                     persistent_volume_claim=t.PersistentVolumeClaimVolumeSource(
                         claim_name="late-claim"))
        ]
        pod.spec.containers[0].volume_mounts = [
            t.VolumeMount(name="v", mount_path="/late")
        ]
        cs.pods.create(pod)
        time.sleep(2.0)
        assert cs.pods.get("waiter", "default").status.phase in (t.POD_PENDING, "")
        pv = t.PersistentVolume()
        pv.metadata.name = "late-pv"
        pv.spec.capacity = {"storage": "1Gi"}
        pv.spec.access_modes = ["ReadWriteOnce"]
        pv.spec.host_path = t.HostPathVolumeSource(path=str(tmp / "late-pv"))
        cs.persistentvolumes.create(pv, "")
        wait_phase(cs, "waiter", t.POD_SUCCEEDED)


class TestEnvironment:
    def test_valuefrom_envfrom_and_downward_api(self, vol_env):
        cs, tmp = vol_env["cs"], vol_env["tmp"]
        cm = t.ConfigMap(data={"LR": "0.001", "STEPS": "100"})
        cm.metadata.name = "hparams"
        cs.configmaps.create(cm)
        sec = t.Secret(data={"WANDB_KEY": "s3cr3t"})
        sec.metadata.name = "creds"
        cs.secrets.create(sec)
        out = str(tmp / "env.json")
        code = (
            "import os, json;"
            f"open({out!r}, 'w').write(json.dumps(dict(os.environ)))"
        )
        pod = py_pod("env-pod", code)
        c = pod.spec.containers[0]
        c.env_from = [
            t.EnvFromSource(prefix="HP_",
                            config_map_ref=t.ConfigMapEnvSource(name="hparams")),
            t.EnvFromSource(secret_ref=t.SecretEnvSource(name="creds")),
        ]
        c.env = [
            t.EnvVar(name="EXPLICIT", value="1"),
            t.EnvVar(name="FROM_CM", value_from=t.EnvVarSource(
                config_map_key_ref=t.ConfigMapKeySelector(name="hparams", key="LR"))),
            t.EnvVar(name="FROM_SEC", value_from=t.EnvVarSource(
                secret_key_ref=t.SecretKeySelector(name="creds", key="WANDB_KEY"))),
            t.EnvVar(name="MY_POD", value_from=t.EnvVarSource(
                field_ref=t.ObjectFieldSelector(field_path="metadata.name"))),
            t.EnvVar(name="MY_NODE", value_from=t.EnvVarSource(
                field_ref=t.ObjectFieldSelector(field_path="spec.nodeName"))),
        ]
        cs.pods.create(pod)
        wait_phase(cs, "env-pod", t.POD_SUCCEEDED)
        import json

        envs = json.loads(open(out).read())
        assert envs["HP_LR"] == "0.001" and envs["HP_STEPS"] == "100"
        assert envs["WANDB_KEY"] == "s3cr3t"
        assert envs["EXPLICIT"] == "1"
        assert envs["FROM_CM"] == "0.001"
        assert envs["FROM_SEC"] == "s3cr3t"
        assert envs["MY_POD"] == "env-pod"
        assert envs["MY_NODE"] == "vol-node-0"
        assert envs["KTPU_APISERVER"].startswith("http")

    def test_sa_token_automounted(self, vol_env):
        """Every pod gets its ServiceAccount token at the canonical path —
        the credential JAX jobs use to reach the API (ref: serviceaccount
        admission + token secret volume)."""
        cs, tmp = vol_env["cs"], vol_env["tmp"]
        # wait for the SA controller to mint default/token
        must_poll_until(
            lambda: bool(cs.serviceaccounts.get("default", "default").secrets),
            timeout=10.0, desc="default SA token",
        )
        out = str(tmp / "sa.txt")
        code = (
            f"import os; d={SA_TOKEN_MOUNT_PATH!r};"
            "tok=os.environ.get('KTPU_VOLUME_KTPU_SA_TOKEN');"
            "src=d if os.path.exists(d+'/token') else tok;"
            f"open({out!r},'w').write(open(src+'/token').read()+'\\n'+open(src+'/namespace').read())"
        )
        pod = py_pod("sa-pod", code)
        cs.pods.create(pod)
        wait_phase(cs, "sa-pod", t.POD_SUCCEEDED)
        token, ns = open(out).read().split("\n")
        assert ns == "default"
        sa = cs.serviceaccounts.get("default", "default")
        sec = cs.secrets.get(sa.secrets[0].name, "default")
        assert token == sec.data["token"]


class TestValidation:
    def test_dangling_volume_mount_rejected(self, vol_env):
        cs = vol_env["cs"]
        pod = py_pod("bad-mount", "pass")
        pod.spec.containers[0].volume_mounts = [
            t.VolumeMount(name="nope", mount_path="/x")
        ]
        with pytest.raises(Invalid, match="references no pod volume"):
            cs.pods.create(pod)

    def test_volume_needs_exactly_one_source(self, vol_env):
        cs = vol_env["cs"]
        pod = py_pod("bad-vol", "pass")
        pod.spec.volumes = [t.Volume(name="v")]
        with pytest.raises(Invalid, match="exactly one source"):
            cs.pods.create(pod)


class TestRestartSafety:
    def test_volumes_survive_kubelet_restart(self, vol_env, tmp_path):
        """emptyDir content persists across a kubelet restart (same uid →
        same dir) and a restarted container still sees its mounts —
        the volume analog of the fork's device-assignment restart e2e."""
        cs = vol_env["cs"]
        code = (
            "import os, time; d=os.environ['KTPU_VOLUME_STATE'];"
            "n=len(os.listdir(d)); open(d+'/run-%d' % n, 'w').write(str(n));"
            "time.sleep(60)"
        )
        pod = py_pod("restartable", code, restart="Always")
        pod.spec.volumes = [t.Volume(name="state", empty_dir=t.EmptyDirVolumeSource())]
        pod.spec.containers[0].volume_mounts = [
            t.VolumeMount(name="state", mount_path="/state")
        ]
        cs.pods.create(pod)
        bound = wait_phase(cs, "restartable", t.POD_RUNNING)
        vm = vol_env["kubelet"].volume_manager
        state_dir = os.path.join(vm.root, "pods", bound.metadata.uid,
                                 "volumes", "emptydir", "state")
        must_poll_until(lambda: os.path.exists(os.path.join(state_dir, "run-0")),
                        timeout=10.0, desc="first write")

        old = vol_env["kubelet"]
        old.stop()
        new = Kubelet(
            cs, node_name="vol-node-0", runtime=vol_env["runtime"],
            plugin_dir=str(vol_env["tmp"] / "plugins"),
            heartbeat_interval=0.5, sync_interval=0.3, pleg_interval=0.3,
        )
        new.start()
        vol_env["kubelet"] = new
        # the adopted container keeps running; its volume dir is untouched
        time.sleep(1.5)
        assert os.path.exists(os.path.join(state_dir, "run-0"))
        assert new.volume_manager.root == vm.root  # derived from runtime root


class TestDynamicProvisioning:
    """StorageClass + hostPath provisioner (VERDICT r4 Missing #2; ref
    pkg/apis/storage/types.go:28, pv_controller.go provisionClaim)."""

    @staticmethod
    def _class(name, mode="Immediate", reclaim="Delete"):
        sc = t.StorageClass()
        sc.metadata.name = name
        sc.provisioner = "ktpu.io/hostpath"
        sc.volume_binding_mode = mode
        sc.reclaim_policy = reclaim
        return sc

    @staticmethod
    def _claim(name, cls):
        pvc = t.PersistentVolumeClaim()
        pvc.metadata.name = name
        pvc.spec.access_modes = ["ReadWriteOnce"]
        pvc.spec.storage_class_name = cls
        pvc.spec.resources = t.ResourceRequirements(
            requests={"storage": "1Gi"})
        return pvc

    def test_pvc_provisions_binds_and_checkpoint_survives_restart(
            self, vol_env):
        """The r5 'done' bar: a PVC naming storageClassName provisions,
        binds, mounts — and the checkpoint survives a pod restart."""
        cs = vol_env["cs"]
        cs.resource("storageclasses").create(self._class("local"))
        cs.persistentvolumeclaims.create(self._claim("dyn-ckpt", "local"))
        must_poll_until(
            lambda: cs.persistentvolumeclaims.get(
                "dyn-ckpt", "default").status.phase == "Bound",
            timeout=20.0, desc="dynamic PVC bound")
        pv_name = cs.persistentvolumeclaims.get(
            "dyn-ckpt", "default").spec.volume_name
        pv = cs.persistentvolumes.get(pv_name, "")
        assert pv.metadata.annotations[
            "pv.kubernetes.io/provisioned-by"] == "ktpu.io/hostpath"
        assert pv.spec.host_path.path

        def writer(name, code):
            pod = py_pod(name, code)
            pod.spec.volumes = [t.Volume(
                name="ckpt",
                persistent_volume_claim=t.PersistentVolumeClaimVolumeSource(
                    claim_name="dyn-ckpt"))]
            pod.spec.containers[0].volume_mounts = [
                t.VolumeMount(name="ckpt", mount_path="/ckpt")]
            return pod

        cs.pods.create(writer(
            "trainer-1",
            "import os; d=os.environ['KTPU_VOLUME_CKPT'];"
            "open(d + '/step.ckpt', 'w').write('step-500')"))
        wait_phase(cs, "trainer-1", t.POD_SUCCEEDED)
        cs.pods.delete("trainer-1", "default")
        # a NEW pod (restart) reads the same provisioned volume
        cs.pods.create(writer(
            "trainer-2",
            "import os,sys; d=os.environ['KTPU_VOLUME_CKPT'];"
            "sys.exit(0 if open(d + '/step.ckpt').read() == 'step-500'"
            " else 1)"))
        wait_phase(cs, "trainer-2", t.POD_SUCCEEDED)

    def test_wait_for_first_consumer(self, vol_env):
        """WFFC as API behavior: the claim stays Pending until a pod that
        consumes it is scheduled."""
        cs = vol_env["cs"]
        cs.resource("storageclasses").create(
            self._class("wffc", mode="WaitForFirstConsumer"))
        cs.persistentvolumeclaims.create(self._claim("lazy", "wffc"))
        time.sleep(2.0)
        assert cs.persistentvolumeclaims.get(
            "lazy", "default").status.phase == "Pending"
        pod = py_pod("consumer", "print('hi')")
        pod.spec.volumes = [t.Volume(
            name="v",
            persistent_volume_claim=t.PersistentVolumeClaimVolumeSource(
                claim_name="lazy"))]
        pod.spec.containers[0].volume_mounts = [
            t.VolumeMount(name="v", mount_path="/v")]
        cs.pods.create(pod)
        must_poll_until(
            lambda: cs.persistentvolumeclaims.get(
                "lazy", "default").status.phase == "Bound",
            timeout=20.0, desc="WFFC claim bound after consumer scheduled")
        wait_phase(cs, "consumer", t.POD_SUCCEEDED)

    def test_delete_reclaim_cleans_up(self, vol_env):
        """reclaimPolicy Delete: deleting the claim deletes the PV and the
        provisioned directory."""
        cs, tmp = vol_env["cs"], vol_env["tmp"]
        cs.resource("storageclasses").create(self._class("scratch"))
        pvc = cs.persistentvolumeclaims.create(
            self._claim("temp", "scratch"))
        must_poll_until(
            lambda: cs.persistentvolumeclaims.get(
                "temp", "default").status.phase == "Bound",
            timeout=20.0, desc="claim bound")
        pv_name = f"pvc-{pvc.metadata.uid}"
        pv_dir = str(tmp / "dynpv" / pv_name)
        assert os.path.isdir(pv_dir)
        cs.persistentvolumeclaims.delete("temp", "default")
        must_poll_until(
            lambda: not os.path.isdir(pv_dir),
            timeout=20.0, desc="provisioned dir reclaimed")
        from kubernetes1_tpu.machinery import NotFound
        with pytest.raises(NotFound):
            cs.persistentvolumes.get(pv_name, "")
