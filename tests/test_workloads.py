"""Workload tests on the virtual 8-device CPU mesh (conftest forces it).

Covers every BASELINE config's compute side: single-chip MNIST, dp ResNet,
dp/fsdp/tp Llama train step, and sequence-parallel ring attention vs the
dense reference.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from kubernetes1_tpu.workloads import (
    bert,
    llama,
    mnist,
    resnet,
    ringattention as ra,
    sharding as sh,
)


def test_mesh_helpers():
    mesh = sh.make_mesh(dp=2, fsdp=2, tp=2)
    assert mesh.axis_names == ("dp", "fsdp", "tp")
    assert sh.auto_mesh().devices.size == 8
    with pytest.raises(ValueError):
        sh.make_mesh(dp=16)


def test_mnist_single_chip_converges():
    loss, acc = mnist.train(steps=40)
    assert loss < 0.1
    assert acc > 0.95


def test_resnet_dp_step_decreases_loss():
    mesh = sh.make_mesh(dp=4, fsdp=2)
    cfg = resnet.tiny()
    l1 = resnet.train_demo(cfg, mesh, steps=1, batch=8, size=16)
    l5 = resnet.train_demo(cfg, mesh, steps=6, batch=8, size=16)
    assert np.isfinite(l1) and np.isfinite(l5)
    assert l5 < l1


def test_llama_3d_sharded_train_step():
    mesh = sh.make_mesh(dp=2, fsdp=2, tp=2)
    cfg = llama.tiny()
    l1 = llama.train_demo(cfg, mesh, steps=1, batch=8, seq=32)
    l8 = llama.train_demo(cfg, mesh, steps=8, batch=8, seq=32)
    assert np.isfinite(l1) and np.isfinite(l8)
    assert l8 < l1  # memorizes the fixed batch


def test_llama_param_shardings_applied():
    mesh = sh.make_mesh(dp=1, fsdp=2, tp=2, devices=jax.devices()[:4])
    cfg = llama.tiny()
    with sh.use_mesh(mesh):
        params, _, _ = llama.make_train_state(cfg, mesh)
    wq = params["layers"]["wq"]
    # (L, d, heads*hd) sharded (None, fsdp, tp) -> each shard d/2 x cols/2
    shard_shape = wq.sharding.shard_shape(wq.shape)
    assert shard_shape[1] == cfg.d_model // 2
    assert shard_shape[2] == (cfg.n_heads * cfg.head_dim) // 2


def test_llama_loss_matches_unsharded():
    cfg = llama.tiny()
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab, (4, 16)), jnp.int32)
    mesh1 = sh.make_mesh(dp=1, fsdp=1, tp=1, devices=jax.devices()[:1])
    mesh8 = sh.make_mesh(dp=2, fsdp=2, tp=2)
    losses = []
    for mesh in (mesh1, mesh8):
        with sh.use_mesh(mesh):
            params, _, _ = llama.make_train_state(cfg, mesh)
            losses.append(float(jax.jit(lambda p, t: llama.loss_fn(cfg, p, t))(params, tokens)))
    assert abs(losses[0] - losses[1]) < 5e-2  # bf16 tolerance


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(causal):
    spmesh = Mesh(np.array(jax.devices()).reshape(8), ("sp",))
    k = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(k[0], (2, 64, 4, 16))
    kk = jax.random.normal(k[1], (2, 64, 2, 16))
    v = jax.random.normal(k[2], (2, 64, 2, 16))
    out = ra.ring_attention(q, kk, v, spmesh, causal=causal)
    ref = ra.reference_attention(q, kk, v, causal=causal)
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-4


def test_ring_attention_grads_flow():
    spmesh = Mesh(np.array(jax.devices()).reshape(8), ("sp",))
    k = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(k[0], (1, 32, 2, 8))
    kv = jax.random.normal(k[1], (1, 32, 2, 8))

    def f(q, kv):
        return jnp.sum(ra.ring_attention(q, kv, kv, spmesh))

    def f_ref(q, kv):
        return jnp.sum(ra.reference_attention(q, kv, kv))

    g = jax.grad(f)(q, kv)
    g_ref = jax.grad(f_ref)(q, kv)
    assert float(jnp.max(jnp.abs(g - g_ref))) < 1e-4


def test_bert_mlm_sharded_train_step():
    mesh = sh.make_mesh(dp=2, fsdp=2, tp=2)
    cfg = bert.tiny()
    l1 = bert.train_demo(cfg, mesh, steps=1, batch=8, seq=32)
    l12 = bert.train_demo(cfg, mesh, steps=12, batch=8, seq=32)
    assert np.isfinite(l1) and np.isfinite(l12)
    assert l12 < l1  # memorizes the fixed masked batch


def test_bert_param_shardings_applied():
    mesh = sh.make_mesh(dp=1, fsdp=2, tp=2, devices=jax.devices()[:4])
    cfg = bert.tiny()
    with sh.use_mesh(mesh):
        params, _, _ = bert.make_train_state(cfg, mesh)
    w_in = params["layers"]["w_in"]
    shard_shape = w_in.sharding.shard_shape(w_in.shape)
    assert shard_shape[1] == cfg.d_model // 2   # fsdp
    assert shard_shape[2] == cfg.d_ff // 2      # tp


def test_bert_loss_matches_unsharded():
    cfg = bert.tiny()
    tokens, mask = bert.synthetic_batch(cfg, 4, 16)
    mesh1 = sh.make_mesh(dp=1, fsdp=1, tp=1, devices=jax.devices()[:1])
    mesh8 = sh.make_mesh(dp=2, fsdp=2, tp=2)
    losses = []
    for mesh in (mesh1, mesh8):
        with sh.use_mesh(mesh):
            params, _, _ = bert.make_train_state(cfg, mesh, seed=0)
            losses.append(float(bert.mlm_loss_fn(cfg, params, tokens, mask)))
    np.testing.assert_allclose(losses[0], losses[1], rtol=2e-2)


def test_bert_masked_positions_drive_loss():
    """Loss ignores unmasked positions: zero mask everywhere but one token."""
    cfg = bert.tiny()
    tokens, _ = bert.synthetic_batch(cfg, 2, 8)
    mesh1 = sh.make_mesh(dp=1, fsdp=1, tp=1, devices=jax.devices()[:1])
    with sh.use_mesh(mesh1):
        params, _, _ = bert.make_train_state(cfg, mesh1)
        full = jnp.ones_like(tokens)
        one = jnp.zeros_like(tokens).at[0, 0].set(1)
        l_full = float(bert.mlm_loss_fn(cfg, params, tokens, full))
        l_one = float(bert.mlm_loss_fn(cfg, params, tokens, one))
    assert np.isfinite(l_full) and np.isfinite(l_one)
    assert l_full != l_one


class TestBenchguardWatchdog:
    """The device-acquisition watchdog is the round-5 fix for the wedged
    chip claim that cost round 4 its flagship number — it must fire from
    a TIMER THREAD (SIGALRM can't: the hang sits in a C call), write the
    distinct error, and hard-exit."""

    REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _run(self, code):
        import subprocess

        return subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=20, cwd=self.REPO,
            env=dict(os.environ, PYTHONPATH=self.REPO))

    def test_fires_writes_error_and_exits_3(self, tmp_path):
        import json
        import time

        out = tmp_path / "result.json"
        t0 = time.monotonic()
        p = self._run(
            "from kubernetes1_tpu.workloads.benchguard import "
            "device_acquisition_watchdog\n"
            f"device_acquisition_watchdog({str(out)!r}, 0.3)\n"
            "import time; time.sleep(30)\n")  # models the stuck claim
        assert p.returncode == 3
        assert time.monotonic() - t0 < 10  # fast-fail, not the sleep(30)
        assert json.load(open(out))["error"] == "device acquisition timeout"

    def test_cancel_stands_down(self, tmp_path):
        out = tmp_path / "result.json"
        p = self._run(
            "from kubernetes1_tpu.workloads.benchguard import "
            "device_acquisition_watchdog\n"
            f"t = device_acquisition_watchdog({str(out)!r}, 0.3)\n"
            "t.cancel()\n"                    # claim succeeded
            "import time; time.sleep(0.6)\n"  # past the timeout
            "print('survived')\n")
        assert p.returncode == 0 and "survived" in p.stdout
        assert not out.exists()
