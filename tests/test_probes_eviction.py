"""Kubelet probe + eviction tests (ref: pkg/kubelet/prober + eviction test
areas): readiness gates the Ready condition and Endpoints membership,
liveness failures restart containers, node pressure evicts lowest-QoS pods
and raises node conditions."""

import threading

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.kubelet import FakeRuntime, Kubelet
from kubernetes1_tpu.kubelet.eviction import (
    QOS_BESTEFFORT,
    QOS_BURSTABLE,
    QOS_GUARANTEED,
    EvictionManager,
    qos_class,
)
from kubernetes1_tpu.kubelet.prober import ProberManager, run_probe
from kubernetes1_tpu.scheduler import Scheduler
from kubernetes1_tpu.utils.waitutil import must_poll_until


@pytest.fixture()
def node(tmp_path):
    master = Master().start()
    cs = Clientset(master.url)
    sched = Scheduler(cs)
    sched.start()
    runtime = FakeRuntime()
    kubelet = Kubelet(
        cs, node_name="probe-node", runtime=runtime,
        plugin_dir=str(tmp_path / "plugins"),
        heartbeat_interval=0.5, sync_interval=0.2, pleg_interval=0.2,
        eviction_interval=0.5,
        eviction_signals_fn=lambda: {"memory.available": 1.0},
    )
    kubelet.start()
    env = {"master": master, "cs": cs, "kubelet": kubelet, "runtime": runtime}
    yield env
    kubelet.stop()
    sched.stop()
    cs.close()
    master.stop()


def probed_pod(name, exec_cmd=("check",), kind="readiness", period=1,
               failure_threshold=1):
    pod = t.Pod()
    pod.metadata.name = name
    pod.spec.containers = [t.Container(name="c", image="x", command=["serve"])]
    probe = t.Probe(
        exec_action=t.ExecAction(command=list(exec_cmd)),
        period_seconds=period, failure_threshold=failure_threshold,
    )
    if kind == "readiness":
        pod.spec.containers[0].readiness_probe = probe
    else:
        pod.spec.containers[0].liveness_probe = probe
    return pod


class TestProbeActions:
    def test_tcp_probe(self):
        import socket

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]
        probe = t.Probe(tcp_socket=t.TCPSocketAction(port=port))
        assert run_probe(probe, "127.0.0.1") is True
        srv.close()
        probe_bad = t.Probe(tcp_socket=t.TCPSocketAction(port=1))
        assert run_probe(probe_bad, "127.0.0.1") is False

    def test_http_probe(self):
        from http.server import BaseHTTPRequestHandler, HTTPServer

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                code = 200 if self.path == "/healthy" else 500
                self.send_response(code)
                self.send_header("Content-Length", "0")
                self.end_headers()

        srv = HTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        port = srv.server_address[1]
        ok = t.Probe(http_get=t.HTTPGetAction(path="/healthy", port=port))
        bad = t.Probe(http_get=t.HTTPGetAction(path="/broken", port=port))
        assert run_probe(ok, "127.0.0.1") is True
        assert run_probe(bad, "127.0.0.1") is False
        srv.shutdown()
        srv.server_close()

    def test_exec_probe_uses_runtime(self):
        results = {"code": 1}
        probe = t.Probe(exec_action=t.ExecAction(command=["check"]))
        assert run_probe(probe, "", exec_fn=lambda cmd: results["code"]) is False
        results["code"] = 0
        assert run_probe(probe, "", exec_fn=lambda cmd: results["code"]) is True


class TestReadiness:
    def test_failing_readiness_keeps_pod_unready(self, node):
        cs, runtime = node["cs"], node["runtime"]
        runtime.set_exec_result("c", 1)  # readiness exec fails
        cs.pods.create(probed_pod("unready", kind="readiness"))
        must_poll_until(
            lambda: cs.pods.get("unready").status.phase == t.POD_RUNNING,
            timeout=15.0, desc="running",
        )

        def ready_condition():
            conds = cs.pods.get("unready").status.conditions
            return next((c.status for c in conds if c.type == "Ready"), None)

        must_poll_until(lambda: ready_condition() == "False", timeout=10.0,
                        desc="NotReady while probe fails")
        # flip the probe to success -> pod becomes Ready
        runtime.set_exec_result("c", 0)
        must_poll_until(lambda: ready_condition() == "True", timeout=15.0,
                        desc="Ready after probe passes")


class TestLiveness:
    def test_failing_liveness_restarts_container(self, node):
        cs, runtime = node["cs"], node["runtime"]
        cs.pods.create(probed_pod("flappy", kind="liveness"))
        must_poll_until(
            lambda: cs.pods.get("flappy").status.phase == t.POD_RUNNING,
            timeout=15.0, desc="running",
        )
        runtime.set_exec_result("c", 1)  # liveness starts failing

        def restarted():
            sts = cs.pods.get("flappy").status.container_statuses
            return sts and sts[0].restart_count >= 1

        must_poll_until(restarted, timeout=20.0, desc="container restarted")
        runtime.set_exec_result("c", 0)  # recover so teardown is clean


class TestQoS:
    def test_qos_classes(self):
        best_effort = t.Pod()
        best_effort.spec.containers = [t.Container(name="c", image="x")]
        assert qos_class(best_effort) == QOS_BESTEFFORT

        burstable = t.Pod()
        burstable.spec.containers = [
            t.Container(name="c", image="x",
                        resources=t.ResourceRequirements(requests={"cpu": "100m"}))
        ]
        assert qos_class(burstable) == QOS_BURSTABLE

        guaranteed = t.Pod()
        guaranteed.spec.containers = [
            t.Container(name="c", image="x",
                        resources=t.ResourceRequirements(
                            requests={"cpu": "1", "memory": "1Gi"},
                            limits={"cpu": "1", "memory": "1Gi"}))
        ]
        assert qos_class(guaranteed) == QOS_GUARANTEED


class TestEviction:
    def test_picks_besteffort_before_burstable(self):
        be = t.Pod()
        be.metadata.name = "be"
        be.metadata.creation_timestamp = "2026-01-01T00:00:00Z"
        be.status.phase = t.POD_RUNNING
        be.spec.containers = [t.Container(name="c", image="x")]
        bu = t.Pod()
        bu.metadata.name = "bu"
        bu.metadata.creation_timestamp = "2026-01-02T00:00:00Z"
        bu.status.phase = t.POD_RUNNING
        bu.spec.containers = [
            t.Container(name="c", image="x",
                        resources=t.ResourceRequirements(requests={"cpu": "1"}))
        ]
        evicted = []
        mgr = EvictionManager(
            thresholds={"memory.available": 0.10},
            signals_fn=lambda: {"memory.available": 0.01},
            evict_fn=lambda pod, reason: evicted.append(pod.metadata.name),
            list_pods=lambda: [bu, be],
        )
        assert mgr.synchronize() == ["be"]
        assert evicted == ["be"]
        conds = {c.type: c.status for c in mgr.node_conditions()}
        assert conds["MemoryPressure"] == "True"

    def test_no_pressure_no_eviction(self):
        mgr = EvictionManager(
            thresholds={"memory.available": 0.05},
            signals_fn=lambda: {"memory.available": 0.50},
            evict_fn=lambda pod, reason: pytest.fail("must not evict"),
            list_pods=lambda: [],
        )
        assert mgr.synchronize() == []
        conds = {c.type: c.status for c in mgr.node_conditions()}
        assert conds["MemoryPressure"] == "False"

    def test_node_pressure_evicts_pod_end_to_end(self, tmp_path):
        master = Master().start()
        cs = Clientset(master.url)
        sched = Scheduler(cs)
        sched.start()
        pressure = {"memory.available": 1.0}
        kubelet = Kubelet(
            cs, node_name="pressured", runtime=FakeRuntime(),
            plugin_dir=str(tmp_path / "p"),
            heartbeat_interval=0.3, sync_interval=0.2, pleg_interval=0.2,
            eviction_interval=0.3,
            eviction_signals_fn=lambda: dict(pressure),
        )
        kubelet.start()
        try:
            pod = t.Pod()
            pod.metadata.name = "victim"
            pod.spec.containers = [t.Container(name="c", image="x", command=["serve"])]
            cs.pods.create(pod)
            must_poll_until(
                lambda: cs.pods.get("victim").status.phase == t.POD_RUNNING,
                timeout=15.0, desc="running",
            )
            pressure["memory.available"] = 0.01
            must_poll_until(
                lambda: cs.pods.get("victim").status.phase == t.POD_FAILED,
                timeout=15.0, desc="evicted",
            )
            assert cs.pods.get("victim").status.reason == "Evicted"
            must_poll_until(
                lambda: any(
                    c.type == "MemoryPressure" and c.status == "True"
                    for c in cs.nodes.get("pressured", "").status.conditions
                ),
                timeout=10.0, desc="pressure condition",
            )
        finally:
            kubelet.stop()
            sched.stop()
            cs.close()
            master.stop()
