"""SecurityContext enforcement + PodSecurityPolicy admission.

Ref: pkg/securitycontext (DetermineEffectiveSecurityContext, runAsNonRoot
verification in kuberuntime), pkg/security/podsecuritypolicy + its
admission plugin.  On a shared TPU host this is the single-tenant vs
multi-tenant line: who processes run as, and whether a pod can reach
/dev/accel* outside the device-plugin allocation path.
"""

import os
import sys
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver.server import Master
from kubernetes1_tpu.client import Clientset
from kubernetes1_tpu.machinery import Forbidden
from kubernetes1_tpu.utils.waitutil import must_poll_until


def make_pod(name, uid=None, gid=None, non_root=None, privileged=None,
             pod_uid=None, host_path=None, command=None):
    pod = t.Pod()
    pod.metadata.name = name
    c = t.Container(name="c", image="img",
                    command=command or ["sh", "-c", "true"])
    if any(v is not None for v in (uid, gid, non_root, privileged)):
        c.security_context = t.SecurityContext(
            run_as_user=uid, run_as_group=gid, run_as_non_root=non_root,
            privileged=privileged)
    pod.spec.containers = [c]
    if pod_uid is not None:
        pod.spec.security_context = t.PodSecurityContext(run_as_user=pod_uid)
    if host_path:
        pod.spec.volumes = [t.Volume(
            name="h", host_path=t.HostPathVolumeSource(path=host_path))]
        c.volume_mounts = [t.VolumeMount(name="h", mount_path="/mnt/h")]
    return pod


class TestEffectiveContext:
    def test_container_overrides_pod(self):
        pod = make_pod("p", uid=1000, pod_uid=2000)
        sc = t.effective_security_context(pod, pod.spec.containers[0])
        assert sc.run_as_user == 1000

    def test_pod_level_inherited(self):
        pod = make_pod("p", pod_uid=2000)
        sc = t.effective_security_context(pod, pod.spec.containers[0])
        assert sc.run_as_user == 2000

    def test_unset_everywhere(self):
        pod = make_pod("p")
        sc = t.effective_security_context(pod, pod.spec.containers[0])
        assert sc.run_as_user is None and not sc.privileged


class TestPSPAdmission:
    @pytest.fixture()
    def cluster(self):
        m = Master().start()
        cs = Clientset(m.url)
        yield m, cs
        cs.close()
        m.stop()

    @staticmethod
    def _psp(name, privileged=False, host_paths=None, rule="RunAsAny"):
        psp = t.PodSecurityPolicy()
        psp.metadata.name = name
        psp.spec.privileged = privileged
        psp.spec.allowed_host_paths = list(host_paths or [])
        psp.spec.run_as_user_rule = rule
        return psp

    def test_no_policies_allows_everything(self, cluster):
        _, cs = cluster
        cs.pods.create(make_pod("free", privileged=True))

    def test_privileged_requires_allowing_policy(self, cluster):
        _, cs = cluster
        cs.resource("podsecuritypolicies").create(self._psp("restricted"))
        with pytest.raises(Forbidden):
            cs.pods.create(make_pod("priv", privileged=True))
        cs.pods.create(make_pod("plain"))  # unprivileged passes
        # adding a privileged-allowing policy admits it (any one admits)
        cs.resource("podsecuritypolicies").create(
            self._psp("privileged", privileged=True))
        cs.pods.create(make_pod("priv2", privileged=True))

    def test_hostpath_allowlist(self, cluster):
        _, cs = cluster
        cs.resource("podsecuritypolicies").create(
            self._psp("paths", host_paths=["/var/data"]))
        cs.pods.create(make_pod("ok", host_path="/var/data/ckpt"))
        with pytest.raises(Forbidden):
            cs.pods.create(make_pod("bad", host_path="/etc"))
        with pytest.raises(Forbidden):
            # prefix match must be path-segment aware
            cs.pods.create(make_pod("sneaky", host_path="/var/database"))

    def test_must_run_as_non_root(self, cluster):
        _, cs = cluster
        cs.resource("podsecuritypolicies").create(
            self._psp("nonroot", rule="MustRunAsNonRoot"))
        with pytest.raises(Forbidden):
            cs.pods.create(make_pod("root-implicit"))  # unset = may be root
        with pytest.raises(Forbidden):
            cs.pods.create(make_pod("root-explicit", uid=0))
        cs.pods.create(make_pod("user", uid=1000))
        # runAsNonRoot=true with NO numeric uid satisfies the rule (image
        # may declare a non-root USER; the kubelet's runtime check still
        # rejects if the effective uid resolves to 0) — upstream's
        # MustRunAsNonRoot strategy defers uid verification the same way
        cs.pods.create(make_pod("image-user", non_root=True))
        with pytest.raises(Forbidden):
            # but an explicit uid 0 loses to runAsNonRoot=true
            cs.pods.create(make_pod("contradiction", uid=0, non_root=True))


class TestRuntimeEnforcement:
    """The kubelet + ProcessRuntime actually realize the identity."""

    @pytest.fixture()
    def node(self, tmp_path):
        from kubernetes1_tpu.kubelet import Kubelet, ProcessRuntime

        master = Master().start()
        cs = Clientset(master.url)
        runtime = ProcessRuntime(root_dir=str(tmp_path / "ktpu"))
        kubelet = Kubelet(cs, node_name="sec-node", runtime=runtime,
                          plugin_dir=str(tmp_path / "plugins"),
                          heartbeat_interval=0.5, sync_interval=0.3,
                          pleg_interval=0.3)
        kubelet.start()
        yield {"cs": cs, "node": "sec-node", "runtime": runtime}
        kubelet.stop()
        runtime.kill_all()  # containers must not outlive the fixture
        cs.close()
        master.stop()

    @pytest.mark.skipif(os.geteuid() != 0, reason="setuid needs root")
    def test_pod_runs_as_requested_uid(self, node):
        cs = node["cs"]
        # stdout goes to the container log — no host file permissions to
        # fight (the dropped uid can't traverse pytest's 0700 tmp dirs)
        pod = make_pod("as-nobody", uid=65534, gid=65534,
                       command=["sh", "-c", "id -u; id -g"])
        pod.spec.restart_policy = "Never"
        pod.spec.node_name = node["node"]
        cs.pods.create(pod)
        must_poll_until(
            lambda: cs.pods.get("as-nobody", "default").status.phase
            == "Succeeded", timeout=30.0, desc="pod completes")
        runtime = node["runtime"]
        cid = next(c.id for c in runtime.list_containers()
                   if c.name == "c" and c.state == "EXITED")
        assert runtime.read_log(cid).split() == ["65534", "65534"]

    def test_run_as_non_root_with_root_uid_fails(self, node):
        cs = node["cs"]
        pod = make_pod("lying", non_root=True)  # uid unset -> would be root
        pod.spec.restart_policy = "Never"
        pod.spec.node_name = node["node"]
        cs.pods.create(pod)
        must_poll_until(
            lambda: cs.pods.get("lying", "default").status.phase == "Failed",
            timeout=30.0, desc="runAsNonRoot violation fails the pod")

    def test_unprivileged_dev_hostpath_denied(self, node):
        cs = node["cs"]
        pod = make_pod("devgrab", host_path="/dev/null")
        pod.spec.restart_policy = "Never"
        pod.spec.node_name = node["node"]
        cs.pods.create(pod)
        must_poll_until(
            lambda: cs.pods.get("devgrab", "default").status.phase
            == "Failed", timeout=30.0,
            desc="unprivileged /dev hostPath fails the pod")


@pytest.mark.skipif(os.geteuid() != 0, reason="setuid needs root")
class TestNativeRuntimeUser:
    def test_native_runtime_drops_uid(self, tmp_path):
        import subprocess

        from kubernetes1_tpu.kubelet.cri import RemoteRuntime
        from kubernetes1_tpu.kubelet.runtime import ContainerConfig

        binary = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
            "kubernetes1_tpu", "native", "bin", "ktpu-cri-runtime")
        if not os.access(binary, os.X_OK):
            pytest.skip("native runtime not built")
        sock = str(tmp_path / "cri.sock")
        root = str(tmp_path / "root")
        proc = subprocess.Popen([binary, "--socket", sock, "--root", root])
        try:
            rt = RemoteRuntime(sock)
            sid = rt.run_pod_sandbox("p", "default", "u1")
            cid = rt.create_container(sid, ContainerConfig(
                name="c", image="img", command=["id", "-u"],
                run_as_user=65534, run_as_group=65534))
            rt.start_container(cid)
            deadline = time.time() + 10
            while time.time() < deadline:
                rec = rt.container_status(cid)
                if rec is not None and rec.state == "EXITED":
                    break
                time.sleep(0.2)
            assert rt.read_log(cid).strip() == "65534"
            rt.close()
        finally:
            proc.terminate()
            proc.wait(timeout=5)
