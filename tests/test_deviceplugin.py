"""Device-plugin protocol + manager tests against an in-process plugin
served over a real unix socket (the reference's device_plugin_stub.go
pattern: real sockets, real streams, scriptable behavior)."""

import os
import threading
import time

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.deviceplugin.api import (
    ContainerSpec,
    PluginClient,
    PluginServer,
    plugin_socket_path,
    resource_from_socket,
)
from kubernetes1_tpu.deviceplugin.tpu_plugin import (
    ANN_COORDINATOR,
    ANN_WORKER_ID,
    TPUDevicePlugin,
    _fake_devices,
)
from kubernetes1_tpu.kubelet.devicemanager import DeviceManager
from kubernetes1_tpu.utils.waitutil import must_poll_until

from tests.helpers import make_tpu_pod


@pytest.fixture()
def plugin_dir(tmp_path):
    return str(tmp_path / "plugins")


@pytest.fixture()
def served_plugin(plugin_dir):
    impl = TPUDevicePlugin(devices=_fake_devices("v5e:4:s0:0"))
    server = PluginServer(impl, plugin_socket_path(plugin_dir, "google.com/tpu"))
    server.start()
    yield impl, server, plugin_dir
    server.stop()


class TestProtocol:
    def test_socket_path_layout(self, plugin_dir):
        p = plugin_socket_path(plugin_dir, "google.com/tpu")
        assert p.endswith("google.com/tpu.sock")
        assert resource_from_socket(plugin_dir, p) == "google.com/tpu"
        assert resource_from_socket(plugin_dir, plugin_dir + "/junk") is None

    def test_get_plugin_info(self, served_plugin):
        impl, server, _ = served_plugin
        client = PluginClient(server.socket_path)
        info = client.call("GetPluginInfo")
        assert info["name"] == "google.com/tpu"
        assert info["device_count"] == 4
        client.close()

    def test_list_and_watch_streams_updates(self, served_plugin):
        impl, server, _ = served_plugin
        client = PluginClient(server.socket_path)
        frames = []
        stream = client.list_and_watch()

        def consume():
            for devices in stream:
                frames.append(devices)

        th = threading.Thread(target=consume, daemon=True)
        th.start()
        must_poll_until(lambda: len(frames) >= 1, desc="initial frame")
        assert len(frames[0]) == 4
        impl.set_health("s0-h0-chip0", t.DEVICE_UNHEALTHY)
        must_poll_until(lambda: len(frames) >= 2, desc="health update frame")
        sick = [d for d in frames[-1] if d["id"] == "s0-h0-chip0"][0]
        assert sick["health"] == t.DEVICE_UNHEALTHY
        client.close()

    def test_admit_and_init(self, served_plugin):
        impl, server, _ = served_plugin
        client = PluginClient(server.socket_path)
        resp = client.call(
            "AdmitPod",
            {"pod_uid": "u1", "assignments": {"r0": ["s0-h0-chip0", "s0-h0-chip1"]}},
        )
        assert resp["allowed"] is True
        resp = client.call(
            "AdmitPod", {"pod_uid": "u2", "assignments": {"r0": ["nope"]}}
        )
        assert resp["allowed"] is False
        result = client.call(
            "InitContainer",
            {
                "pod_uid": "u1",
                "container_name": "main",
                "device_ids": ["s0-h0-chip0", "s0-h0-chip1"],
                "pod_annotations": {
                    ANN_WORKER_ID: "3",
                    ANN_COORDINATOR: "trainer-0.trainer:8476",
                },
            },
        )
        spec = ContainerSpec.from_dict(result)
        assert spec.envs["TPU_VISIBLE_CHIPS"] == "0,1"
        assert spec.envs["TPU_WORKER_ID"] == "3"
        assert spec.envs["JAX_COORDINATOR_ADDRESS"] == "trainer-0.trainer:8476"
        assert spec.envs["TPU_ACCELERATOR_TYPE"] == "v5e"
        client.close()


class TestDeviceManager:
    def test_discovery_and_capacity(self, served_plugin):
        _, _, plugin_dir = served_plugin
        dm = DeviceManager(plugin_dir, poll_interval=0.1).start()
        try:
            must_poll_until(
                lambda: "google.com/tpu" in dm.get_capacity(), desc="plugin discovered"
            )
            devices = dm.get_capacity()["google.com/tpu"]
            assert len(devices) == 4
            assert devices[0].attributes[t.ATTR_TPU_SLICE] == "s0"
        finally:
            dm.stop()

    def test_admit_pod_paths(self, served_plugin):
        impl, _, plugin_dir = served_plugin
        dm = DeviceManager(plugin_dir, poll_interval=0.1).start()
        try:
            must_poll_until(lambda: dm.has_plugin("google.com/tpu"), desc="plugin up")
            must_poll_until(
                lambda: dm.get_capacity().get("google.com/tpu"), desc="devices known"
            )
            pod = make_tpu_pod("p", tpus=2)
            pod.metadata.uid = "uid-1"
            # no assignment -> permanent reject
            res = dm.admit_pod(pod)
            assert not res.allowed and "no assignment" in res.reason
            assert not res.retriable
            # good assignment
            pod.spec.extended_resources[0].assigned = ["s0-h0-chip2", "s0-h0-chip3"]
            res = dm.admit_pod(pod)
            assert res.allowed, res.reason
            # unknown device
            pod2 = make_tpu_pod("p2", tpus=1)
            pod2.metadata.uid = "uid-2"
            pod2.spec.extended_resources[0].assigned = ["bogus"]
            res = dm.admit_pod(pod2)
            assert not res.allowed and "not in local inventory" in res.reason
            # unhealthy device
            impl.set_health("s0-h0-chip1", t.DEVICE_UNHEALTHY)
            must_poll_until(
                lambda: any(
                    d.health == t.DEVICE_UNHEALTHY
                    for d in dm.get_capacity()["google.com/tpu"]
                ),
                desc="unhealthy propagated",
            )
            pod3 = make_tpu_pod("p3", tpus=1)
            pod3.metadata.uid = "uid-3"
            pod3.spec.extended_resources[0].assigned = ["s0-h0-chip1"]
            res = dm.admit_pod(pod3)
            assert not res.allowed and "unhealthy" in res.reason
            assert dm.allocation_latency.count >= 1
        finally:
            dm.stop()

    def test_plugin_removal_marks_unhealthy(self, served_plugin):
        _, server, plugin_dir = served_plugin
        dm = DeviceManager(plugin_dir, poll_interval=0.1).start()
        try:
            must_poll_until(
                lambda: dm.get_capacity().get("google.com/tpu"), desc="devices known"
            )
            server.stop()  # socket unlinked
            must_poll_until(
                lambda: all(
                    d.health == t.DEVICE_UNHEALTHY
                    for d in dm.get_capacity()["google.com/tpu"]
                ),
                timeout=5.0,
                desc="all devices unhealthy after plugin death",
            )
        finally:
            dm.stop()

    def test_killed_plugin_stale_socket_marks_unhealthy(self, plugin_dir):
        """A SIGKILLed plugin process leaves its socket file behind; the
        endpoint's refused reconnects must mark the inventory unhealthy
        (probe-found bug)."""
        import signal
        import subprocess
        import sys

        proc = subprocess.Popen(
            [sys.executable, "-m", "kubernetes1_tpu.deviceplugin.tpu_plugin",
             "--plugin-dir", plugin_dir],
            env={**os.environ, "KTPU_FAKE_TPUS": "v5e:4:s0:0"},
        )
        dm = DeviceManager(plugin_dir, poll_interval=0.1).start()
        try:
            must_poll_until(
                lambda: dm.get_capacity().get("google.com/tpu"),
                timeout=10.0,
                desc="devices known",
            )
            sock = plugin_socket_path(plugin_dir, "google.com/tpu")
            proc.kill()  # SIGKILL: no cleanup, socket file stays
            proc.wait()
            assert os.path.exists(sock)  # file really is stale
            must_poll_until(
                lambda: all(
                    d.health == t.DEVICE_UNHEALTHY
                    for d in dm.get_capacity()["google.com/tpu"]
                ),
                timeout=8.0,
                desc="stale-socket plugin marked unhealthy",
            )
        finally:
            dm.stop()
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=5)  # collect the exit: no zombie left
