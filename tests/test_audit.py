"""Advanced audit: policy levels + webhook backend (ref:
staging/src/k8s.io/apiserver/pkg/audit, plugin/pkg/audit/{log,webhook})."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubernetes1_tpu.api import types as t
from kubernetes1_tpu.apiserver import Master
from kubernetes1_tpu.apiserver.audit import AuditPolicy
from kubernetes1_tpu.client import Clientset


def make_pod(name):
    pod = t.Pod()
    pod.metadata.name = name
    pod.spec.containers = [t.Container(name="c", image="i",
                                       command=["sleep", "1"])]
    return pod


class TestPolicy:
    def test_first_match_wins(self):
        p = AuditPolicy.from_dict({"rules": [
            {"level": "None", "resources": ["events"]},
            {"level": "RequestResponse", "resources": ["pods"]},
            {"level": "Metadata"},
        ]})
        assert p.level_for("u", "create", "events", "default") == "None"
        assert p.level_for("u", "create", "pods", "default") == "RequestResponse"
        assert p.level_for("u", "create", "nodes", "") == "Metadata"

    def test_user_and_namespace_scoping(self):
        p = AuditPolicy.from_dict({"rules": [
            {"level": "Request", "users": ["system:admin"],
             "namespaces": ["kube-system"]},
        ], "defaultLevel": "Metadata"})
        assert p.level_for("system:admin", "create", "pods",
                           "kube-system") == "Request"
        assert p.level_for("system:admin", "create", "pods",
                           "default") == "Metadata"
        assert p.level_for("alice", "create", "pods",
                           "kube-system") == "Metadata"


class TestLevels:
    def test_none_drops_and_request_captures(self):
        log = []
        master = Master(audit_log=log, audit_policy={"rules": [
            {"level": "None", "resources": ["events"]},
            {"level": "RequestResponse", "resources": ["pods"]},
        ]}).start()
        cs = Clientset(master.url)
        try:
            cs.pods.create(make_pod("audited"))
            ev = t.Event()
            ev.metadata.name = "noisy"
            ev.source_component = "test"
            cs.events.create(ev)
            pod_entries = [e for e in log if e["resource"] == "pods"]
            assert pod_entries and pod_entries[0]["level"] == "RequestResponse"
            assert pod_entries[0]["requestObject"]["metadata"]["name"] == "audited"
            assert pod_entries[0]["responseObject"]["kind"] == "Pod"
            assert not any(e["resource"] == "events" for e in log)
        finally:
            cs.close()
            master.stop()

    def test_metadata_level_has_no_objects(self):
        log = []
        master = Master(audit_log=log).start()  # default: Metadata
        cs = Clientset(master.url)
        try:
            cs.pods.create(make_pod("meta"))
            entry = [e for e in log if e["resource"] == "pods"][0]
            assert entry["level"] == "Metadata"
            assert "requestObject" not in entry
        finally:
            cs.close()
            master.stop()


class TestWebhookBackend:
    def test_events_batched_to_sink(self):
        batches = []

        class _H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                batches.append(json.loads(self.rfile.read(n)))
                self.send_response(200)
                self.send_header("Content-Length", "0")
                self.end_headers()

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        httpd.daemon_threads = True
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{httpd.server_address[1]}/audit"
        master = Master(audit_webhook_url=url).start()
        cs = Clientset(master.url)
        try:
            for i in range(3):
                cs.pods.create(make_pod(f"whk-{i}"))
            deadline = time.time() + 5
            while time.time() < deadline:
                got = [i for b in batches for i in b.get("items", [])
                       if i["resource"] == "pods"]
                if len(got) >= 3:
                    break
                time.sleep(0.1)
            assert len(got) >= 3
            assert batches[0]["kind"] == "EventList"
        finally:
            cs.close()
            master.stop()
            httpd.shutdown()
            httpd.server_close()
